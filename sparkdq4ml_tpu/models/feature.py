"""Feature-layer transformers.

``VectorAssembler`` packs input columns into one ``(n, d)`` feature-matrix
column (`DataQuality4MachineLearningApp.java:110-113`). TPU-first: the
"vector column" is literally the feature matrix in HBM, laid out densely so
the fit's Gramian is a single MXU matmul — there is no per-row vector object.

``StandardScaler`` / ``MinMaxScaler`` / ``MaxAbsScaler`` are the adjacent
MLlib feature estimators (same ``spark.ml.feature`` package the reference's
VectorAssembler comes from, pom.xml:29-32 mllib dependency). Statistics are
mask-weighted one-pass device reductions — filtered rows never leak into the
moments (SURVEY.md §7 "Masked-filter semantics") — and MLlib conventions are
kept: StandardScaler uses the *sample* (n−1) std, defaults
``with_mean=False, with_std=True``, and maps zero-variance features to 0;
MinMaxScaler maps constant features to ``(min+max)/2``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from .base import Estimator, Model, Transformer


class VectorAssembler(Transformer):
    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_col: str = "features"):
        self.input_cols = list(input_cols) if input_cols else []
        self.output_col = output_col

    def set_input_cols(self, cols: Sequence[str]) -> "VectorAssembler":
        self.input_cols = list(cols)
        return self

    setInputCols = set_input_cols

    def set_output_col(self, name: str) -> "VectorAssembler":
        self.output_col = name
        return self

    setOutputCol = set_output_col

    def get_input_cols(self):
        return list(self.input_cols)

    getInputCols = get_input_cols

    def get_output_col(self):
        return self.output_col

    getOutputCol = get_output_col

    def transform(self, frame):
        if not self.input_cols:
            raise ValueError("VectorAssembler: input_cols not set")
        dt = float_dtype()
        parts = []
        for name in self.input_cols:
            arr = jnp.asarray(frame._column_values(name), dt)
            parts.append(arr[:, None] if arr.ndim == 1 else arr)
        return frame.with_column(self.output_col, jnp.concatenate(parts, axis=1))


class _ScalerBase(Estimator):
    """Shared input/output-col builder surface for the feature scalers."""

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features"):
        self.input_col = input_col
        self.output_col = output_col

    def set_input_col(self, name: str):
        self.input_col = name
        return self

    setInputCol = set_input_col

    def set_output_col(self, name: str):
        self.output_col = name
        return self

    setOutputCol = set_output_col

    def _masked_feature_matrix(self, frame):
        """(n, d) feature matrix + (n,) mask weights on device."""
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        w = frame.mask.astype(X.dtype)
        return X, w


@jax.jit
def _masked_moments(X, w):
    """Mask-weighted count, mean, and sample variance — one fused pass."""
    n = jnp.sum(w)
    wc = w[:, None]
    mean = jnp.sum(X * wc, axis=0) / n
    centered = (X - mean) * wc
    var = jnp.sum(centered * centered, axis=0) / jnp.maximum(n - 1.0, 1.0)
    return n, mean, var


@jax.jit
def _masked_min_max(X, w):
    big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
    wc = w[:, None] > 0
    lo = jnp.min(jnp.where(wc, X, big), axis=0)
    hi = jnp.max(jnp.where(wc, X, -big), axis=0)
    return lo, hi


class StandardScaler(_ScalerBase):
    """MLlib ``StandardScaler``: defaults ``with_mean=False, with_std=True``;
    sample (n−1) std; zero-variance features scale to 0.0."""

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features",
                 with_mean: bool = False, with_std: bool = True):
        super().__init__(input_col, output_col)
        self.with_mean = with_mean
        self.with_std = with_std

    def set_with_mean(self, v: bool):
        self.with_mean = v
        return self

    setWithMean = set_with_mean

    def set_with_std(self, v: bool):
        self.with_std = v
        return self

    setWithStd = set_with_std

    def fit(self, frame) -> "StandardScalerModel":
        X, w = self._masked_feature_matrix(frame)
        _, mean, var = _masked_moments(X, w)
        return StandardScalerModel(np.asarray(mean), np.asarray(jnp.sqrt(var)),
                                   self.with_mean, self.with_std,
                                   self.input_col, self.output_col)


class StandardScalerModel(Model):
    def __init__(self, mean, std, with_mean, with_std, input_col, output_col):
        self.mean = np.asarray(mean)
        self.std = np.asarray(std)
        self.with_mean = with_mean
        self.with_std = with_std
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        if self.with_mean:
            X = X - jnp.asarray(self.mean, X.dtype)
        if self.with_std:
            # MLlib: features with std == 0 map to 0.0 (scale factor 0).
            inv = np.where(self.std > 0, 1.0 / np.where(self.std > 0,
                                                        self.std, 1.0), 0.0)
            X = X * jnp.asarray(inv, X.dtype)
        return frame.with_column(self.output_col,
                                 X[:, 0] if squeeze else X)


class MinMaxScaler(_ScalerBase):
    """MLlib ``MinMaxScaler``: rescale to [min, max] per feature; constant
    features map to ``(min+max)/2``."""

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features",
                 min: float = 0.0, max: float = 1.0):
        super().__init__(input_col, output_col)
        self.min = float(min)
        self.max = float(max)

    def set_min(self, v: float):
        self.min = float(v)
        return self

    setMin = set_min

    def set_max(self, v: float):
        self.max = float(v)
        return self

    setMax = set_max

    def fit(self, frame) -> "MinMaxScalerModel":
        X, w = self._masked_feature_matrix(frame)
        lo, hi = _masked_min_max(X, w)
        return MinMaxScalerModel(np.asarray(lo), np.asarray(hi),
                                 self.min, self.max,
                                 self.input_col, self.output_col)


class MinMaxScalerModel(Model):
    def __init__(self, original_min, original_max, min, max,
                 input_col, output_col):
        self.original_min = np.asarray(original_min)
        self.original_max = np.asarray(original_max)
        self.min = min
        self.max = max
        self.input_col = input_col
        self.output_col = output_col

    originalMin = property(lambda self: self.original_min)
    originalMax = property(lambda self: self.original_max)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        rng = self.original_max - self.original_min
        constant = rng == 0
        inv = np.where(constant, 0.0, 1.0 / np.where(constant, 1.0, rng))
        scaled = (X - jnp.asarray(self.original_min, X.dtype)) \
            * jnp.asarray(inv, X.dtype) * (self.max - self.min) + self.min
        half = 0.5 * (self.max + self.min)
        scaled = jnp.where(jnp.asarray(constant), jnp.asarray(half, X.dtype),
                           scaled)
        return frame.with_column(self.output_col,
                                 scaled[:, 0] if squeeze else scaled)


class MaxAbsScaler(_ScalerBase):
    """MLlib ``MaxAbsScaler``: divide by per-feature max |x| (sparsity
    preserving); all-zero features stay 0."""

    def fit(self, frame) -> "MaxAbsScalerModel":
        X, w = self._masked_feature_matrix(frame)
        lo, hi = _masked_min_max(X, w)
        max_abs = np.maximum(np.abs(np.asarray(lo)), np.abs(np.asarray(hi)))
        return MaxAbsScalerModel(max_abs, self.input_col, self.output_col)


class MaxAbsScalerModel(Model):
    def __init__(self, max_abs, input_col, output_col):
        self.max_abs = np.asarray(max_abs)
        self.input_col = input_col
        self.output_col = output_col

    maxAbs = property(lambda self: self.max_abs)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        inv = np.where(self.max_abs > 0,
                       1.0 / np.where(self.max_abs > 0, self.max_abs, 1.0), 0.0)
        X = X * jnp.asarray(inv, X.dtype)
        return frame.with_column(self.output_col, X[:, 0] if squeeze else X)
