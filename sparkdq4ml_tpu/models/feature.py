"""Feature-layer transformers.

``VectorAssembler`` packs input columns into one ``(n, d)`` feature-matrix
column (`DataQuality4MachineLearningApp.java:110-113`). TPU-first: the
"vector column" is literally the feature matrix in HBM, laid out densely so
the fit's Gramian is a single MXU matmul — there is no per-row vector object.

``StandardScaler`` / ``MinMaxScaler`` / ``MaxAbsScaler`` are the adjacent
MLlib feature estimators (same ``spark.ml.feature`` package the reference's
VectorAssembler comes from, pom.xml:29-32 mllib dependency). Statistics are
mask-weighted one-pass device reductions — filtered rows never leak into the
moments (SURVEY.md §7 "Masked-filter semantics") — and MLlib conventions are
kept: StandardScaler uses the *sample* (n−1) std, defaults
``with_mean=False, with_std=True``, and maps zero-variance features to 0;
MinMaxScaler maps constant features to ``(min+max)/2``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype, int_dtype
from .base import Estimator, Model, Transformer, host_fetch, persistable


@persistable
class VectorAssembler(Transformer):
    _persist_attrs = ('input_cols', 'output_col')
    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_col: str = "features"):
        self.input_cols = list(input_cols) if input_cols else []
        self.output_col = output_col

    def set_input_cols(self, cols: Sequence[str]) -> "VectorAssembler":
        self.input_cols = list(cols)
        return self

    setInputCols = set_input_cols

    def set_output_col(self, name: str) -> "VectorAssembler":
        self.output_col = name
        return self

    setOutputCol = set_output_col

    def get_input_cols(self):
        return list(self.input_cols)

    getInputCols = get_input_cols

    def get_output_col(self):
        return self.output_col

    getOutputCol = get_output_col

    def transform(self, frame):
        if not self.input_cols:
            raise ValueError("VectorAssembler: input_cols not set")
        dt = float_dtype()
        parts = []
        for name in self.input_cols:
            arr = jnp.asarray(frame._column_values(name), dt)
            parts.append(arr[:, None] if arr.ndim == 1 else arr)
        return frame.with_column(self.output_col, jnp.concatenate(parts, axis=1))


@persistable
class VectorSizeHint(Transformer):
    """MLlib ``VectorSizeHint``: declare (and validate) the size of a vector
    column so downstream stages (VectorAssembler in a streaming/persisted
    pipeline) know their output width without seeing data.

    Columnar-engine semantics: vector columns are dense ``(n, d)`` device
    arrays, so the size is uniform and checked once against the declared
    ``size`` — there are no per-row ragged vectors. Spark's
    ``handle_invalid`` modes map accordingly: ``error`` raises on a
    mismatch (including a scalar column when ``size != 1``);
    ``skip`` drops mismatching rows — a uniform column mismatching the
    hint means every row, so the frame comes back fully masked (empty);
    ``optimistic`` is Spark's no-validation mode and passes through.
    """

    _persist_attrs = ('input_col', 'size', 'handle_invalid')

    def __init__(self, input_col: str = None, size: int = None,
                 handle_invalid: str = "error"):
        if handle_invalid not in ("error", "skip", "optimistic"):
            raise ValueError(
                f"handle_invalid must be error/skip/optimistic, "
                f"got {handle_invalid!r}")
        self.input_col = input_col
        self.size = None if size is None else int(size)
        self.handle_invalid = handle_invalid

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_size(self, v):
        self.size = int(v)
        return self

    setSize = set_size

    def set_handle_invalid(self, v):
        if v not in ("error", "skip", "optimistic"):
            raise ValueError(
                f"handle_invalid must be error/skip/optimistic, got {v!r}")
        self.handle_invalid = v
        return self

    setHandleInvalid = set_handle_invalid

    def transform(self, frame):
        if self.input_col is None or self.size is None:
            raise ValueError("VectorSizeHint: input_col and size must be set")
        if self.size < 1:
            raise ValueError(f"VectorSizeHint: invalid size {self.size}")
        arr = frame._column_values(self.input_col)
        width = 1 if arr.ndim == 1 else arr.shape[1]
        if width != self.size:
            if self.handle_invalid == "error":
                raise ValueError(
                    f"VectorSizeHint: column {self.input_col!r} has size "
                    f"{width}, expected {self.size}")
            if self.handle_invalid == "skip":
                return frame.filter(
                    jnp.zeros((frame.num_slots,), bool))
        return frame


@persistable
class StringIndexer(Estimator):
    """MLlib ``StringIndexer``: map string categories to double indices,
    most-frequent-first (``frequencyDesc``; ties broken alphabetically, as
    Spark does). ``handle_invalid``: ``"error"`` (default) | ``"keep"``
    (unseen → numLabels) | ``"skip"`` (unseen → masked out on transform).

    The index *fit* is host-side (categories are host strings); the
    transformed column is a device array ready for VectorAssembler.
    """

    _persist_attrs = ('input_col', 'output_col', 'handle_invalid')

    def __init__(self, input_col: str = None, output_col: str = None,
                 handle_invalid: str = "error"):
        self.input_col = input_col
        self.output_col = output_col
        if handle_invalid not in ("error", "keep", "skip"):
            raise ValueError(f"handle_invalid={handle_invalid!r}")
        self.handle_invalid = handle_invalid

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def set_handle_invalid(self, v):
        self.handle_invalid = v
        return self

    setHandleInvalid = set_handle_invalid

    def fit(self, frame) -> "StringIndexerModel":
        col = frame._column_values(self.input_col)
        mask = np.asarray(frame.mask)
        values = [str(v) for v, m in zip(np.asarray(col, object), mask)
                  if m and v is not None]
        from collections import Counter

        counts = Counter(values)
        labels = sorted(counts, key=lambda k: (-counts[k], k))
        return StringIndexerModel(labels, self.input_col, self.output_col,
                                  self.handle_invalid)


@persistable
class StringIndexerModel(Model):
    _persist_attrs = ('labels', 'input_col', 'output_col', 'handle_invalid')

    def __init__(self, labels, input_col, output_col, handle_invalid="error"):
        self.labels = list(labels)
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid
        self._index = {l: i for i, l in enumerate(self.labels)}

    def _post_load(self):
        self.labels = list(self.labels)
        self._index = {l: i for i, l in enumerate(self.labels)}

    labelsArray = property(lambda self: [list(self.labels)])

    def transform(self, frame):
        col = np.asarray(frame._column_values(self.input_col), object)
        n_labels = len(self.labels)
        idx = np.empty(len(col), dtype=np.dtype(float_dtype()))
        invalid = np.zeros(len(col), bool)
        host_mask = np.asarray(frame.mask)
        for i, v in enumerate(col):
            j = self._index.get(str(v)) if v is not None else None
            if j is None:
                invalid[i] = True
                idx[i] = n_labels
            else:
                idx[i] = j
        if self.handle_invalid == "error" and bool((invalid & host_mask).any()):
            bad = sorted({str(col[i]) for i in np.nonzero(invalid & host_mask)[0]})
            raise ValueError(f"StringIndexer: unseen labels {bad}; set "
                             f"handle_invalid='keep' or 'skip'")
        out = frame.with_column(self.output_col, jnp.asarray(idx))
        if self.handle_invalid == "skip":
            out = out.filter(jnp.asarray(~invalid))
        return out


@persistable
class IndexToString(Transformer):
    """Inverse of StringIndexer: indices → label strings (host column)."""

    _persist_attrs = ('input_col', 'output_col', 'labels')

    def __init__(self, input_col: str = None, output_col: str = None,
                 labels=None):
        self.input_col = input_col
        self.output_col = output_col
        self.labels = list(labels) if labels is not None else None

    def transform(self, frame):
        idx = np.asarray(frame._column_values(self.input_col))
        labels = self.labels
        out = np.asarray([labels[int(i)] if 0 <= int(i) < len(labels) else None
                          for i in idx], dtype=object)
        return frame.with_column(self.output_col, out)


@persistable
class OneHotEncoder(Estimator):
    """MLlib ``OneHotEncoder``: index column → one-hot vector column.

    ``drop_last=True`` (Spark default) omits the last category so the
    encoding stays linearly independent with an intercept. The encode is a
    device comparison against an iota — one fused op, no host loop.
    """

    _persist_attrs = ('input_col', 'output_col', 'drop_last',
                      'input_cols', 'output_cols')
    input_cols = None     # back-compat default for pre-plural saves
    output_cols = None

    def __init__(self, input_col: str = None, output_col: str = None,
                 drop_last: bool = True, input_cols=None, output_cols=None):
        if input_col is not None and input_cols is not None:
            raise ValueError("set input_col OR input_cols, not both")
        self.input_col = input_col
        self.output_col = output_col
        self.input_cols = list(input_cols) if input_cols is not None else None
        self.output_cols = (list(output_cols) if output_cols is not None
                            else None)
        self.drop_last = drop_last

    def set_drop_last(self, v: bool):
        self.drop_last = v
        return self

    setDropLast = set_drop_last

    def _col_pairs(self):
        """Normalized [(in, out)] across the single- and plural-column
        forms (Spark 2.4's OneHotEncoderEstimator / 3.x OneHotEncoder
        take inputCols/outputCols lists)."""
        if self.input_cols is not None:
            if not self.input_cols:
                raise ValueError("input_cols must not be empty")
            outs = self.output_cols
            if outs is None or len(outs) != len(self.input_cols):
                raise ValueError("output_cols must match input_cols")
            return list(zip(self.input_cols, outs))
        if self.input_col is None:
            raise ValueError("OneHotEncoder needs input_col or input_cols")
        return [(self.input_col, self.output_col)]

    def fit(self, frame) -> "OneHotEncoderModel":
        w = frame.mask
        # stack the per-column maxes and cross device->host ONCE (a sync
        # per column would scale fit latency with the column count)
        maxes = jnp.stack([
            jnp.max(jnp.where(w, jnp.asarray(frame._column_values(cin)), -1))
            for cin, _ in self._col_pairs()])
        sizes = (np.asarray(maxes).astype(np.int64) + 1).tolist()
        if self.input_cols is not None:
            return OneHotEncoderModel(sizes[0], None, None, self.drop_last,
                                      category_sizes=sizes,
                                      input_cols=self.input_cols,
                                      output_cols=self.output_cols)
        return OneHotEncoderModel(sizes[0], self.input_col, self.output_col,
                                  self.drop_last)


# Spark 2.4 ships this estimator under the name OneHotEncoderEstimator
# (the old OneHotEncoder transformer was deprecated); 3.0 renamed it back.
# Both names resolve here.
OneHotEncoderEstimator = OneHotEncoder


@persistable
class OneHotEncoderModel(Model):
    _persist_attrs = ('category_size', 'input_col', 'output_col',
                      'drop_last', 'category_sizes', 'input_cols',
                      'output_cols')
    category_sizes = None  # back-compat defaults for pre-plural saves
    input_cols = None
    output_cols = None

    def __init__(self, category_size, input_col, output_col, drop_last=True,
                 category_sizes=None, input_cols=None, output_cols=None):
        self.category_size = int(category_size)
        self.input_col = input_col
        self.output_col = output_col
        self.drop_last = drop_last
        self.category_sizes = (list(map(int, category_sizes))
                               if category_sizes is not None else None)
        self.input_cols = list(input_cols) if input_cols is not None else None
        self.output_cols = (list(output_cols) if output_cols is not None
                            else None)
        self._check_plural_invariant()

    def _check_plural_invariant(self):
        """zip in _triples would silently truncate on mismatched lists."""
        if self.input_cols is not None:
            if (self.output_cols is None or self.category_sizes is None
                    or len(self.output_cols) != len(self.input_cols)
                    or len(self.category_sizes) != len(self.input_cols)):
                raise ValueError(
                    "input_cols / output_cols / category_sizes lengths "
                    "must match")

    def _post_load(self):
        # load_stage constructs via __new__ + setattr, bypassing __init__:
        # re-establish the invariant for saved (possibly hand-edited or
        # truncated) stage files too
        self._check_plural_invariant()

    @property
    def categorySizes(self):
        if self.category_sizes is not None:
            return list(self.category_sizes)
        return [self.category_size]

    def _triples(self):
        if self.input_cols is not None:
            return list(zip(self.input_cols, self.output_cols,
                            self.category_sizes))
        return [(self.input_col, self.output_col, self.category_size)]

    def transform(self, frame):
        out = frame
        for cin, cout, size in self._triples():
            # read indices from the ORIGINAL frame: an earlier output
            # name colliding with a later input name must not feed a
            # one-hot matrix back in as indices
            idx = jnp.asarray(frame._column_values(cin), int_dtype())
            width = size - (1 if self.drop_last else 0)
            eye = jnp.arange(width, dtype=int_dtype())
            onehot = (idx[:, None] == eye[None, :]).astype(float_dtype())
            out = out.with_column(cout, onehot)
        return out


@persistable
class Bucketizer(Transformer):
    """MLlib ``Bucketizer``: continuous column → bucket index by split
    points (``splits`` of length b+1, monotonic; use ±inf for open ends).
    One device ``searchsorted``; values outside the splits raise unless
    ``handle_invalid='keep'`` (→ NaN) or ``'skip'`` (→ masked)."""

    _persist_attrs = ('splits', 'input_col', 'output_col', 'handle_invalid')

    def __init__(self, splits=None, input_col: str = None,
                 output_col: str = None, handle_invalid: str = "error"):
        self.splits = list(splits) if splits is not None else None
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid

    def set_splits(self, v):
        self.splits = list(v)
        return self

    setSplits = set_splits

    def transform(self, frame):
        s = np.asarray(self.splits, np.dtype(float_dtype()))
        if s.ndim != 1 or len(s) < 3 or not np.all(np.diff(s) > 0):
            raise ValueError("splits must be >=3 strictly increasing values")
        x = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        # right-closed last bucket, Spark semantics: x == splits[-1] falls in
        # the last bucket; outside [splits[0], splits[-1]] is invalid.
        idx = jnp.clip(jnp.searchsorted(jnp.asarray(s), x, side="right") - 1,
                       0, len(s) - 2).astype(float_dtype())
        # NaN is invalid too (it compares false to both bounds, and Spark
        # routes it through handleInvalid rather than into a bucket)
        invalid = jnp.logical_or(jnp.logical_or(x < s[0], x > s[-1]),
                                 jnp.isnan(x))
        if self.handle_invalid == "error":
            if bool(host_fetch(jnp.logical_and(invalid, frame.mask)).any()):
                raise ValueError("Bucketizer: values outside splits; set "
                                 "handle_invalid='keep' or 'skip'")
        elif self.handle_invalid == "keep":
            # Spark's 'keep': invalid values land in a special extra bucket
            # with index numBuckets (= len(splits) - 1)
            idx = jnp.where(invalid,
                            jnp.asarray(float(len(s) - 1), float_dtype()),
                            idx)
        out = frame.with_column(self.output_col, idx)
        if self.handle_invalid == "skip":
            out = out.filter(jnp.logical_not(invalid))
        return out


class _ScalerBase(Estimator):
    """Shared input/output-col builder surface for the feature scalers."""

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features"):
        self.input_col = input_col
        self.output_col = output_col

    def set_input_col(self, name: str):
        self.input_col = name
        return self

    setInputCol = set_input_col

    def set_output_col(self, name: str):
        self.output_col = name
        return self

    setOutputCol = set_output_col

    def _masked_feature_matrix(self, frame):
        """(n, d) feature matrix + (n,) mask weights on device."""
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        w = frame.mask.astype(X.dtype)
        return X, w


@jax.jit
def _masked_moments(X, w):
    """Mask-weighted count, mean, and sample variance — one fused pass."""
    n = jnp.sum(w)
    wc = w[:, None]
    mean = jnp.sum(X * wc, axis=0) / n
    centered = (X - mean) * wc
    var = jnp.sum(centered * centered, axis=0) / jnp.maximum(n - 1.0, 1.0)
    return n, mean, var


@jax.jit
def _masked_min_max(X, w):
    big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
    wc = w[:, None] > 0
    lo = jnp.min(jnp.where(wc, X, big), axis=0)
    hi = jnp.max(jnp.where(wc, X, -big), axis=0)
    return lo, hi


@persistable
class StandardScaler(_ScalerBase):
    """MLlib ``StandardScaler``: defaults ``with_mean=False, with_std=True``;
    sample (n−1) std; zero-variance features scale to 0.0."""

    _persist_attrs = ('input_col', 'output_col', 'with_mean', 'with_std')

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features",
                 with_mean: bool = False, with_std: bool = True):
        super().__init__(input_col, output_col)
        self.with_mean = with_mean
        self.with_std = with_std

    def set_with_mean(self, v: bool):
        self.with_mean = v
        return self

    setWithMean = set_with_mean

    def set_with_std(self, v: bool):
        self.with_std = v
        return self

    setWithStd = set_with_std

    def fit(self, frame) -> "StandardScalerModel":
        X, w = self._masked_feature_matrix(frame)
        _, mean, var = _masked_moments(X, w)
        return StandardScalerModel(np.asarray(mean), host_fetch(jnp.sqrt(var)),
                                   self.with_mean, self.with_std,
                                   self.input_col, self.output_col)


@persistable
class StandardScalerModel(Model):
    _persist_attrs = ('mean', 'std', 'with_mean', 'with_std', 'input_col', 'output_col')
    def __init__(self, mean, std, with_mean, with_std, input_col, output_col):
        self.mean = np.asarray(mean)
        self.std = np.asarray(std)
        self.with_mean = with_mean
        self.with_std = with_std
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        if self.with_mean:
            X = X - jnp.asarray(self.mean, X.dtype)
        if self.with_std:
            # MLlib: features with std == 0 map to 0.0 (scale factor 0).
            inv = np.where(self.std > 0, 1.0 / np.where(self.std > 0,
                                                        self.std, 1.0), 0.0)
            X = X * jnp.asarray(inv, X.dtype)
        return frame.with_column(self.output_col,
                                 X[:, 0] if squeeze else X)


@persistable
class MinMaxScaler(_ScalerBase):
    """MLlib ``MinMaxScaler``: rescale to [min, max] per feature; constant
    features map to ``(min+max)/2``."""

    _persist_attrs = ('input_col', 'output_col', 'min', 'max')

    def __init__(self, input_col: str = "features",
                 output_col: str = "scaled_features",
                 min: float = 0.0, max: float = 1.0):
        super().__init__(input_col, output_col)
        self.min = float(min)
        self.max = float(max)

    def set_min(self, v: float):
        self.min = float(v)
        return self

    setMin = set_min

    def set_max(self, v: float):
        self.max = float(v)
        return self

    setMax = set_max

    def fit(self, frame) -> "MinMaxScalerModel":
        X, w = self._masked_feature_matrix(frame)
        lo, hi = _masked_min_max(X, w)
        return MinMaxScalerModel(np.asarray(lo), np.asarray(hi),
                                 self.min, self.max,
                                 self.input_col, self.output_col)


@persistable
class MinMaxScalerModel(Model):
    _persist_attrs = ('original_min', 'original_max', 'min', 'max', 'input_col', 'output_col')
    def __init__(self, original_min, original_max, min, max,
                 input_col, output_col):
        self.original_min = np.asarray(original_min)
        self.original_max = np.asarray(original_max)
        self.min = min
        self.max = max
        self.input_col = input_col
        self.output_col = output_col

    originalMin = property(lambda self: self.original_min)
    originalMax = property(lambda self: self.original_max)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        rng = self.original_max - self.original_min
        constant = rng == 0
        inv = np.where(constant, 0.0, 1.0 / np.where(constant, 1.0, rng))
        scaled = (X - jnp.asarray(self.original_min, X.dtype)) \
            * jnp.asarray(inv, X.dtype) * (self.max - self.min) + self.min
        half = 0.5 * (self.max + self.min)
        scaled = jnp.where(jnp.asarray(constant), jnp.asarray(half, X.dtype),
                           scaled)
        return frame.with_column(self.output_col,
                                 scaled[:, 0] if squeeze else scaled)


@persistable
class MaxAbsScaler(_ScalerBase):
    """MLlib ``MaxAbsScaler``: divide by per-feature max |x| (sparsity
    preserving); all-zero features stay 0."""

    _persist_attrs = ('input_col', 'output_col')

    def fit(self, frame) -> "MaxAbsScalerModel":
        X, w = self._masked_feature_matrix(frame)
        lo, hi = _masked_min_max(X, w)
        max_abs = np.maximum(np.abs(np.asarray(lo)), np.abs(np.asarray(hi)))
        return MaxAbsScalerModel(max_abs, self.input_col, self.output_col)


@persistable
class MaxAbsScalerModel(Model):
    _persist_attrs = ('max_abs', 'input_col', 'output_col')
    def __init__(self, max_abs, input_col, output_col):
        self.max_abs = np.asarray(max_abs)
        self.input_col = input_col
        self.output_col = output_col

    maxAbs = property(lambda self: self.max_abs)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        inv = np.where(self.max_abs > 0,
                       1.0 / np.where(self.max_abs > 0, self.max_abs, 1.0), 0.0)
        X = X * jnp.asarray(inv, X.dtype)
        return frame.with_column(self.output_col, X[:, 0] if squeeze else X)


@persistable
class Imputer(Estimator):
    """MLlib ``Imputer``: replace missing values (NaN by default, or a
    configured ``missing_value`` sentinel) in numeric columns with the
    column's mean / median / mode, learned over valid rows only.

    Statistics are computed at the host boundary (median/mode are sort- and
    histogram-shaped, not device hot loops); the transform itself is a device
    ``jnp.where`` per column, fused by XLA with downstream ops.
    """

    _persist_attrs = ('input_cols', 'output_cols', 'strategy',
                      'missing_value')

    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_cols: Optional[Sequence[str]] = None,
                 strategy: str = "mean", missing_value: float = float("nan")):
        self.input_cols = list(input_cols) if input_cols else []
        self.output_cols = list(output_cols) if output_cols else []
        if strategy not in ("mean", "median", "mode"):
            raise ValueError(f"strategy={strategy!r} (mean|median|mode)")
        self.strategy = strategy
        self.missing_value = float(missing_value)

    def set_input_cols(self, v):
        self.input_cols = list(v)
        return self

    setInputCols = set_input_cols

    def set_output_cols(self, v):
        self.output_cols = list(v)
        return self

    setOutputCols = set_output_cols

    def set_strategy(self, v):
        if v not in ("mean", "median", "mode"):
            raise ValueError(f"strategy={v!r}")
        self.strategy = v
        return self

    setStrategy = set_strategy

    def set_missing_value(self, v):
        self.missing_value = float(v)
        return self

    setMissingValue = set_missing_value

    def _out_cols(self):
        return self.output_cols or self.input_cols

    def fit(self, frame) -> "ImputerModel":
        if not self.input_cols:
            raise ValueError("Imputer: input_cols not set")
        if self.output_cols and len(self.output_cols) != len(self.input_cols):
            raise ValueError("output_cols length must match input_cols")
        mask = np.asarray(frame.mask)
        surrogates = []
        for name in self.input_cols:
            x = np.asarray(frame._column_values(name), np.float64)[mask]
            miss = np.isnan(x) if np.isnan(self.missing_value) \
                else (x == self.missing_value)
            vals = x[~miss & ~np.isnan(x)]
            if len(vals) == 0:
                raise ValueError(f"Imputer: column {name!r} has no valid "
                                 "values to learn a surrogate from")
            if self.strategy == "mean":
                s = float(vals.mean())
            elif self.strategy == "median":
                s = float(np.median(vals))
            else:  # mode: most frequent, smallest on ties (Spark)
                uniq, cnt = np.unique(vals, return_counts=True)
                s = float(uniq[np.argmax(cnt)])
            surrogates.append(s)
        return ImputerModel(self.input_cols, self._out_cols(),
                            surrogates, self.missing_value)


@persistable
class ImputerModel(Model):
    _persist_attrs = ('input_cols', 'output_cols', 'surrogates',
                      'missing_value')

    def __init__(self, input_cols, output_cols, surrogates, missing_value):
        self.input_cols = list(input_cols)
        self.output_cols = list(output_cols)
        self.surrogates = [float(s) for s in surrogates]
        self.missing_value = float(missing_value)

    @property
    def surrogate_df(self):
        """The learned surrogates as a 1-row Frame (MLlib surrogateDF)."""
        from ..frame import Frame

        return Frame({c: [s] for c, s in zip(self.input_cols,
                                             self.surrogates)})

    surrogateDF = surrogate_df

    def transform(self, frame):
        for name, out, s in zip(self.input_cols, self.output_cols,
                                self.surrogates):
            x = jnp.asarray(frame._column_values(name), float_dtype())
            # NaN (the engine's null) is always missing — Spark imputes
            # nulls regardless of the configured missingValue sentinel
            miss = jnp.isnan(x)
            if not np.isnan(self.missing_value):
                miss = jnp.logical_or(miss, x == self.missing_value)
            frame = frame.with_column(out,
                                      jnp.where(miss, jnp.asarray(s, x.dtype),
                                                x))
        return frame


@persistable
class Normalizer(Transformer):
    """MLlib ``Normalizer``: scale each row of a vector column to unit
    p-norm (default p=2). Zero rows stay zero. Pure device elementwise —
    XLA fuses the norm and the divide into one kernel."""

    _persist_attrs = ('input_col', 'output_col', 'p')

    def __init__(self, input_col: str = "features",
                 output_col: str = "normalized_features", p: float = 2.0):
        self.input_col = input_col
        self.output_col = output_col
        if not p >= 1.0:
            raise ValueError("p must be >= 1")
        self.p = float(p)

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def set_p(self, v):
        if not v >= 1.0:
            raise ValueError("p must be >= 1")
        self.p = float(v)
        return self

    setP = set_p

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        if self.p == float("inf"):
            norm = jnp.max(jnp.abs(X), axis=1, keepdims=True)
        elif self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(X * X, axis=1, keepdims=True))
        elif self.p == 1.0:
            norm = jnp.sum(jnp.abs(X), axis=1, keepdims=True)
        else:
            norm = jnp.sum(jnp.abs(X) ** self.p, axis=1,
                           keepdims=True) ** (1.0 / self.p)
        out = jnp.where(norm > 0, X / jnp.where(norm > 0, norm, 1.0), X)
        return frame.with_column(self.output_col,
                                 out[:, 0] if squeeze else out)


@persistable
class Binarizer(Transformer):
    """MLlib ``Binarizer``: 1.0 where x > threshold else 0.0, on a scalar
    or vector column (NaN compares false → 0.0, as Spark's codegen does)."""

    _persist_attrs = ('threshold', 'input_col', 'output_col')

    def __init__(self, threshold: float = 0.0, input_col: str = None,
                 output_col: str = None):
        self.threshold = float(threshold)
        self.input_col = input_col
        self.output_col = output_col

    def set_threshold(self, v):
        self.threshold = float(v)
        return self

    setThreshold = set_threshold

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def transform(self, frame):
        x = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        out = jnp.where(x > self.threshold,
                        jnp.asarray(1.0, x.dtype), jnp.asarray(0.0, x.dtype))
        return frame.with_column(self.output_col, out)


@persistable
class PolynomialExpansion(Transformer):
    """MLlib ``PolynomialExpansion``: expand an (n, d) vector column into
    all monomials of total degree 1..``degree`` over the d features.

    The monomial *plan* (which feature-index multisets to multiply) is a
    tiny host-side enumeration; the expansion itself is one stacked device
    product per monomial, fused by XLA — the MXU-friendly dense layout is
    preserved (output is a single (n, D) matrix). Ordering: grouped by
    degree, lexicographic within a degree (MLlib interleaves; the *set* of
    monomials is identical, only column order differs — documented because
    downstream fits are order-insensitive)."""

    _persist_attrs = ('degree', 'input_col', 'output_col')

    def __init__(self, degree: int = 2, input_col: str = "features",
                 output_col: str = "poly_features"):
        if degree < 1:
            raise ValueError("degree must be >= 1")
        self.degree = int(degree)
        self.input_col = input_col
        self.output_col = output_col

    def set_degree(self, v):
        if v < 1:
            raise ValueError("degree must be >= 1")
        self.degree = int(v)
        return self

    setDegree = set_degree

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def transform(self, frame):
        from itertools import combinations_with_replacement

        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        d = X.shape[1]
        cols = []
        for deg in range(1, self.degree + 1):
            for combo in combinations_with_replacement(range(d), deg):
                term = X[:, combo[0]]
                for j in combo[1:]:
                    term = term * X[:, j]
                cols.append(term)
        return frame.with_column(self.output_col, jnp.stack(cols, axis=1))


@persistable
class QuantileDiscretizer(Estimator):
    """MLlib ``QuantileDiscretizer``: learn ``num_buckets`` quantile split
    points over the valid rows and return a :class:`Bucketizer` with open
    (±inf) outer splits. Exact quantiles (the reference engine's
    approxQuantile relative-error knob is unnecessary at this scale);
    duplicate quantiles collapse, so the fitted bucketizer may have fewer
    buckets, exactly like Spark."""

    _persist_attrs = ('num_buckets', 'input_col', 'output_col',
                      'handle_invalid')

    def __init__(self, num_buckets: int = 2, input_col: str = None,
                 output_col: str = None, handle_invalid: str = "error"):
        if num_buckets < 2:
            raise ValueError("num_buckets must be >= 2")
        self.num_buckets = int(num_buckets)
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid

    def set_num_buckets(self, v):
        if v < 2:
            raise ValueError("num_buckets must be >= 2")
        self.num_buckets = int(v)
        return self

    setNumBuckets = set_num_buckets

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def set_handle_invalid(self, v):
        self.handle_invalid = v
        return self

    setHandleInvalid = set_handle_invalid

    def fit(self, frame) -> "Bucketizer":
        mask = np.asarray(frame.mask)
        x = np.asarray(frame._column_values(self.input_col),
                       np.float64)[mask]
        x = x[~np.isnan(x)]
        if len(x) == 0:
            raise ValueError("QuantileDiscretizer: no valid rows to fit on")
        qs = np.quantile(x, np.linspace(0, 1, self.num_buckets + 1)[1:-1])
        inner = np.unique(qs)  # duplicate quantiles collapse (Spark)
        splits = [-float("inf"), *inner.tolist(), float("inf")]
        return Bucketizer(splits, self.input_col, self.output_col,
                          self.handle_invalid)


@persistable
class PCA(Estimator):
    """MLlib ``PCA``: learn the top-k principal components of a vector
    column. Fit is one masked covariance (a single MXU matmul over the
    row-sharded data, psum-reduced under a mesh) + a device ``eigh`` on the
    tiny (d, d) matrix. Transform follows MLlib exactly: rows are projected
    onto the components **without** mean subtraction (Spark's documented
    behavior — the components themselves come from the centered covariance,
    but ``transform`` multiplies raw rows)."""

    _persist_attrs = ('k', 'input_col', 'output_col')

    def __init__(self, k: int = None, input_col: str = "features",
                 output_col: str = "pca_features"):
        self.k = k
        self.input_col = input_col
        self.output_col = output_col

    def set_k(self, v):
        self.k = int(v)
        return self

    setK = set_k

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def fit(self, frame) -> "PCAModel":
        if not self.k or self.k < 1:
            raise ValueError("PCA: k must be a positive integer")
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        d = X.shape[1]
        if self.k > d:
            raise ValueError(f"k={self.k} exceeds the {d} input features")
        if int(np.asarray(frame.mask).sum()) == 0:
            raise ValueError("PCA: no valid rows to fit on")
        w = frame.mask.astype(X.dtype)
        n = jnp.sum(w)
        mean = jnp.sum(X * w[:, None], axis=0) / n
        C = (X - mean) * w[:, None]
        cov = (C.T @ C) / jnp.maximum(n - 1.0, 1.0)      # sample covariance
        vals, vecs = jnp.linalg.eigh(cov)                # ascending order
        vals = vals[::-1][: self.k]
        vecs = vecs[:, ::-1][:, : self.k]                # (d, k) columns
        # deterministic sign: largest-|.| element of each component positive
        vecs_np = np.asarray(vecs)
        signs = np.sign(vecs_np[np.argmax(np.abs(vecs_np), axis=0),
                                np.arange(self.k)])
        signs[signs == 0] = 1.0
        total = float(host_fetch(jnp.sum(jnp.clip(jnp.diagonal(cov),
                                                  0.0, None))))
        ev = np.clip(np.asarray(vals), 0.0, None)
        ratios = ev / total if total > 0 else np.zeros_like(ev)
        return PCAModel(vecs_np * signs, ratios, self.k,
                        self.input_col, self.output_col)


@persistable
class PCAModel(Model):
    _persist_attrs = ('pc', 'explained_variance', 'k', 'input_col',
                      'output_col')

    def __init__(self, pc, explained_variance, k, input_col, output_col):
        self.pc = np.asarray(pc)                         # (d, k)
        self.explained_variance = np.asarray(explained_variance)
        self.k = int(k)
        self.input_col = input_col
        self.output_col = output_col

    explainedVariance = property(lambda self: self.explained_variance)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        return frame.with_column(self.output_col,
                                 X @ jnp.asarray(self.pc, X.dtype))


@persistable
class Interaction(Transformer):
    """MLlib ``Interaction``: the per-row tensor (Kronecker) product of the
    input columns — scalars or vectors — as one output vector of dimension
    ∏ dᵢ. TPU-first: built as a chain of broadcasted outer products
    reshaped flat, one fused elementwise kernel, no per-row work.
    (spark.ml.feature surface, `/root/reference/pom.xml:29-32`.)"""

    _persist_attrs = ('input_cols', 'output_col')

    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_col: str = "interacted"):
        self.input_cols = list(input_cols) if input_cols else []
        self.output_col = output_col

    def set_input_cols(self, v):
        self.input_cols = list(v)
        return self

    setInputCols = set_input_cols

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def transform(self, frame):
        if len(self.input_cols) < 2:
            raise ValueError("Interaction needs at least two input columns")
        dt = float_dtype()
        out = None
        for name in self.input_cols:
            arr = jnp.asarray(frame._column_values(name), dt)
            if arr.ndim == 1:
                arr = arr[:, None]
            if out is None:
                out = arr
            else:
                n = out.shape[0]
                out = (out[:, :, None] * arr[:, None, :]).reshape(n, -1)
        return frame.with_column(self.output_col, out)


@persistable
class SQLTransformer(Transformer):
    """MLlib ``SQLTransformer``: a SQL statement over the placeholder view
    ``__THIS__`` — wired straight into the framework's own SQL engine
    (sql/parser.py), so the full supported SELECT surface (CAST, WHERE,
    CASE, window functions, ...) is available in pipelines."""

    _persist_attrs = ('statement',)

    def __init__(self, statement: Optional[str] = None):
        self.statement = statement

    def set_statement(self, v):
        self.statement = v
        return self

    setStatement = set_statement

    def get_statement(self):
        return self.statement

    getStatement = get_statement

    def transform(self, frame):
        if not self.statement:
            raise ValueError("SQLTransformer: statement not set")
        import uuid

        from ..sql.catalog import default_catalog
        from ..sql.parser import execute

        # run against the session catalog (so joins against registered
        # temp views work, like Spark), registering the placeholder under
        # a collision-free name and always dropping it afterwards
        view = f"sql_transformer_{uuid.uuid4().hex[:12]}"
        cat = default_catalog()
        cat.register(view, frame)
        try:
            return execute(self.statement.replace("__THIS__", view), cat)
        finally:
            cat.drop(view)


@persistable
class VectorIndexer(Estimator):
    """MLlib ``VectorIndexer``: scan a vector column; every feature with
    ≤ ``max_categories`` distinct values becomes categorical and is
    re-encoded to 0..k−1 category indices (by value order); the rest pass
    through. The scan is one host pass over the fitted column; transform
    is a vectorized ``searchsorted`` per categorical feature."""

    _persist_attrs = ('input_col', 'output_col', 'max_categories',
                      'handle_invalid')

    def __init__(self, max_categories: int = 20,
                 input_col: str = "features",
                 output_col: str = "indexed",
                 handle_invalid: str = "error"):
        if max_categories < 2:
            raise ValueError("max_categories must be >= 2")
        if handle_invalid not in ("error", "keep"):
            raise ValueError(f"handle_invalid={handle_invalid!r}")
        self.max_categories = int(max_categories)
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid

    def set_max_categories(self, v):
        if v < 2:
            raise ValueError("max_categories must be >= 2")
        self.max_categories = int(v)
        return self

    setMaxCategories = set_max_categories

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def fit(self, frame) -> "VectorIndexerModel":
        X = np.asarray(frame._column_values(self.input_col), np.float64)
        if X.ndim == 1:
            X = X[:, None]
        mask = np.asarray(frame.mask)
        Xv = X[mask]
        category_maps = {}
        for j in range(X.shape[1]):
            uniq = np.unique(Xv[:, j])
            uniq = uniq[~np.isnan(uniq)]
            # 0 observed values (all-NaN/all-masked) ⇒ treat as continuous
            # passthrough rather than an empty, untransformable map
            if 0 < len(uniq) <= self.max_categories:
                category_maps[j] = uniq.tolist()
        return VectorIndexerModel(X.shape[1], category_maps,
                                  self.input_col, self.output_col,
                                  self.handle_invalid)


@persistable
class VectorIndexerModel(Model):
    _persist_attrs = ('num_features', '_category_maps_json', 'input_col',
                      'output_col', 'handle_invalid')

    def __init__(self, num_features, category_maps, input_col="features",
                 output_col="indexed", handle_invalid="error"):
        self.num_features = int(num_features)
        self.category_maps = {int(k): list(v)
                              for k, v in category_maps.items()}
        # JSON keys are strings; persist through a string-keyed mirror
        self._category_maps_json = {str(k): list(v)
                                    for k, v in self.category_maps.items()}
        self.input_col = input_col
        self.output_col = output_col
        self.handle_invalid = handle_invalid

    def _post_load(self):
        self.category_maps = {int(k): list(v)
                              for k, v in self._category_maps_json.items()}

    @property
    def category_maps_(self):
        return dict(self.category_maps)

    categoryMaps = category_maps_

    def transform(self, frame):
        X = np.asarray(frame._column_values(self.input_col), np.float64)
        if X.ndim == 1:
            X = X[:, None]
        mask = np.asarray(frame.mask)
        out = X.copy()
        for j, cats in self.category_maps.items():
            cats_arr = np.asarray(cats, np.float64)
            idx = np.searchsorted(cats_arr, X[:, j])
            idx_c = np.clip(idx, 0, len(cats_arr) - 1)
            known = cats_arr[idx_c] == X[:, j]
            is_nan = np.isnan(X[:, j])
            if self.handle_invalid == "error":
                bad = mask & ~known & ~is_nan
                if bad.any():
                    raise ValueError(
                        f"VectorIndexer: unseen category "
                        f"{X[bad, j][0]!r} in feature {j}")
                # NaN stays NaN (it is not a category), never index k−1
                out[:, j] = np.where(is_nan, np.nan, idx_c)
            else:   # keep → unseen (incl. NaN) gets index k
                out[:, j] = np.where(known & ~is_nan, idx_c, len(cats_arr))
        return frame.with_column(self.output_col,
                                 jnp.asarray(out, float_dtype()))


@persistable
class ChiSqSelector(Estimator):
    """MLlib ``ChiSqSelector``: pick features by the χ² independence test
    against a categorical label. ``selector_type``: ``numTopFeatures``
    (default, smallest p-values first), ``percentile``, or ``fpr``.
    The per-feature contingency tables are one-hot matmuls (see
    ``stat.ChiSquareTest``)."""

    _persist_attrs = ('num_top_features', 'selector_type', 'percentile',
                      'fpr', 'features_col', 'label_col', 'output_col')

    def __init__(self, num_top_features: int = 50,
                 selector_type: str = "numTopFeatures",
                 percentile: float = 0.1, fpr: float = 0.05,
                 features_col: str = "features", label_col: str = "label",
                 output_col: str = "selected"):
        if selector_type not in ("numTopFeatures", "percentile", "fpr"):
            raise ValueError(f"selector_type={selector_type!r}")
        self.num_top_features = int(num_top_features)
        self.selector_type = selector_type
        self.percentile = float(percentile)
        self.fpr = float(fpr)
        self.features_col = features_col
        self.label_col = label_col
        self.output_col = output_col

    def set_num_top_features(self, v):
        self.num_top_features = int(v)
        return self

    setNumTopFeatures = set_num_top_features

    def set_selector_type(self, v):
        if v not in ("numTopFeatures", "percentile", "fpr"):
            raise ValueError(f"selector_type={v!r}")
        self.selector_type = v
        return self

    setSelectorType = set_selector_type

    def set_percentile(self, v):
        self.percentile = float(v)
        return self

    setPercentile = set_percentile

    def set_fpr(self, v):
        self.fpr = float(v)
        return self

    setFpr = set_fpr

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_label_col(self, v):
        self.label_col = v
        return self

    setLabelCol = set_label_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def fit(self, frame) -> "ChiSqSelectorModel":
        from .stat import ChiSquareTest

        res = ChiSquareTest.test(frame, self.features_col,
                                 self.label_col).to_pydict()
        p_values = np.asarray(res["pValues"][0], np.float64)
        d = len(p_values)
        order = np.argsort(p_values, kind="stable")
        if self.selector_type == "numTopFeatures":
            chosen = order[: self.num_top_features]
        elif self.selector_type == "percentile":
            chosen = order[: max(1, int(d * self.percentile))]
        else:   # fpr
            chosen = np.flatnonzero(p_values < self.fpr)
        return ChiSqSelectorModel(sorted(int(i) for i in chosen),
                                  self.features_col, self.output_col)


class _SelectorModelBase(Model):
    """Shared surface of the feature selectors: a list of selected indices
    + a gather transform (an empty selection yields an (n, 0) column, the
    MLlib behavior)."""

    _persist_attrs = ('selected_features', 'features_col', 'output_col')

    def __init__(self, selected_features, features_col="features",
                 output_col="selected"):
        self.selected_features = [int(i) for i in selected_features]
        self.features_col = features_col
        self.output_col = output_col

    selectedFeatures = property(lambda self: list(self.selected_features))

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.features_col),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        sel = jnp.asarray(np.asarray(self.selected_features, np.int32))
        return frame.with_column(self.output_col, X[:, sel])


@persistable
class ChiSqSelectorModel(_SelectorModelBase):
    pass


def _is_string_col(arr) -> bool:
    """The frame's canonical string-column test, tolerant of raw lists and
    numpy 'U'/'S' arrays that have not passed through Frame normalization."""
    from ..frame.frame import _is_string_col as _frame_is_string

    a = np.asarray(arr) if not isinstance(arr, np.ndarray) else arr
    if getattr(a, "dtype", None) is not None and a.dtype.kind in ("U", "S"):
        return True
    try:
        return _frame_is_string(a)
    except TypeError:
        return a.dtype == object


def _parse_r_formula(formula: str):
    """``label ~ term + term - term`` → (label, include_terms,
    exclude_terms); a term is a tuple of column names (len > 1 ⇒ ``:``
    interaction). ``.`` means "all other columns"."""
    if "~" not in formula:
        raise ValueError(f"RFormula: missing '~' in {formula!r}")
    lhs, rhs = formula.split("~", 1)
    label = lhs.strip()
    include, exclude = [], []
    # split on +/- at top level, tracking sign
    sign, token = 1, ""
    tokens = []
    for ch in rhs + "+":
        if ch in "+-":
            if token.strip():
                tokens.append((sign, token.strip()))
            sign = 1 if ch == "+" else -1
            token = ""
        else:
            token += ch
    for sg, tok in tokens:
        term = tuple(t.strip() for t in tok.split(":"))
        if any(not t for t in term):
            raise ValueError(f"RFormula: empty term in {formula!r}")
        (include if sg > 0 else exclude).append(term)
    return label, include, exclude


@persistable
class RFormula(Estimator):
    """MLlib ``RFormula``: R-style model formulas — ``label ~ col1 + col2``,
    ``.`` (all other columns), ``-`` (exclusion), ``:`` (interaction).
    Numeric terms pass through; string terms are StringIndexed
    (frequencyDesc) and dummy-coded with the last category dropped, exactly
    Spark's encoding. Produces ``features`` + ``label`` columns.
    (spark.ml.feature surface, `/root/reference/pom.xml:29-32`.)"""

    _persist_attrs = ('formula', 'features_col', 'label_col',
                      'force_index_label')

    def __init__(self, formula: Optional[str] = None,
                 features_col: str = "features", label_col: str = "label",
                 force_index_label: bool = False):
        self.formula = formula
        self.features_col = features_col
        self.label_col = label_col
        self.force_index_label = bool(force_index_label)

    def set_formula(self, v):
        self.formula = v
        return self

    setFormula = set_formula

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_label_col(self, v):
        self.label_col = v
        return self

    setLabelCol = set_label_col

    def set_force_index_label(self, v):
        self.force_index_label = bool(v)
        return self

    setForceIndexLabel = set_force_index_label

    def _encode_col(self, frame, col):
        """One column → encoder spec: ("num", col) or ("cat", col, labels)."""
        values = frame._column_values(col)
        if not _is_string_col(values):
            return ("num", col)
        model = StringIndexer(input_col=col, output_col="_idx").fit(frame)
        return ("cat", col, model.labels)

    def fit(self, frame) -> "RFormulaModel":
        if not self.formula:
            raise ValueError("RFormula: formula not set")
        label, include, exclude = _parse_r_formula(self.formula)
        excluded = {t[0] for t in exclude if len(t) == 1}
        terms = []
        for term in include:
            if term == (".",):
                for c in frame.columns:
                    if c != label and c not in excluded and \
                            (c,) not in terms:
                        terms.append((c,))
            elif term not in terms:
                terms.append(term)
        terms = [t for t in terms if t not in exclude]

        encoders = [[self._encode_col(frame, c) for c in t] for t in terms]
        label_labels = None
        if label:
            lv = frame._column_values(label)
            if _is_string_col(lv) or self.force_index_label:
                label_labels = StringIndexer(
                    input_col=label, output_col="_l").fit(frame).labels
        return RFormulaModel(encoders, label, label_labels,
                             self.features_col, self.label_col)


@persistable
class RFormulaModel(Model):
    _persist_attrs = ('_encoders_json', 'label_source', 'label_labels',
                      'features_col', 'label_col')

    def __init__(self, encoders=None, label_source="", label_labels=None,
                 features_col="features", label_col="label"):
        self.encoders = encoders or []
        self._encoders_json = [[list(e) for e in term]
                               for term in self.encoders]
        self.label_source = label_source
        self.label_labels = (None if label_labels is None
                             else list(label_labels))
        self.features_col = features_col
        self.label_col = label_col

    def _post_load(self):
        self.encoders = [[tuple(e) for e in term]
                         for term in self._encoders_json]

    def _encode_one(self, frame, enc):
        """Encoder spec → (n, k) float matrix."""
        kind = enc[0]
        if kind == "num":
            arr = np.asarray(frame._column_values(enc[1]), np.float64)
            return arr[:, None] if arr.ndim == 1 else arr
        _, col, labels = enc
        values = np.asarray(frame._column_values(col), object)
        lut = {l: i for i, l in enumerate(labels)}
        k = len(labels)
        idx = np.asarray([lut.get(str(v) if v is not None else None, k)
                          for v in values])
        mask = np.asarray(frame.mask)
        unseen = mask & (idx == k)
        if unseen.any():
            # an unseen category would otherwise dummy-code identically to
            # the dropped reference level; Spark's RFormula errors too
            bad = sorted({str(values[i])
                          for i in np.flatnonzero(unseen)})[:5]
            raise ValueError(f"RFormula: unseen categories {bad} in "
                             f"column {col!r}")
        onehot = np.zeros((len(values), max(k - 1, 1)), np.float64)
        known = idx < k - 1   # last category → all-zero row (dropLast)
        onehot[np.arange(len(values))[known], idx[known]] = 1.0
        if k == 1:            # single category: dropLast leaves zero width
            return onehot[:, :0]
        return onehot

    def transform(self, frame):
        mats = []
        for term in self.encoders:
            mat = None
            for enc in term:
                m = self._encode_one(frame, enc)
                if mat is None:
                    mat = m
                else:   # ':' interaction = per-row outer product, flattened
                    n = mat.shape[0]
                    mat = (mat[:, :, None] * m[:, None, :]).reshape(n, -1)
            if mat is not None and mat.shape[1] > 0:
                mats.append(mat)
        if not mats:
            raise ValueError("RFormula produced no feature columns")
        X = np.concatenate(mats, axis=1)
        out = frame.with_column(self.features_col,
                                jnp.asarray(X, float_dtype()))
        if self.label_source:
            lv = frame._column_values(self.label_source)
            if self.label_labels is not None:
                lut = {l: i for i, l in enumerate(self.label_labels)}
                y = np.asarray([float(lut.get(str(v), np.nan))
                                for v in np.asarray(lv, object)])
            else:
                y = np.asarray(lv, np.float64)
            out = out.with_column(self.label_col,
                                  jnp.asarray(y, float_dtype()))
        return out


@persistable
class ElementwiseProduct(Transformer):
    """MLlib ``ElementwiseProduct``: Hadamard product of each row with a
    fixed ``scaling_vec`` — one fused VPU multiply."""

    _persist_attrs = ('scaling_vec', 'input_col', 'output_col')

    def __init__(self, scaling_vec=None, input_col: str = "features",
                 output_col: str = "scaled_features"):
        self.scaling_vec = None if scaling_vec is None \
            else np.asarray(scaling_vec, np.float64)
        self.input_col = input_col
        self.output_col = output_col

    def set_scaling_vec(self, v):
        self.scaling_vec = np.asarray(v, np.float64)
        return self

    def set_input_col(self, v):
        self.input_col = v
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    setScalingVec = set_scaling_vec
    setInputCol = set_input_col
    setOutputCol = set_output_col

    def transform(self, frame):
        if self.scaling_vec is None:
            raise ValueError("ElementwiseProduct: scaling_vec not set")
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        v = jnp.asarray(self.scaling_vec, X.dtype)
        if v.shape[0] != X.shape[1]:
            raise ValueError(f"scaling_vec length {v.shape[0]} != "
                             f"vector size {X.shape[1]}")
        return frame.with_column(self.output_col, X * v[None, :])


@persistable
class VectorSlicer(Transformer):
    """MLlib ``VectorSlicer``: select a subset of vector indices — one
    device gather."""

    _persist_attrs = ('indices', 'input_col', 'output_col')

    def __init__(self, indices=(), input_col: str = "features",
                 output_col: str = "sliced_features"):
        self.indices = [int(i) for i in indices]
        self.input_col = input_col
        self.output_col = output_col

    def set_indices(self, v):
        self.indices = [int(i) for i in v]
        return self

    def set_input_col(self, v):
        self.input_col = v
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    setIndices = set_indices
    setInputCol = set_input_col
    setOutputCol = set_output_col

    def transform(self, frame):
        if not self.indices:
            raise ValueError("VectorSlicer: indices not set")
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        d = X.shape[1]
        if any(i < 0 or i >= d for i in self.indices):
            raise ValueError(f"indices out of range for vector size {d}")
        return frame.with_column(
            self.output_col, X[:, jnp.asarray(self.indices, jnp.int32)])


@persistable
class DCT(Transformer):
    """MLlib ``DCT``: orthonormal 1-D DCT-II (or its inverse, DCT-III) of
    each row. TPU-first: the transform is ONE ``(n,d)×(d,d)`` MXU matmul
    against a precomputed orthonormal cosine basis — the scaled output
    matches MLlib's jTransforms ``forward(..., true)`` convention."""

    _persist_attrs = ('inverse', 'input_col', 'output_col')

    def __init__(self, inverse: bool = False, input_col: str = "features",
                 output_col: str = "dct_features"):
        self.inverse = bool(inverse)
        self.input_col = input_col
        self.output_col = output_col

    def set_inverse(self, v):
        self.inverse = bool(v)
        return self

    def set_input_col(self, v):
        self.input_col = v
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    setInverse = set_inverse
    setInputCol = set_input_col
    setOutputCol = set_output_col

    @staticmethod
    @functools.lru_cache(maxsize=None)
    def _basis(d: int, dtype_name: str):
        """Orthonormal DCT-II matrix B (d, d): y = B @ x."""
        k = np.arange(d)[:, None]
        i = np.arange(d)[None, :]
        B = np.cos(np.pi * k * (2 * i + 1) / (2 * d))
        B *= np.sqrt(2.0 / d)
        B[0] *= 1.0 / np.sqrt(2.0)
        return jnp.asarray(B, dtype_name)

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        B = self._basis(X.shape[1], str(X.dtype))
        out = X @ (B if self.inverse else B.T)  # inverse: Bᵀ orthonormality
        return frame.with_column(self.output_col,
                                 out[:, 0] if squeeze else out)


@persistable
class FeatureHasher(Transformer):
    """MLlib ``FeatureHasher``: hash any mix of numeric and string columns
    into one fixed-dimension vector. Numeric column → bucket(hash(name)),
    value added; string column → bucket(hash(name=value)), +1. Hashing is
    per unique (column, value) pair on host; the scatter is one
    ``np.add.at`` (same vectorized shape as HashingTF)."""

    _persist_attrs = ('num_features', 'input_cols', 'output_col')

    def __init__(self, num_features: int = 1024, input_cols=(),
                 output_col: str = "features"):
        if num_features < 1:
            raise ValueError("num_features must be >= 1")
        self.num_features = int(num_features)
        self.input_cols = list(input_cols)
        self.output_col = output_col

    def set_num_features(self, v):
        if v < 1:
            raise ValueError("num_features must be >= 1")
        self.num_features = int(v)
        return self

    def set_input_cols(self, v):
        self.input_cols = list(v)
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    setNumFeatures = set_num_features
    setInputCols = set_input_cols
    setOutputCol = set_output_col

    def transform(self, frame):
        from .text import _stable_hash

        if not self.input_cols:
            raise ValueError("FeatureHasher: input_cols not set")
        first = frame._column_values(self.input_cols[0])
        n = int(np.asarray(first).shape[0])
        M = np.zeros((n, self.num_features), np.dtype(float_dtype()))
        rows = np.arange(n)
        for name in self.input_cols:
            arr = frame._column_values(name)
            if _is_string_col(arr):
                vals = np.asarray(
                    ["" if v is None else str(v) for v in arr])
                uniq, inv = np.unique(vals, return_inverse=True)
                buckets = np.fromiter(
                    (_stable_hash(f"{name}={u}", self.num_features)
                     for u in uniq), np.int64, count=uniq.size)
                present = np.asarray([v is not None for v in arr])
                np.add.at(M, (rows[present], buckets[inv][present]), 1.0)
            else:
                j = _stable_hash(name, self.num_features)
                col = np.asarray(arr, np.float64)
                M[:, j] += np.where(np.isfinite(col), col, 0.0)
        return frame.with_column(self.output_col, jnp.asarray(M))


@persistable
class RobustScaler(_ScalerBase):
    """MLlib ``RobustScaler``: center by median, scale by IQR (quantile
    range). Quantiles are a host pass over valid rows (data-dependent
    order statistics — same boundary as QuantileDiscretizer); the
    transform is one fused device subtract/divide."""

    _persist_attrs = ('with_centering', 'with_scaling', 'lower', 'upper',
                      'input_col', 'output_col')

    def __init__(self, with_centering: bool = False,
                 with_scaling: bool = True, lower: float = 0.25,
                 upper: float = 0.75, input_col: str = "features",
                 output_col: str = "scaled_features"):
        super().__init__(input_col, output_col)
        self.with_centering = bool(with_centering)
        self.with_scaling = bool(with_scaling)
        self.lower = float(lower)
        self.upper = float(upper)
        self._check_bounds()

    def set_with_centering(self, v):
        self.with_centering = bool(v)
        return self

    def set_with_scaling(self, v):
        self.with_scaling = bool(v)
        return self

    def set_lower(self, v):
        self.lower = float(v)
        self._check_bounds()
        return self

    def set_upper(self, v):
        self.upper = float(v)
        self._check_bounds()
        return self

    def _check_bounds(self):
        if not 0.0 <= self.lower < self.upper <= 1.0:
            raise ValueError("need 0 <= lower < upper <= 1")

    setWithCentering = set_with_centering
    setWithScaling = set_with_scaling
    setLower = set_lower
    setUpper = set_upper

    def fit(self, frame) -> "RobustScalerModel":
        self._check_bounds()
        X = np.asarray(frame._column_values(self.input_col),
                       np.dtype(float_dtype()))
        if X.ndim == 1:
            X = X[:, None]
        mask = np.asarray(frame.mask)
        if mask.sum() == 0:
            raise ValueError("RobustScaler: no valid rows")
        Xv = X[mask]
        d = Xv.shape[1]
        # NaN values are ignored in the statistics (MLlib convention); each
        # pass is skipped entirely when its statistic is unused
        with np.errstate(all="ignore"):
            med = np.nanmedian(Xv, axis=0) if self.with_centering \
                else np.zeros(d)
            if self.with_scaling:
                rng = (np.nanquantile(Xv, self.upper, axis=0)
                       - np.nanquantile(Xv, self.lower, axis=0))
                # MLlib: zero-range (constant) features map to 0.0
                scale = np.where(np.nan_to_num(rng) > 0, 1.0 / rng, 0.0)
            else:
                scale = np.ones(d)
        med = np.nan_to_num(med)     # all-NaN column: center 0, scale 0
        return RobustScalerModel(med, scale, self.input_col,
                                 self.output_col)


@persistable
class RobustScalerModel(Model):
    """``scale`` is the multiplicative factor (0 for zero-range features —
    the MLlib convention StandardScalerModel also follows)."""

    _persist_attrs = ('median', 'scale', 'input_col', 'output_col')

    def __init__(self, median, scale, input_col="features",
                 output_col="scaled_features"):
        self.median = np.asarray(median, np.float64)
        self.scale = np.asarray(scale, np.float64)
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, frame):
        X = jnp.asarray(frame._column_values(self.input_col), float_dtype())
        squeeze = X.ndim == 1
        if squeeze:
            X = X[:, None]
        out = (X - jnp.asarray(self.median, X.dtype)) \
            * jnp.asarray(self.scale, X.dtype)
        return frame.with_column(self.output_col,
                                 out[:, 0] if squeeze else out)


@persistable
class VarianceThresholdSelector(Estimator):
    """MLlib ``VarianceThresholdSelector``: keep features whose (sample)
    variance exceeds ``variance_threshold`` — ONE masked moment pass on
    device (the Summarizer statistic), selection is a gather."""

    _persist_attrs = ('variance_threshold', 'features_col', 'output_col')

    def __init__(self, variance_threshold: float = 0.0,
                 features_col: str = "features",
                 output_col: str = "selected_features"):
        self.variance_threshold = float(variance_threshold)
        self.features_col = features_col
        self.output_col = output_col

    def set_variance_threshold(self, v):
        self.variance_threshold = float(v)
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    setVarianceThreshold = set_variance_threshold
    setFeaturesCol = set_features_col
    setOutputCol = set_output_col

    def fit(self, frame) -> "VarianceThresholdSelectorModel":
        from .stat import _extract, _moment_pass

        if not np.asarray(frame.mask).any():
            raise ValueError("VarianceThresholdSelector: no valid rows")
        X, w = _extract(frame, self.features_col)
        n, _, C, *_ = _moment_pass(X, w)
        var = np.diag(np.asarray(C)) / max(float(n) - 1.0, 1.0)
        keep = np.nonzero(var > self.variance_threshold)[0]
        # empty selection is a valid model (MLlib; ChiSqSelector's fpr
        # path behaves the same) — transform yields an (n, 0) column
        return VarianceThresholdSelectorModel(
            keep.astype(np.int64).tolist(), self.features_col,
            self.output_col)


@persistable
class VarianceThresholdSelectorModel(_SelectorModelBase):
    def __init__(self, selected_features, features_col="features",
                 output_col="selected_features"):
        super().__init__(selected_features, features_col, output_col)


@persistable
class UnivariateFeatureSelector(Estimator):
    """MLlib ``UnivariateFeatureSelector``: score every feature against the
    label with the test implied by (featureType, labelType) — χ² for
    categorical/categorical, ANOVA F for continuous features vs categorical
    label, F-regression for continuous/continuous — then select by mode
    (numTopFeatures | percentile | fpr | fdr | fwe).

    TPU-first: all three statistics come from one-hot / moment matmuls over
    masked rows (the ChiSquareTest & Summarizer passes); only the final
    p-value tail probabilities use scipy on the tiny (d,) statistics.
    """

    _persist_attrs = ('feature_type', 'label_type', 'selection_mode',
                      'selection_threshold', 'features_col', 'label_col',
                      'output_col')

    _MODES = ("numTopFeatures", "percentile", "fpr", "fdr", "fwe")

    def __init__(self, feature_type: str = "continuous",
                 label_type: str = "categorical",
                 selection_mode: str = "numTopFeatures",
                 selection_threshold: Optional[float] = None,
                 features_col: str = "features", label_col: str = "label",
                 output_col: str = "selected_features"):
        if feature_type not in ("categorical", "continuous"):
            raise ValueError(f"feature_type={feature_type!r}")
        if label_type not in ("categorical", "continuous"):
            raise ValueError(f"label_type={label_type!r}")
        if selection_mode not in self._MODES:
            raise ValueError(f"selection_mode={selection_mode!r}; "
                             f"expected one of {self._MODES}")
        self.feature_type = feature_type
        self.label_type = label_type
        self.selection_mode = selection_mode
        self.selection_threshold = selection_threshold
        self.features_col = features_col
        self.label_col = label_col
        self.output_col = output_col

    def set_feature_type(self, v):
        if v not in ("categorical", "continuous"):
            raise ValueError(f"feature_type={v!r}")
        self.feature_type = v
        return self

    def set_label_type(self, v):
        if v not in ("categorical", "continuous"):
            raise ValueError(f"label_type={v!r}")
        self.label_type = v
        return self

    def set_selection_mode(self, v):
        if v not in self._MODES:
            raise ValueError(f"selection_mode={v!r}")
        self.selection_mode = v
        return self

    def set_selection_threshold(self, v):
        self.selection_threshold = float(v)
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    setFeatureType = set_feature_type
    setLabelType = set_label_type
    setSelectionMode = set_selection_mode
    setSelectionThreshold = set_selection_threshold
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setOutputCol = set_output_col

    def _p_values(self, X, y):
        """(d,) p-values for the CONTINUOUS-feature tests (the chi2 path
        reuses ChiSquareTest in :meth:`fit` — device matmuls + its input
        validation, no duplicate table logic)."""
        from scipy import stats as sstats

        n, d = X.shape
        if self.label_type == "categorical":   # ANOVA F (f_classif)
            classes = np.unique(y)
            grand = X.mean(axis=0)
            ss_between = np.zeros(d)
            ss_within = np.zeros(d)
            for c in classes:
                Xi = X[y == c]
                ss_between += len(Xi) * (Xi.mean(axis=0) - grand) ** 2
                ss_within += ((Xi - Xi.mean(axis=0)) ** 2).sum(axis=0)
            df_b = len(classes) - 1
            df_w = n - len(classes)
            with np.errstate(divide="ignore", invalid="ignore"):
                F = (ss_between / df_b) / (ss_within / df_w)
            F = np.nan_to_num(F)
            return sstats.f.sf(F, df_b, df_w)
        # continuous/continuous: F-regression on the Pearson correlation
        Xc = X - X.mean(axis=0)
        yc = y - y.mean()
        denom = np.sqrt((Xc ** 2).sum(axis=0) * (yc ** 2).sum())
        with np.errstate(divide="ignore", invalid="ignore"):
            r = np.where(denom > 0, Xc.T @ yc / denom, 0.0)
            F = r * r / np.maximum(1.0 - r * r, 1e-300) * (n - 2)
        return sstats.f.sf(F, 1, n - 2)

    def fit(self, frame) -> "UnivariateFeatureSelectorModel":
        X = np.asarray(frame._column_values(self.features_col),
                       np.dtype(float_dtype()))
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(frame._column_values(self.label_col), np.float64)
        mask = np.asarray(frame.mask)
        if not mask.any():
            raise ValueError("UnivariateFeatureSelector: no valid rows")
        Xv, yv = X[mask].astype(np.float64), y[mask]
        d = Xv.shape[1]
        if self.feature_type == "categorical":
            if self.label_type != "categorical":
                raise ValueError("categorical features require a "
                                 "categorical label (chi2)")
            from .stat import ChiSquareTest

            res = ChiSquareTest.test(frame, self.features_col,
                                     self.label_col).to_pydict()
            pvals = np.asarray(res["pValues"][0], np.float64)
        else:
            pvals = self._p_values(Xv, yv)

        mode = self.selection_mode
        # Spark's defaults per mode
        thr = self.selection_threshold
        if thr is None:
            thr = {"numTopFeatures": 50, "percentile": 0.1,
                   "fpr": 0.05, "fdr": 0.05, "fwe": 0.05}[mode]
        order = np.argsort(pvals, kind="stable")
        if mode == "numTopFeatures":
            keep = np.sort(order[: int(thr)])
        elif mode == "percentile":
            # Spark floors (and keeps at least one), like ChiSqSelector
            keep = np.sort(order[: max(1, int(thr * d))])
        elif mode == "fpr":
            keep = np.nonzero(pvals < thr)[0]
        elif mode == "fwe":
            keep = np.nonzero(pvals < thr / d)[0]
        else:  # fdr: Benjamini–Hochberg
            ranked = pvals[order]
            below = ranked <= thr * (np.arange(1, d + 1) / d)
            k = int(np.nonzero(below)[0].max()) + 1 if below.any() else 0
            keep = np.sort(order[:k])
        return UnivariateFeatureSelectorModel(
            keep.astype(np.int64).tolist(), self.features_col,
            self.output_col)


@persistable
class UnivariateFeatureSelectorModel(_SelectorModelBase):
    def __init__(self, selected_features, features_col="features",
                 output_col="selected_features"):
        super().__init__(selected_features, features_col, output_col)
