"""VectorAssembler — packs input columns into one ``(n, d)`` feature matrix
column (`DataQuality4MachineLearningApp.java:110-113`).

TPU-first: the "vector column" is literally the feature matrix in HBM, laid
out densely so the fit's Gramian is a single MXU matmul — there is no per-row
vector object.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp

from ..config import float_dtype
from .base import Transformer


class VectorAssembler(Transformer):
    def __init__(self, input_cols: Optional[Sequence[str]] = None,
                 output_col: str = "features"):
        self.input_cols = list(input_cols) if input_cols else []
        self.output_col = output_col

    def set_input_cols(self, cols: Sequence[str]) -> "VectorAssembler":
        self.input_cols = list(cols)
        return self

    setInputCols = set_input_cols

    def set_output_col(self, name: str) -> "VectorAssembler":
        self.output_col = name
        return self

    setOutputCol = set_output_col

    def get_input_cols(self):
        return list(self.input_cols)

    getInputCols = get_input_cols

    def get_output_col(self):
        return self.output_col

    getOutputCol = get_output_col

    def transform(self, frame):
        if not self.input_cols:
            raise ValueError("VectorAssembler: input_cols not set")
        dt = float_dtype()
        parts = []
        for name in self.input_cols:
            arr = jnp.asarray(frame._column_values(name), dt)
            parts.append(arr[:, None] if arr.ndim == 1 else arr)
        return frame.with_column(self.output_col, jnp.concatenate(parts, axis=1))
