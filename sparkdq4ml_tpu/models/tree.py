"""Tree ensembles: DecisionTree / RandomForest / GBT (classifier+regressor)
— the MLlib ``org.apache.spark.ml`` tree family (shipped by the reference's
mllib dependency, pom.xml:29-32; the reference app itself fits only
LinearRegression, `DataQuality4MachineLearningApp.java:120-126`).

TPU-first design — this is NOT a port of MLlib's per-partition
``findBestSplits`` RPC machinery:

* **Histogram trees, level-wise.** Features are quantile-binned once
  (``max_bins``, like MLlib). A tree grows breadth-first; at each level the
  per-(node, feature, bin) sufficient statistics are ONE ``segment_sum``
  per feature (vmapped over features → a single fused XLA kernel), the
  TPU analogue of MLlib's per-level ``aggregateByKey``. Split scoring is a
  cumulative-sum scan over bins — no per-row Python anywhere.
* **Static shapes.** The tree is a dense heap array of 2^(depth+1)−1 node
  slots (feature, threshold, leaf value, is-leaf); every level's node count
  is static, so the whole build jits. Prediction is ``max_depth`` vectorized
  descent steps over the heap — one gather per level, batched over rows.
* **A forest is a vmap.** RandomForest vmaps the identical build over
  per-tree Poisson(1) bootstrap weights and per-node random feature masks —
  T trees build in one XLA program, instead of MLlib's
  groups-of-trees-per-pass scheduling. GBT reuses the same builder
  sequentially on Newton gradients (squared loss / logistic).
* **Masked rows never vote**: the row weight folds the frame's validity
  mask, the same rule as every other estimator here.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from ..frame import Frame
from .base import Estimator, Model, host_fetch, persistable
from ..parallel.mesh import serialize_collectives

_NEG = -1e30


# ---------------------------------------------------------------------------
# binning (host, one-time — the MLlib findSplits analogue)
# ---------------------------------------------------------------------------

def bin_features(X: np.ndarray, mask: np.ndarray, max_bins: int):
    """Quantile bin edges per feature + binned matrix.

    Returns (edges (d, max_bins-1) float64 — ascending, +inf padded on the
    right; binned (n, d) int32 in [0, max_bins)). Bin b holds values in
    (edges[b-1], edges[b]]; a split "at bin b" sends bins ≤ b left with
    threshold edges[b].
    """
    n, d = X.shape
    edges = np.full((d, max_bins - 1), np.inf, np.float64)
    valid = X[mask] if mask is not None else X
    for j in range(d):
        col = valid[:, j]
        col = col[~np.isnan(col)]
        if len(col) == 0:
            continue
        qs = np.quantile(col, np.linspace(0, 1, max_bins + 1)[1:-1])
        uniq = np.unique(qs)
        edges[j, :len(uniq)] = uniq
    binned = np.empty((n, d), np.int32)
    for j in range(d):
        binned[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    return edges, binned


# ---------------------------------------------------------------------------
# jitted level builder
# ---------------------------------------------------------------------------

def _level_histogram(binned, node_pos, targets, n_nodes, B, psum_axis=None):
    """(d, n_nodes, B, s) sufficient statistics for one level.

    ``binned`` (n, d) int32; ``node_pos`` (n,) int32 position of the row's
    node within the level (n_nodes slot = parked/leaf rows — excluded);
    ``targets`` (n, s) already mask/bootstrap-weighted stat rows.

    ``psum_axis``: mesh axis name when rows are sharded — the local
    segment_sum histograms reduce with ONE ``lax.psum`` over ICI, the exact
    analogue of MLlib's per-level ``aggregateByKey`` shuffle
    (`findBestSplits`, implied by the reference's mllib dep pom.xml:29-32).
    """
    s = targets.shape[1]
    idx = node_pos[:, None] * B + binned                     # (n, d)
    oob = node_pos >= n_nodes

    def per_feature(idx_f):
        safe = jnp.where(oob, 0, idx_f)
        t = jnp.where(oob[:, None], 0.0, targets)
        return jax.ops.segment_sum(t, safe, num_segments=n_nodes * B)

    hist = jax.vmap(per_feature, in_axes=1)(idx)             # (d, nodes*B, s)
    if psum_axis is not None:
        hist = jax.lax.psum(hist, psum_axis)
    return hist.reshape((-1, n_nodes, B, s))


def _impurity_sse(agg):
    """Variance-scaled impurity (SSE) from [w, wy, wy²] stats."""
    w = jnp.maximum(agg[..., 0], 1e-12)
    return agg[..., 2] - agg[..., 1] ** 2 / w


def _impurity_gini(agg):
    """Weighted gini from per-class counts: w·(1 − Σp²) = w − Σc²/w."""
    w = jnp.maximum(jnp.sum(agg, axis=-1), 1e-12)
    return w - jnp.sum(agg * agg, axis=-1) / w


def _impurity_entropy(agg):
    w = jnp.maximum(jnp.sum(agg, axis=-1), 1e-12)
    p = agg / w[..., None]
    return -w * jnp.sum(jnp.where(p > 0, p * jnp.log2(jnp.maximum(p, 1e-12)),
                                  0.0), axis=-1)


_IMPURITY = {"variance": _impurity_sse, "gini": _impurity_gini,
             "entropy": _impurity_entropy}


def _find_splits(hist, edges, impurity, min_instances, min_info_gain,
                 feat_mask=None):
    """Best (feature, threshold, gain) per node from level histograms.

    hist (d, m, B, s); edges (d, B-1). Candidate split b sends bins ≤ b
    left (threshold edges[:, b]). Returns per-node best feature (int32),
    threshold, gain (−inf when no valid split), plus left/right stat sums.
    """
    imp_fn = _IMPURITY[impurity]
    left = jnp.cumsum(hist, axis=2)[:, :, :-1, :]            # (d, m, B-1, s)
    total = jnp.sum(hist, axis=2)                            # (d, m, s)
    right = total[:, :, None, :] - left
    gain = imp_fn(total)[:, :, None] - imp_fn(left) - imp_fn(right)

    def weight(a):
        return a[..., 0] if impurity == "variance" else jnp.sum(a, axis=-1)

    ok = jnp.logical_and(weight(left) >= min_instances,
                         weight(right) >= min_instances)
    # +inf-padded edges mark bins beyond the feature's true quantiles
    real = jnp.isfinite(edges)[:, None, :]                   # (d, 1, B-1)
    ok = jnp.logical_and(ok, real)
    gain = jnp.where(ok, gain, _NEG)
    if feat_mask is not None:                                # (m, d) per node
        gain = jnp.where(feat_mask.T[:, :, None], gain, _NEG)

    d, m, bm1 = gain.shape
    flat = gain.transpose(1, 0, 2).reshape(m, d * bm1)       # (m, d*(B-1))
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    best_feat = (best // bm1).astype(jnp.int32)
    best_bin = (best % bm1).astype(jnp.int32)
    thr = edges[best_feat, best_bin]
    split = best_gain > jnp.maximum(min_info_gain, 1e-12)
    return best_feat, best_bin, thr, split, best_gain


class TreeArrays(NamedTuple):
    """Dense heap tree: node i's children are 2i+1 / 2i+2."""
    feature: jnp.ndarray       # (N,) int32
    threshold: jnp.ndarray     # (N,)
    is_leaf: jnp.ndarray       # (N,) bool
    value: jnp.ndarray         # (N, v) leaf payload (mean or class counts)
    gain: jnp.ndarray          # (N,) split gain (0 for leaves)


def build_tree(binned, edges, targets, max_depth, max_bins, impurity,
               min_instances, min_info_gain, feat_masks=None,
               psum_axis=None):
    """Level-wise histogram tree build (jit-compatible; vmappable over a
    leading bootstrap axis via ``targets``/``feat_masks``).

    ``targets`` (n, s): weighted stat rows ([w, wy, wy²] or class one-hots).
    ``feat_masks`` optional (levels, max_nodes_at_level..) — supplied as a
    (2^max_depth - 1 + ..., d) per-heap-node mask, indexed by heap id.

    ``psum_axis``: set inside ``shard_map`` when rows are sharded over a
    mesh axis. Each device histograms its row shard and the level stats
    psum over ICI; the (replicated) split decisions are then identical on
    every device, so each device descends only its own rows and the final
    tree arrays come out replicated — zero host syncs per level.
    """
    n, d = binned.shape
    N = 2 ** (max_depth + 1) - 1
    s = targets.shape[1]
    dt = targets.dtype

    feature = jnp.zeros((N,), jnp.int32)
    threshold = jnp.zeros((N,), dt)
    is_leaf = jnp.ones((N,), bool)
    value = jnp.zeros((N, s), dt)
    gains = jnp.zeros((N,), dt)

    heap = jnp.zeros((n,), jnp.int32)          # heap node id per row
    alive = jnp.ones((n,), bool)               # row's node may still split

    for depth in range(max_depth + 1):
        m = 2 ** depth
        base = m - 1                            # first heap id of this level
        node_pos = jnp.where(alive, heap - base, m)  # m = parked sentinel
        hist = _level_histogram(binned, node_pos, targets, m, max_bins,
                                psum_axis)
        # every feature's bins partition the same rows; feature 0's
        # histogram summed over bins is the exact node total
        total = jnp.sum(hist[0], axis=1)                     # (m, s)
        value = jax.lax.dynamic_update_slice(value, total.astype(dt),
                                             (base, 0))
        if depth == max_depth:
            break
        fm = None
        if feat_masks is not None:
            fm = jax.lax.dynamic_slice(feat_masks, (base, 0), (m, d))
        feat, split_bin, thr, split, gain = _find_splits(
            hist, edges, impurity, min_instances, min_info_gain, fm)
        feature = jax.lax.dynamic_update_slice(feature,
                                               feat.astype(jnp.int32),
                                               (base,))
        threshold = jax.lax.dynamic_update_slice(threshold, thr.astype(dt),
                                                 (base,))
        is_leaf = jax.lax.dynamic_update_slice(is_leaf,
                                               jnp.logical_not(split),
                                               (base,))
        gains = jax.lax.dynamic_update_slice(
            gains, jnp.where(split, gain, 0.0).astype(dt), (base,))

        # descend: rows in split nodes go to a child (bins ≤ split_bin left
        # — identical to raw value ≤ threshold); rows in leaves park forever
        pos = jnp.clip(node_pos, 0, m - 1)
        row_split = jnp.logical_and(split[pos], alive)
        row_bin = jnp.take_along_axis(binned, feat[pos][:, None],
                                      axis=1)[:, 0]
        go_left = row_bin <= split_bin[pos]
        child = jnp.where(go_left, 2 * heap + 1, 2 * heap + 2)
        heap = jnp.where(row_split, child, heap)
        alive = row_split

    return TreeArrays(feature, threshold, is_leaf, value, gains)


def predict_heap(X, feature, threshold, is_leaf, max_depth):
    """Vectorized heap descent: (n,) leaf heap ids for raw feature rows."""
    node = jnp.zeros((X.shape[0],), jnp.int32)
    for _ in range(max_depth):
        feat = feature[node]
        thr = threshold[node]
        leaf = is_leaf[node]
        xv = jnp.take_along_axis(X, feat[:, None], axis=1)[:, 0]
        child = jnp.where(xv <= thr, 2 * node + 1, 2 * node + 2)
        node = jnp.where(leaf, node, child)
    return node


def feature_importances(trees: TreeArrays, d: int) -> np.ndarray:
    """Gain-summed importances over all trees/nodes, normalized (MLlib)."""
    feat = np.asarray(trees.feature).reshape(-1)
    gain = np.asarray(trees.gain, np.float64).reshape(-1)
    imp = np.zeros((d,), np.float64)
    np.add.at(imp, feat, np.maximum(gain, 0.0))
    total = imp.sum()
    return imp / total if total > 0 else imp


# ---------------------------------------------------------------------------
# estimator/model surface
# ---------------------------------------------------------------------------

class _TreeParams:
    """Shared builder surface for the MLlib tree params."""

    def set_max_depth(self, v):
        self.max_depth = int(v)
        return self

    setMaxDepth = set_max_depth

    def set_max_bins(self, v):
        self.max_bins = int(v)
        return self

    setMaxBins = set_max_bins

    def set_min_instances_per_node(self, v):
        self.min_instances_per_node = int(v)
        return self

    setMinInstancesPerNode = set_min_instances_per_node

    def set_min_info_gain(self, v):
        self.min_info_gain = float(v)
        return self

    setMinInfoGain = set_min_info_gain

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_label_col(self, v):
        self.label_col = v
        return self

    setLabelCol = set_label_col

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setPredictionCol = set_prediction_col

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def _extract(self, frame):
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(frame._column_values(self.label_col), np.float64)
        mask = np.asarray(frame.mask)
        if mask.sum() == 0:
            raise ValueError(f"{type(self).__name__}: no valid rows")
        if not np.all(np.isfinite(y[mask])):
            raise ValueError(f"{type(self).__name__}: label column has "
                             "NaN/inf in valid rows")
        if not np.all(np.isfinite(X[mask])):
            raise ValueError(f"{type(self).__name__}: feature matrix has "
                             "NaN/inf in valid rows")
        # masked slots may hold NaN (dropna/filter keep values in place);
        # zero them so 0-weighted stats stay finite (0 * NaN = NaN otherwise)
        y = np.where(mask, y, 0.0)
        return X, y, mask


def _n_subset_features(strategy, d, is_classification, n_trees=1):
    """Spark's featureSubsetStrategy table: 'auto' = all for a single tree,
    sqrt(d) for classification forests, d/3 for regression forests; also
    accepts 'n' (an integer count) and '0.x' (a fraction)."""
    if strategy == "all":
        return d
    if strategy == "auto":
        if n_trees <= 1:
            return d
        return max(1, int(np.sqrt(d))) if is_classification \
            else max(1, d // 3)
    if strategy == "sqrt":
        return max(1, int(np.sqrt(d)))
    if strategy == "onethird":
        return max(1, d // 3)
    if strategy == "log2":
        return max(1, int(np.log2(d)))
    try:
        if isinstance(strategy, str) and strategy.isdigit():
            return min(d, max(1, int(strategy)))  # Spark's 'n' count form
        frac = float(strategy)
        if not 0.0 < frac <= 1.0:
            raise ValueError
        return max(1, int(round(frac * d)))
    except (TypeError, ValueError):
        raise ValueError(f"unknown featureSubsetStrategy {strategy!r}") \
            from None


def _fit_forest(binned, edges, y, w, *, n_trees, max_depth, max_bins,
                impurity, min_instances, min_info_gain, n_classes,
                subsample, n_feat, seed, mesh=None):
    """Build n_trees trees in one vmapped XLA program.

    Regression (n_classes=0): targets [w, wy, wy²]; leaf value = wy/w.
    Classification: targets = per-class weighted one-hots.

    Under a ``mesh``, rows shard over the data axis and each level's
    histogram psums over ICI (see :func:`build_tree`); zero-padded rows
    carry zero target weight so they never vote.
    """
    n, d = binned.shape
    dt = np.dtype(float_dtype())
    rng = np.random.default_rng(seed)
    N = 2 ** (max_depth + 1) - 1

    if n_trees == 1:
        boot = w[None, :]
    else:  # Poisson(subsample) bootstrap, Spark's sampling model
        boot = (rng.poisson(subsample, size=(n_trees, n)) * w[None, :]) \
            .astype(np.float64)

    if n_classes:
        # y was sanitized by _extract (masked slots → 0), so the int cast
        # is always within [0, k)
        onehot = np.eye(n_classes)[np.clip(y.astype(int), 0, n_classes - 1)]
        targets = boot[:, :, None] * onehot[None, :, :]
    else:
        stats = np.stack([np.ones_like(y), y, y * y], axis=1)  # (n, 3)
        targets = boot[:, :, None] * stats[None, :, :]
    targets = targets.astype(dt)

    feat_masks = None
    if n_feat < d:
        scores = rng.random(size=(n_trees, N, d))
        kth = np.partition(scores, n_feat - 1, axis=2)[:, :, n_feat - 1]
        feat_masks = scores <= kth[:, :, None]

    if mesh is not None and mesh.devices.size <= 1:
        mesh = None
    fn = _forest_builder(max_depth, max_bins, impurity, min_instances,
                         min_info_gain, feat_masks is not None, mesh)
    if mesh is None:
        args = (jnp.asarray(binned), jnp.asarray(edges, dt),
                jnp.asarray(targets))
        if feat_masks is not None:
            args += (jnp.asarray(feat_masks),)
        return jax.block_until_ready(fn(*args))

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    nsh = mesh.devices.size
    rem = (-n) % nsh
    if rem:  # zero-weight pad rows (bin 0, target 0) never vote
        binned = np.concatenate([binned, np.zeros((rem, d), np.int32)])
        targets = np.concatenate(
            [targets, np.zeros((n_trees, rem, targets.shape[2]), dt)],
            axis=1)
    args = (jax.device_put(binned, NamedSharding(mesh, P(DATA_AXIS, None))),
            jax.device_put(np.asarray(edges, dt), NamedSharding(mesh, P())),
            jax.device_put(targets,
                           NamedSharding(mesh, P(None, DATA_AXIS, None))))
    if feat_masks is not None:
        args += (jax.device_put(feat_masks, NamedSharding(mesh, P())),)
    return jax.block_until_ready(fn(*args))


@functools.lru_cache(maxsize=None)
def _forest_builder(max_depth, max_bins, impurity, min_instances,
                    min_info_gain, with_masks, mesh=None):
    """Jitted vmapped tree builder, cached per (hyperparameters, mesh) so
    repeated fits (cross-validation grids, boosting rounds) reuse the
    compiled XLA program instead of re-tracing (cf glm._fit_cached).

    With a mesh: ``shard_map`` over the data axis — per-shard descent,
    psum'd level histograms, replicated tree outputs."""

    def one_tree(binned, edges, t, fm, axis=None):
        return build_tree(binned, edges, t, max_depth, max_bins, impurity,
                          min_instances, min_info_gain, fm, psum_axis=axis)

    if mesh is None:
        if with_masks:
            return jax.jit(jax.vmap(one_tree, in_axes=(None, None, 0, 0)))
        return jax.jit(jax.vmap(lambda b, e, t: one_tree(b, e, t, None),
                                in_axes=(None, None, 0)))

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    if with_masks:
        def local(b, e, t, fm):
            return jax.vmap(
                lambda tt, ff: one_tree(b, e, tt, ff, DATA_AXIS),
                in_axes=(0, 0))(t, fm)

        fn = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS, None), P(), P(None, DATA_AXIS, None),
                      P()),
            out_specs=P())
    else:
        def local(b, e, t):
            return jax.vmap(
                lambda tt: one_tree(b, e, tt, None, DATA_AXIS))(t)

        fn = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS, None), P(), P(None, DATA_AXIS, None)),
            out_specs=P())
    return serialize_collectives(jax.jit(fn), mesh)


class _TreeModelBase(Model):
    """Shared prediction over a stacked (T, N) heap forest."""

    def _leaf_values(self, X):
        """(T, n, s) leaf payloads for every tree."""
        Xd = jnp.asarray(X, float_dtype())
        if Xd.ndim == 1:
            Xd = Xd[:, None]

        def per_tree(feature, threshold, is_leaf, value):
            node = predict_heap(Xd, feature, threshold, is_leaf,
                                self.max_depth)
            return value[node]

        return jax.vmap(per_tree)(jnp.asarray(self.feature),
                                  jnp.asarray(self.threshold),
                                  jnp.asarray(self.is_leaf),
                                  jnp.asarray(self.value))

    @property
    def feature_importances(self):
        trees = TreeArrays(jnp.asarray(self.feature),
                           jnp.asarray(self.threshold),
                           jnp.asarray(self.is_leaf),
                           jnp.asarray(self.value),
                           jnp.asarray(self.gain))
        return feature_importances(trees, self.num_features)

    featureImportances = feature_importances

    @property
    def num_features(self):
        return int(self._num_features)

    numFeatures = num_features

    def _frame_X(self, frame):
        X = np.asarray(frame._column_values(
            self._params.get("features_col", "features")),
            np.dtype(float_dtype()))
        return X[:, None] if X.ndim == 1 else X


@persistable
class DecisionTreeRegressor(Estimator, _TreeParams):
    """MLlib ``DecisionTreeRegressor`` (variance impurity)."""

    _persist_attrs = ('max_depth', 'max_bins', 'min_instances_per_node',
                      'min_info_gain', 'features_col', 'label_col',
                      'prediction_col', 'seed')

    def __init__(self, max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction", seed: int = 0):
        self.max_depth = int(max_depth)
        self.max_bins = int(max_bins)
        self.min_instances_per_node = int(min_instances_per_node)
        self.min_info_gain = float(min_info_gain)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.seed = int(seed)

    _n_trees = 1
    _subsample = 1.0
    _feature_subset = "all"

    def fit(self, frame: Frame, mesh=None) -> "DecisionTreeRegressionModel":
        X, y, mask = self._extract(frame)
        edges, binned = bin_features(X, mask, self.max_bins)
        w = mask.astype(np.float64)
        trees = _fit_forest(
            binned, edges, y, w, n_trees=self._n_trees,
            max_depth=self.max_depth, max_bins=self.max_bins,
            impurity="variance",
            min_instances=self.min_instances_per_node,
            min_info_gain=self.min_info_gain, n_classes=0,
            subsample=self._subsample,
            n_feat=_n_subset_features(self._feature_subset, X.shape[1],
                                      False, self._n_trees),
            seed=self.seed, mesh=mesh)
        return self._make_model(trees, X.shape[1])

    def _make_model(self, trees, d):
        return DecisionTreeRegressionModel(
            np.asarray(trees.feature), np.asarray(trees.threshold),
            np.asarray(trees.is_leaf), np.asarray(trees.value),
            np.asarray(trees.gain), d, self.max_depth,
            {"features_col": self.features_col,
             "prediction_col": self.prediction_col})


@persistable
class DecisionTreeRegressionModel(_TreeModelBase):
    _persist_attrs = ('feature', 'threshold', 'is_leaf', 'value', 'gain',
                      '_num_features', 'max_depth', '_params')

    def __init__(self, feature, threshold, is_leaf, value, gain,
                 num_features, max_depth, params=None):
        self.feature = np.asarray(feature)
        self.threshold = np.asarray(threshold)
        self.is_leaf = np.asarray(is_leaf)
        self.value = np.asarray(value)
        self.gain = np.asarray(gain)
        self._num_features = int(num_features)
        self.max_depth = int(max_depth)
        self._params = dict(params or {})

    def _predict_array(self, X):
        vals = self._leaf_values(X)                  # (T, n, 3): [w, wy, wy²]
        # MLlib averages per-tree leaf predictions with equal tree weight —
        # NOT pooled leaf stats, which would weight trees by bootstrap count.
        per_tree = vals[:, :, 1] / jnp.maximum(vals[:, :, 0], 1e-12)
        return jnp.mean(per_tree, axis=0)

    def transform(self, frame: Frame) -> Frame:
        pred = self._predict_array(self._frame_X(frame))
        return frame.with_column(
            self._params.get("prediction_col", "prediction"),
            pred.astype(float_dtype()))

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.asarray(self._predict_array(x))[0])


@persistable
class RandomForestRegressor(DecisionTreeRegressor):
    """MLlib ``RandomForestRegressor``: Poisson bootstrap + per-node random
    feature subsets, all trees built in one vmapped program."""

    _persist_attrs = DecisionTreeRegressor._persist_attrs + (
        'num_trees', 'subsampling_rate', 'feature_subset_strategy')

    def __init__(self, num_trees: int = 20, subsampling_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", **kw):
        super().__init__(**kw)
        self.num_trees = int(num_trees)
        self.subsampling_rate = float(subsampling_rate)
        self.feature_subset_strategy = feature_subset_strategy

    def set_num_trees(self, v):
        self.num_trees = int(v)
        return self

    setNumTrees = set_num_trees

    def set_subsampling_rate(self, v):
        self.subsampling_rate = float(v)
        return self

    setSubsamplingRate = set_subsampling_rate

    def set_feature_subset_strategy(self, v):
        self.feature_subset_strategy = v
        return self

    setFeatureSubsetStrategy = set_feature_subset_strategy

    @property
    def _n_trees(self):
        return self.num_trees

    @property
    def _subsample(self):
        return self.subsampling_rate

    @property
    def _feature_subset(self):
        return self.feature_subset_strategy

    def _make_model(self, trees, d):
        return RandomForestRegressionModel(
            np.asarray(trees.feature), np.asarray(trees.threshold),
            np.asarray(trees.is_leaf), np.asarray(trees.value),
            np.asarray(trees.gain), d, self.max_depth,
            {"features_col": self.features_col,
             "prediction_col": self.prediction_col})


@persistable
class RandomForestRegressionModel(DecisionTreeRegressionModel):
    @property
    def num_trees(self):
        return int(np.asarray(self.feature).shape[0])

    getNumTrees = num_trees


@persistable
class DecisionTreeClassifier(Estimator, _TreeParams):
    """MLlib ``DecisionTreeClassifier`` (gini default / entropy)."""

    _persist_attrs = ('max_depth', 'max_bins', 'min_instances_per_node',
                      'min_info_gain', 'impurity', 'features_col',
                      'label_col', 'prediction_col', 'probability_col',
                      'raw_prediction_col', 'seed')

    def __init__(self, max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 impurity: str = "gini", features_col: str = "features",
                 label_col: str = "label", prediction_col: str = "prediction",
                 probability_col: str = "probability",
                 raw_prediction_col: str = "rawPrediction", seed: int = 0):
        if impurity not in ("gini", "entropy"):
            raise ValueError(f"impurity={impurity!r} (gini|entropy)")
        self.max_depth = int(max_depth)
        self.max_bins = int(max_bins)
        self.min_instances_per_node = int(min_instances_per_node)
        self.min_info_gain = float(min_info_gain)
        self.impurity = impurity
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.probability_col = probability_col
        self.raw_prediction_col = raw_prediction_col
        self.seed = int(seed)

    def set_impurity(self, v):
        if v not in ("gini", "entropy"):
            raise ValueError(f"impurity={v!r}")
        self.impurity = v
        return self

    setImpurity = set_impurity

    _n_trees = 1
    _subsample = 1.0
    _feature_subset = "all"

    def fit(self, frame: Frame, mesh=None) \
            -> "DecisionTreeClassificationModel":
        X, y, mask = self._extract(frame)
        yv = y[mask]
        if np.any(yv < 0) or np.any(yv != np.floor(yv)):
            raise ValueError("labels must be nonnegative integers 0..k-1")
        k = int(yv.max()) + 1
        edges, binned = bin_features(X, mask, self.max_bins)
        w = mask.astype(np.float64)
        trees = _fit_forest(
            binned, edges, y, w, n_trees=self._n_trees,
            max_depth=self.max_depth, max_bins=self.max_bins,
            impurity=self.impurity,
            min_instances=self.min_instances_per_node,
            min_info_gain=self.min_info_gain, n_classes=k,
            subsample=self._subsample,
            n_feat=_n_subset_features(self._feature_subset, X.shape[1],
                                      True, self._n_trees),
            seed=self.seed, mesh=mesh)
        return self._make_model(trees, X.shape[1], k)

    def _params_for_model(self):
        return {"features_col": self.features_col,
                "prediction_col": self.prediction_col,
                "probability_col": self.probability_col,
                "raw_prediction_col": self.raw_prediction_col}

    def _make_model(self, trees, d, k):
        return DecisionTreeClassificationModel(
            np.asarray(trees.feature), np.asarray(trees.threshold),
            np.asarray(trees.is_leaf), np.asarray(trees.value),
            np.asarray(trees.gain), d, self.max_depth, k,
            self._params_for_model())


@persistable
class DecisionTreeClassificationModel(_TreeModelBase):
    _persist_attrs = ('feature', 'threshold', 'is_leaf', 'value', 'gain',
                      '_num_features', 'max_depth', 'num_classes', '_params')

    def __init__(self, feature, threshold, is_leaf, value, gain,
                 num_features, max_depth, num_classes, params=None):
        self.feature = np.asarray(feature)
        self.threshold = np.asarray(threshold)
        self.is_leaf = np.asarray(is_leaf)
        self.value = np.asarray(value)
        self.gain = np.asarray(gain)
        self._num_features = int(num_features)
        self.max_depth = int(max_depth)
        self.num_classes = int(num_classes)
        self._params = dict(params or {})

    numClasses = property(lambda self: self.num_classes)

    def _counts_and_proba(self, X):
        vals = self._leaf_values(X)                  # (T, n, k) class counts
        per_tree = vals / jnp.maximum(
            jnp.sum(vals, axis=2, keepdims=True), 1e-12)
        if vals.shape[0] == 1:
            # single tree (MLlib): rawPrediction = the leaf's class counts
            return vals[0], per_tree[0]
        # forest (MLlib): rawPrediction = summed per-tree probability votes,
        # so argmax(rawPrediction) == argmax(probability) always holds
        raw = jnp.sum(per_tree, axis=0)
        return raw, raw / vals.shape[0]

    def _proba(self, X):
        return self._counts_and_proba(X)[1]

    def transform(self, frame: Frame) -> Frame:
        p = self._params
        raw, prob = self._counts_and_proba(self._frame_X(frame))
        pred = jnp.argmax(prob, axis=1).astype(float_dtype())
        out = frame.with_column(p.get("raw_prediction_col", "rawPrediction"),
                                raw)
        out = out.with_column(p.get("probability_col", "probability"), prob)
        return out.with_column(p.get("prediction_col", "prediction"), pred)

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(host_fetch(jnp.argmax(self._proba(x), axis=1))[0])

    def predict_probability(self, features):
        x = np.asarray(features, np.float64).reshape(1, -1)
        return np.asarray(self._proba(x))[0]

    predictProbability = predict_probability


@persistable
class RandomForestClassifier(DecisionTreeClassifier):
    """MLlib ``RandomForestClassifier``: bootstrap + sqrt feature subsets
    ("auto"), soft-vote probabilities."""

    _persist_attrs = DecisionTreeClassifier._persist_attrs + (
        'num_trees', 'subsampling_rate', 'feature_subset_strategy')

    def __init__(self, num_trees: int = 20, subsampling_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", **kw):
        super().__init__(**kw)
        self.num_trees = int(num_trees)
        self.subsampling_rate = float(subsampling_rate)
        self.feature_subset_strategy = feature_subset_strategy

    set_num_trees = RandomForestRegressor.set_num_trees
    setNumTrees = set_num_trees
    set_subsampling_rate = RandomForestRegressor.set_subsampling_rate
    setSubsamplingRate = set_subsampling_rate
    set_feature_subset_strategy = \
        RandomForestRegressor.set_feature_subset_strategy
    setFeatureSubsetStrategy = set_feature_subset_strategy

    @property
    def _n_trees(self):
        return self.num_trees

    @property
    def _subsample(self):
        return self.subsampling_rate

    @property
    def _feature_subset(self):
        return self.feature_subset_strategy

    def _make_model(self, trees, d, k):
        return RandomForestClassificationModel(
            np.asarray(trees.feature), np.asarray(trees.threshold),
            np.asarray(trees.is_leaf), np.asarray(trees.value),
            np.asarray(trees.gain), d, self.max_depth, k,
            self._params_for_model())


@persistable
class RandomForestClassificationModel(DecisionTreeClassificationModel):
    @property
    def num_trees(self):
        return int(np.asarray(self.feature).shape[0])

    getNumTrees = num_trees


# ---------------------------------------------------------------------------
# Gradient-boosted trees: sequential Newton boosting over the same builder
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _gbt_round_builder(max_depth, max_bins, min_instances, min_info_gain,
                       mesh=None):
    """Jitted single-round GBT tree build, cached per hyperparameters so
    every boosting round (and every refit) reuses one compiled program.
    With a mesh, rows shard over the data axis exactly like
    :func:`_forest_builder` (psum'd level histograms)."""

    def one_round(binned, edges, targets, axis=None):
        return build_tree(binned, edges, targets, max_depth, max_bins,
                          "variance", min_instances, min_info_gain,
                          psum_axis=axis)

    if mesh is None:
        return jax.jit(one_round)

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    fn = shard_map(
        lambda b, e, t: one_round(b, e, t, DATA_AXIS), mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(), P(DATA_AXIS, None)),
        out_specs=P())
    return serialize_collectives(jax.jit(fn), mesh)


@functools.lru_cache(maxsize=None)
def _gbt_leaf_fn(max_depth):
    def tree_leaf_stats(tree_value, tree_feature, tree_threshold,
                        tree_is_leaf, Xd):
        node = predict_heap(Xd, tree_feature, tree_threshold, tree_is_leaf,
                            max_depth)
        v = tree_value[node]
        return v[:, 1] / jnp.maximum(v[:, 3], 1e-12)   # Newton leaf Σg/Σh

    return jax.jit(tree_leaf_stats)


def _gbt_fit(X, y, w, *, loss, max_iter, step, max_depth, max_bins,
             min_instances, min_info_gain, subsample, seed, mesh=None,
             valid_w=None, validation_tol=0.01):
    """Returns (F0, stacked TreeArrays). Stats rows per tree:
    [w, w·g, w·g², w·h] — variance-of-gradient splits (Friedman), Newton
    leaf values Σg/Σh. For squared loss h ≡ 1 so the leaf is the residual
    mean; for logistic h = p(1−p).

    Under a ``mesh`` each boosting round's tree builds row-sharded
    (psum'd level histograms); the replicated tree then scores the full
    rows for the next round's gradients.

    ``valid_w``: optional held-out row weights (MLlib
    ``validationIndicatorCol``). After each round the validation loss is
    evaluated on those rows; boosting stops once the relative improvement
    over the best loss so far drops below ``validation_tol``, and the
    returned ensemble is truncated at the best round."""
    dt = np.dtype(float_dtype())
    edges, binned = bin_features(X, w > 0, max_bins)
    rng = np.random.default_rng(seed)
    n = len(y)

    if mesh is not None and mesh.devices.size <= 1:
        mesh = None
    if mesh is None:
        pad = 0
        binned_d = jnp.asarray(binned)
        edges_d = jnp.asarray(edges, dt)
        row_shard = None
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS, shard_map

        pad = (-n) % mesh.devices.size
        if pad:
            binned = np.concatenate(
                [binned, np.zeros((pad, binned.shape[1]), np.int32)])
        row_shard = NamedSharding(mesh, P(DATA_AXIS, None))
        binned_d = jax.device_put(binned, row_shard)
        edges_d = jax.device_put(np.asarray(edges, dt),
                                 NamedSharding(mesh, P()))

    wsum = max(w.sum(), 1e-12)
    if loss == "squared":
        F0 = float(np.sum(w * y) / wsum)
    else:  # logistic: F0 = log-odds of the weighted base rate
        p0 = min(max(float(np.sum(w * y) / wsum), 1e-6), 1 - 1e-6)
        F0 = float(np.log(p0 / (1 - p0)))

    one_round = _gbt_round_builder(max_depth, max_bins, min_instances,
                                   min_info_gain, mesh)
    tree_leaf_stats = _gbt_leaf_fn(max_depth)

    def _val_loss(F_now):
        vs = max(valid_w.sum(), 1e-12)
        if loss == "squared":
            return float(np.sum(valid_w * (y - F_now) ** 2) / vs)
        z = np.where(y > 0.5, F_now, -F_now)
        return float(np.sum(valid_w * np.logaddexp(0.0, -z)) / vs)

    Xd = jnp.asarray(X, dt)
    F = np.full((n,), F0, np.float64)
    all_trees = []
    best_loss = _val_loss(F) if valid_w is not None else None
    best_k = 0
    for _ in range(max_iter):
        if loss == "squared":
            g = y - F
            h = np.ones_like(y)
        else:
            p = 1.0 / (1.0 + np.exp(-F))
            g = y - p
            h = np.maximum(p * (1 - p), 1e-12)
        ww = w if subsample >= 1.0 else \
            w * (rng.random(n) < subsample).astype(np.float64)
        targets = np.stack([ww, ww * g, ww * g * g, ww * h], axis=1) \
            .astype(dt)
        if pad:
            targets = np.concatenate([targets, np.zeros((pad, 4), dt)])
        targets_d = jnp.asarray(targets) if row_shard is None \
            else jax.device_put(targets, row_shard)
        tree = one_round(binned_d, edges_d, targets_d)
        all_trees.append(jax.tree_util.tree_map(np.asarray, tree))
        leaf = np.asarray(tree_leaf_stats(tree.value, tree.feature,
                                          tree.threshold, tree.is_leaf, Xd),
                          np.float64)
        F = F + step * leaf
        if valid_w is not None:
            cur = _val_loss(F)
            if cur < best_loss - validation_tol * max(abs(best_loss), 1e-12):
                best_loss = cur
                best_k = len(all_trees)
            else:
                break            # no meaningful improvement: stop boosting
    if valid_w is not None:
        # truncate at the best round; keep at least one tree (an ensemble
        # of zero trees has no stacked arrays and MLlib keeps one too)
        all_trees = all_trees[:max(best_k, 1)]
    stacked = TreeArrays(*[np.stack([getattr(t, f) for t in all_trees])
                           for f in TreeArrays._fields])
    return F0, stacked


class _GbtBase(Estimator, _TreeParams):
    # back-compat defaults for pre-validationIndicatorCol saves
    validation_indicator_col = None
    validation_tol = 0.01

    def __init__(self, max_iter: int = 20, step_size: float = 0.1,
                 max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 subsampling_rate: float = 1.0,
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction", seed: int = 0,
                 validation_indicator_col=None, validation_tol: float = 0.01):
        self.max_iter = int(max_iter)
        self.step_size = float(step_size)
        self.max_depth = int(max_depth)
        self.max_bins = int(max_bins)
        self.min_instances_per_node = int(min_instances_per_node)
        self.min_info_gain = float(min_info_gain)
        self.subsampling_rate = float(subsampling_rate)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.seed = int(seed)
        self.validation_indicator_col = validation_indicator_col
        self.validation_tol = float(validation_tol)

    def _split_weights(self, frame, mask):
        """(training weights, validation weights or None) from the
        validationIndicatorCol, mask-aware."""
        w = mask.astype(np.float64)
        if self.validation_indicator_col is None:
            return w, None
        v = np.asarray(
            frame._column_values(self.validation_indicator_col)) > 0
        return w * (~v), w * v

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_validation_indicator_col(self, v):
        self.validation_indicator_col = v
        return self

    setValidationIndicatorCol = set_validation_indicator_col

    def set_validation_tol(self, v):
        self.validation_tol = float(v)
        return self

    setValidationTol = set_validation_tol

    def set_step_size(self, v):
        self.step_size = float(v)
        return self

    setStepSize = set_step_size

    def set_subsampling_rate(self, v):
        self.subsampling_rate = float(v)
        return self

    setSubsamplingRate = set_subsampling_rate


@persistable
class GBTRegressor(_GbtBase):
    """MLlib ``GBTRegressor`` (squared loss)."""

    _persist_attrs = ('max_iter', 'step_size', 'max_depth', 'max_bins',
                      'min_instances_per_node', 'min_info_gain',
                      'subsampling_rate', 'features_col', 'label_col',
                      'prediction_col', 'seed',
                      'validation_indicator_col', 'validation_tol')

    def fit(self, frame: Frame, mesh=None) -> "GBTRegressionModel":
        X, y, mask = self._extract(frame)
        w_train, w_val = self._split_weights(frame, mask)
        F0, trees = _gbt_fit(
            X, y, w_train, loss="squared",
            max_iter=self.max_iter, step=self.step_size,
            max_depth=self.max_depth, max_bins=self.max_bins,
            min_instances=self.min_instances_per_node,
            min_info_gain=self.min_info_gain,
            subsample=self.subsampling_rate, seed=self.seed, mesh=mesh,
            valid_w=w_val, validation_tol=self.validation_tol)
        return GBTRegressionModel(
            trees.feature, trees.threshold, trees.is_leaf, trees.value,
            trees.gain, X.shape[1], self.max_depth, F0, self.step_size,
            {"features_col": self.features_col,
             "prediction_col": self.prediction_col})


class _GbtModelBase(_TreeModelBase):
    def _score(self, X):
        vals = self._leaf_values(X)                  # (T, n, 4)
        leaf = vals[:, :, 1] / jnp.maximum(vals[:, :, 3], 1e-12)
        return self.f0 + self.step_size * jnp.sum(leaf, axis=0)


@persistable
class GBTRegressionModel(_GbtModelBase):
    _persist_attrs = ('feature', 'threshold', 'is_leaf', 'value', 'gain',
                      '_num_features', 'max_depth', 'f0', 'step_size',
                      '_params')

    def __init__(self, feature, threshold, is_leaf, value, gain,
                 num_features, max_depth, f0, step_size, params=None):
        self.feature = np.asarray(feature)
        self.threshold = np.asarray(threshold)
        self.is_leaf = np.asarray(is_leaf)
        self.value = np.asarray(value)
        self.gain = np.asarray(gain)
        self._num_features = int(num_features)
        self.max_depth = int(max_depth)
        self.f0 = float(f0)
        self.step_size = float(step_size)
        self._params = dict(params or {})

    def transform(self, frame: Frame) -> Frame:
        pred = self._score(self._frame_X(frame))
        return frame.with_column(
            self._params.get("prediction_col", "prediction"),
            pred.astype(float_dtype()))

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.asarray(self._score(x))[0])

    @property
    def num_trees(self):
        return int(np.asarray(self.feature).shape[0])

    getNumTrees = num_trees


@persistable
class GBTClassifier(_GbtBase):
    """MLlib ``GBTClassifier`` (binary, logistic loss, Newton leaves)."""

    _persist_attrs = GBTRegressor._persist_attrs + (
        'probability_col', 'raw_prediction_col')

    def __init__(self, probability_col: str = "probability",
                 raw_prediction_col: str = "rawPrediction", **kw):
        super().__init__(**kw)
        self.probability_col = probability_col
        self.raw_prediction_col = raw_prediction_col

    def fit(self, frame: Frame, mesh=None) -> "GBTClassificationModel":
        X, y, mask = self._extract(frame)
        yv = y[mask]
        if not np.all((yv == 0) | (yv == 1)):
            raise ValueError("GBTClassifier requires binary 0/1 labels")
        w_train, w_val = self._split_weights(frame, mask)
        F0, trees = _gbt_fit(
            X, y, w_train, loss="logistic",
            max_iter=self.max_iter, step=self.step_size,
            max_depth=self.max_depth, max_bins=self.max_bins,
            min_instances=self.min_instances_per_node,
            min_info_gain=self.min_info_gain,
            subsample=self.subsampling_rate, seed=self.seed, mesh=mesh,
            valid_w=w_val, validation_tol=self.validation_tol)
        return GBTClassificationModel(
            trees.feature, trees.threshold, trees.is_leaf, trees.value,
            trees.gain, X.shape[1], self.max_depth, F0, self.step_size,
            {"features_col": self.features_col,
             "prediction_col": self.prediction_col,
             "probability_col": self.probability_col,
             "raw_prediction_col": self.raw_prediction_col})


@persistable
class GBTClassificationModel(_GbtModelBase):
    _persist_attrs = GBTRegressionModel._persist_attrs

    __init__ = GBTRegressionModel.__init__

    def transform(self, frame: Frame) -> Frame:
        p = self._params
        F = self._score(self._frame_X(frame))
        prob1 = jax.nn.sigmoid(F)
        prob = jnp.stack([1.0 - prob1, prob1], axis=1)
        raw = jnp.stack([-F, F], axis=1)
        pred = (F > 0).astype(float_dtype())
        out = frame.with_column(p.get("raw_prediction_col", "rawPrediction"),
                                raw)
        out = out.with_column(p.get("probability_col", "probability"), prob)
        return out.with_column(p.get("prediction_col", "prediction"), pred)

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.asarray(self._score(x))[0] > 0)

    @property
    def num_trees(self):
        return int(np.asarray(self.feature).shape[0])

    getNumTrees = num_trees
