"""``spark.ml.stat`` equivalents: Correlation and Summarizer.

``Correlation.corr`` produces the full (d×d) correlation matrix of a vector
column in ONE masked Gramian pass — the same ``A = ZᵀZ`` statistic the
solvers consume (models/solvers.py), unpacked into correlations instead of
a standardized Gram. ``Summarizer`` exposes MLlib's per-feature summary
metrics from the same single pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype


@jax.jit
def _moment_pass(X, w):
    """One masked pass: count, per-feature sum/mean, centered second moments,
    min/max, L1/L2 norms."""
    wc = w[:, None]
    n = jnp.sum(w)
    mean = jnp.sum(X * wc, axis=0) / n
    C = ((X - mean) * wc).T @ ((X - mean) * wc)  # centered scatter
    big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
    mn = jnp.min(jnp.where(wc > 0, X, big), axis=0)
    mx = jnp.max(jnp.where(wc > 0, X, -big), axis=0)
    l1 = jnp.sum(jnp.abs(X) * wc, axis=0)
    l2 = jnp.sqrt(jnp.sum(X * X * wc, axis=0))
    nnz = jnp.sum((X != 0) * wc, axis=0)
    return n, mean, C, mn, mx, l1, l2, nnz


def _extract(frame, col):
    X = jnp.asarray(frame._column_values(col), float_dtype())
    if X.ndim == 1:
        X = X[:, None]
    w = frame.mask.astype(X.dtype)
    return X, w


class Correlation:
    """``org.apache.spark.ml.stat.Correlation`` equivalent."""

    @staticmethod
    def corr(frame, column: str = "features", method: str = "pearson"):
        """(d×d) correlation matrix of a vector column as a numpy array.

        ``pearson`` runs fully on device from one scatter-matrix pass;
        ``spearman`` ranks host-side first (ranking is a data-dependent
        permutation — not a static-shape XLA op) then reuses the same pass.
        """
        X, w = _extract(frame, column)
        if method == "spearman":
            import scipy.stats

            Xn = np.asarray(X)
            keep = np.asarray(w) > 0
            ranked = np.zeros_like(Xn)
            ranked[keep] = scipy.stats.rankdata(Xn[keep], axis=0)
            X = jnp.asarray(ranked, X.dtype)
        elif method != "pearson":
            raise ValueError(f"unknown correlation method {method!r}")
        _, _, C, *_ = _moment_pass(X, w)
        d = np.sqrt(np.diag(np.asarray(C)))
        denom = np.outer(d, d)
        with np.errstate(invalid="ignore", divide="ignore"):
            out = np.asarray(C) / denom
        out[denom == 0] = np.nan
        np.fill_diagonal(out, 1.0)
        return out


class Summarizer:
    """``org.apache.spark.ml.stat.Summarizer`` equivalent: one-pass
    per-feature metrics of a vector column. ``metrics(...)`` selects named
    metrics; ``summary(frame, col)`` returns them all as a dict."""

    METRICS = ("mean", "variance", "std", "count", "numNonZeros", "min",
               "max", "normL1", "normL2")

    def __init__(self, metrics=("mean", "variance")):
        unknown = set(metrics) - set(self.METRICS)
        if unknown:
            raise ValueError(f"unknown metrics {sorted(unknown)}")
        self._metrics = tuple(metrics)

    @classmethod
    def metrics(cls, *names) -> "Summarizer":
        return cls(names)

    def summary(self, frame, column: str = "features") -> dict:
        X, w = _extract(frame, column)
        n, mean, C, mn, mx, l1, l2, nnz = map(np.asarray, _moment_pass(X, w))
        var = np.diag(C) / max(float(n) - 1.0, 1.0)
        all_metrics = {
            "mean": mean, "variance": var, "std": np.sqrt(var),
            "count": int(n), "numNonZeros": nnz, "min": mn, "max": mx,
            "normL1": l1, "normL2": l2,
        }
        return {k: all_metrics[k] for k in self._metrics}


def summary(frame, column: str = "features") -> dict:
    """All Summarizer metrics of a vector column in one pass."""
    return Summarizer(Summarizer.METRICS).summary(frame, column)
