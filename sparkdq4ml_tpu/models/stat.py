"""``spark.ml.stat`` equivalents: Correlation and Summarizer.

``Correlation.corr`` produces the full (d×d) correlation matrix of a vector
column in ONE masked Gramian pass — the same ``A = ZᵀZ`` statistic the
solvers consume (models/solvers.py), unpacked into correlations instead of
a standardized Gram. ``Summarizer`` exposes MLlib's per-feature summary
metrics from the same single pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from ..parallel.mesh import normalize_mesh, serialize_collectives


def _moment_stats(X, w, psum_axis=None):
    """One masked moment pass over (a row shard of) a matrix: count,
    per-feature sum/mean, CENTERED second moments (numerically stable — no
    raw-moment cancellation), min/max, L1/L2 norms.

    With ``psum_axis`` set (inside shard_map), the count/sum psum first so
    every device centers on the GLOBAL mean, then the centered scatter and
    the remaining statistics psum (pmin/pmax for extrema) — two cheap
    collectives, same math as the single-device pass."""
    wc = w[:, None]
    n = jnp.sum(w)
    s1 = jnp.sum(X * wc, axis=0)
    if psum_axis is not None:
        n, s1 = jax.lax.psum((n, s1), psum_axis)
    mean = s1 / n
    # √w scaling ⇒ C = Σ w·(x−μ)(x−μ)ᵀ — weighted ONCE. (X−μ)·w would
    # square the weight inside the Gram product: identical for 0/1 masks
    # but ~w× off for real weights (the r3 weighted-variance bug).
    Xc = (X - mean) * jnp.sqrt(wc)
    C = Xc.T @ Xc                                 # centered scatter
    big = jnp.asarray(jnp.finfo(X.dtype).max, X.dtype)
    mn = jnp.min(jnp.where(wc > 0, X, big), axis=0)
    mx = jnp.max(jnp.where(wc > 0, X, -big), axis=0)
    l1 = jnp.sum(jnp.abs(X) * wc, axis=0)
    sq = jnp.sum(X * X * wc, axis=0)
    nnz = jnp.sum((X != 0) * wc, axis=0)
    if psum_axis is not None:
        C, l1, sq, nnz = jax.lax.psum((C, l1, sq, nnz), psum_axis)
        mn = jax.lax.pmin(mn, psum_axis)
        mx = jax.lax.pmax(mx, psum_axis)
    return n, mean, C, mn, mx, l1, jnp.sqrt(sq), nnz


@jax.jit
def _moment_pass(X, w):
    """One masked pass: count, per-feature sum/mean, centered second moments,
    min/max, L1/L2 norms."""
    return _moment_stats(X, w)


@functools.lru_cache(maxsize=None)
def _moment_pass_fn(mesh):
    """Mesh-sharded variant of :func:`_moment_pass` (cached per mesh)."""
    if mesh is None:
        return _moment_pass

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    return serialize_collectives(jax.jit(shard_map(
        lambda X, w: _moment_stats(X, w, DATA_AXIS), mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS)),
        out_specs=P())), mesh)


def _extract(frame, col, mesh=None):
    if mesh is None:
        # stay on device — np.asarray on a device array is a device→host
        # read (and the first such read must never happen here; see
        # parallel/distributed.pack_design)
        X = jnp.asarray(frame._column_values(col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        return X, frame.mask.astype(X.dtype)
    from ..parallel.distributed import pad_and_shard_rows

    X = np.asarray(frame._column_values(col), np.dtype(float_dtype()))
    if X.ndim == 1:
        X = X[:, None]
    return pad_and_shard_rows(mesh, X, np.asarray(frame.mask, X.dtype))


class Correlation:
    """``org.apache.spark.ml.stat.Correlation`` equivalent."""

    @staticmethod
    def corr(frame, column: str = "features", method: str = "pearson",
             mesh=None):
        """(d×d) correlation matrix of a vector column as a numpy array.

        ``pearson`` runs fully on device from one scatter-matrix pass
        (row-sharded + psum'd under a ``mesh``); ``spearman`` ranks
        host-side first (ranking is a data-dependent permutation — not a
        static-shape XLA op) then reuses the same pass.
        """
        mesh = normalize_mesh(mesh)
        if method == "spearman":
            import scipy.stats

            from ..parallel.distributed import pad_and_shard_rows

            # ranking is inherently host-side; the host read is the point
            Xn, wn = _extract(frame, column)
            Xn = np.asarray(Xn)
            wn = np.asarray(wn)
            keep = wn > 0
            ranked = np.zeros_like(Xn)
            ranked[keep] = scipy.stats.rankdata(Xn[keep], axis=0)
            X, w = pad_and_shard_rows(mesh, ranked, wn)
        elif method == "pearson":
            X, w = _extract(frame, column, mesh)
        else:
            raise ValueError(f"unknown correlation method {method!r}")
        _, _, C, *_ = _moment_pass_fn(mesh)(X, w)
        d = np.sqrt(np.diag(np.asarray(C)))
        denom = np.outer(d, d)
        with np.errstate(invalid="ignore", divide="ignore"):
            out = np.asarray(C) / denom
        out[denom == 0] = np.nan
        np.fill_diagonal(out, 1.0)
        return out


class Summarizer:
    """``org.apache.spark.ml.stat.Summarizer`` equivalent: one-pass
    per-feature metrics of a vector column. ``metrics(...)`` selects named
    metrics; ``summary(frame, col)`` returns them all as a dict."""

    METRICS = ("mean", "variance", "std", "count", "numNonZeros", "min",
               "max", "normL1", "normL2")

    def __init__(self, metrics=("mean", "variance")):
        unknown = set(metrics) - set(self.METRICS)
        if unknown:
            raise ValueError(f"unknown metrics {sorted(unknown)}")
        self._metrics = tuple(metrics)

    @classmethod
    def metrics(cls, *names) -> "Summarizer":
        return cls(names)

    def summary(self, frame, column: str = "features", mesh=None,
                weight_col: str = None) -> dict:
        """One-pass metrics; ``weight_col`` (MLlib's optional weight
        argument) weights mean/variance/norms/numNonZeros, while ``count``
        stays the number of weight-positive rows and min/max ignore
        weights — MLlib's MultivariateOnlineSummarizer semantics
        (zero-weight rows are skipped entirely)."""
        mesh = normalize_mesh(mesh)
        X, w = _extract(frame, column, mesh)
        count = None
        if weight_col is not None:
            uw = np.asarray(frame._column_values(weight_col), np.float64)
            valid = np.asarray(frame.mask)
            if not np.all(uw[valid] >= 0):     # NaN fails >= too
                raise ValueError("weights must be nonnegative")
            uw = np.where(valid, uw, 0.0)
            count = int((uw > 0).sum())
            w = jnp.asarray(uw, X.dtype)
            if mesh is not None:
                # re-shard the replaced weights like _extract did
                from ..parallel.distributed import pad_and_shard_rows

                X_np = np.asarray(X)
                X, w = pad_and_shard_rows(mesh, X_np[:len(uw)], uw)[0:2]
        n, mean, C, mn, mx, l1, l2, nnz = map(np.asarray,
                                              _moment_pass_fn(mesh)(X, w))
        var = np.diag(C) / max(float(n) - 1.0, 1.0)
        all_metrics = {
            "mean": mean, "variance": var, "std": np.sqrt(var),
            "count": int(n) if count is None else count,
            "numNonZeros": nnz, "min": mn, "max": mx,
            "normL1": l1, "normL2": l2,
        }
        return {k: all_metrics[k] for k in self._metrics}


def summary(frame, column: str = "features", mesh=None,
            weight_col: str = None) -> dict:
    """All Summarizer metrics of a vector column in one pass."""
    return Summarizer(Summarizer.METRICS).summary(frame, column, mesh,
                                                  weight_col)


@functools.lru_cache(maxsize=None)
def _contingency_fn(mesh):
    """Contingency matmul ``fxᵀ @ ly``, row-sharded + psum'd under a mesh."""
    if mesh is None:
        return jax.jit(lambda fx, ly: fx.T @ ly)

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    return serialize_collectives(jax.jit(shard_map(
        lambda fx, ly: jax.lax.psum(fx.T @ ly, DATA_AXIS), mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS, None)),
        out_specs=P())), mesh)


class ChiSquareTest:
    """``org.apache.spark.ml.stat.ChiSquareTest`` equivalent: Pearson
    χ² independence test of every (categorical) feature against the label.

    TPU-first: each feature's contingency table is ONE one-hot matmul
    (``onehot(feature)ᵀ @ onehot(label)``, MXU-shaped) over masked rows —
    no per-row host work; only the (c_f × c_l) table comes back to the host
    for the χ² tail probability (scipy). Under a ``mesh`` the rows shard
    and the table psums over ICI (per-feature ``aggregateByKey`` analogue).
    """

    @staticmethod
    def test(frame, features_col: str = "features",
             label_col: str = "label", mesh=None):
        from scipy import stats as sstats

        from ..frame import Frame

        mesh = normalize_mesh(mesh)
        X, w = _extract(frame, features_col)
        y = jnp.asarray(frame._column_values(label_col), X.dtype)

        Xh = np.asarray(X)
        yh = np.asarray(y)
        keep = np.asarray(w) > 0
        if not keep.any():
            raise ValueError("ChiSquareTest: no valid rows")
        if np.any(Xh[keep] != np.floor(Xh[keep])) or np.any(Xh[keep] < 0):
            raise ValueError("ChiSquareTest requires nonnegative integer "
                             "(categorical) features")
        yv = yh[keep]
        if np.any(yv != np.floor(yv)) or np.any(yv < 0):
            raise ValueError("ChiSquareTest requires nonnegative integer "
                             "labels")
        n_label = int(yv.max()) + 1
        if mesh is not None:
            # masked rows already weight ly to zero; pad rows do the same
            from ..parallel.distributed import pad_and_shard_rows

            X, y, w = pad_and_shard_rows(mesh, Xh,
                                         np.where(keep, yh, 0.0),
                                         np.asarray(w))
        ly = jax.nn.one_hot(y.astype(jnp.int32), n_label,
                            dtype=X.dtype) * w[:, None]

        contingency = _contingency_fn(mesh)
        p_values, dofs, statistics = [], [], []
        for j in range(Xh.shape[1]):
            n_feat = int(Xh[keep, j].max()) + 1
            fx = jax.nn.one_hot(X[:, j].astype(jnp.int32), n_feat,
                                dtype=X.dtype)
            table = np.asarray(contingency(fx, ly))  # (c_f, c_l)
            # drop empty rows/cols (Spark's degrees of freedom use observed
            # categories only)
            table = table[table.sum(axis=1) > 0][:, table.sum(axis=0) > 0]
            if table.shape[0] < 2 or table.shape[1] < 2:
                statistics.append(0.0)
                dofs.append(0)
                p_values.append(1.0)
                continue
            row = table.sum(axis=1, keepdims=True)
            col = table.sum(axis=0, keepdims=True)
            expected = row @ col / table.sum()
            stat = float(((table - expected) ** 2 / expected).sum())
            dof = (table.shape[0] - 1) * (table.shape[1] - 1)
            statistics.append(stat)
            dofs.append(dof)
            p_values.append(float(sstats.chi2.sf(stat, dof)))

        return Frame({
            "pValues": np.asarray([np.asarray(p_values)], object),
            "degreesOfFreedom": np.asarray([np.asarray(dofs)], object),
            "statistics": np.asarray([np.asarray(statistics)], object),
        })


class KolmogorovSmirnovTest:
    """``org.apache.spark.ml.stat.KolmogorovSmirnovTest`` equivalent:
    one-sample, two-sided KS test of a sample column against a theoretical
    distribution (``"norm"``, with optional mean/std params like MLlib, or
    any ``scipy.stats`` distribution name).

    The valid-row subset is a data-dependent gather, so the sort + D
    statistic run host-side (numpy); the p-value is the asymptotic
    Kolmogorov tail probability (scipy), matching MLlib's two-sided test.
    """

    @staticmethod
    def test(frame, sample_col: str, dist: str = "norm", *params):
        from scipy import stats as sstats

        from ..frame import Frame

        x = jnp.asarray(frame._column_values(sample_col), float_dtype())
        w = frame.mask
        xh = np.asarray(x)[np.asarray(w)]
        n = xh.size
        if n == 0:
            raise ValueError("KolmogorovSmirnovTest: no valid rows")

        dist_obj = getattr(sstats, dist, None)
        if dist_obj is None:
            raise ValueError(f"unknown distribution {dist!r}")
        if dist == "norm" and not params:
            params = (0.0, 1.0)    # MLlib default: standard normal

        xs = np.sort(xh)
        cdf = dist_obj.cdf(xs, *params)
        i = np.arange(1, n + 1, dtype=np.float64)
        d_plus = np.max(i / n - cdf)
        d_minus = np.max(cdf - (i - 1) / n)
        statistic = float(max(d_plus, d_minus))
        p_value = float(
            sstats.distributions.kstwobign.sf(np.sqrt(n) * statistic))
        return Frame({"pValue": np.asarray([p_value]),
                      "statistic": np.asarray([statistic])})
