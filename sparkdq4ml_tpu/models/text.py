"""Text feature pipeline: Tokenizer → StopWordsRemover / NGram →
HashingTF / CountVectorizer → IDF (the ``spark.ml.feature`` text stages
shipped by the reference's mllib dependency, pom.xml:29-32).

Design: token columns are host-side object arrays of string lists (TPUs do
not hold strings — same rule as Frame's string columns); the moment text
becomes *counts* (HashingTF / CountVectorizerModel) the data lands in a
dense device matrix, and everything after (IDF scaling, any estimator) is
device math. IDF's document-frequency statistic is one masked device
reduction; its transform is a broadcast multiply fused by XLA.

HashingTF uses Python's stable string hash (md5-based here, process-stable,
documented) modulo ``num_features`` — the same trick as Spark's
murmur3-mod hashing; hash values differ from Spark's, the semantics (fixed
dimension, collision-tolerant bag-of-words) are identical.
"""

from __future__ import annotations

import hashlib
import re
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from .base import Estimator, Model, Transformer, persistable

# Spark's english default list (abridged to the common core; the full list
# is data, not behavior — users can pass their own)
_ENGLISH_STOP_WORDS = [
    "a", "about", "above", "after", "again", "against", "all", "am", "an",
    "and", "any", "are", "as", "at", "be", "because", "been", "before",
    "being", "below", "between", "both", "but", "by", "could", "did", "do",
    "does", "doing", "down", "during", "each", "few", "for", "from",
    "further", "had", "has", "have", "having", "he", "her", "here", "hers",
    "herself", "him", "himself", "his", "how", "i", "if", "in", "into",
    "is", "it", "its", "itself", "me", "more", "most", "my", "myself",
    "no", "nor", "not", "of", "off", "on", "once", "only", "or", "other",
    "ought", "our", "ours", "ourselves", "out", "over", "own", "same",
    "she", "should", "so", "some", "such", "than", "that", "the", "their",
    "theirs", "them", "themselves", "then", "there", "these", "they",
    "this", "those", "through", "to", "too", "under", "until", "up",
    "very", "was", "we", "were", "what", "when", "where", "which", "while",
    "who", "whom", "why", "with", "would", "you", "your", "yours",
    "yourself", "yourselves"]


from ..frame.frame import list_column as _obj_array  # public home moved;
# the old private name stays importable for existing callers


def _token_col(frame, name):
    col = frame._column_values(name)
    if not (isinstance(col, np.ndarray) and col.dtype == object):
        raise ValueError(f"column {name!r} must be a string/token column")
    return col


@persistable
class Tokenizer(Transformer):
    """MLlib ``Tokenizer``: lowercase + split on whitespace."""

    _persist_attrs = ('input_col', 'output_col')

    def __init__(self, input_col: str = None, output_col: str = None):
        self.input_col = input_col
        self.output_col = output_col

    def set_input_col(self, v):
        self.input_col = v
        return self

    setInputCol = set_input_col

    def set_output_col(self, v):
        self.output_col = v
        return self

    setOutputCol = set_output_col

    def transform(self, frame):
        col = _token_col(frame, self.input_col)
        out = _obj_array(
            [None if s is None else str(s).lower().split() for s in col])
        return frame.with_column(self.output_col, out)


@persistable
class RegexTokenizer(Tokenizer):
    """MLlib ``RegexTokenizer``: split by ``pattern`` (gaps=True, default
    ``\\s+``) or match tokens (gaps=False); optional lowercase,
    ``min_token_length`` filter."""

    _persist_attrs = ('input_col', 'output_col', 'pattern', 'gaps',
                      'to_lowercase', 'min_token_length')

    def __init__(self, input_col: str = None, output_col: str = None,
                 pattern: str = r"\s+", gaps: bool = True,
                 to_lowercase: bool = True, min_token_length: int = 1):
        super().__init__(input_col, output_col)
        self.pattern = pattern
        self.gaps = gaps
        self.to_lowercase = to_lowercase
        self.min_token_length = int(min_token_length)

    def set_pattern(self, v):
        self.pattern = v
        return self

    setPattern = set_pattern

    def transform(self, frame):
        col = _token_col(frame, self.input_col)
        rx = re.compile(self.pattern)

        def tok(s):
            if s is None:
                return None
            if self.to_lowercase:
                s = s.lower()
            toks = rx.split(s) if self.gaps else rx.findall(s)
            return [t for t in toks if len(t) >= self.min_token_length]

        out = _obj_array([tok(s) for s in col])
        return frame.with_column(self.output_col, out)


@persistable
class StopWordsRemover(Transformer):
    """MLlib ``StopWordsRemover``: drop stop words from a token column."""

    _persist_attrs = ('input_col', 'output_col', 'stop_words',
                      'case_sensitive')

    def __init__(self, input_col: str = None, output_col: str = None,
                 stop_words: Optional[Sequence[str]] = None,
                 case_sensitive: bool = False):
        self.input_col = input_col
        self.output_col = output_col
        self.stop_words = list(stop_words) if stop_words is not None \
            else list(_ENGLISH_STOP_WORDS)
        self.case_sensitive = case_sensitive

    @staticmethod
    def load_default_stop_words(language: str = "english"):
        if language != "english":
            raise ValueError("only the english default list ships here")
        return list(_ENGLISH_STOP_WORDS)

    loadDefaultStopWords = load_default_stop_words

    def set_stop_words(self, v):
        self.stop_words = list(v)
        return self

    setStopWords = set_stop_words

    def transform(self, frame):
        col = _token_col(frame, self.input_col)
        if self.case_sensitive:
            stop = set(self.stop_words)

            def keep(t):
                return t not in stop
        else:
            stop = {w.lower() for w in self.stop_words}

            def keep(t):
                return t.lower() not in stop

        out = _obj_array(
            [None if toks is None else [t for t in toks if keep(t)]
             for toks in col])
        return frame.with_column(self.output_col, out)


@persistable
class NGram(Transformer):
    """MLlib ``NGram``: sliding n-grams (space-joined) over a token column."""

    _persist_attrs = ('input_col', 'output_col', 'n')

    def __init__(self, n: int = 2, input_col: str = None,
                 output_col: str = None):
        if n < 1:
            raise ValueError("n must be >= 1")
        self.n = int(n)
        self.input_col = input_col
        self.output_col = output_col

    def set_n(self, v):
        if v < 1:
            raise ValueError("n must be >= 1")
        self.n = int(v)
        return self

    setN = set_n

    def transform(self, frame):
        col = _token_col(frame, self.input_col)
        n = self.n
        out = _obj_array(
            [None if toks is None else
             [" ".join(toks[i:i + n]) for i in range(len(toks) - n + 1)]
             for toks in col])
        return frame.with_column(self.output_col, out)


def _stable_hash(token: str, mod: int) -> int:
    return int.from_bytes(hashlib.md5(token.encode()).digest()[:8],
                          "little") % mod


@persistable
class HashingTF(Transformer):
    """MLlib ``HashingTF``: hashed term-frequency vectors of a fixed
    dimension. Token → bucket via a process-stable hash (md5-based; Spark
    uses murmur3 — bucket assignments differ, semantics match). Output is
    a DENSE device matrix ready for any estimator — hence the default
    dimension is 1024, not Spark's sparse-vector 2^18 (which would allocate
    n_docs x 262144 floats); raise it explicitly when the corpus warrants
    the memory."""

    _persist_attrs = ('num_features', 'input_col', 'output_col', 'binary')

    def __init__(self, num_features: int = 1024, input_col: str = None,
                 output_col: str = None, binary: bool = False):
        if num_features < 1:
            raise ValueError("num_features must be >= 1")
        self.num_features = int(num_features)
        self.input_col = input_col
        self.output_col = output_col
        self.binary = binary

    def set_num_features(self, v):
        if v < 1:
            raise ValueError("num_features must be >= 1")
        self.num_features = int(v)
        return self

    setNumFeatures = set_num_features

    def set_binary(self, v):
        self.binary = bool(v)
        return self

    setBinary = set_binary

    def transform(self, frame):
        # Vectorized over the flattened corpus: md5 runs once per UNIQUE
        # token (np.unique), bucket scatter is one np.add.at — the only
        # Python-level loop left is per-document length collection.
        col = _token_col(frame, self.input_col)
        n = len(col)
        dt = np.dtype(float_dtype())
        M = np.zeros((n, self.num_features), dt)
        lens = np.fromiter((0 if t is None else len(t) for t in col),
                           np.int64, count=n)
        flat = [t for toks in col if toks is not None for t in toks]
        if flat:
            uniq, inv = np.unique(np.asarray(flat), return_inverse=True)
            buckets = np.fromiter(
                (_stable_hash(str(t), self.num_features) for t in uniq),
                np.int64, count=uniq.size)
            doc_ids = np.repeat(np.arange(n), lens)
            np.add.at(M, (doc_ids, buckets[inv]), 1.0)
            if self.binary:
                M = (M > 0).astype(dt)
        return frame.with_column(self.output_col, jnp.asarray(M))


@persistable
class CountVectorizer(Estimator):
    """MLlib ``CountVectorizer``: learn a vocabulary (top ``vocab_size`` by
    corpus frequency, ties alphabetical) with ``min_df`` document-frequency
    and ``min_tf`` in-document filters; transform to dense count vectors."""

    _persist_attrs = ('vocab_size', 'min_df', 'min_tf', 'binary',
                      'input_col', 'output_col')

    def __init__(self, vocab_size: int = 262144, min_df: float = 1.0,
                 min_tf: float = 1.0, binary: bool = False,
                 input_col: str = None, output_col: str = None):
        self.vocab_size = int(vocab_size)
        self.min_df = float(min_df)
        self.min_tf = float(min_tf)
        self.binary = binary
        self.input_col = input_col
        self.output_col = output_col

    def set_vocab_size(self, v):
        self.vocab_size = int(v)
        return self

    setVocabSize = set_vocab_size

    def set_min_df(self, v):
        self.min_df = float(v)
        return self

    setMinDF = set_min_df

    def fit(self, frame) -> "CountVectorizerModel":
        # Vectorized document-frequency: unique (doc, term) pairs via one
        # np.unique over integer-encoded pair ids, then a bincount — no
        # per-token Python loop.
        col = _token_col(frame, self.input_col)
        mask = np.asarray(frame.mask)
        docs = [toks for toks, m in zip(col, mask)
                if m and toks is not None]
        n_docs = len(docs)
        flat = [t for toks in docs for t in toks]
        if flat:
            lens = np.fromiter((len(t) for t in docs), np.int64,
                               count=n_docs)
            uniq, inv = np.unique(np.asarray(flat), return_inverse=True)
            doc_ids = np.repeat(np.arange(n_docs, dtype=np.int64), lens)
            pair_ids = np.unique(doc_ids * np.int64(uniq.size) + inv)
            df_counts = np.bincount(pair_ids % np.int64(uniq.size),
                                    minlength=uniq.size)
        else:
            uniq = np.asarray([], dtype=object)
            df_counts = np.asarray([], np.int64)
        # min_df: absolute count if >= 1, else fraction of documents
        thresh = self.min_df if self.min_df >= 1.0 \
            else self.min_df * max(n_docs, 1)
        keep = df_counts >= thresh
        terms, cnts = uniq[keep], df_counts[keep]
        order = np.lexsort((terms, -cnts))        # (-count, token) like MLlib
        vocab = [str(t) for t in terms[order][: self.vocab_size]]
        return CountVectorizerModel(vocab, self.min_tf, self.binary,
                                    self.input_col, self.output_col)


@persistable
class CountVectorizerModel(Model):
    _persist_attrs = ('vocabulary', 'min_tf', 'binary', 'input_col',
                      'output_col')

    def __init__(self, vocabulary, min_tf=1.0, binary=False,
                 input_col=None, output_col=None):
        self.vocabulary = list(vocabulary)
        self.min_tf = float(min_tf)
        self.binary = binary
        self.input_col = input_col
        self.output_col = output_col
        self._build_index()

    def _post_load(self):
        self.vocabulary = list(self.vocabulary)
        self._build_index()

    def _build_index(self):
        """Sorted-vocabulary lookup tables, built once per model so every
        transform pays only the searchsorted, not an O(V log V) re-sort."""
        vocab_arr = np.asarray(self.vocabulary)
        self._vocab_order = np.argsort(vocab_arr)
        self._sorted_vocab = vocab_arr[self._vocab_order]

    def transform(self, frame):
        # Vectorized: one sorted-vocabulary searchsorted over the flattened
        # corpus, one np.add.at count scatter, matrix-level min_tf/binary.
        col = _token_col(frame, self.input_col)
        n = len(col)
        dt = np.dtype(float_dtype())
        V = len(self.vocabulary)
        M = np.zeros((n, V), dt)
        lens = np.fromiter((0 if t is None else len(t) for t in col),
                           np.int64, count=n)
        flat = [t for toks in col if toks is not None for t in toks]
        if flat and V:
            doc_ids = np.repeat(np.arange(n), lens)
            flat_arr = np.asarray(flat)
            sv = self._sorted_vocab
            pos = np.minimum(np.searchsorted(sv, flat_arr), V - 1)
            hit = sv[pos] == flat_arr
            np.add.at(M, (doc_ids[hit], self._vocab_order[pos[hit]]), 1.0)
        if self.min_tf >= 1.0:
            M[M < self.min_tf] = 0.0
        else:  # fraction-of-document threshold; empty docs are all-zero
            M[M / np.maximum(lens, 1)[:, None] < self.min_tf] = 0.0
        if self.binary:
            M = (M > 0).astype(dt)
        return frame.with_column(self.output_col, jnp.asarray(M))


@persistable
class IDF(Estimator):
    """MLlib ``IDF``: log((n+1)/(df+1)) weights over a TF vector column;
    document frequency is ONE masked device reduction, the transform a
    fused broadcast multiply."""

    _persist_attrs = ('min_doc_freq', 'input_col', 'output_col')

    def __init__(self, min_doc_freq: int = 0, input_col: str = None,
                 output_col: str = None):
        self.min_doc_freq = int(min_doc_freq)
        self.input_col = input_col
        self.output_col = output_col

    def set_min_doc_freq(self, v):
        self.min_doc_freq = int(v)
        return self

    setMinDocFreq = set_min_doc_freq

    def fit(self, frame) -> "IDFModel":
        tf = jnp.asarray(frame._column_values(self.input_col),
                         float_dtype())
        w = frame.mask.astype(tf.dtype)
        df = jnp.sum((tf > 0).astype(tf.dtype) * w[:, None], axis=0)
        n = jnp.sum(w)
        idf = jnp.log((n + 1.0) / (df + 1.0))
        if self.min_doc_freq > 0:
            idf = jnp.where(df >= self.min_doc_freq, idf, 0.0)
        return IDFModel(np.asarray(idf), self.input_col, self.output_col)


@persistable
class IDFModel(Model):
    _persist_attrs = ('idf', 'input_col', 'output_col')

    def __init__(self, idf, input_col=None, output_col=None):
        self.idf = np.asarray(idf)
        self.input_col = input_col
        self.output_col = output_col

    def transform(self, frame):
        tf = jnp.asarray(frame._column_values(self.input_col),
                         float_dtype())
        return frame.with_column(self.output_col,
                                 tf * jnp.asarray(self.idf, tf.dtype))
