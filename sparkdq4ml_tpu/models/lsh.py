"""Locality-sensitive hashing (MLlib ``org.apache.spark.ml.feature``
``BucketedRandomProjectionLSH`` / ``MinHashLSH`` — shipped by the
reference's mllib dependency, pom.xml:29-32).

TPU-first design:

* **Hashing is one device op.** Random-projection hashes are a single
  ``(n, d) × (d, L)`` MXU matmul + floor; MinHash is one masked min
  reduction over the (n, 1, d) × (1, L, d) broadcast of precomputed
  per-index hash values. No per-row Python.
* **Candidate generation reuses the vectorized join planner**: bucket ids
  are integer keys, so ``approxSimilarityJoin`` plans candidate pairs with
  the same sort/searchsorted machinery as ``Frame.join`` (frame/frame.py
  ``_vector_join_plan``) instead of a per-row hash probe — Spark's
  shuffle-on-hash analogue.
* **Exact re-ranking on device**: candidate distances are batched norms /
  Jaccard reductions, then ``top_k``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from .base import Estimator, Model, host_fetch, persistable

_MINHASH_PRIME = 2038074743  # MLlib's MinHashLSH prime


class _LSHParams:
    @staticmethod
    def _check_tables(v):
        if v < 1:
            raise ValueError("num_hash_tables must be >= 1")
        return int(v)

    def set_input_col(self, v):
        self.input_col = v
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    def set_num_hash_tables(self, v):
        self.num_hash_tables = self._check_tables(v)
        return self

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setInputCol = set_input_col
    setOutputCol = set_output_col
    setNumHashTables = set_num_hash_tables
    setSeed = set_seed


def _extract_matrix(frame, col):
    X = jnp.asarray(frame._column_values(col), float_dtype())
    if X.ndim == 1:
        X = X[:, None]
    return X


class _LSHModelBase(Model):
    """Shared approxNearestNeighbors / approxSimilarityJoin on top of a
    subclass-provided ``_hashes(X) -> (n, L) int`` and
    ``_distance(A, B) -> (n,)``."""

    def _validate(self, X, mask=None):
        """Subclass hook: reject inputs the hash family is undefined on."""

    def transform(self, frame):
        # hash ids stay int32 — a float32 column would quantize MinHash's
        # ~2^31-range ids (resolution 128 above 2^24)
        X = _extract_matrix(frame, self.input_col)
        self._validate(np.asarray(X), np.asarray(frame.mask))
        return frame.with_column(self.output_col, self._hashes(X))

    def approx_nearest_neighbors(self, frame, key, num_neighbors: int,
                                 dist_col: str = "distCol"):
        """Top-k rows of ``frame`` nearest to vector ``key`` among
        candidates sharing ≥1 hash bucket (falls back to all valid rows
        when the candidate set is smaller than k — MLlib warns instead;
        deterministic beats partial here)."""
        X = _extract_matrix(frame, self.input_col)
        keyv = jnp.asarray(np.atleast_1d(np.asarray(key, np.float64)),
                           X.dtype)
        self._validate(np.asarray(X), np.asarray(frame.mask))
        self._validate(np.asarray(keyv)[None, :])
        hx = np.asarray(self._hashes(X))                   # (n, L)
        hk = np.asarray(self._hashes(keyv[None, :]))[0]    # (L,)
        valid = np.asarray(frame.mask)
        cand = ((hx == hk[None, :]).any(axis=1)) & valid
        if cand.sum() < num_neighbors:
            cand = valid
        idx = np.nonzero(cand)[0]
        d = host_fetch(self._distance(X[jnp.asarray(idx)], keyv))
        k = min(num_neighbors, idx.size)
        top = np.argsort(d, kind="stable")[:k]
        keep = np.zeros(X.shape[0], bool)
        keep[idx[top]] = True
        out = frame.filter(np.asarray(keep))
        dist_full = np.full(X.shape[0], np.nan)
        dist_full[idx] = d
        return out.with_column(dist_col,
                               jnp.asarray(dist_full, float_dtype()))

    approxNearestNeighbors = approx_nearest_neighbors

    def approx_similarity_join(self, frame_a, frame_b, threshold: float,
                               dist_col: str = "distCol"):
        """All (a, b) pairs with distance ≤ threshold among candidates
        sharing a hash bucket in ANY table. Candidate planning reuses the
        vectorized numeric join plan per table; exact distances batch on
        device. Returns a Frame with ``idA``/``idB`` (source row positions
        among valid rows) + the distance column."""
        from ..frame.frame import _vector_join_plan

        Xa = _extract_matrix(frame_a, self.input_col)
        Xb = _extract_matrix(frame_b, self.input_col)
        self._validate(np.asarray(Xa), np.asarray(frame_a.mask))
        self._validate(np.asarray(Xb), np.asarray(frame_b.mask))
        ha = np.asarray(self._hashes(Xa), np.int64)
        hb = np.asarray(self._hashes(Xb), np.int64)
        ia = np.nonzero(np.asarray(frame_a.mask))[0]
        ib = np.nonzero(np.asarray(frame_b.mask))[0]

        # plan over COMPACT positions (0..n_valid-1): idA/idB then index
        # the frames' valid rows directly (the to_pydict() order)
        pos_a = np.arange(ia.size)
        pos_b = np.arange(ib.size)
        lps, rps = [], []
        for t in range(ha.shape[1]):
            plan = _vector_join_plan([ha[ia, t]], [hb[ib, t]], pos_a,
                                     pos_b, "inner")
            if plan is not None:
                lps.append(plan[0])
                rps.append(plan[1])
        lp = np.concatenate(lps) if lps else np.zeros((0,), np.int64)
        rp = np.concatenate(rps) if rps else np.zeros((0,), np.int64)
        if lp.size == 0:
            from ..frame import Frame

            return Frame({"idA": np.zeros((0,), np.int64),
                          "idB": np.zeros((0,), np.int64),
                          dist_col: np.zeros((0,), np.float64)})
        # dedupe across tables in one vectorized pass (a Python tuple-set
        # would be interpreter-bound exactly when buckets are skewed)
        nb = int(rp.max()) + 1
        uniq = np.unique(lp * np.int64(nb) + rp)
        pa, pb = uniq // nb, uniq % nb
        d = host_fetch(self._distance_rows(Xa[jnp.asarray(ia[pa])],
                                           Xb[jnp.asarray(ib[pb])]))
        keep = d <= threshold
        from ..frame import Frame

        return Frame({"idA": pa[keep].astype(np.int64),
                      "idB": pb[keep].astype(np.int64),
                      dist_col: d[keep].astype(np.float64)})

    approxSimilarityJoin = approx_similarity_join


# ---------------------------------------------------------------------------
# BucketedRandomProjectionLSH (Euclidean)
# ---------------------------------------------------------------------------

@persistable
class BucketedRandomProjectionLSH(Estimator, _LSHParams):
    """Euclidean-distance LSH: ``h_l(x) = floor(x·w_l / bucketLength)`` for
    ``num_hash_tables`` Gaussian unit directions ``w_l``."""

    _persist_attrs = ('bucket_length', 'num_hash_tables', 'seed',
                      'input_col', 'output_col')

    def __init__(self, bucket_length: float = None,
                 num_hash_tables: int = 1, seed: int = 0,
                 input_col: str = "features", output_col: str = "hashes"):
        if bucket_length is not None and bucket_length <= 0:
            raise ValueError("bucket_length must be > 0")
        self.bucket_length = bucket_length
        self.num_hash_tables = self._check_tables(num_hash_tables)
        self.seed = int(seed)
        self.input_col = input_col
        self.output_col = output_col

    def set_bucket_length(self, v):
        if v <= 0:
            raise ValueError("bucket_length must be > 0")
        self.bucket_length = float(v)
        return self

    setBucketLength = set_bucket_length

    def fit(self, frame) -> "BucketedRandomProjectionLSHModel":
        if self.bucket_length is None:
            raise ValueError("bucket_length must be set")
        X = _extract_matrix(frame, self.input_col)
        d = X.shape[1]
        rng = np.random.default_rng(self.seed)
        W = rng.normal(size=(d, self.num_hash_tables))
        W /= np.linalg.norm(W, axis=0, keepdims=True)   # unit directions
        return BucketedRandomProjectionLSHModel(
            W.astype(np.float64), float(self.bucket_length),
            self.input_col, self.output_col)


@persistable
class BucketedRandomProjectionLSHModel(_LSHModelBase):
    _persist_attrs = ('projections', 'bucket_length', 'input_col',
                      'output_col')

    def __init__(self, projections, bucket_length, input_col="features",
                 output_col="hashes"):
        self.projections = np.asarray(projections)
        self.bucket_length = float(bucket_length)
        self.input_col = input_col
        self.output_col = output_col

    def _hashes(self, X):
        W = jnp.asarray(self.projections, X.dtype)
        return jnp.floor((X @ W) / self.bucket_length).astype(jnp.int32)

    def _distance(self, A, key):
        return jnp.sqrt(jnp.sum((A - key[None, :]) ** 2, axis=1))

    def _distance_rows(self, A, B):
        return jnp.sqrt(jnp.sum((A - B) ** 2, axis=1))


# ---------------------------------------------------------------------------
# MinHashLSH (Jaccard, binary vectors)
# ---------------------------------------------------------------------------

@persistable
class MinHashLSH(Estimator, _LSHParams):
    """Jaccard-distance LSH over binary vectors:
    ``h_l(x) = min over nonzero j of ((a_l·(j+1) + b_l) mod prime)``
    (MLlib's 1-indexed perfect-hash family)."""

    _persist_attrs = ('num_hash_tables', 'seed', 'input_col', 'output_col')

    def __init__(self, num_hash_tables: int = 1, seed: int = 0,
                 input_col: str = "features", output_col: str = "hashes"):
        self.num_hash_tables = self._check_tables(num_hash_tables)
        self.seed = int(seed)
        self.input_col = input_col
        self.output_col = output_col

    def fit(self, frame) -> "MinHashLSHModel":
        X = _extract_matrix(frame, self.input_col)
        Xh = np.asarray(X)
        valid = np.asarray(frame.mask)
        if not np.all((Xh[valid] == 0) | (Xh[valid] == 1)):
            raise ValueError("MinHashLSH requires binary 0/1 vectors")
        if np.any(Xh[valid].sum(axis=1) == 0):
            raise ValueError("MinHashLSH: every valid vector needs at "
                             "least one nonzero entry")
        rng = np.random.default_rng(self.seed)
        a = rng.integers(1, _MINHASH_PRIME, size=self.num_hash_tables)
        b = rng.integers(0, _MINHASH_PRIME, size=self.num_hash_tables)
        return MinHashLSHModel(a.astype(np.int64), b.astype(np.int64),
                               self.input_col, self.output_col)


@persistable
class MinHashLSHModel(_LSHModelBase):
    _persist_attrs = ('coeff_a', 'coeff_b', 'input_col', 'output_col')

    def _validate(self, X, mask=None):
        """MinHash of the empty set is undefined (MLlib raises too) — an
        all-zero vector would hash to the sentinel in every table and
        collide with every other empty vector."""
        nz = np.asarray(X).sum(axis=1) > 0
        if mask is not None:
            nz = nz | ~np.asarray(mask)
        if not np.all(nz):
            raise ValueError("MinHashLSH: vectors must have at least one "
                             "nonzero entry")

    def __init__(self, coeff_a, coeff_b, input_col="features",
                 output_col="hashes"):
        self.coeff_a = np.asarray(coeff_a, np.int64)
        self.coeff_b = np.asarray(coeff_b, np.int64)
        self.input_col = input_col
        self.output_col = output_col

    def _hashes(self, X):
        d = X.shape[1]
        j = np.arange(1, d + 1, dtype=np.int64)            # 1-indexed
        hv = (self.coeff_a[:, None] * j[None, :]
              + self.coeff_b[:, None]) % _MINHASH_PRIME     # (L, d)
        # int32 masked min — float32 would collapse ids above 2^24
        hvd = jnp.asarray(hv, jnp.int32)
        big = jnp.asarray(np.int32(_MINHASH_PRIME))
        masked = jnp.where(X[:, None, :] > 0, hvd[None, :, :], big)
        return jnp.min(masked, axis=2)                     # (n, L) int32

    def _jaccard_dist(self, A, B):
        inter = jnp.sum((A > 0) & (B > 0), axis=1)
        union = jnp.sum((A > 0) | (B > 0), axis=1)
        return 1.0 - inter / jnp.maximum(union, 1)

    def _distance(self, A, key):
        return self._jaccard_dist(A, key[None, :])

    def _distance_rows(self, A, B):
        return self._jaccard_dist(A, B)
