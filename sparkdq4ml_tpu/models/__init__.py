from .base import Estimator, Model, Pipeline, PipelineModel, Transformer
from .feature import VectorAssembler
from .linalg import Vectors
from .regression import (LinearRegression, LinearRegressionModel,
                         LinearRegressionSummary,
                         LinearRegressionTrainingSummary)
