from .base import Estimator, Model, Pipeline, PipelineModel, Transformer
from .classification import (BinaryLogisticRegressionSummary,
                             BinaryLogisticRegressionTrainingSummary,
                             LinearSVC, LinearSVCModel,
                             LogisticRegression, LogisticRegressionModel,
                             LogisticRegressionSummary,
                             LogisticRegressionTrainingSummary,
                             NaiveBayes, NaiveBayesModel, OneVsRest,
                             OneVsRestModel)
from .clustering import (BisectingKMeans, BisectingKMeansModel,
                         GaussianMixture, GaussianMixtureModel,
                         GaussianMixtureSummary, KMeans, KMeansModel,
                         KMeansSummary, PowerIterationClustering)
from .lda import LDA, LDAModel
from .evaluation import (BinaryClassificationEvaluator, ClusteringEvaluator,
                         Evaluator, MulticlassClassificationEvaluator,
                         RegressionEvaluator)
from .feature import (Binarizer, Bucketizer, ChiSqSelector,
                      ChiSqSelectorModel, DCT, ElementwiseProduct,
                      FeatureHasher, Imputer, ImputerModel,
                      IndexToString, Interaction, MaxAbsScaler,
                      MaxAbsScalerModel, MinMaxScaler, MinMaxScalerModel,
                      Normalizer, OneHotEncoder, OneHotEncoderEstimator,
                      OneHotEncoderModel, PCA,
                      PCAModel, PolynomialExpansion, QuantileDiscretizer,
                      RFormula, RFormulaModel, RobustScaler,
                      RobustScalerModel, SQLTransformer,
                      StandardScaler, StandardScalerModel, StringIndexer,
                      StringIndexerModel, VectorAssembler, VectorIndexer,
                      VectorIndexerModel, VectorSizeHint, VectorSlicer,
                      UnivariateFeatureSelector,
                      UnivariateFeatureSelectorModel,
                      VarianceThresholdSelector,
                      VarianceThresholdSelectorModel)
from .glm import (GeneralizedLinearRegression,
                  GeneralizedLinearRegressionModel, GlmTrainingSummary)
from .linalg import Matrices, Vectors
from .stat import (ChiSquareTest, Correlation, KolmogorovSmirnovTest,
                   Summarizer)
from .text import (CountVectorizer, CountVectorizerModel, HashingTF, IDF,
                   IDFModel, NGram, RegexTokenizer, StopWordsRemover,
                   Tokenizer)
from .tree import (DecisionTreeClassificationModel, DecisionTreeClassifier,
                   DecisionTreeRegressionModel, DecisionTreeRegressor,
                   GBTClassificationModel, GBTClassifier,
                   GBTRegressionModel, GBTRegressor,
                   RandomForestClassificationModel, RandomForestClassifier,
                   RandomForestRegressionModel, RandomForestRegressor)
from .recommendation import ALS, ALSModel
from .regression import (IsotonicRegression, IsotonicRegressionModel,
                         LinearRegression, LinearRegressionModel,
                         LinearRegressionSummary,
                         LinearRegressionTrainingSummary)
from .survival import AFTSurvivalRegression, AFTSurvivalRegressionModel
from .tuning import (CrossValidator, CrossValidatorModel, ParamGridBuilder,
                     TrainValidationSplit, TrainValidationSplitModel)
from .fm import (FMClassificationModel, FMClassifier, FMRegressionModel,
                 FMRegressor)
from .fpm import FPGrowth, FPGrowthModel, PrefixSpan
from .mlp import (MultilayerPerceptronClassificationModel,
                  MultilayerPerceptronClassifier)
from .lsh import (BucketedRandomProjectionLSH,
                  BucketedRandomProjectionLSHModel, MinHashLSH,
                  MinHashLSHModel)
from .word2vec import Word2Vec, Word2VecModel
