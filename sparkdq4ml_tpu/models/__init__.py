from .base import Estimator, Model, Pipeline, PipelineModel, Transformer
from .classification import (BinaryLogisticRegressionSummary,
                             BinaryLogisticRegressionTrainingSummary,
                             LogisticRegression, LogisticRegressionModel)
from .evaluation import (BinaryClassificationEvaluator, Evaluator,
                         MulticlassClassificationEvaluator,
                         RegressionEvaluator)
from .feature import (Bucketizer, IndexToString, MaxAbsScaler,
                      MaxAbsScalerModel, MinMaxScaler, MinMaxScalerModel,
                      OneHotEncoder, OneHotEncoderModel, StandardScaler,
                      StandardScalerModel, StringIndexer, StringIndexerModel,
                      VectorAssembler)
from .linalg import Vectors
from .stat import Correlation, Summarizer
from .regression import (LinearRegression, LinearRegressionModel,
                         LinearRegressionSummary,
                         LinearRegressionTrainingSummary)
from .tuning import (CrossValidator, CrossValidatorModel, ParamGridBuilder,
                     TrainValidationSplit, TrainValidationSplitModel)
