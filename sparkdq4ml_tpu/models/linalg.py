"""``org.apache.spark.ml.linalg.Vectors`` equivalent — host-side helpers for
single-point inference (`DataQuality4MachineLearningApp.java:150`)."""

from __future__ import annotations

import numpy as np

from ..config import float_dtype


class Vectors:
    @staticmethod
    def dense(*values) -> np.ndarray:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            values = values[0]
        return np.asarray(values, dtype=np.dtype(float_dtype()))
