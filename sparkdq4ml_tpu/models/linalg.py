"""``org.apache.spark.ml.linalg.Vectors`` equivalent — host-side helpers for
single-point inference (`DataQuality4MachineLearningApp.java:150`)."""

from __future__ import annotations

import numpy as np

from ..config import float_dtype


class Vectors:
    @staticmethod
    def dense(*values) -> np.ndarray:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            values = values[0]
        return np.asarray(values, dtype=np.dtype(float_dtype()))


class Matrices:
    """``org.apache.spark.ml.linalg.Matrices`` equivalent (dense only —
    the engine's matrices are dense HBM arrays by design)."""

    @staticmethod
    def dense(num_rows: int, num_cols: int, values) -> np.ndarray:
        arr = np.asarray(values, dtype=np.dtype(float_dtype()))
        if arr.size != num_rows * num_cols:
            raise ValueError(
                f"{arr.size} values for a {num_rows}x{num_cols} matrix")
        # Spark's Matrices.dense is column-major
        return arr.reshape(num_cols, num_rows).T
