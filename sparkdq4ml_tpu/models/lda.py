"""Latent Dirichlet Allocation (MLlib ``org.apache.spark.ml.clustering.LDA``
equivalent — part of the mllib dependency surface the reference pulls,
`/root/reference/pom.xml:29-32`; the app itself fits only LinearRegression,
`DataQuality4MachineLearningApp.java:120-126`).

TPU-first design — variational inference is matmuls:

* **Documents are a dense ``(n, V)`` count matrix in HBM** (the output of
  CountVectorizer/HashingTF). The variational E-step for a whole batch is
  three MXU matmuls per inner iteration:
  ``phinorm = expElogtheta @ expElogbeta`` (n, V),
  ``gamma = alpha + expElogtheta * ((cnts / phinorm) @ expElogbetaᵀ)``,
  and the sufficient statistics ``sstats = expElogthetaᵀ @ (cnts/phinorm)``
  — no per-token sampling, no sparse gather/scatter hot loop. This is the
  Hoffman/Blei/Bach online VB formulation, the same algorithm MLlib's
  ``optimizer="online"`` implements.
* **The whole fit is one jit.** The outer iteration loop is a
  ``lax.scan`` carrying ``(lambda, key)``; each step samples a fixed-size
  minibatch (static shapes — the engine never re-traces), runs the fixed
  inner E-step loop, and applies the online M-step
  ``lambda ← (1−ρ_t)·lambda + ρ_t·(eta + (D/B)·sstats)`` with
  ``ρ_t = (offset + t)^−decay``. Zero host round-trips per iteration —
  MLlib's per-iteration RDD ``sample()``+``treeAggregate`` barrier
  disappears.
* **``optimizer="em"``** runs the same E-step over the FULL batch with
  ``ρ = 1`` (batch variational EM, the deterministic limit of online VB) —
  the TPU-native analogue of mllib's GraphX-based EM: identical
  estimator/model surface, deterministic given the seed, and the natural
  target for mesh sharding.
* **Distributed = psum.** Under a mesh the batch rows are sharded on the
  data axis inside ``shard_map``; the per-iteration ``(k, V)`` sufficient
  statistics reduce with one ``jax.lax.psum`` over ICI — the
  ``treeAggregate`` replacement (SURVEY.md §3.3), exactly the shape of the
  linear fit's Gramian reduction.
* **Masked rows never vote**: counts are pre-multiplied by the validity
  mask, so filtered rows contribute zero tokens to every statistic.

``logLikelihood``/``logPerplexity`` are the standard variational lower
bound (ELBO) and its negation per token, the same quantities Spark's local
model reports.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import digamma, gammaln
from jax.sharding import PartitionSpec as P

from ..config import float_dtype
from ..frame import Frame
from ..parallel.mesh import (DATA_AXIS, normalize_mesh,
                             serialize_collectives, shard_map)
from .base import Estimator, Model, host_fetch, persistable

_EPS = 1e-30


def _dirichlet_expectation(a):
    """E[log x] for x ~ Dir(a), rows of ``a`` (…, m)."""
    return digamma(a) - digamma(jnp.sum(a, axis=-1, keepdims=True))


def _e_step(cnts, expElogbeta, alpha, inner_iter):
    """Batch variational E-step: returns (gamma, sstats_unscaled).

    ``sstats_unscaled`` must be multiplied by ``expElogbeta`` by the
    caller (Hoffman's formulation keeps the factorization so the (k, V)
    product happens once)."""
    n = cnts.shape[0]
    k = expElogbeta.shape[0]
    gamma0 = jnp.ones((n, k), cnts.dtype)

    def body(gamma, _):
        expElogtheta = jnp.exp(_dirichlet_expectation(gamma))     # (n, k)
        phinorm = expElogtheta @ expElogbeta + _EPS               # (n, V)
        gamma_new = alpha + expElogtheta * ((cnts / phinorm)
                                            @ expElogbeta.T)
        return gamma_new, None

    gamma, _ = jax.lax.scan(body, gamma0, None, length=inner_iter)
    expElogtheta = jnp.exp(_dirichlet_expectation(gamma))
    sstats = expElogtheta.T @ (cnts / (expElogtheta @ expElogbeta + _EPS))
    return gamma, sstats                                          # (k, V)


@functools.lru_cache(maxsize=None)
def _online_fit_fn(mesh, n_total: int, batch: int, k: int, vocab: int,
                   max_iter: int, inner_iter: int, alpha: float, eta: float,
                   offset: float, decay: float, em: bool):
    """The whole LDA fit as one jitted program (cached per configuration).

    ``em=True``: full-batch deterministic VB (ρ=1, batch = all rows).
    Otherwise: online VB over uniformly sampled fixed-size minibatches.
    Under a mesh, the E-step rows are sharded and sstats psum-reduced."""
    dt = float_dtype()
    use_mesh = mesh is not None and mesh.devices.size > 1

    def sharded_sstats(cnts_b, expElogbeta):
        if not use_mesh:
            return _e_step(cnts_b, expElogbeta, alpha, inner_iter)[1]

        def local(c_shard, beta_rep):
            s = _e_step(c_shard, beta_rep, alpha, inner_iter)[1]
            return jax.lax.psum(s, DATA_AXIS)

        return shard_map(
            local, mesh=mesh, in_specs=(P(DATA_AXIS), P()), out_specs=P(),
            check_vma=False)(cnts_b, expElogbeta)

    def fit(cnts, seed):
        def step(carry, t):
            lam, key = carry
            expElogbeta = jnp.exp(_dirichlet_expectation(lam))    # (k, V)
            if em:
                cnts_b = cnts
                scale = 1.0
                rho = jnp.asarray(1.0, dt)
            else:
                key, sub = jax.random.split(key)
                idx = jax.random.randint(sub, (batch,), 0, n_total)
                cnts_b = cnts[idx]
                scale = n_total / batch
                rho = jnp.power(offset + t + 1.0, -decay).astype(dt)
            sstats = sharded_sstats(cnts_b, expElogbeta) * expElogbeta
            lam_hat = eta + scale * sstats
            lam_new = (1.0 - rho) * lam + rho * lam_hat
            return (lam_new, key), None

        key = jax.random.PRNGKey(seed)
        key, init = jax.random.split(key)
        # Hoffman's init: lambda ~ Gamma(100, 1/100), breaks topic symmetry
        lam0 = jax.random.gamma(init, 100.0, (k, vocab)).astype(dt) / 100.0
        (lam, _), _ = jax.lax.scan(step, (lam0, key),
                                   jnp.arange(max_iter, dtype=dt))
        return lam

    return serialize_collectives(jax.jit(fit), mesh)


@functools.lru_cache(maxsize=None)
def _transform_fn(k: int, vocab: int, alpha: float, inner_iter: int):
    """Jitted inference: counts → normalized topic distribution."""
    def run(cnts, expElogbeta):
        gamma, _ = _e_step(cnts, expElogbeta, alpha, inner_iter)
        return gamma / jnp.sum(gamma, axis=1, keepdims=True)

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _bound_fn(k: int, vocab: int, alpha: float, eta: float, inner_iter: int):
    """Jitted variational lower bound (Hoffman's ``approx_bound``):
    E_q[log p(docs, θ, z | α, β)] − E_q[log q(θ, z)] + topic prior term."""
    def run(cnts, lam, mask):
        Elogbeta = _dirichlet_expectation(lam)                    # (k, V)
        gamma, _ = _e_step(cnts, jnp.exp(Elogbeta), alpha, inner_iter)
        Elogtheta = _dirichlet_expectation(gamma)                 # (n, k)

        # token term: Σ_dw n_dw · log Σ_k exp(Elogtheta_dk + Elogbeta_kw)
        # via logsumexp over k. Scanned in fixed row chunks so peak memory
        # is O(chunk·k·V), not O(n·k·V) — n·k·V would be k× the fit's own
        # footprint and OOM exactly when the corpus is big enough to care.
        n = cnts.shape[0]
        chunk = min(n, 128)
        pad = (-n) % chunk
        cnts_p = jnp.concatenate(
            [cnts, jnp.zeros((pad, cnts.shape[1]), cnts.dtype)]) \
            if pad else cnts
        th_p = jnp.concatenate(
            [Elogtheta, jnp.zeros((pad, Elogtheta.shape[1]),
                                  Elogtheta.dtype)]) \
            if pad else Elogtheta

        def chunk_term(carry, ck):
            c, th = ck                                        # (chunk, V/k)
            m = th[:, :, None] + Elogbeta[None, :, :]         # (chunk, k, V)
            mmax = jnp.max(m, axis=1)
            t = jnp.sum(c * (mmax + jnp.log(
                jnp.sum(jnp.exp(m - mmax[:, None, :]), axis=1) + _EPS)))
            return carry + t, None

        token, _ = jax.lax.scan(
            chunk_term, jnp.asarray(0.0, cnts.dtype),
            (cnts_p.reshape(-1, chunk, cnts.shape[1]),
             th_p.reshape(-1, chunk, Elogtheta.shape[1])))

        # theta prior/entropy term per doc
        th = (jnp.sum((alpha - gamma) * Elogtheta, axis=1)
              + jnp.sum(gammaln(gamma), axis=1)
              - gammaln(jnp.sum(gamma, axis=1))
              + gammaln(jnp.asarray(alpha * k, gamma.dtype))
              - k * gammaln(jnp.asarray(alpha, gamma.dtype)))
        theta_term = jnp.sum(jnp.where(mask, th, 0.0))

        # beta prior/entropy term (document-count independent)
        beta_term = (jnp.sum((eta - lam) * Elogbeta)
                     + jnp.sum(gammaln(lam))
                     - jnp.sum(gammaln(jnp.sum(lam, axis=1)))
                     + k * (gammaln(jnp.asarray(eta * vocab, lam.dtype))
                            - vocab * gammaln(jnp.asarray(eta, lam.dtype))))
        return token + theta_term + beta_term

    return jax.jit(run)


@persistable
class LDA(Estimator):
    """MLlib ``LDA`` surface: ``setK/setMaxIter/setOptimizer/
    setDocConcentration/setTopicConcentration/setSubsamplingRate/
    setLearningOffset/setLearningDecay/setSeed/setFeaturesCol/
    setTopicDistributionCol`` + ``fit(frame[, mesh])``.

    ``doc_concentration``/``topic_concentration`` accept MLlib's ``auto``
    default (−1 → 1/k, the online-optimizer default). The online
    optimizer samples fixed-size minibatches WITH replacement (static
    shapes for the scan; statistically equivalent to mllib's Bernoulli
    ``sample()`` at the same expected batch size).
    ``optimize_doc_concentration`` is not supported (alpha stays fixed,
    as in sklearn's implementation) and raises if enabled.
    """

    _persist_attrs = ('k', 'max_iter', 'optimizer', 'doc_concentration',
                      'topic_concentration', 'subsampling_rate',
                      'learning_offset', 'learning_decay', 'seed',
                      'inner_iter', 'features_col', 'topic_distribution_col')

    def __init__(self, k: int = 10, max_iter: int = 20,
                 optimizer: str = "online",
                 doc_concentration: float = -1.0,
                 topic_concentration: float = -1.0,
                 subsampling_rate: float = 0.05,
                 learning_offset: float = 1024.0,
                 learning_decay: float = 0.51,
                 optimize_doc_concentration: bool = False,
                 seed: int = 0, inner_iter: int = 50,
                 features_col: str = "features",
                 topic_distribution_col: str = "topicDistribution"):
        if k < 2:
            raise ValueError("k must be >= 2")
        if optimizer not in ("online", "em"):
            raise ValueError(f"optimizer must be online or em, "
                             f"got {optimizer!r}")
        if optimize_doc_concentration:
            raise ValueError(
                "optimize_doc_concentration is not supported: alpha stays "
                "fixed (set doc_concentration explicitly instead)")
        if not (0.0 < subsampling_rate <= 1.0):
            raise ValueError("subsampling_rate must be in (0, 1]")
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.optimizer = optimizer
        self.doc_concentration = float(doc_concentration)
        self.topic_concentration = float(topic_concentration)
        self.subsampling_rate = float(subsampling_rate)
        self.learning_offset = float(learning_offset)
        self.learning_decay = float(learning_decay)
        self.seed = int(seed)
        self.inner_iter = int(inner_iter)
        self.features_col = features_col
        self.topic_distribution_col = topic_distribution_col

    def set_k(self, v):
        if v < 2:
            raise ValueError("k must be >= 2")
        self.k = int(v)
        return self

    setK = set_k

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_optimizer(self, v):
        if v not in ("online", "em"):
            raise ValueError(f"optimizer must be online or em, got {v!r}")
        self.optimizer = v
        return self

    setOptimizer = set_optimizer

    def set_doc_concentration(self, v):
        self.doc_concentration = float(v)
        return self

    setDocConcentration = set_doc_concentration

    def set_topic_concentration(self, v):
        self.topic_concentration = float(v)
        return self

    setTopicConcentration = set_topic_concentration

    def set_subsampling_rate(self, v):
        if not (0.0 < v <= 1.0):
            raise ValueError("subsampling_rate must be in (0, 1]")
        self.subsampling_rate = float(v)
        return self

    setSubsamplingRate = set_subsampling_rate

    def set_learning_offset(self, v):
        self.learning_offset = float(v)
        return self

    setLearningOffset = set_learning_offset

    def set_learning_decay(self, v):
        self.learning_decay = float(v)
        return self

    setLearningDecay = set_learning_decay

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_topic_distribution_col(self, v):
        self.topic_distribution_col = v
        return self

    setTopicDistributionCol = set_topic_distribution_col

    def _alpha_eta(self):
        alpha = (1.0 / self.k if self.doc_concentration <= 0
                 else self.doc_concentration)
        eta = (1.0 / self.k if self.topic_concentration <= 0
               else self.topic_concentration)
        return float(alpha), float(eta)

    def fit(self, frame: Frame, mesh=None) -> "LDAModel":
        dt = float_dtype()
        cnts = jnp.asarray(frame._column_values(self.features_col), dt)
        if cnts.ndim != 2:
            raise ValueError("LDA features must be a vector column of "
                             "term counts (CountVectorizer/HashingTF)")
        # masked rows carry no tokens; np.where (not multiply) so NaN
        # payloads in masked slots cannot poison the statistics (0·NaN=NaN)
        mask = jnp.asarray(frame.mask)
        cnts = jnp.where(mask[:, None], cnts, jnp.asarray(0.0, dt))
        n, vocab = int(cnts.shape[0]), int(cnts.shape[1])
        alpha, eta = self._alpha_eta()

        mesh = normalize_mesh(mesh)
        ndev = 1 if mesh is None else mesh.devices.size
        em = self.optimizer == "em"
        if em:
            batch = n
        else:
            batch = max(1, int(round(self.subsampling_rate * n)))
        batch += (-batch) % ndev               # shardable minibatch
        if em and batch != n:
            pad = batch - n
            cnts = jnp.concatenate([cnts, jnp.zeros((pad, vocab), dt)])

        fit = _online_fit_fn(mesh if ndev > 1 else None, n, batch, self.k,
                             vocab, self.max_iter, self.inner_iter, alpha,
                             eta, self.learning_offset, self.learning_decay,
                             em)
        lam = fit(cnts, self.seed)
        return LDAModel(topics=np.asarray(lam), params=dict(
            k=self.k, vocab_size=vocab, alpha=alpha, eta=eta,
            optimizer=self.optimizer, inner_iter=self.inner_iter,
            features_col=self.features_col,
            topic_distribution_col=self.topic_distribution_col,
            training_docs=n))


@persistable
class LDAModel(Model):
    """Fitted LDA: ``topicsMatrix`` (V × k, column-normalized topic-word
    expectation, Spark's layout), ``describeTopics``, ``transform`` (adds
    the topic-distribution vector column), ``logLikelihood`` (variational
    lower bound) and ``logPerplexity`` (−bound per token)."""

    _persist_attrs = ('topics', '_params')

    def __init__(self, topics: np.ndarray = None, params: dict = None):
        self.topics = np.asarray(topics)       # (k, V) variational lambda
        self._params = dict(params or {})

    @property
    def vocab_size(self):
        return int(self._params["vocab_size"])

    vocabSize = vocab_size

    @property
    def is_distributed(self):
        return False                            # local model semantics

    isDistributed = is_distributed

    @property
    def estimated_doc_concentration(self):
        return np.full(int(self._params["k"]), self._params["alpha"])

    estimatedDocConcentration = estimated_doc_concentration

    def topics_matrix(self) -> np.ndarray:
        """(V, k): topic-word expectation E[beta], column per topic
        (Spark's ``topicsMatrix`` orientation), columns sum to 1."""
        beta = self.topics / self.topics.sum(axis=1, keepdims=True)
        return beta.T

    topicsMatrix = topics_matrix

    def describe_topics(self, max_terms_per_topic: int = 10) -> Frame:
        beta = self.topics / self.topics.sum(axis=1, keepdims=True)
        k = beta.shape[0]
        top = np.argsort(-beta, axis=1)[:, :max_terms_per_topic]
        weights = np.take_along_axis(beta, top, axis=1)
        return Frame({
            "topic": np.arange(k, dtype=np.int64),
            "termIndices": top.astype(np.int64),
            "termWeights": weights,
        })

    describeTopics = describe_topics

    def _expElogbeta(self):
        return jnp.exp(_dirichlet_expectation(
            jnp.asarray(self.topics, float_dtype())))

    def transform(self, frame: Frame) -> Frame:
        p = self._params
        cnts = jnp.asarray(frame._column_values(p["features_col"]),
                           float_dtype())
        run = _transform_fn(int(p["k"]), int(p["vocab_size"]),
                            float(p["alpha"]), int(p["inner_iter"]))
        theta = run(cnts, self._expElogbeta())
        return frame.with_column(p["topic_distribution_col"], theta)

    def log_likelihood(self, frame: Frame) -> float:
        p = self._params
        cnts = jnp.asarray(frame._column_values(p["features_col"]),
                           float_dtype())
        mask = jnp.asarray(frame.mask)
        cnts = jnp.where(mask[:, None], cnts,
                         jnp.asarray(0.0, cnts.dtype))
        run = _bound_fn(int(p["k"]), int(p["vocab_size"]),
                        float(p["alpha"]), float(p["eta"]),
                        int(p["inner_iter"]))
        return float(host_fetch(run(cnts, jnp.asarray(self.topics,
                                                      cnts.dtype), mask)))

    logLikelihood = log_likelihood

    def log_perplexity(self, frame: Frame) -> float:
        p = self._params
        d = np.asarray(frame._column_values(p["features_col"]), np.float64)
        tokens = float(np.where(np.asarray(frame.mask)[:, None],
                                d, 0.0).sum())
        if tokens == 0:
            raise ValueError("log_perplexity: no tokens in the dataset")
        return -self.log_likelihood(frame) / tokens

    logPerplexity = log_perplexity
