"""Clustering: TPU-native KMeans (MLlib ``org.apache.spark.ml.clustering``
equivalent — a capability upgrade; the reference app itself fits only
LinearRegression, `DataQuality4MachineLearningApp.java:120-126`, but its
MLlib dependency ships the clustering package and an estimator/model surface
identical to this one).

TPU-first design:

* **Lloyd's step is matmuls.** Squared distances use the expansion
  ‖x−c‖² = ‖x‖² − 2·x·cᵀ + ‖c‖², so the (n, k) distance matrix is one MXU
  matmul per iteration; the center update is the transposed one-hot matmul
  ``assignᵀ·X`` — also MXU. No per-row Python, no dynamic shapes.
* **The whole fit is one jit.** The iteration loop is a
  ``lax.while_loop`` (converged-or-max-iter) carrying the (k, d) centers;
  zero host round-trips per iteration — MLlib's per-iteration
  ``collectAsMap``/broadcast barrier disappears.
* **Distributed = psum.** Under a mesh, rows are sharded on the data axis
  inside ``shard_map``; the per-iteration sufficient statistics (one-hot
  sums and counts) reduce with ``jax.lax.psum`` over ICI — the
  ``treeAggregate`` replacement, same shape as the linear fit's Gramian
  reduction (SURVEY.md §3.3).
* **Masked rows never vote.** All statistics are mask-weighted; empty
  clusters keep their previous center (Spark keeps stale centers likewise).

Init: ``k-means++`` greedy seeding on the host (a one-time, data-dependent
sequential scan — not a device hot loop), or ``random`` distinct rows.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import float_dtype
from ..frame import Frame
from ..parallel.mesh import DATA_AXIS
from .base import Estimator, Model, persistable


def _lloyd_step(X, w, centers):
    """One Lloyd iteration's local sufficient statistics.

    Returns (per-cluster weighted coordinate sums, per-cluster weights,
    local weighted SSE) for masked rows X with weights w against the
    replicated (k, d) centers. All matmul-shaped for the MXU.
    """
    x_sq = jnp.sum(X * X, axis=1, keepdims=True)          # (n, 1)
    c_sq = jnp.sum(centers * centers, axis=1)             # (k,)
    d2 = x_sq - 2.0 * (X @ centers.T) + c_sq[None, :]     # (n, k) one matmul
    assign = jnp.argmin(d2, axis=1)                       # (n,)
    onehot = jax.nn.one_hot(assign, centers.shape[0],
                            dtype=X.dtype) * w[:, None]   # (n, k) masked
    sums = onehot.T @ X                                   # (k, d) MXU
    counts = jnp.sum(onehot, axis=0)                      # (k,)
    best = jnp.min(d2, axis=1)
    cost = jnp.sum(jnp.maximum(best, 0.0) * w)
    return sums, counts, cost


def _make_fit(mesh, k, max_iter, tol):
    """Build the jitted full KMeans fit: while_loop of psum'd Lloyd steps."""

    if mesh is None:
        def stats(X, w, centers):
            return _lloyd_step(X, w, centers)
    else:
        def local(X, w, centers):
            s, c, cost = _lloyd_step(X, w, centers)
            return (jax.lax.psum(s, DATA_AXIS), jax.lax.psum(c, DATA_AXIS),
                    jax.lax.psum(cost, DATA_AXIS))

        stats = jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
            out_specs=(P(), P(), P()))

    def fit(X, w, centers0):
        def body(carry):
            centers, _, it, _ = carry
            sums, counts, cost = stats(X, w, centers)
            safe = jnp.maximum(counts, 1e-12)[:, None]
            new = jnp.where(counts[:, None] > 0, sums / safe, centers)
            shift = jnp.max(jnp.sum((new - centers) ** 2, axis=1))
            return (new, cost, it + 1, shift)

        def cond(carry):
            _, _, it, shift = carry
            return jnp.logical_and(it < max_iter, shift > tol * tol)

        init = (centers0, jnp.asarray(jnp.inf, X.dtype),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, X.dtype))
        centers, cost, iters, _ = jax.lax.while_loop(cond, body, init)
        # one final stats pass so the reported cost matches the final centers
        _, counts, cost = stats(X, w, centers)
        return centers, cost, iters, counts

    return jax.jit(fit)


@functools.lru_cache(maxsize=None)
def _fit_cached(mesh, k, max_iter, tol):
    return _make_fit(mesh, k, max_iter, tol)


def _kmeans_pp_init(X, w, k, rng):
    """Greedy k-means++ seeding (host): first center uniform over valid
    rows, then each next center sampled ∝ current squared distance."""
    valid = np.flatnonzero(w > 0)
    if len(valid) < k:
        raise ValueError(f"k={k} exceeds the {len(valid)} valid rows")
    centers = [X[rng.choice(valid)]]
    d2 = None
    for _ in range(k - 1):
        diff = X[valid] - centers[-1]
        nd2 = np.sum(diff * diff, axis=1)
        d2 = nd2 if d2 is None else np.minimum(d2, nd2)
        total = d2.sum()
        if total <= 0:          # all remaining mass at existing centers
            extra = rng.choice(valid, size=k - len(centers), replace=False)
            centers.extend(X[i] for i in extra)
            break
        centers.append(X[valid[rng.choice(len(valid), p=d2 / total)]])
    return np.stack(centers[:k])


@persistable
class KMeans(Estimator):
    """MLlib ``KMeans`` surface: ``setK/setMaxIter/setTol/setSeed/
    setInitMode/setFeaturesCol/setPredictionCol`` + ``fit(frame[, mesh])``."""

    _persist_attrs = ('k', 'max_iter', 'tol', 'seed', 'init_mode',
                      'features_col', 'prediction_col')

    def __init__(self, k: int = 2, max_iter: int = 20, tol: float = 1e-4,
                 seed: int = 0, init_mode: str = "k-means||",
                 features_col: str = "features",
                 prediction_col: str = "prediction"):
        if k < 1:
            raise ValueError("k must be >= 1")
        if init_mode not in ("k-means||", "k-means++", "random"):
            raise ValueError(f"init_mode={init_mode!r}")
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.seed = int(seed)
        self.init_mode = init_mode
        self.features_col = features_col
        self.prediction_col = prediction_col

    def set_k(self, v):
        if v < 1:
            raise ValueError("k must be >= 1")
        self.k = int(v)
        return self

    setK = set_k

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_tol(self, v):
        self.tol = float(v)
        return self

    setTol = set_tol

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def set_init_mode(self, v):
        if v not in ("k-means||", "k-means++", "random"):
            raise ValueError(f"init_mode={v!r}")
        self.init_mode = v
        return self

    setInitMode = set_init_mode

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setPredictionCol = set_prediction_col

    def get_k(self):
        return self.k

    getK = get_k

    def fit(self, frame: Frame, mesh=None) -> "KMeansModel":
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        w = np.asarray(frame.mask, dt)

        rng = np.random.default_rng(self.seed)
        if self.init_mode == "random":
            valid = np.flatnonzero(w > 0)
            if len(valid) < self.k:
                raise ValueError(
                    f"k={self.k} exceeds the {len(valid)} valid rows")
            centers0 = X[rng.choice(valid, size=self.k, replace=False)]
        else:  # k-means|| / k-means++ → greedy k-means++ seeding
            centers0 = _kmeans_pp_init(X, w, self.k, rng)

        if mesh is not None:
            n_shards = mesh.devices.size
            rem = (-X.shape[0]) % n_shards
            if rem:
                X = np.concatenate([X, np.zeros((rem, X.shape[1]), dt)])
                w = np.concatenate([w, np.zeros((rem,), dt)])
            shard = NamedSharding(mesh, P(DATA_AXIS))
            Xd = jax.device_put(X, shard)
            wd = jax.device_put(w, shard)
        else:
            Xd, wd = jnp.asarray(X), jnp.asarray(w)

        fit_fn = _fit_cached(mesh, self.k, self.max_iter, self.tol)
        centers, cost, iters, counts = jax.block_until_ready(
            fit_fn(Xd, wd, jnp.asarray(centers0)))
        return KMeansModel(np.asarray(centers), self.features_col,
                           self.prediction_col, float(cost), int(iters),
                           np.asarray(counts).astype(np.int64).tolist())


@persistable
class KMeansModel(Model):
    """Fitted centers + the MLlib model surface: ``transform`` (nearest
    center as the prediction column), ``clusterCenters``, ``summary``
    (cluster sizes, training cost, iterations), ``predict`` (host scalar
    path, like ``LinearRegressionModel.predict``)."""

    _persist_attrs = ('centers', 'features_col', 'prediction_col',
                      'training_cost', 'num_iters', 'cluster_sizes')

    def __init__(self, centers, features_col, prediction_col,
                 training_cost=float("nan"), num_iters=0,
                 cluster_sizes=None):
        self.centers = np.asarray(centers)
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.training_cost = training_cost
        self.num_iters = num_iters
        self.cluster_sizes = cluster_sizes or []

    def cluster_centers(self):
        return [c for c in self.centers]

    clusterCenters = cluster_centers

    @property
    def k(self):
        return self.centers.shape[0]

    def _distances(self, X):
        C = jnp.asarray(self.centers, X.dtype)
        x_sq = jnp.sum(X * X, axis=1, keepdims=True)
        c_sq = jnp.sum(C * C, axis=1)
        return x_sq - 2.0 * (X @ C.T) + c_sq[None, :]

    def transform(self, frame: Frame) -> Frame:
        X = jnp.asarray(frame._column_values(self.features_col),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        pred = jnp.argmin(self._distances(X), axis=1).astype(float_dtype())
        return frame.with_column(self.prediction_col, pred)

    def predict(self, features) -> int:
        x = np.asarray(features, np.dtype(float_dtype())).reshape(1, -1)
        return int(np.asarray(jnp.argmin(self._distances(jnp.asarray(x)))))

    def compute_cost(self, frame: Frame) -> float:
        """Weighted SSE to nearest center over valid rows (MLlib 2.x
        ``computeCost``)."""
        X = jnp.asarray(frame._column_values(self.features_col),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        w = frame.mask.astype(X.dtype)
        best = jnp.min(self._distances(X), axis=1)
        return float(jnp.sum(jnp.maximum(best, 0.0) * w))

    computeCost = compute_cost

    @property
    def summary(self):
        return KMeansSummary(self)

    @property
    def has_summary(self):
        return True

    hasSummary = has_summary


class KMeansSummary:
    """MLlib ``KMeansSummary``: k, cluster sizes, training cost, iterations."""

    def __init__(self, model: KMeansModel):
        self._model = model

    @property
    def k(self):
        return self._model.k

    @property
    def cluster_sizes(self):
        return list(self._model.cluster_sizes)

    clusterSizes = cluster_sizes

    @property
    def training_cost(self):
        return self._model.training_cost

    trainingCost = training_cost

    @property
    def num_iter(self):
        return self._model.num_iters

    numIter = num_iter
