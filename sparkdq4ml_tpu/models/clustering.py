"""Clustering: TPU-native KMeans (MLlib ``org.apache.spark.ml.clustering``
equivalent — a capability upgrade; the reference app itself fits only
LinearRegression, `DataQuality4MachineLearningApp.java:120-126`, but its
MLlib dependency ships the clustering package and an estimator/model surface
identical to this one).

TPU-first design:

* **Lloyd's step is matmuls.** Squared distances use the expansion
  ‖x−c‖² = ‖x‖² − 2·x·cᵀ + ‖c‖², so the (n, k) distance matrix is one MXU
  matmul per iteration; the center update is the transposed one-hot matmul
  ``assignᵀ·X`` — also MXU. No per-row Python, no dynamic shapes.
* **The whole fit is one jit.** The iteration loop is a
  ``lax.while_loop`` (converged-or-max-iter) carrying the (k, d) centers;
  zero host round-trips per iteration — MLlib's per-iteration
  ``collectAsMap``/broadcast barrier disappears.
* **Distributed = psum.** Under a mesh, rows are sharded on the data axis
  inside ``shard_map``; the per-iteration sufficient statistics (one-hot
  sums and counts) reduce with ``jax.lax.psum`` over ICI — the
  ``treeAggregate`` replacement, same shape as the linear fit's Gramian
  reduction (SURVEY.md §3.3).
* **Masked rows never vote.** All statistics are mask-weighted; empty
  clusters keep their previous center (Spark keeps stale centers likewise).

Init: ``k-means++`` greedy seeding on the host (a one-time, data-dependent
sequential scan — not a device hot loop), or ``random`` distinct rows.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import float_dtype
from ..frame import Frame
from ..parallel.mesh import (DATA_AXIS, normalize_mesh,
                             serialize_collectives, shard_map)
from .base import Estimator, Model, host_fetch, persistable


def _pad_and_shard(X, w, mesh, dt):
    """Zero-pad rows to the shard count and place (X, w) row-sharded —
    thin wrapper over the shared ``distributed.pad_and_shard_rows``."""
    from ..parallel.distributed import pad_and_shard_rows

    return pad_and_shard_rows(mesh, X, w)


def _lloyd_step(X, w, centers):
    """One Lloyd iteration's local sufficient statistics.

    Returns (per-cluster weighted coordinate sums, per-cluster weights,
    local weighted SSE) for masked rows X with weights w against the
    replicated (k, d) centers. All matmul-shaped for the MXU.
    """
    x_sq = jnp.sum(X * X, axis=1, keepdims=True)          # (n, 1)
    c_sq = jnp.sum(centers * centers, axis=1)             # (k,)
    d2 = x_sq - 2.0 * (X @ centers.T) + c_sq[None, :]     # (n, k) one matmul
    assign = jnp.argmin(d2, axis=1)                       # (n,)
    onehot = jax.nn.one_hot(assign, centers.shape[0],
                            dtype=X.dtype) * w[:, None]   # (n, k) masked
    sums = onehot.T @ X                                   # (k, d) MXU
    counts = jnp.sum(onehot, axis=0)                      # (k,)
    best = jnp.min(d2, axis=1)
    cost = jnp.sum(jnp.maximum(best, 0.0) * w)
    return sums, counts, cost


def _make_fit(mesh, k, max_iter, tol):
    """Build the jitted full KMeans fit: while_loop of psum'd Lloyd steps."""

    if mesh is None:
        def stats(X, w, centers):
            return _lloyd_step(X, w, centers)
    else:
        def local(X, w, centers):
            s, c, cost = _lloyd_step(X, w, centers)
            return (jax.lax.psum(s, DATA_AXIS), jax.lax.psum(c, DATA_AXIS),
                    jax.lax.psum(cost, DATA_AXIS))

        stats = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P()),
            out_specs=(P(), P(), P()))

    def fit(X, w, centers0):
        def body(carry):
            centers, _, it, _ = carry
            sums, counts, cost = stats(X, w, centers)
            safe = jnp.maximum(counts, 1e-12)[:, None]
            new = jnp.where(counts[:, None] > 0, sums / safe, centers)
            shift = jnp.max(jnp.sum((new - centers) ** 2, axis=1))
            return (new, cost, it + 1, shift)

        def cond(carry):
            _, _, it, shift = carry
            return jnp.logical_and(it < max_iter, shift > tol * tol)

        init = (centers0, jnp.asarray(jnp.inf, X.dtype),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, X.dtype))
        centers, cost, iters, _ = jax.lax.while_loop(cond, body, init)
        # one final stats pass so the reported cost matches the final centers
        _, counts, cost = stats(X, w, centers)
        return centers, cost, iters, counts

    return serialize_collectives(jax.jit(fit), mesh)


@functools.lru_cache(maxsize=None)
def _fit_cached(mesh, k, max_iter, tol):
    return _make_fit(mesh, k, max_iter, tol)


def _kmeans_pp_init(X, w, k, rng):
    """Greedy k-means++ seeding (host): first center uniform over valid
    rows, then each next center sampled ∝ current squared distance."""
    valid = np.flatnonzero(w > 0)
    if len(valid) < k:
        raise ValueError(f"k={k} exceeds the {len(valid)} valid rows")
    centers = [X[rng.choice(valid)]]
    d2 = None
    for _ in range(k - 1):
        diff = X[valid] - centers[-1]
        nd2 = np.sum(diff * diff, axis=1)
        d2 = nd2 if d2 is None else np.minimum(d2, nd2)
        total = d2.sum()
        if total <= 0:          # all remaining mass at existing centers
            extra = rng.choice(valid, size=k - len(centers), replace=False)
            centers.extend(X[i] for i in extra)
            break
        centers.append(X[valid[rng.choice(len(valid), p=d2 / total)]])
    return np.stack(centers[:k])


@persistable
class KMeans(Estimator):
    """MLlib ``KMeans`` surface: ``setK/setMaxIter/setTol/setSeed/
    setInitMode/setFeaturesCol/setPredictionCol`` + ``fit(frame[, mesh])``."""

    _persist_attrs = ('k', 'max_iter', 'tol', 'seed', 'init_mode',
                      'features_col', 'prediction_col')

    def __init__(self, k: int = 2, max_iter: int = 20, tol: float = 1e-4,
                 seed: int = 0, init_mode: str = "k-means||",
                 features_col: str = "features",
                 prediction_col: str = "prediction"):
        if k < 1:
            raise ValueError("k must be >= 1")
        if init_mode not in ("k-means||", "k-means++", "random"):
            raise ValueError(f"init_mode={init_mode!r}")
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.seed = int(seed)
        self.init_mode = init_mode
        self.features_col = features_col
        self.prediction_col = prediction_col

    def set_k(self, v):
        if v < 1:
            raise ValueError("k must be >= 1")
        self.k = int(v)
        return self

    setK = set_k

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_tol(self, v):
        self.tol = float(v)
        return self

    setTol = set_tol

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def set_init_mode(self, v):
        if v not in ("k-means||", "k-means++", "random"):
            raise ValueError(f"init_mode={v!r}")
        self.init_mode = v
        return self

    setInitMode = set_init_mode

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setPredictionCol = set_prediction_col

    def get_k(self):
        return self.k

    getK = get_k

    def fit(self, frame: Frame, mesh=None) -> "KMeansModel":
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        w = np.asarray(frame.mask, dt)
        # masked slots may hold NaN (dropna/filter keep values in place);
        # zero them so 0-weighted statistics stay finite (0·NaN = NaN)
        X = np.where(w[:, None] > 0, X, 0.0)

        rng = np.random.default_rng(self.seed)
        if self.init_mode == "random":
            valid = np.flatnonzero(w > 0)
            if len(valid) < self.k:
                raise ValueError(
                    f"k={self.k} exceeds the {len(valid)} valid rows")
            centers0 = X[rng.choice(valid, size=self.k, replace=False)]
        else:  # k-means|| / k-means++ → greedy k-means++ seeding
            centers0 = _kmeans_pp_init(X, w, self.k, rng)

        mesh = normalize_mesh(mesh)
        Xd, wd = _pad_and_shard(X, w, mesh, dt)
        fit_fn = _fit_cached(mesh, self.k, self.max_iter, self.tol)
        centers, cost, iters, counts = jax.block_until_ready(
            fit_fn(Xd, wd, jnp.asarray(centers0)))
        return KMeansModel(np.asarray(centers), self.features_col,
                           self.prediction_col, float(cost), int(iters),
                           np.asarray(counts).astype(np.int64).tolist())


@persistable
class KMeansModel(Model):
    """Fitted centers + the MLlib model surface: ``transform`` (nearest
    center as the prediction column), ``clusterCenters``, ``summary``
    (cluster sizes, training cost, iterations), ``predict`` (host scalar
    path, like ``LinearRegressionModel.predict``)."""

    _persist_attrs = ('centers', 'features_col', 'prediction_col',
                      'training_cost', 'num_iters', 'cluster_sizes')

    def __init__(self, centers, features_col, prediction_col,
                 training_cost=float("nan"), num_iters=0,
                 cluster_sizes=None):
        self.centers = np.asarray(centers)
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.training_cost = training_cost
        self.num_iters = num_iters
        self.cluster_sizes = cluster_sizes or []

    def cluster_centers(self):
        return [c for c in self.centers]

    clusterCenters = cluster_centers

    @property
    def k(self):
        return self.centers.shape[0]

    def _distances(self, X):
        C = jnp.asarray(self.centers, X.dtype)
        x_sq = jnp.sum(X * X, axis=1, keepdims=True)
        c_sq = jnp.sum(C * C, axis=1)
        return x_sq - 2.0 * (X @ C.T) + c_sq[None, :]

    def transform(self, frame: Frame) -> Frame:
        X = jnp.asarray(frame._column_values(self.features_col),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        pred = jnp.argmin(self._distances(X), axis=1).astype(float_dtype())
        return frame.with_column(self.prediction_col, pred)

    def predict(self, features) -> int:
        x = np.asarray(features, np.dtype(float_dtype())).reshape(1, -1)
        return int(host_fetch(jnp.argmin(self._distances(jnp.asarray(x)))))

    def compute_cost(self, frame: Frame) -> float:
        """Weighted SSE to nearest center over valid rows (MLlib 2.x
        ``computeCost``)."""
        X = jnp.asarray(frame._column_values(self.features_col),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        w = frame.mask.astype(X.dtype)
        best = jnp.min(self._distances(X), axis=1)
        return float(host_fetch(jnp.sum(jnp.maximum(best, 0.0) * w)))

    computeCost = compute_cost

    @property
    def summary(self):
        return KMeansSummary(self)

    @property
    def has_summary(self):
        return True

    hasSummary = has_summary


class KMeansSummary:
    """MLlib ``KMeansSummary``: k, cluster sizes, training cost, iterations."""

    def __init__(self, model: KMeansModel):
        self._model = model

    @property
    def k(self):
        return self._model.k

    @property
    def cluster_sizes(self):
        return list(self._model.cluster_sizes)

    clusterSizes = cluster_sizes

    @property
    def training_cost(self):
        return self._model.training_cost

    trainingCost = training_cost

    @property
    def num_iter(self):
        return self._model.num_iters

    numIter = num_iter


# ---------------------------------------------------------------------------
# GaussianMixture (MLlib org.apache.spark.ml.clustering.GaussianMixture)
# ---------------------------------------------------------------------------

def _gmm_log_prob(X, means, chols):
    """(n, k) log N(x | mean_j, cov_j) via per-component Cholesky solves.

    ``chols`` (k, d, d) lower Cholesky factors. vmapped over components:
    each solve is a batched triangular solve + reduction — all XLA-native,
    no per-row work.
    """
    d = X.shape[1]
    log2pi = jnp.log(2.0 * jnp.pi).astype(X.dtype)

    def one(mean, chol):
        diff = (X - mean[None, :]).T                       # (d, n)
        z = jax.scipy.linalg.solve_triangular(chol, diff, lower=True)
        maha = jnp.sum(z * z, axis=0)                      # (n,)
        logdet = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol)))
        return -0.5 * (d * log2pi + logdet + maha)

    return jax.vmap(one)(means, chols).T                   # (n, k)


def _gmm_estep(X, w, weights, means, chols):
    """Local E-step sufficient statistics for one shard.

    Returns (Nk (k,), Sk (k, d), Ck (k, d, d) raw scatter Σ r·x·xᵀ,
    weighted log-likelihood). Responsibilities never leave the device.
    """
    logp = _gmm_log_prob(X, means, chols) + jnp.log(weights)[None, :]
    lse = jax.nn.logsumexp(logp, axis=1)                   # (n,)
    resp = jnp.exp(logp - lse[:, None]) * w[:, None]       # masked (n, k)
    Nk = jnp.sum(resp, axis=0)
    Sk = resp.T @ X                                        # (k, d) MXU
    # per-component scatter: k MXU matmuls via vmap over the component axis
    Ck = jax.vmap(lambda r: (X * r[:, None]).T @ X)(resp.T)
    ll = jnp.sum(lse * w)
    return Nk, Sk, Ck, ll


def _make_gmm_fit(mesh, k, max_iter, tol, reg):
    if mesh is None:
        def stats(X, w, weights, means, chols):
            return _gmm_estep(X, w, weights, means, chols)
    else:
        def local(X, w, weights, means, chols):
            Nk, Sk, Ck, ll = _gmm_estep(X, w, weights, means, chols)
            return (jax.lax.psum(Nk, DATA_AXIS), jax.lax.psum(Sk, DATA_AXIS),
                    jax.lax.psum(Ck, DATA_AXIS), jax.lax.psum(ll, DATA_AXIS))

        stats = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(), P(), P()),
            out_specs=(P(), P(), P(), P()))

    def chol_of(covs):
        d = covs.shape[-1]
        return jnp.linalg.cholesky(
            covs + reg * jnp.eye(d, dtype=covs.dtype)[None])

    def fit(X, w, n, weights0, means0, covs0):
        def body(carry):
            weights, means, covs, last_ll, it, _ = carry
            Nk, Sk, Ck, ll = stats(X, w, weights, means, chol_of(covs))
            safe = jnp.maximum(Nk, 1e-12)
            new_means = Sk / safe[:, None]
            new_covs = (Ck / safe[:, None, None]
                        - new_means[:, :, None] * new_means[:, None, :])
            new_weights = Nk / n
            return (new_weights, new_means, new_covs, ll, it + 1,
                    jnp.abs(ll - last_ll))

        def cond(carry):
            _, _, _, _, it, delta = carry
            return jnp.logical_and(it < max_iter, delta > tol)

        init = (weights0, means0, covs0,
                jnp.asarray(-jnp.inf, X.dtype), jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, X.dtype))
        weights, means, covs, ll, iters, _ = jax.lax.while_loop(
            cond, body, init)
        return weights, means, covs, ll, iters

    return serialize_collectives(jax.jit(fit), mesh)


@functools.lru_cache(maxsize=None)
def _gmm_fit_cached(mesh, k, max_iter, tol, reg):
    return _make_gmm_fit(mesh, k, max_iter, tol, reg)


@persistable
class GaussianMixture(Estimator):
    """MLlib ``GaussianMixture``: full-covariance GMM fit by EM.

    TPU-first: the E-step is one fused (n, k) log-prob computation (batched
    triangular solves + an MXU matmul per component for the scatter); the
    whole EM loop runs inside one ``lax.while_loop`` with zero host
    round-trips, and under a mesh the (k + k·d + k·d²+1) sufficient
    statistics reduce with one fused psum — the ``treeAggregate`` analogue
    (SURVEY.md §3.3). MLlib dependency surface: `/root/reference/pom.xml:29-32`.
    """

    _persist_attrs = ('k', 'max_iter', 'tol', 'seed', 'reg',
                      'features_col', 'prediction_col', 'probability_col')

    def __init__(self, k: int = 2, max_iter: int = 100, tol: float = 0.01,
                 seed: int = 0, reg: float = 1e-6,
                 features_col: str = "features",
                 prediction_col: str = "prediction",
                 probability_col: str = "probability"):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.seed = int(seed)
        self.reg = float(reg)
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.probability_col = probability_col

    def set_k(self, v):
        if v < 1:
            raise ValueError("k must be >= 1")
        self.k = int(v)
        return self

    setK = set_k

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_tol(self, v):
        self.tol = float(v)
        return self

    setTol = set_tol

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def fit(self, frame: Frame, mesh=None) -> "GaussianMixtureModel":
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        w = np.asarray(frame.mask, dt)
        # masked slots may hold NaN (dropna/filter keep values in place);
        # zero them so 0-weighted statistics stay finite (0·NaN = NaN)
        X = np.where(w[:, None] > 0, X, 0.0)
        n_valid = float(w.sum())
        if n_valid < self.k:
            raise ValueError(f"k={self.k} exceeds the {int(n_valid)} valid rows")

        # init: k-means++ means, shared diagonal covariance of the data,
        # uniform weights (deterministic given seed)
        rng = np.random.default_rng(self.seed)
        means0 = _kmeans_pp_init(X, w, self.k, rng).astype(dt)
        mu = (w @ X) / n_valid
        var = (w @ (X * X)) / n_valid - mu * mu
        covs0 = np.tile(np.diag(np.maximum(var, 1e-6)).astype(dt),
                        (self.k, 1, 1))
        weights0 = np.full((self.k,), 1.0 / self.k, dt)

        mesh = normalize_mesh(mesh)
        Xd, wd = _pad_and_shard(X, w, mesh, dt)
        fit_fn = _gmm_fit_cached(mesh, self.k, self.max_iter, self.tol,
                                 self.reg)
        weights, means, covs, ll, iters = jax.block_until_ready(
            fit_fn(Xd, wd, jnp.asarray(n_valid, dt), jnp.asarray(weights0),
                   jnp.asarray(means0), jnp.asarray(covs0)))
        return GaussianMixtureModel(
            np.asarray(weights, np.float64), np.asarray(means, np.float64),
            np.asarray(covs, np.float64), self._params_dict(),
            log_likelihood=float(ll), num_iters=int(iters))

    def _params_dict(self):
        return {k: getattr(self, k) for k in (
            "k", "max_iter", "tol", "seed", "reg", "features_col",
            "prediction_col", "probability_col")}


@persistable
class GaussianMixtureModel(Model):
    """Fitted mixture: ``weights`` (k,), per-component ``gaussians``
    (mean, cov). ``transform`` appends probability (posterior vector) and
    prediction (argmax posterior) columns, like MLlib."""

    _persist_attrs = ('weights', 'means', 'covs', '_params',
                      'log_likelihood', 'num_iters')

    def __init__(self, weights, means, covs, params=None,
                 log_likelihood=float("nan"), num_iters=0):
        self.weights = np.asarray(weights)
        self.means = np.asarray(means)
        self.covs = np.asarray(covs)
        self._params = dict(params or {})
        self.log_likelihood = log_likelihood
        self.num_iters = num_iters

    @property
    def k(self):
        return int(self.weights.shape[0])

    getK = k

    @property
    def gaussians(self):
        return [{"mean": self.means[j], "cov": self.covs[j]}
                for j in range(self.k)]

    @property
    def gaussians_df(self) -> Frame:
        """MLlib's ``gaussiansDF``: one row per component."""
        return Frame({
            "mean": np.asarray([m for m in self.means], object),
            "cov": np.asarray([c for c in self.covs], object),
        })

    gaussiansDF = gaussians_df

    def _posterior(self, X):
        dt = X.dtype
        reg = self._params.get("reg", 1e-6)
        chols = jnp.linalg.cholesky(
            jnp.asarray(self.covs, dt)
            + reg * jnp.eye(self.covs.shape[-1], dtype=dt)[None])
        logp = _gmm_log_prob(X, jnp.asarray(self.means, dt), chols) \
            + jnp.log(jnp.asarray(self.weights, dt))[None, :]
        return jax.nn.softmax(logp, axis=1)

    def transform(self, frame: Frame) -> Frame:
        p = self._params
        X = jnp.asarray(frame._column_values(p.get("features_col",
                                                   "features")),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        post = self._posterior(X)
        pred = jnp.argmax(post, axis=1).astype(float_dtype())
        out = frame.with_column(p.get("probability_col", "probability"),
                                post)
        return out.with_column(p.get("prediction_col", "prediction"), pred)

    def predict(self, features) -> int:
        x = jnp.asarray(np.asarray(features, np.float64).reshape(1, -1),
                        float_dtype())
        return int(host_fetch(jnp.argmax(self._posterior(x), axis=1))[0])

    def predict_probability(self, features) -> np.ndarray:
        x = jnp.asarray(np.asarray(features, np.float64).reshape(1, -1),
                        float_dtype())
        return np.asarray(self._posterior(x))[0]

    predictProbability = predict_probability

    @property
    def summary(self):
        return GaussianMixtureSummary(self)

    @property
    def has_summary(self):
        return True

    hasSummary = has_summary


class GaussianMixtureSummary:
    """MLlib ``GaussianMixtureSummary``: logLikelihood + iterations."""

    def __init__(self, model: GaussianMixtureModel):
        self._model = model

    @property
    def log_likelihood(self):
        return self._model.log_likelihood

    logLikelihood = log_likelihood

    @property
    def num_iter(self):
        return self._model.num_iters

    numIter = num_iter

    @property
    def k(self):
        return self._model.k


# ---------------------------------------------------------------------------
# BisectingKMeans (MLlib org.apache.spark.ml.clustering.BisectingKMeans)
# ---------------------------------------------------------------------------

@persistable
class BisectingKMeans(Estimator):
    """MLlib ``BisectingKMeans``: divisive hierarchical clustering — start
    from one cluster, repeatedly bisect (larger clusters first, MLlib's
    priority order) with a 2-means run until there are ``k`` leaves.

    TPU-first: every bisection reuses the jitted masked 2-means program
    (``_fit_cached``) on the FULL row set with a per-cluster weight vector —
    subsetting by weights instead of gathers keeps one static shape for all
    splits, so the 2-means program compiles once and every split is a pure
    device dispatch. The split loop itself is host-side (≤ k−1 steps over a
    data-dependent tree — not a device hot loop). MLlib dependency surface:
    `/root/reference/pom.xml:29-32`.
    """

    _persist_attrs = ('k', 'max_iter', 'tol', 'seed',
                      'min_divisible_cluster_size', 'features_col',
                      'prediction_col')

    def __init__(self, k: int = 4, max_iter: int = 20, tol: float = 1e-4,
                 seed: int = 0, min_divisible_cluster_size: float = 1.0,
                 features_col: str = "features",
                 prediction_col: str = "prediction"):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.seed = int(seed)
        self.min_divisible_cluster_size = float(min_divisible_cluster_size)
        self.features_col = features_col
        self.prediction_col = prediction_col

    def set_k(self, v):
        if v < 1:
            raise ValueError("k must be >= 1")
        self.k = int(v)
        return self

    setK = set_k

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def set_min_divisible_cluster_size(self, v):
        self.min_divisible_cluster_size = float(v)
        return self

    setMinDivisibleClusterSize = set_min_divisible_cluster_size

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def fit(self, frame: Frame, mesh=None) -> "BisectingKMeansModel":
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        w = np.asarray(frame.mask, dt)
        # masked slots may hold NaN (dropna/filter keep values in place);
        # zero them so 0-weighted statistics stay finite (0·NaN = NaN)
        X = np.where(w[:, None] > 0, X, 0.0)
        n_valid = int(w.sum())
        if n_valid < self.k:
            raise ValueError(f"k={self.k} exceeds the {n_valid} valid rows")
        rng = np.random.default_rng(self.seed)

        mesh = normalize_mesh(mesh)
        Xd, _ = _pad_and_shard(X, w, mesh, dt)
        if mesh is not None and Xd.shape[0] != X.shape[0]:
            # keep the host-side copies in the padded shape too, so the
            # per-split weight vectors built below line up with Xd
            pad_rows = Xd.shape[0] - X.shape[0]
            X = np.concatenate([X, np.zeros((pad_rows, X.shape[1]), dt)])
            w = np.concatenate([w, np.zeros((pad_rows,), dt)])
        two_means = _fit_cached(mesh, 2, self.max_iter, self.tol)

        # tree arrays: center per node, children (−1 = leaf)
        centers = [np.asarray((w @ X) / max(w.sum(), 1e-12))]
        left, right = [-1], [-1]
        assign = np.zeros(X.shape[0], np.int64)       # row → node id
        leaf_sizes = {0: n_valid}
        min_size = self.min_divisible_cluster_size
        if min_size <= 1.0:
            min_size = min_size * n_valid if min_size < 1.0 else 1.0
        undivisible: set[int] = set()

        while len(leaf_sizes) < self.k:
            divisible = [(sz, nid) for nid, sz in leaf_sizes.items()
                         if nid not in undivisible and sz >= max(min_size, 2)]
            if not divisible:
                break
            _, nid = max(divisible)                    # largest first
            sel = (assign == nid) & (w > 0)
            wc = np.where(sel, w, 0.0).astype(dt)
            try:
                c0 = _kmeans_pp_init(X, wc, 2, rng)
            except ValueError:
                undivisible.add(nid)
                continue
            if mesh is not None:
                wd = jax.device_put(wc, NamedSharding(mesh, P(DATA_AXIS)))
            else:
                wd = jnp.asarray(wc)
            c, _, _, counts = jax.block_until_ready(
                two_means(Xd, wd, jnp.asarray(c0)))
            counts = np.asarray(counts)
            if counts.min() < 1:                       # degenerate split
                undivisible.add(nid)
                continue
            c = np.asarray(c)
            # children assignment for this cluster's rows
            d2 = ((X[sel][:, None, :] - c[None, :, :]) ** 2).sum(-1)
            child = np.argmin(d2, axis=1)
            lid, rid = len(centers), len(centers) + 1
            centers.extend([c[0], c[1]])
            left.extend([-1, -1])
            right.extend([-1, -1])
            left[nid], right[nid] = lid, rid
            assign[np.flatnonzero(sel)] = np.where(child == 0, lid, rid)
            del leaf_sizes[nid]
            leaf_sizes[lid] = int((child == 0).sum())
            leaf_sizes[rid] = int((child == 1).sum())

        model = BisectingKMeansModel(
            np.stack(centers), np.asarray(left, np.int64),
            np.asarray(right, np.int64), self.features_col,
            self.prediction_col)
        # training cost: SSE of valid rows to their leaf center
        leaf_center = np.stack(centers)[assign]
        model.training_cost = float(
            np.sum(((X - leaf_center) ** 2).sum(-1) * w))
        model.cluster_sizes = [leaf_sizes[nid]
                               for nid in sorted(leaf_sizes)]
        return model


@persistable
class BisectingKMeansModel(Model):
    """Binary cluster tree: prediction walks root→leaf picking the nearer
    child center at each internal node (MLlib's traversal), vectorized —
    one gather + distance comparison per tree level."""

    _persist_attrs = ('node_centers', 'left', 'right', 'features_col',
                      'prediction_col', 'training_cost', 'cluster_sizes')

    def __init__(self, node_centers, left, right, features_col="features",
                 prediction_col="prediction", training_cost=float("nan"),
                 cluster_sizes=None):
        self.node_centers = np.asarray(node_centers)
        self.left = np.asarray(left, np.int64)
        self.right = np.asarray(right, np.int64)
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.training_cost = training_cost
        self.cluster_sizes = list(cluster_sizes or [])
        self.num_iters = 0          # tree build has no single iteration count
        self._post_load()

    def _post_load(self):
        """Rebuild the leaf index (derived state) after load_stage."""
        self.left = np.asarray(self.left, np.int64)
        self.right = np.asarray(self.right, np.int64)
        self.node_centers = np.asarray(self.node_centers)
        if not hasattr(self, "num_iters"):
            self.num_iters = 0
        # leaf ids in stable order → cluster index 0..k−1
        self._leaves = np.flatnonzero(self.left < 0)
        self._leaf_index = np.full(len(self.left), -1, np.int64)
        self._leaf_index[self._leaves] = np.arange(len(self._leaves))
        # actual tree depth (descent steps needed), computed once from the
        # static child arrays — the predict loop runs exactly this many
        # rounds, not k−1
        depth = np.zeros(len(self.left), np.int64)
        for nid in range(len(self.left) - 1, -1, -1):   # children have
            if self.left[nid] >= 0:                     # larger ids
                depth[nid] = 1 + max(depth[self.left[nid]],
                                     depth[self.right[nid]])
        self._depth = int(depth[0]) if len(depth) else 0

    @property
    def k(self):
        return len(self._leaves)

    def cluster_centers(self):
        return [self.node_centers[i] for i in self._leaves]

    clusterCenters = cluster_centers

    def _predict_nodes(self, X):
        """(n,) leaf node id per row — root→leaf descent, ≤ depth steps."""
        C = jnp.asarray(self.node_centers, X.dtype)
        L = jnp.asarray(self.left)
        R = jnp.asarray(self.right)
        node = jnp.zeros(X.shape[0], jnp.int64)
        for _ in range(self._depth):
            l, r = L[node], R[node]
            is_leaf = l < 0
            dl = jnp.sum((X - C[jnp.maximum(l, 0)]) ** 2, axis=1)
            dr = jnp.sum((X - C[jnp.maximum(r, 0)]) ** 2, axis=1)
            nxt = jnp.where(dl <= dr, l, r)
            node = jnp.where(is_leaf, node, nxt)
        return node

    def transform(self, frame: Frame) -> Frame:
        X = jnp.asarray(frame._column_values(self.features_col),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        nodes = np.asarray(self._predict_nodes(X))
        pred = self._leaf_index[nodes].astype(np.dtype(float_dtype()))
        return frame.with_column(self.prediction_col, jnp.asarray(pred))

    def predict(self, features) -> int:
        x = jnp.asarray(np.asarray(features, np.float64).reshape(1, -1),
                        float_dtype())
        return int(self._leaf_index[int(np.asarray(self._predict_nodes(x))[0])])

    def compute_cost(self, frame: Frame) -> float:
        X = jnp.asarray(frame._column_values(self.features_col),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        w = frame.mask.astype(X.dtype)
        nodes = self._predict_nodes(X)
        C = jnp.asarray(self.node_centers, X.dtype)
        return float(host_fetch(jnp.sum(jnp.sum((X - C[nodes]) ** 2,
                                                axis=1) * w)))

    computeCost = compute_cost

    @property
    def summary(self):
        return KMeansSummary(self)

    @property
    def has_summary(self):
        return True

    hasSummary = has_summary


@persistable
class PowerIterationClustering(Estimator):
    """MLlib ``PowerIterationClustering`` (spark.ml 2.4,
    ``org.apache.spark.ml.clustering.PowerIterationClustering`` — part of
    the mllib dependency surface, `/root/reference/pom.xml:29-32`): cluster
    the nodes of a weighted similarity graph by power-iterating the
    degree-normalized affinity matrix to a 1-D pseudo-eigenvector
    embedding, then running k-means on the embedding (Lin & Cohen, the
    algorithm MLlib cites).

    TPU-first design: the affinity matrix is built DENSE ``(n, n)`` in HBM
    (PIC graphs are node-count-bounded — the embedding itself is (n,); a
    dense W turns every power step into one MXU matvec instead of mllib's
    per-edge aggregateMessages shuffle). The whole iteration runs inside
    one jit as a ``lax.scan`` carrying the embedding; under a mesh the
    rows of W are sharded and each step is ``local matvec →
    all_gather over ICI`` inside ``shard_map`` — the GraphX
    aggregateMessages/shuffle replacement. The final 1-D k-means reuses
    the mesh-aware :class:`KMeans`.

    API parity: ``assignClusters(dataset) -> Frame(id, cluster)`` with
    ``src``/``dst``/``weight`` columns; ``initMode`` ``"random"`` |
    ``"degree"``; ids are arbitrary integers (mapped to dense indices
    internally, reported back as the original ids, ascending).
    """

    _persist_attrs = ('k', 'max_iter', 'init_mode', 'src_col', 'dst_col',
                      'weight_col', 'seed')

    def __init__(self, k: int = 2, max_iter: int = 20,
                 init_mode: str = "random", src_col: str = "src",
                 dst_col: str = "dst", weight_col: str = "weight",
                 seed: int = 0):
        if k < 2:
            raise ValueError("k must be >= 2")
        if init_mode not in ("random", "degree"):
            raise ValueError(f"init_mode must be random or degree, "
                             f"got {init_mode!r}")
        self.k = int(k)
        self.max_iter = int(max_iter)
        self.init_mode = init_mode
        self.src_col = src_col
        self.dst_col = dst_col
        self.weight_col = weight_col
        self.seed = int(seed)

    def set_k(self, v):
        if v < 2:
            raise ValueError("k must be >= 2")
        self.k = int(v)
        return self

    setK = set_k

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_init_mode(self, v):
        if v not in ("random", "degree"):
            raise ValueError(f"init_mode must be random or degree, got {v!r}")
        self.init_mode = v
        return self

    setInitMode = set_init_mode

    def set_src_col(self, v):
        self.src_col = v
        return self

    setSrcCol = set_src_col

    def set_dst_col(self, v):
        self.dst_col = v
        return self

    setDstCol = set_dst_col

    def set_weight_col(self, v):
        self.weight_col = v
        return self

    setWeightCol = set_weight_col

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def assign_clusters(self, frame: Frame, mesh=None) -> Frame:
        dt = float_dtype()
        d = frame.to_pydict()
        src = np.asarray(d[self.src_col], np.int64)
        dst = np.asarray(d[self.dst_col], np.int64)
        if self.weight_col in frame.columns:
            w = np.asarray(d[self.weight_col], np.float64)
        else:
            w = np.ones(len(src), np.float64)
        if np.any(w < 0):
            raise ValueError("similarity weights must be nonnegative")
        ids = np.unique(np.concatenate([src, dst]))
        n = len(ids)
        if n < self.k:
            raise ValueError(f"k={self.k} exceeds node count {n}")
        si = np.searchsorted(ids, src)
        di = np.searchsorted(ids, dst)

        mesh = normalize_mesh(mesh)
        ndev = 1 if mesh is None else mesh.devices.size
        n_pad = n + ((-n) % ndev)

        # Dense symmetric affinity; mllib sums duplicate/bidirectional
        # entries the same way (aggregateMessages add). Self-loops add
        # once — the reverse scatter must not hit the diagonal again.
        w_dev = jnp.asarray(w, dt)
        W = jnp.zeros((n_pad, n_pad), dt)
        W = W.at[si, di].add(w_dev)
        W = W.at[di, si].add(jnp.where(jnp.asarray(si == di), 0.0, w_dev))

        deg = jnp.sum(W, axis=1)                          # (n_pad,)
        inv_deg = jnp.where(deg > 0, 1.0 / jnp.where(deg > 0, deg, 1.0), 0.0)
        vol = jnp.sum(deg)
        if self.init_mode == "degree":
            v0 = deg / jnp.where(vol > 0, vol, 1.0)
        else:
            key = jax.random.PRNGKey(self.seed)
            u = jax.random.uniform(key, (n_pad,), dt)
            u = jnp.where(jnp.arange(n_pad) < n, u, 0.0)
            v0 = u / jnp.maximum(jnp.sum(jnp.abs(u)), 1e-30)

        max_iter = self.max_iter

        if mesh is None:
            @jax.jit
            def power(Wm, v):
                def body(vc, _):
                    nv = inv_deg * (Wm @ vc)
                    nv = nv / jnp.maximum(jnp.sum(jnp.abs(nv)), 1e-30)
                    return nv, None
                v_out, _ = jax.lax.scan(body, v, None, length=max_iter)
                return v_out

            v = power(W, v0)
        else:
            # Row-sharded matvec: local rows → all_gather over ICI each
            # step; the scan (and therefore the whole loop) stays on
            # device inside the manual region.
            inv_deg_h = inv_deg

            @jax.jit
            @functools.partial(
                shard_map, mesh=mesh,
                in_specs=(P(DATA_AXIS), P(), P(DATA_AXIS)), out_specs=P(),
                check_vma=False)
            def power(Ws, v, inv_deg_s):
                def body(vc, _):
                    local = inv_deg_s * (Ws @ vc)          # (n_pad/ndev,)
                    nv = jax.lax.all_gather(local, DATA_AXIS, tiled=True)
                    nv = nv / jnp.maximum(jnp.sum(jnp.abs(nv)), 1e-30)
                    return nv, None
                v_out, _ = jax.lax.scan(body, v, None, length=max_iter)
                return v_out

            v = power(W, v0, inv_deg_h)

        emb = v[:n]
        km = KMeans(k=self.k, max_iter=30, seed=self.seed,
                    init_mode="k-means++", features_col="features",
                    prediction_col="cluster")
        emb_frame = Frame({"features": jnp.reshape(emb, (n, 1))})
        model = km.fit(emb_frame, mesh=mesh)
        out = model.transform(emb_frame)
        cluster = np.asarray(out._column_values("cluster"), np.int64)
        return Frame({"id": ids, "cluster": cluster})

    assignClusters = assign_clusters
