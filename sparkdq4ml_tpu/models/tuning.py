"""Model selection: ParamGridBuilder, CrossValidator, TrainValidationSplit
(BASELINE.json config: "CrossValidator grid (regParam × elasticNetParam)
pmapped across TPU cores").

TPU-first design — the grid axis is *grid-parallel* (SURVEY.md §5
"Parallelism strategies"): for linear regression every (fold × param) fit is
a tiny solve on sufficient statistics, so the whole cross-validation runs as

1. ONE data pass building ALL per-fold augmented Gramians from the packed
   design (``vmap`` over folds inside ``shard_map`` + one psum when a mesh
   is active),
2. train-fold Gramians by subtraction (``A_train = A_all − A_fold`` — the
   Gramian is additive, so k-fold CV needs no second data pass),
3. a single ``vmap`` over the flattened (param × fold) axis of the FISTA
   solver, with that cell axis SHARDED over the mesh — every core solves
   its slice of the grid simultaneously (the grid-parallel axis),
4. held-out metrics (rmse/mse/r2) computed from the fold Gramians directly.

Estimators without a sufficient-statistics path (LogisticRegression, custom)
take the generic fit-per-cell path, which still shares the session mesh.
"""

from __future__ import annotations

import copy
import functools
import itertools
import re
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .base import Estimator, Model
from .evaluation import Evaluator, RegressionEvaluator
from .regression import LinearRegression, _extract_xy
from .solvers import fista_solve, resolve_solver
from ..parallel.mesh import serialize_collectives


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


class ParamGridBuilder:
    """``addGrid(param, values)`` builder; params are attribute names
    (snake_case or MLlib camelCase)."""

    def __init__(self):
        self._grids: dict[str, Sequence] = {}

    def add_grid(self, param: str, values: Sequence) -> "ParamGridBuilder":
        self._grids[_snake(param)] = list(values)
        return self

    addGrid = add_grid

    def base_on(self, params: dict) -> "ParamGridBuilder":
        for k, v in params.items():
            self._grids[_snake(k)] = [v]
        return self

    baseOn = base_on

    def build(self) -> list[dict]:
        names = list(self._grids)
        out = []
        for combo in itertools.product(*(self._grids[n] for n in names)):
            out.append(dict(zip(names, combo)))
        return out or [{}]


def _apply_params(estimator: Estimator, params: dict) -> Estimator:
    est = copy.copy(estimator)
    for k, v in params.items():
        if not hasattr(est, k):
            raise AttributeError(f"{type(est).__name__} has no param {k!r}")
        setattr(est, k, v)
    return est


def _best_index(metrics: np.ndarray, larger_better: bool) -> int:
    if np.all(np.isnan(metrics)):
        raise ValueError(
            "all cross-validation metrics are NaN — typically a fold with "
            "only one class (binary metrics) or an empty fold; use more data, "
            "fewer folds, or a different seed")
    return int(np.nanargmax(metrics) if larger_better else np.nanargmin(metrics))


def _fold_ids(n_slots: int, num_folds: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, num_folds, size=n_slots)


# --- fast path: linear regression on per-fold Gramians ----------------------

_FAST_METRICS = ("rmse", "mse", "r2")


def _holdout_metric_from_gram(A, coef, intercept, metric: str):
    """rmse/mse/r2 on a fold, from its Gramian and a raw-space model."""
    d = A.shape[0] - 2
    XtX = A[:d, :d]
    Xty = A[:d, d]
    sum_x = A[:d, d + 1]
    sum_y = A[d, d + 1]
    yy = A[d, d]
    n = A[d + 1, d + 1]
    sse = (yy - 2.0 * coef @ Xty - 2.0 * intercept * sum_y
           + 2.0 * intercept * (coef @ sum_x) + coef @ XtX @ coef
           + n * intercept * intercept)
    mse = sse / n
    if metric == "mse":
        return mse
    if metric == "rmse":
        return jnp.sqrt(jnp.maximum(mse, 0.0))
    ss_tot = yy - n * (sum_y / n) ** 2
    return 1.0 - sse / ss_tot


@functools.lru_cache(maxsize=8)
def _fold_ids_device(n_slots: int, num_folds: int, seed: int):
    """Fold assignment as a cached DEVICE array — the assignment is a pure
    function of (n, k, seed), so repeated ``fit`` calls must not pay the
    host→device transfer again. Bounded (unlike the program caches, this
    pins (n,)-sized device buffers in HBM, not compiled code)."""
    return jnp.asarray(_fold_ids(n_slots, num_folds, seed))


def _refit_solvers(estimator, param_maps: list[dict]) -> tuple:
    """Statically resolve the refit solver for every grid point — the grid
    only varies (reg_param, elastic_net_param), so MLlib's ``auto``
    resolution (normal vs iterative) is known at trace time per param."""
    out = []
    for p in param_maps:
        est = _apply_params(estimator, p)
        out.append(resolve_solver(est.solver, est.reg_param,
                                  est.elastic_net_param))
    return tuple(out)


def _cv_flat_layout(n_params: int, d: int, max_iter: int, refit: tuple):
    """(offset, history_len) per distinct solver in the packed CV output:
    ``[metrics(m) | best | per-solver (coef(d), intercept, iters,
    converged, history)]``."""
    distinct = tuple(dict.fromkeys(refit))
    off = n_params + 1
    layout = {}
    for s in distinct:
        hlen = 1 if s == "normal" else max_iter + 1
        layout[s] = (off, hlen)
        off += d + 3 + hlen
    return distinct, layout, off


@functools.lru_cache(maxsize=None)
def _cv_program_fn(mesh, num_folds: int, n_params: int, n_features: int,
                   max_iter: int, tol: float, fit_intercept: bool,
                   standardization: bool, metric: str, larger_better: bool,
                   refit: tuple):
    """The ENTIRE fast-path cross-validation as one jitted program — a
    single dispatch returning a single packed buffer.

    Inside: pack ``Z = [X, y, 1]·mask``, pad rows to the shard count, build
    ALL per-fold augmented Gramians in one data pass (for 0/1 fold weight
    ``w``, ``(Z·w)ᵀZ`` is the fold's masked Gramian; invalid rows are
    already zero in Z), train Gramians by subtraction (the Gramian is
    additive — k-fold CV needs no second data pass), solve every
    (param × fold) FISTA cell vmapped with the cell axis SHARDED over the
    mesh (the grid-parallel axis, BASELINE.json config e), fold-mean the
    held-out metrics, pick the winner, and REFIT the winning params on the
    all-data Gramian with each statically-resolved solver the grid can
    select (``refit``, per-param; ``auto`` ⇒ normal vs FISTA known at
    trace time) — GridSearchCV(refit=True) semantics, end to end on
    device.

    Everything rides out in ONE flat vector (see :func:`_cv_flat_layout`)
    because on the tunneled TPU every dispatch after the first device→host
    read AND every read costs ~70 ms (bench.py module docstring): the
    staged implementation (~a dozen dispatches + several reads per ``fit``)
    spent its whole wall-clock on that floor, not on solving. One dispatch
    + one read is the floor for a fit whose results the caller
    materializes. Cached per configuration — constructing the jit inline
    would re-lower the grid program on every ``fit`` call."""
    from .owlqn import owlqn_solve
    from .solvers import normal_solve

    solver_fns = {
        "normal": lambda A, r, a: normal_solve(
            A, r, a, fit_intercept=fit_intercept,
            standardization=standardization),
        "fista": lambda A, r, a: fista_solve(
            A, r, a, max_iter=max_iter, tol=tol, fit_intercept=fit_intercept,
            standardization=standardization),
        "owlqn": lambda A, r, a: owlqn_solve(
            A, r, a, max_iter=max_iter, tol=tol, fit_intercept=fit_intercept,
            standardization=standardization),
    }
    distinct, _, _ = _cv_flat_layout(n_params, n_features, max_iter, refit)
    use_mesh = mesh is not None and mesh.devices.size > 1
    ndev = mesh.devices.size if use_mesh else 1
    k = num_folds
    m = n_params
    n_cells = m * k
    cell_pad = (-n_cells) % ndev
    # Wrap-around duplicates (works even when pad > n_cells, e.g. a 3-cell
    # grid on 8 devices); duplicates are trimmed by the [:n_cells] slice.
    cell_idx = np.arange(n_cells + cell_pad) % n_cells

    def fold_grams(Zs, fs):
        def one(f):
            w = (fs == f).astype(Zs.dtype)
            return (Zs * w[:, None]).T @ Zs
        return jax.vmap(one)(jnp.arange(k))

    def cell(A_tr, A_te, reg, alpha):
        # record_history=False: the trace is unused here, and its scan
        # stacking is the op the 0.4.x partitioner miscompiles inside a
        # sharded cell (see fista_solve)
        r = fista_solve(A_tr, reg, alpha, max_iter=max_iter, tol=tol,
                        fit_intercept=fit_intercept,
                        standardization=standardization,
                        record_history=False)
        return _holdout_metric_from_gram(A_te, r.coefficients, r.intercept,
                                         metric)

    if use_mesh:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS, shard_map

        grams_fn = shard_map(
            lambda Zs, fs: jax.lax.psum(fold_grams(Zs, fs), DATA_AXIS),
            mesh=mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P())
        # check_vma off: the FISTA scan's replicated init carry (w=0) meets
        # a device-varying Gramian inside the manual region, which the
        # varying-manual-axes checker rejects even though the computation is
        # per-device-pure (no collectives inside the scan).
        cells_fn = shard_map(
            jax.vmap(cell), mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=P(DATA_AXIS), check_vma=False)
    else:
        grams_fn = fold_grams
        cells_fn = jax.vmap(cell)

    def program(X, y, mask, fold, regs, alphas):
        Z = jnp.concatenate(
            [X, y[:, None], jnp.ones_like(y)[:, None]], axis=1)
        Z = Z * mask.astype(Z.dtype)[:, None]
        rem = (-Z.shape[0]) % ndev
        if rem:
            # Padding rows: zero in Z (no contribution) and fold −1 (no fold).
            Z = jnp.concatenate([Z, jnp.zeros((rem, Z.shape[1]), Z.dtype)])
            fold = jnp.concatenate([fold, jnp.full((rem,), -1, fold.dtype)])
        A_folds = grams_fn(Z, fold)                      # (k, d+2, d+2)
        A_all = jnp.sum(A_folds, axis=0)
        A_train = A_all[None] - A_folds

        # Flatten (param × fold); every cell solves simultaneously.
        A_rep = jnp.tile(A_train, (m, 1, 1))[cell_idx]
        A_hold = jnp.tile(A_folds, (m, 1, 1))[cell_idx]
        reg_rep = jnp.repeat(regs, k)[cell_idx]
        alpha_rep = jnp.repeat(alphas, k)[cell_idx]
        metrics_cells = cells_fn(A_rep, A_hold, reg_rep, alpha_rep)[:n_cells]
        metrics = metrics_cells.reshape(m, k).mean(axis=1)
        # NaN-safe winner (matches _best_index): a fold can go degenerate
        # for one param without poisoning the whole grid.
        guarded = jnp.where(jnp.isnan(metrics),
                            -jnp.inf if larger_better else jnp.inf, metrics)
        best = jnp.argmax(guarded) if larger_better else jnp.argmin(guarded)

        dt = metrics.dtype
        parts = [metrics, best.astype(dt).reshape(1)]
        for s in distinct:
            r = solver_fns[s](A_all, regs[best], alphas[best])
            parts += [r.coefficients.astype(dt),
                      r.intercept.astype(dt).reshape(1),
                      r.iterations.astype(dt).reshape(1),
                      r.converged.astype(dt).reshape(1),
                      r.objective_history.astype(dt)]
        return jnp.concatenate(parts)

    return serialize_collectives(jax.jit(program), mesh)


def cv_device_program(frame: Frame, estimator: LinearRegression,
                      param_maps: list[dict], metric: str, num_folds: int,
                      seed: int, mesh, larger_better: bool):
    """Build the fused CV program and its device arguments WITHOUT running
    it. Used by ``_linear_cv_fast`` and by the benchmark harness (which
    times the device-complete program under async dispatch, like every
    other packed fit)."""
    # _extract_xy already returns float-dtype device arrays with X 2-D
    X, y, mask = _extract_xy(frame, estimator.features_col, estimator.label_col)
    fold = _fold_ids_device(X.shape[0], num_folds, seed)

    regs = jnp.asarray([p.get("reg_param", estimator.reg_param)
                        for p in param_maps], X.dtype)
    alphas = jnp.asarray([p.get("elastic_net_param", estimator.elastic_net_param)
                          for p in param_maps], X.dtype)

    refit = _refit_solvers(estimator, param_maps)
    program = _cv_program_fn(
        mesh if (mesh is not None and mesh.devices.size > 1) else None,
        num_folds, len(param_maps), X.shape[1], estimator.max_iter,
        estimator.tol, estimator.fit_intercept, estimator.standardization,
        metric, larger_better, refit)
    args = (X, y, jnp.asarray(mask), fold, regs, alphas)
    return program, args, refit, X.shape[1]


def _linear_cv_fast(frame: Frame, estimator: LinearRegression,
                    param_maps: list[dict], metric: str, num_folds: int,
                    seed: int, mesh, larger_better: bool):
    """Run the fused CV program: one dispatch, one host read. Returns
    (metrics[num_params], best_index, best FitResult)."""
    from .solvers import FitResult

    program, args, refit, d = cv_device_program(
        frame, estimator, param_maps, metric, num_folds, seed, mesh,
        larger_better)
    flat = np.asarray(program(*args))                    # the ONE host read

    m = len(param_maps)
    metrics = flat[:m]
    if np.all(np.isnan(metrics)):
        _best_index(metrics, larger_better)              # raise the shared error
    best = int(flat[m])
    _, layout, _ = _cv_flat_layout(m, d, estimator.max_iter, refit)
    off, hlen = layout[refit[best]]
    result = FitResult(
        coefficients=flat[off:off + d],
        intercept=flat[off + d],
        iterations=np.int32(flat[off + d + 1]),
        objective_history=flat[off + d + 3:off + d + 3 + hlen],
        converged=bool(flat[off + d + 2]))
    return metrics, best, result


# --- public API --------------------------------------------------------------

class CrossValidatorModel(Model):
    def __init__(self, best_model: Model, avg_metrics: np.ndarray,
                 best_index: int, sub_models=None):
        self.best_model = best_model
        self.avg_metrics = np.asarray(avg_metrics)
        self.best_index = int(best_index)
        self.sub_models = sub_models

    bestModel = property(lambda self: self.best_model)
    avgMetrics = property(lambda self: self.avg_metrics)

    def transform(self, frame: Frame) -> Frame:
        return self.best_model.transform(frame)


class CrossValidator(Estimator):
    def __init__(self, estimator: Optional[Estimator] = None,
                 estimator_param_maps: Optional[list[dict]] = None,
                 evaluator: Optional[Evaluator] = None,
                 num_folds: int = 3, seed: int = 0,
                 collect_sub_models: bool = False,
                 parallelism: int = 1):
        self.estimator = estimator
        self.estimator_param_maps = estimator_param_maps or [{}]
        self.evaluator = evaluator or RegressionEvaluator()
        self.num_folds = num_folds
        self.seed = seed
        self.collect_sub_models = collect_sub_models
        # MLlib's thread-pool width; meaningless here because the grid is
        # vmapped (all cells run at once). Accepted for API parity.
        self.parallelism = parallelism

    def set_estimator(self, e): self.estimator = e; return self
    def set_estimator_param_maps(self, m): self.estimator_param_maps = m; return self
    def set_evaluator(self, e): self.evaluator = e; return self
    def set_num_folds(self, k): self.num_folds = int(k); return self
    def set_seed(self, s): self.seed = int(s); return self

    setEstimator = set_estimator
    setEstimatorParamMaps = set_estimator_param_maps
    setEvaluator = set_evaluator
    setNumFolds = set_num_folds
    setSeed = set_seed

    def _use_fast_path(self) -> bool:
        if not isinstance(self.estimator, LinearRegression):
            return False
        if getattr(self.estimator, "loss", "squaredError") != "squaredError":
            return False  # huber has no Gramian statistic: generic path
        if getattr(self.estimator, "weight_col", None):
            return False  # weighted fits take the generic fit-per-cell path
        if self.collect_sub_models:
            return False  # per-fold models only exist on the generic path
        if not isinstance(self.evaluator, RegressionEvaluator):
            return False
        if self.evaluator.metric_name not in _FAST_METRICS:
            return False
        # fast path solves every cell with FISTA; exact for any elastic net
        try:
            for p in self.estimator_param_maps:
                est = _apply_params(self.estimator, p)
                resolve_solver(est.solver, est.reg_param, est.elastic_net_param)
        except (ValueError, AttributeError):
            return False
        # grid must only vary solver-vmappable params
        varied = {k for p in self.estimator_param_maps for k in p}
        return varied <= {"reg_param", "elastic_net_param"}

    def fit(self, frame: Frame, mesh=None) -> CrossValidatorModel:
        if self.estimator is None:
            raise ValueError("CrossValidator: estimator not set")
        if mesh is None:
            from ..session import TpuSession

            active = TpuSession.active()
            mesh = active.mesh if active is not None else None

        larger_better = self.evaluator.is_larger_better()
        if self._use_fast_path():
            from .regression import LinearRegressionModel

            metrics, best, result = _linear_cv_fast(
                frame, self.estimator, self.estimator_param_maps,
                self.evaluator.metric_name, self.num_folds, self.seed, mesh,
                larger_better)
            best_est = _apply_params(self.estimator,
                                     self.estimator_param_maps[best])
            # best model was refit inside the fused program (all-data
            # Gramian) — no extra data pass, no extra dispatch
            best_model = LinearRegressionModel(
                coefficients=np.asarray(result.coefficients),
                intercept=float(result.intercept),
                params=best_est._params_dict())
            best_model._summary_source = (frame, result)
            return CrossValidatorModel(best_model, metrics, best)

        # generic path: fit/evaluate each (param, fold) cell
        fold = _fold_ids(frame.num_slots, self.num_folds, self.seed)
        fold_arr = jnp.asarray(fold)
        metrics = np.zeros(len(self.estimator_param_maps))
        sub_models = [] if self.collect_sub_models else None
        for pi, params in enumerate(self.estimator_param_maps):
            est = _apply_params(self.estimator, params)
            scores = []
            for f in range(self.num_folds):
                train = frame.filter(fold_arr != f)
                test = frame.filter(fold_arr == f)
                model = est.fit(train) if mesh is None else est.fit(train, mesh=mesh)
                scores.append(self.evaluator.evaluate(model.transform(test)))
                if sub_models is not None:
                    sub_models.append(model)
            metrics[pi] = float(np.mean(scores))
        best = _best_index(metrics, larger_better)
        best_est = _apply_params(self.estimator, self.estimator_param_maps[best])
        best_model = (best_est.fit(frame) if mesh is None
                      else best_est.fit(frame, mesh=mesh))
        return CrossValidatorModel(best_model, metrics, best, sub_models)


class TrainValidationSplitModel(CrossValidatorModel):
    @property
    def validation_metrics(self):
        return self.avg_metrics

    validationMetrics = validation_metrics


class TrainValidationSplit(CrossValidator):
    """Single random train/validation split (MLlib TrainValidationSplit);
    implemented as 1-fold holdout with ``train_ratio``."""

    def __init__(self, estimator=None, estimator_param_maps=None,
                 evaluator=None, train_ratio: float = 0.75, seed: int = 0):
        super().__init__(estimator, estimator_param_maps, evaluator,
                         num_folds=2, seed=seed)
        self.train_ratio = train_ratio

    def set_train_ratio(self, r): self.train_ratio = float(r); return self

    setTrainRatio = set_train_ratio

    def fit(self, frame: Frame, mesh=None) -> TrainValidationSplitModel:
        rng = np.random.default_rng(self.seed)
        is_val = jnp.asarray(rng.random(frame.num_slots) >= self.train_ratio)
        train = frame.filter(jnp.logical_not(is_val))
        val = frame.filter(is_val)
        larger_better = self.evaluator.is_larger_better()
        metrics = np.zeros(len(self.estimator_param_maps))
        for pi, params in enumerate(self.estimator_param_maps):
            est = _apply_params(self.estimator, params)
            model = est.fit(train) if mesh is None else est.fit(train, mesh=mesh)
            metrics[pi] = self.evaluator.evaluate(model.transform(val))
        best = _best_index(metrics, larger_better)
        best_est = _apply_params(self.estimator, self.estimator_param_maps[best])
        best_model = (best_est.fit(frame) if mesh is None
                      else best_est.fit(frame, mesh=mesh))
        return TrainValidationSplitModel(best_model, metrics, best)
