"""Model selection: ParamGridBuilder, CrossValidator, TrainValidationSplit
(BASELINE.json config: "CrossValidator grid (regParam × elasticNetParam)
pmapped across TPU cores").

TPU-first design — the grid axis is *grid-parallel* (SURVEY.md §5
"Parallelism strategies"): for linear regression every (fold × param) fit is
a tiny solve on sufficient statistics, so the whole cross-validation runs as

1. ONE data pass building ALL per-fold augmented Gramians from the packed
   design (``vmap`` over folds inside ``shard_map`` + one psum when a mesh
   is active),
2. train-fold Gramians by subtraction (``A_train = A_all − A_fold`` — the
   Gramian is additive, so k-fold CV needs no second data pass),
3. a single ``vmap`` over the flattened (param × fold) axis of the FISTA
   solver, with that cell axis SHARDED over the mesh — every core solves
   its slice of the grid simultaneously (the grid-parallel axis),
4. held-out metrics (rmse/mse/r2) computed from the fold Gramians directly.

Estimators without a sufficient-statistics path (LogisticRegression, custom)
take the generic fit-per-cell path, which still shares the session mesh.
"""

from __future__ import annotations

import copy
import functools
import itertools
import re
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .base import Estimator, Model
from .evaluation import Evaluator, RegressionEvaluator
from .regression import LinearRegression, _extract_xy
from .solvers import fista_solve, resolve_solver


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


class ParamGridBuilder:
    """``addGrid(param, values)`` builder; params are attribute names
    (snake_case or MLlib camelCase)."""

    def __init__(self):
        self._grids: dict[str, Sequence] = {}

    def add_grid(self, param: str, values: Sequence) -> "ParamGridBuilder":
        self._grids[_snake(param)] = list(values)
        return self

    addGrid = add_grid

    def base_on(self, params: dict) -> "ParamGridBuilder":
        for k, v in params.items():
            self._grids[_snake(k)] = [v]
        return self

    baseOn = base_on

    def build(self) -> list[dict]:
        names = list(self._grids)
        out = []
        for combo in itertools.product(*(self._grids[n] for n in names)):
            out.append(dict(zip(names, combo)))
        return out or [{}]


def _apply_params(estimator: Estimator, params: dict) -> Estimator:
    est = copy.copy(estimator)
    for k, v in params.items():
        if not hasattr(est, k):
            raise AttributeError(f"{type(est).__name__} has no param {k!r}")
        setattr(est, k, v)
    return est


def _best_index(metrics: np.ndarray, larger_better: bool) -> int:
    if np.all(np.isnan(metrics)):
        raise ValueError(
            "all cross-validation metrics are NaN — typically a fold with "
            "only one class (binary metrics) or an empty fold; use more data, "
            "fewer folds, or a different seed")
    return int(np.nanargmax(metrics) if larger_better else np.nanargmin(metrics))


def _fold_ids(n_slots: int, num_folds: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, num_folds, size=n_slots)


# --- fast path: linear regression on per-fold Gramians ----------------------

_FAST_METRICS = ("rmse", "mse", "r2")


def _holdout_metric_from_gram(A, coef, intercept, metric: str):
    """rmse/mse/r2 on a fold, from its Gramian and a raw-space model."""
    d = A.shape[0] - 2
    XtX = A[:d, :d]
    Xty = A[:d, d]
    sum_x = A[:d, d + 1]
    sum_y = A[d, d + 1]
    yy = A[d, d]
    n = A[d + 1, d + 1]
    sse = (yy - 2.0 * coef @ Xty - 2.0 * intercept * sum_y
           + 2.0 * intercept * (coef @ sum_x) + coef @ XtX @ coef
           + n * intercept * intercept)
    mse = sse / n
    if metric == "mse":
        return mse
    if metric == "rmse":
        return jnp.sqrt(jnp.maximum(mse, 0.0))
    ss_tot = yy - n * (sum_y / n) ** 2
    return 1.0 - sse / ss_tot


@functools.lru_cache(maxsize=None)
def _fold_grams_fn(mesh, num_folds: int):
    """ONE data pass building ALL per-fold Gramians from the packed design
    ``Z = [X, y, 1]·mask``: for 0/1 fold weight ``w``, ``(Z·w)ᵀZ = ZᵀWZ``
    is the fold's masked Gramian (invalid rows are already zero in Z).
    Sharded over the mesh: each device grams its row shard for every fold
    (vmap over the fold axis), then one psum reduces over ICI."""
    def local(Zs, fs):
        def one(f):
            w = (fs == f).astype(Zs.dtype)
            return (Zs * w[:, None]).T @ Zs
        return jax.vmap(one)(jnp.arange(num_folds))

    if mesh is None or mesh.devices.size <= 1:
        return jax.jit(local)
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS

    return jax.jit(jax.shard_map(
        lambda Zs, fs: jax.lax.psum(local(Zs, fs), DATA_AXIS),
        mesh=mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS)), out_specs=P()))


@functools.lru_cache(maxsize=None)
def _cell_solver_fn(max_iter: int, tol: float, fit_intercept: bool,
                    standardization: bool, metric: str):
    """Jitted vmapped per-cell FISTA solve + holdout metric, cached per
    hyperparameters — constructing the jit inline would re-lower the whole
    grid program on EVERY ``fit`` call (a ~90 ms floor that dwarfed the
    solve itself)."""
    def cell(A_tr, A_te, reg, alpha):
        r = fista_solve(A_tr, reg, alpha, max_iter=max_iter, tol=tol,
                        fit_intercept=fit_intercept,
                        standardization=standardization)
        return _holdout_metric_from_gram(A_te, r.coefficients, r.intercept,
                                         metric)

    return jax.jit(jax.vmap(cell))


def _linear_cv_fast(frame: Frame, estimator: LinearRegression,
                    param_maps: list[dict], metric: str, num_folds: int,
                    seed: int, mesh):
    """The vmapped sufficient-stats CV described in the module docstring.
    Returns (metrics[num_params], A_all) — A_all lets the caller refit the
    best model with zero extra data passes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.distributed import pack_design
    from ..parallel.mesh import DATA_AXIS

    X, y, mask = _extract_xy(frame, estimator.features_col, estimator.label_col)
    Z = pack_design(X, y, mask)                          # device-side, packed
    fold = _fold_ids(Z.shape[0], num_folds, seed)

    ndev = 1 if mesh is None else mesh.devices.size
    rem = (-Z.shape[0]) % ndev
    if rem:
        # Padding rows: zero in Z (no contribution) and fold −1 (no fold).
        Z = jnp.concatenate([Z, jnp.zeros((rem, Z.shape[1]), Z.dtype)])
        fold = np.concatenate([fold, np.full(rem, -1, fold.dtype)])
    fold_d = jnp.asarray(fold)
    if ndev > 1:
        shard = NamedSharding(mesh, P(DATA_AXIS))
        Z = jax.device_put(Z, shard)
        fold_d = jax.device_put(fold_d, shard)
    A_folds = _fold_grams_fn(mesh if ndev > 1 else None, num_folds)(Z, fold_d)
    A_all = jnp.sum(A_folds, axis=0)
    A_train = A_all[None] - A_folds                      # (k, d+2, d+2)

    dt = Z.dtype
    regs = jnp.asarray([p.get("reg_param", estimator.reg_param)
                        for p in param_maps], dt)
    alphas = jnp.asarray([p.get("elastic_net_param", estimator.elastic_net_param)
                          for p in param_maps], dt)

    # Flatten (param × fold) and solve every cell simultaneously.
    k = num_folds
    m = len(param_maps)
    A_rep = jnp.tile(A_train, (m, 1, 1))                 # (m*k, d+2, d+2)
    A_hold = jnp.tile(A_folds, (m, 1, 1))
    reg_rep = jnp.repeat(regs, k)
    alpha_rep = jnp.repeat(alphas, k)

    n_cells = m * k
    if ndev > 1:
        # Grid-parallel axis (BASELINE.json config e): shard the cell axis
        # over the mesh so every core solves its slice of the grid.
        cell_pad = (-n_cells) % ndev
        if cell_pad:
            # Wrap-around duplicates (works even when pad > n_cells, e.g. a
            # 3-cell grid on 8 devices); duplicates are trimmed after fetch.
            idx = jnp.arange(n_cells + cell_pad) % n_cells
            A_rep, A_hold = A_rep[idx], A_hold[idx]
            reg_rep, alpha_rep = reg_rep[idx], alpha_rep[idx]
        cell_shard = NamedSharding(mesh, P(DATA_AXIS))
        A_rep = jax.device_put(A_rep, cell_shard)
        A_hold = jax.device_put(A_hold, cell_shard)
        reg_rep = jax.device_put(reg_rep, cell_shard)
        alpha_rep = jax.device_put(alpha_rep, cell_shard)

    cell_fn = _cell_solver_fn(estimator.max_iter, estimator.tol,
                              estimator.fit_intercept,
                              estimator.standardization, metric)
    metrics_cells = cell_fn(A_rep, A_hold, reg_rep, alpha_rep)
    metrics = (np.asarray(metrics_cells)[:n_cells]
               .reshape(m, k).mean(axis=1))
    return metrics, A_all


# --- public API --------------------------------------------------------------

class CrossValidatorModel(Model):
    def __init__(self, best_model: Model, avg_metrics: np.ndarray,
                 best_index: int, sub_models=None):
        self.best_model = best_model
        self.avg_metrics = np.asarray(avg_metrics)
        self.best_index = int(best_index)
        self.sub_models = sub_models

    bestModel = property(lambda self: self.best_model)
    avgMetrics = property(lambda self: self.avg_metrics)

    def transform(self, frame: Frame) -> Frame:
        return self.best_model.transform(frame)


class CrossValidator(Estimator):
    def __init__(self, estimator: Optional[Estimator] = None,
                 estimator_param_maps: Optional[list[dict]] = None,
                 evaluator: Optional[Evaluator] = None,
                 num_folds: int = 3, seed: int = 0,
                 collect_sub_models: bool = False,
                 parallelism: int = 1):
        self.estimator = estimator
        self.estimator_param_maps = estimator_param_maps or [{}]
        self.evaluator = evaluator or RegressionEvaluator()
        self.num_folds = num_folds
        self.seed = seed
        self.collect_sub_models = collect_sub_models
        # MLlib's thread-pool width; meaningless here because the grid is
        # vmapped (all cells run at once). Accepted for API parity.
        self.parallelism = parallelism

    def set_estimator(self, e): self.estimator = e; return self
    def set_estimator_param_maps(self, m): self.estimator_param_maps = m; return self
    def set_evaluator(self, e): self.evaluator = e; return self
    def set_num_folds(self, k): self.num_folds = int(k); return self
    def set_seed(self, s): self.seed = int(s); return self

    setEstimator = set_estimator
    setEstimatorParamMaps = set_estimator_param_maps
    setEvaluator = set_evaluator
    setNumFolds = set_num_folds
    setSeed = set_seed

    def _use_fast_path(self) -> bool:
        if not isinstance(self.estimator, LinearRegression):
            return False
        if self.collect_sub_models:
            return False  # per-fold models only exist on the generic path
        if not isinstance(self.evaluator, RegressionEvaluator):
            return False
        if self.evaluator.metric_name not in _FAST_METRICS:
            return False
        # fast path solves every cell with FISTA; exact for any elastic net
        try:
            for p in self.estimator_param_maps:
                est = _apply_params(self.estimator, p)
                resolve_solver(est.solver, est.reg_param, est.elastic_net_param)
        except (ValueError, AttributeError):
            return False
        # grid must only vary solver-vmappable params
        varied = {k for p in self.estimator_param_maps for k in p}
        return varied <= {"reg_param", "elastic_net_param"}

    def fit(self, frame: Frame, mesh=None) -> CrossValidatorModel:
        if self.estimator is None:
            raise ValueError("CrossValidator: estimator not set")
        if mesh is None:
            from ..session import TpuSession

            active = TpuSession.active()
            mesh = active.mesh if active is not None else None

        larger_better = self.evaluator.is_larger_better()
        if self._use_fast_path():
            metrics, A_all = _linear_cv_fast(
                frame, self.estimator, self.estimator_param_maps,
                self.evaluator.metric_name, self.num_folds, self.seed, mesh)
            best = _best_index(metrics, larger_better)
            best_est = _apply_params(self.estimator,
                                     self.estimator_param_maps[best])
            # refit from the already-reduced statistics — no extra data pass
            best_model = best_est.fit_from_gram(A_all, frame)
            return CrossValidatorModel(best_model, metrics, best)

        # generic path: fit/evaluate each (param, fold) cell
        fold = _fold_ids(frame.num_slots, self.num_folds, self.seed)
        fold_arr = jnp.asarray(fold)
        metrics = np.zeros(len(self.estimator_param_maps))
        sub_models = [] if self.collect_sub_models else None
        for pi, params in enumerate(self.estimator_param_maps):
            est = _apply_params(self.estimator, params)
            scores = []
            for f in range(self.num_folds):
                train = frame.filter(fold_arr != f)
                test = frame.filter(fold_arr == f)
                model = est.fit(train) if mesh is None else est.fit(train, mesh=mesh)
                scores.append(self.evaluator.evaluate(model.transform(test)))
                if sub_models is not None:
                    sub_models.append(model)
            metrics[pi] = float(np.mean(scores))
        best = _best_index(metrics, larger_better)
        best_est = _apply_params(self.estimator, self.estimator_param_maps[best])
        best_model = (best_est.fit(frame) if mesh is None
                      else best_est.fit(frame, mesh=mesh))
        return CrossValidatorModel(best_model, metrics, best, sub_models)


class TrainValidationSplitModel(CrossValidatorModel):
    @property
    def validation_metrics(self):
        return self.avg_metrics

    validationMetrics = validation_metrics


class TrainValidationSplit(CrossValidator):
    """Single random train/validation split (MLlib TrainValidationSplit);
    implemented as 1-fold holdout with ``train_ratio``."""

    def __init__(self, estimator=None, estimator_param_maps=None,
                 evaluator=None, train_ratio: float = 0.75, seed: int = 0):
        super().__init__(estimator, estimator_param_maps, evaluator,
                         num_folds=2, seed=seed)
        self.train_ratio = train_ratio

    def set_train_ratio(self, r): self.train_ratio = float(r); return self

    setTrainRatio = set_train_ratio

    def fit(self, frame: Frame, mesh=None) -> TrainValidationSplitModel:
        rng = np.random.default_rng(self.seed)
        is_val = jnp.asarray(rng.random(frame.num_slots) >= self.train_ratio)
        train = frame.filter(jnp.logical_not(is_val))
        val = frame.filter(is_val)
        larger_better = self.evaluator.is_larger_better()
        metrics = np.zeros(len(self.estimator_param_maps))
        for pi, params in enumerate(self.estimator_param_maps):
            est = _apply_params(self.estimator, params)
            model = est.fit(train) if mesh is None else est.fit(train, mesh=mesh)
            metrics[pi] = self.evaluator.evaluate(model.transform(val))
        best = _best_index(metrics, larger_better)
        best_est = _apply_params(self.estimator, self.estimator_param_maps[best])
        best_model = (best_est.fit(frame) if mesh is None
                      else best_est.fit(frame, mesh=mesh))
        return TrainValidationSplitModel(best_model, metrics, best)
