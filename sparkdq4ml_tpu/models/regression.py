"""LinearRegression estimator/model/summary — the MLlib surface the
reference app exercises (`DataQuality4MachineLearningApp.java:120-154`):
``setMaxIter/setRegParam/setElasticNetParam``, ``fit``, ``transform``,
``summary`` (totalIterations, objectiveHistory, residuals, RMSE, r²),
``intercept``/``getRegParam``/``getTol``, and host-side ``predict``.

The fit path is the TPU-native design from :mod:`~sparkdq4ml_tpu.models.solvers`:
one masked-Gramian data pass (sharded over the session mesh with a ``psum``
when it has >1 device) + an on-device solver loop on the replicated
statistics. MLlib parameter defaults are preserved: ``maxIter=100``,
``regParam=0``, ``elasticNetParam=0``, ``tol=1e-6``, ``fitIntercept=True``,
``standardization=True``, ``solver="auto"``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from ..frame.frame import Frame
from ..ops.expressions import col
from .base import Estimator, Model, persistable, read_json, write_json
from .solvers import FitResult, resolve_solver


def _extract_xy(frame: Frame, features_col: str, label_col: str):
    X = jnp.asarray(frame._column_values(features_col), float_dtype())
    if X.ndim == 1:
        X = X[:, None]
    y = jnp.asarray(frame._column_values(label_col), float_dtype())
    return X, y, frame.mask


@persistable
class LinearRegression(Estimator):
    """Elastic-net linear regression, MLlib numeric convention."""

    # class-level default: estimators persisted before this param existed
    # load via setattr (base.load_stage) and must still resolve it
    weight_col = None

    _persist_attrs = ("max_iter", "reg_param", "elastic_net_param", "tol",
                      "fit_intercept", "standardization", "solver",
                      "features_col", "label_col", "prediction_col",
                      "weight_col", "aggregation_depth", "loss", "epsilon")

    # class-level defaults: stages persisted before these params existed
    loss = "squaredError"
    epsilon = 1.35

    def __init__(self, max_iter: int = 100, reg_param: float = 0.0,
                 elastic_net_param: float = 0.0, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True,
                 solver: str = "auto", features_col: str = "features",
                 label_col: str = "label", prediction_col: str = "prediction",
                 weight_col: Optional[str] = None,
                 aggregation_depth: int = 2, loss: str = "squaredError",
                 epsilon: float = 1.35):
        self.max_iter = max_iter
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization
        self.solver = solver
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.weight_col = weight_col
        # treeAggregate tree depth in MLlib; meaningless under psum (the ICI
        # all-reduce is already log-depth in hardware). Accepted for API parity.
        self.aggregation_depth = aggregation_depth
        if loss not in ("squaredError", "huber"):
            raise ValueError(f"unknown loss {loss!r} "
                             "(squaredError or huber)")
        self.loss = loss
        self.epsilon = float(epsilon)

    # -- MLlib-style fluent setters/getters --------------------------------
    def set_max_iter(self, v: int):
        self.max_iter = int(v); return self

    def set_reg_param(self, v: float):
        self.reg_param = float(v); return self

    def set_elastic_net_param(self, v: float):
        self.elastic_net_param = float(v); return self

    def set_tol(self, v: float):
        self.tol = float(v); return self

    def set_fit_intercept(self, v: bool):
        self.fit_intercept = bool(v); return self

    def set_standardization(self, v: bool):
        self.standardization = bool(v); return self

    def set_solver(self, v: str):
        self.solver = v; return self

    def set_features_col(self, v: str):
        self.features_col = v; return self

    def set_label_col(self, v: str):
        self.label_col = v; return self

    def set_prediction_col(self, v: str):
        self.prediction_col = v; return self

    def set_weight_col(self, v):
        self.weight_col = v; return self

    def set_aggregation_depth(self, v: int):
        self.aggregation_depth = int(v); return self

    setMaxIter = set_max_iter
    setRegParam = set_reg_param
    setElasticNetParam = set_elastic_net_param
    setTol = set_tol
    setFitIntercept = set_fit_intercept
    setStandardization = set_standardization
    setSolver = set_solver
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setPredictionCol = set_prediction_col
    setWeightCol = set_weight_col
    setAggregationDepth = set_aggregation_depth

    def get_max_iter(self): return self.max_iter
    def get_reg_param(self): return self.reg_param
    def get_elastic_net_param(self): return self.elastic_net_param
    def get_tol(self): return self.tol
    def get_fit_intercept(self): return self.fit_intercept
    def get_standardization(self): return self.standardization
    def get_solver(self): return self.solver

    getMaxIter = get_max_iter
    getRegParam = get_reg_param
    getElasticNetParam = get_elastic_net_param
    getTol = get_tol
    getFitIntercept = get_fit_intercept
    getStandardization = get_standardization
    getSolver = get_solver

    def _params_dict(self) -> dict:
        return {k: getattr(self, k) for k in (
            "max_iter", "reg_param", "elastic_net_param", "tol",
            "fit_intercept", "standardization", "solver", "features_col",
            "label_col", "prediction_col", "weight_col",
            "aggregation_depth", "loss", "epsilon")}

    # -- fit ----------------------------------------------------------------
    def fit(self, frame: Frame, mesh=None) -> "LinearRegressionModel":
        """Fit on the frame's valid rows. ``mesh`` defaults to the active
        session's device mesh (row-sharded psum path when >1 device)."""
        if mesh is None:
            from ..session import TpuSession

            active = TpuSession.active()
            mesh = active.mesh if active is not None else None
        # Imported here, not at module top: parallel.distributed imports
        # models.solvers, so a top-level import would make package init
        # order-sensitive (importing parallel first used to crash).
        from ..parallel.distributed import (fused_linear_fit_packed,
                                            pack_design, place_packed,
                                            unpack_fit_result)

        X, y, mask = _extract_xy(frame, self.features_col, self.label_col)
        if self.weight_col is not None:
            # Instance weights (MLlib weightCol): scaling packed rows by
            # sqrt(w) makes the Gramian ZᵀZ = Σ w·zzᵀ — every moment the
            # solver unpacks (n = Σw, weighted mean/std, Gram, correlation)
            # becomes its weighted form, so an integer weight k is EXACTLY
            # a row repeated k times (the regression test for this path).
            # Summary metrics remain unweighted row statistics.
            # Masked rows' weight VALUES never participate: validation
            # only inspects valid rows, and sqrt() sees 0 there (a NaN/
            # negative payload in a filtered slot must not poison Z).
            # Validating costs one host read — a weighted-fit-only price.
            w = frame._column_values(self.weight_col)
            w_host = np.asarray(w)
            # NaN fails >= too: a NaN weight on a valid row must raise,
            # not silently poison the Gramian
            if not bool(np.all(w_host[np.asarray(mask)] >= 0)):
                raise ValueError("weights must be nonnegative")
            mask_b = mask
            mask = mask.astype(float_dtype()) * jnp.sqrt(
                jnp.where(mask_b, jnp.asarray(w, float_dtype()), 0.0))
        if self.loss == "huber":
            return self._fit_huber(frame, X, y, mask)
        solver_name = resolve_solver(self.solver, self.reg_param,
                                     self.elastic_net_param)
        if mesh is not None and mesh.devices.size <= 1:
            mesh = None  # unify the single-device cache key
        from ..utils import faults as _faults
        from ..utils import observability as _obs
        from ..utils import recovery as _recovery
        from ..utils.profiling import counters
        from .solvers import downgrade_solver

        Z = pack_design(X, y, mask)
        hyper = jnp.asarray([self.reg_param, self.elastic_net_param],
                            float_dtype())
        d = X.shape[1]

        def make_call(m, sname):
            # Everything stays inside the closure: fallback rungs must
            # cost nothing (no trace, no placement) unless they run.
            def call():
                _faults.inject("fit_packed")
                fit_fn = fused_linear_fit_packed(
                    m, sname, self.max_iter, self.tol, self.fit_intercept,
                    self.standardization)
                Zd = place_packed(Z, m)
                return _faults.corrupt(
                    "solver", unpack_fit_result(fit_fn(Zd, hyper), d))
            return call

        # Fallback ladder: sharded fit → single-device fit → closed-form
        # solver (when the penalty permits). Identical statistics on every
        # rung; only throughput/solver trajectory degrade. Rungs after the
        # first run only when the one before exhausted its retry policy.
        fallbacks = []
        if mesh is not None:
            fallbacks.append(("single_device", make_call(None, solver_name)))
        downgraded = downgrade_solver(solver_name, self.reg_param,
                                      self.elastic_net_param)
        if downgraded is not None:
            fallbacks.append((f"solver_{downgraded}",
                              make_call(None, downgraded)))
        # Observability: the fit span records the cold-compile vs steady
        # split (trace-cache probe on the lru-cached jit factory), the
        # solver trajectory (iterations/objective — read from the packed
        # result, which unpack_fit_result already materialized on host, so
        # no added sync), and any retry/fallback the resilience layer took.
        with _obs.fit_span("fit.linear_regression", fused_linear_fit_packed,
                           rows=int(X.shape[0]), features=d,
                           solver=solver_name,
                           shards=(mesh.devices.size if mesh is not None
                                   else 1),
                           max_iter=self.max_iter) as s:
            with _obs.span("fit.solve", cat="solver", solver=solver_name):
                result = _recovery.resilient_call(
                    make_call(mesh, solver_name), site="fit_packed",
                    policy=_recovery.active_policy("fit_packed"),
                    validate=_recovery.result_validator(),
                    fallbacks=fallbacks, breaker=_recovery.DEVICE_BREAKER)
            iters = int(result.iterations)
            counters.increment("solver.fits")
            counters.increment("solver.iterations", iters)
            if s is not _obs._NOOP:
                from ..utils import meminfo as _meminfo

                hist = np.asarray(result.objective_history, np.float64)
                # input_bytes: static-shape estimate of the packed design
                # the fit dispatched (the fit-node device-memory figure
                # EXPLAIN/memory_report cross-reference) — metadata only,
                # never a device read.
                s.set(iterations=iters, converged=bool(result.converged),
                      objective_final=float(
                          hist[min(iters, hist.shape[0] - 1)]),
                      input_bytes=_meminfo.estimated_bytes(Z))
        model = LinearRegressionModel(
            coefficients=np.asarray(result.coefficients),
            intercept=float(result.intercept),
            params=self._params_dict())
        # Summary is constructed lazily on first access: it needs a full
        # batch transform + host gather, which sweep-style callers that only
        # read coefficients should never pay for.
        model._summary_source = (frame, result)
        return model


    def _fit_huber(self, frame, X, y, mask) -> "LinearRegressionModel":
        """MLlib ``loss="huber"``: robust fit of Huber's concomitant-scale
        objective (see ``solvers.huber_fit``). L1 is unsupported exactly
        as in MLlib; the scale estimate surfaces as ``model.scale``.
        The robust loss has no Gramian sufficient statistic, so this
        path revisits rows per iteration inside one jitted while_loop
        (a mesh would psum the per-iteration gradient; the single-program
        form covers the reference's row counts with headroom)."""
        from .solvers import huber_fit

        if self.elastic_net_param not in (0, 0.0):
            raise ValueError("huber loss supports only L2 regularization "
                             "(elasticNetParam must be 0), as in MLlib")
        b_, c_, sigma, iters, obj = huber_fit(
            X, y, mask, epsilon=self.epsilon, reg_param=self.reg_param,
            fit_intercept=self.fit_intercept, max_iter=self.max_iter,
            tol=self.tol, standardization=self.standardization)
        model = LinearRegressionModel(
            coefficients=np.asarray(b_), intercept=float(c_),
            params=self._params_dict(), scale=float(sigma))
        fd = jnp.asarray(X).dtype
        result = FitResult(
            coefficients=jnp.asarray(b_), intercept=jnp.asarray(c_, fd),
            iterations=jnp.asarray(int(iters), jnp.int32),
            objective_history=jnp.asarray([float(obj)], fd),
            converged=jnp.asarray(int(iters) < self.max_iter))
        model._summary_source = (frame, result)
        return model

    def fit_from_gram(self, A, frame: Frame) -> "LinearRegressionModel":
        """Fit from a precomputed augmented Gramian — zero data passes.
        Used by CrossValidator's fast path to refit the best model from the
        already-reduced statistics."""
        from .solvers import solve

        result = solve(A, self.reg_param, self.elastic_net_param,
                       max_iter=self.max_iter, tol=self.tol,
                       fit_intercept=self.fit_intercept,
                       standardization=self.standardization,
                       solver=self.solver)
        model = LinearRegressionModel(
            coefficients=np.asarray(result.coefficients),
            intercept=float(result.intercept),
            params=self._params_dict())
        model._summary_source = (frame, result)
        return model


@persistable
class LinearRegressionModel(Model):
    def __init__(self, coefficients: np.ndarray, intercept: float,
                 params: Optional[dict] = None, scale: float = 1.0):
        self.coefficients = np.asarray(coefficients)
        self.intercept = float(intercept)
        # MLlib: 1.0 for squared-error fits; the fitted sigma for huber
        self.scale = float(scale)
        self._params = dict(params or {})
        self._training_summary: Optional[LinearRegressionTrainingSummary] = None
        self._summary_source = None  # (frame, FitResult) until first access

    # Parameter read-back used by the app (`App.java:141-146`)
    def get_reg_param(self): return self._params.get("reg_param", 0.0)
    def get_tol(self): return self._params.get("tol", 1e-6)
    def get_max_iter(self): return self._params.get("max_iter", 100)
    def get_elastic_net_param(self): return self._params.get("elastic_net_param", 0.0)

    getRegParam = get_reg_param
    getTol = get_tol
    getMaxIter = get_max_iter
    getElasticNetParam = get_elastic_net_param

    @property
    def features_col(self):
        return self._params.get("features_col", "features")

    @property
    def prediction_col(self):
        return self._params.get("prediction_col", "prediction")

    @property
    def label_col(self):
        return self._params.get("label_col", "label")

    @property
    def num_features(self) -> int:
        return int(self.coefficients.shape[0])

    # -- inference ----------------------------------------------------------
    def transform(self, frame: Frame) -> Frame:
        """Append the prediction column (batch inference, one fused matvec —
        `App.java:129`)."""
        X = jnp.asarray(frame._column_values(self.features_col), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        pred = X @ jnp.asarray(self.coefficients, X.dtype) + self.intercept
        return frame.with_column(self.prediction_col, pred)

    def predict(self, features) -> float:
        """Host-side single-point inference (`App.java:149-151`) — a dot+add
        with no device round-trip, like MLlib's driver-local predict."""
        v = np.asarray(features, dtype=np.float64).reshape(-1)
        return float(v @ self.coefficients.astype(np.float64) + self.intercept)

    # -- summaries -----------------------------------------------------------
    @property
    def summary(self) -> "LinearRegressionTrainingSummary":
        if self._training_summary is None:
            if self._summary_source is None:
                raise RuntimeError("model was not fit with summary (loaded model?)")
            frame, result = self._summary_source
            self._training_summary = LinearRegressionTrainingSummary(
                self, frame, result)
        return self._training_summary

    @property
    def has_summary(self) -> bool:
        return self._training_summary is not None or self._summary_source is not None

    hasSummary = has_summary

    def evaluate(self, frame: Frame) -> "LinearRegressionSummary":
        return LinearRegressionSummary(self, frame)

    # -- persistence (capability upgrade over the reference; SURVEY.md §5
    #    "Checkpoint / resume") ---------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        write_json(os.path.join(path, "metadata.json"), {
            "class": "LinearRegressionModel",
            "intercept": self.intercept,
            "scale": self.scale,
            "params": self._params,
        })
        np.save(os.path.join(path, "coefficients.npy"), self.coefficients)

    @classmethod
    def load(cls, path: str) -> "LinearRegressionModel":
        meta = read_json(os.path.join(path, "metadata.json"))
        if meta.get("class") != "LinearRegressionModel":
            raise ValueError(f"not a LinearRegressionModel checkpoint: {path}")
        coef = np.load(os.path.join(path, "coefficients.npy"))
        return cls(coef, meta["intercept"], meta.get("params"),
                   scale=meta.get("scale", 1.0))

    # Pipeline-persistence hooks (base.save_stage/load_stage dispatch here).
    def _save_to_dir(self, path: str) -> None:
        self.save(path)

    @classmethod
    def _load_from_dir(cls, path: str, meta: dict):
        return cls.load(path)


class LinearRegressionSummary:
    """Evaluation metrics over a frame's valid rows (mask-weighted — the
    masked-filter semantics of SURVEY.md §7 never leak into the stats)."""

    def __init__(self, model: LinearRegressionModel, frame: Frame):
        self._model = model
        self._frame = frame
        pred_frame = model.transform(frame)
        d = pred_frame.to_pydict()
        self._label = d[model.label_col].astype(np.float64)
        self._pred = d[model.prediction_col].astype(np.float64)
        self._predictions_frame = pred_frame

    @property
    def predictions(self) -> Frame:
        return self._predictions_frame

    @property
    def num_instances(self) -> int:
        return int(self._label.shape[0])

    numInstances = num_instances

    @property
    def residuals(self) -> Frame:
        return Frame({"residuals": self._label - self._pred})

    @property
    def mean_squared_error(self) -> float:
        return float(np.mean((self._label - self._pred) ** 2))

    meanSquaredError = mean_squared_error

    @property
    def root_mean_squared_error(self) -> float:
        return float(np.sqrt(self.mean_squared_error))

    rootMeanSquaredError = root_mean_squared_error

    @property
    def mean_absolute_error(self) -> float:
        return float(np.mean(np.abs(self._label - self._pred)))

    meanAbsoluteError = mean_absolute_error

    @property
    def explained_variance(self) -> float:
        return float(np.var(self._pred))

    explainedVariance = explained_variance

    @property
    def r2(self) -> float:
        ss_res = float(np.sum((self._label - self._pred) ** 2))
        ss_tot = float(np.sum((self._label - np.mean(self._label)) ** 2))
        if ss_tot == 0.0:  # constant label: undefined, like MLlib's 0/0 → NaN
            return float("nan")
        return 1.0 - ss_res / ss_tot

    @property
    def r2adj(self) -> float:
        n = self.num_instances
        d = self._model.num_features
        return 1.0 - (1.0 - self.r2) * (n - 1) / (n - d - 1)

    @property
    def degrees_of_freedom(self) -> int:
        extra = 1 if self._model._params.get("fit_intercept", True) else 0
        return self.num_instances - self._model.num_features - extra

    degreesOfFreedom = degrees_of_freedom

    # -- inference statistics (MLlib: solver="normal" surface) -------------
    def _inference(self):
        """(std_errors, t_values, p_values), intercept LAST (MLlib's
        layout). Classical OLS covariance ``σ̂²(XᵀX)⁻¹`` — exact only for
        unpenalized, unweighted TRAINING fits, so anything else raises
        like MLlib's UnsupportedOperationException (evaluate() summaries
        have no valid Wald statistics; weighted fits should use the GLM
        gaussian path, which computes the weighted versions properly)."""
        cached = getattr(self, "_inference_cache", None)
        if cached is not None:
            return cached
        params = self._model._params or {}
        if float(params.get("reg_param", 0.0)) > 0.0:
            raise ValueError(
                "standard errors / t-values / p-values are available only "
                "for unpenalized fits (MLlib: solver='normal' without "
                "regularization); this model has regParam > 0")
        if params.get("weight_col") is not None:
            raise ValueError(
                "standard errors for weighted fits are not computed here; "
                "use GeneralizedLinearRegression(family='gaussian', "
                "weight_col=...) whose summary implements the weighted "
                "Wald statistics")
        if not isinstance(self, LinearRegressionTrainingSummary):
            raise ValueError(
                "inference statistics exist only on the TRAINING summary "
                "(MLlib: evaluate() summaries throw); held-out residuals "
                "do not form Wald statistics for the training estimate")
        from scipy import stats as _sstats

        Xd, _, mask = _extract_xy(self._frame, self._model.features_col,
                                  self._model.label_col)
        X = np.asarray(Xd, np.float64)[np.asarray(mask)]
        fit_intercept = bool(params.get("fit_intercept", True))
        A = np.concatenate([X, np.ones((len(X), 1))], axis=1) \
            if fit_intercept else X
        dof = self.degrees_of_freedom
        if dof <= 0:
            raise ValueError("non-positive degrees of freedom")
        G = A.T @ A                  # p×p Gram: rank check + inverse share it
        if np.linalg.matrix_rank(G) < A.shape[1]:
            # MLlib's normal solver fails on singular normal equations; a
            # pinv here would return finite-but-meaningless errors for an
            # unidentifiable (collinear) design
            raise ValueError(
                "design matrix is rank-deficient (collinear features); "
                "standard errors are not identifiable")
        resid = self._label - self._pred
        sigma2 = float(resid @ resid) / dof
        cov = sigma2 * np.linalg.pinv(G)
        se = np.sqrt(np.diag(cov))
        coef = np.asarray(self._model.coefficients, np.float64)
        beta = np.concatenate([coef, [self._model.intercept]]) \
            if fit_intercept else coef
        with np.errstate(divide="ignore", invalid="ignore"):
            t = beta / se
        p = 2.0 * _sstats.t.sf(np.abs(t), dof)
        self._inference_cache = (se, t, p)
        return self._inference_cache

    @property
    def coefficient_standard_errors(self) -> np.ndarray:
        return self._inference()[0]

    coefficientStandardErrors = coefficient_standard_errors

    @property
    def t_values(self) -> np.ndarray:
        return self._inference()[1]

    tValues = t_values

    @property
    def p_values(self) -> np.ndarray:
        return self._inference()[2]

    pValues = p_values


class LinearRegressionTrainingSummary(LinearRegressionSummary):
    """Training summary: evaluation metrics + solver trajectory
    (`App.java:132-139`)."""

    def __init__(self, model: LinearRegressionModel, frame: Frame,
                 result: FitResult):
        super().__init__(model, frame)
        self._iterations = int(result.iterations)
        hist = np.asarray(result.objective_history, dtype=np.float64)
        # history[0] is the initial objective; keep entries up to convergence.
        self._objective_history = hist[: self._iterations + 1]

    @property
    def total_iterations(self) -> int:
        return self._iterations

    totalIterations = total_iterations

    @property
    def objective_history(self) -> np.ndarray:
        return self._objective_history

    objectiveHistory = objective_history


# ---------------------------------------------------------------------------
# IsotonicRegression (MLlib org.apache.spark.ml.regression.IsotonicRegression)
# ---------------------------------------------------------------------------

@persistable
class IsotonicRegression(Estimator):
    """MLlib ``IsotonicRegression``: weighted isotonic (or antitonic) fit of
    label vs ONE feature, via pool-adjacent-violators.

    Design: PAVA is inherently sequential pooling — a host algorithm by
    nature (same rule as the KS test's sort, stat.py) — but it runs ONCE on
    ≤ n aggregated points; prediction is vectorized interpolation over the
    fitted boundaries and rides the device path through ``with_column``.
    MLlib semantics reproduced: points with equal feature values aggregate
    to their weighted-mean label first; prediction linearly interpolates
    between boundaries and is constant beyond them; ``isotonic=False``
    fits the antitonic (decreasing) function.
    """

    _persist_attrs = ("isotonic", "features_col", "label_col",
                      "prediction_col", "weight_col", "feature_index")

    def __init__(self, isotonic: bool = True, features_col: str = "features",
                 label_col: str = "label", prediction_col: str = "prediction",
                 weight_col: Optional[str] = None, feature_index: int = 0):
        self.isotonic = bool(isotonic)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.weight_col = weight_col
        self.feature_index = int(feature_index)

    def set_isotonic(self, v):
        self.isotonic = bool(v)
        return self

    def set_feature_index(self, v):
        self.feature_index = int(v)
        return self

    def set_weight_col(self, v):
        self.weight_col = v
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setIsotonic = set_isotonic
    setFeatureIndex = set_feature_index
    setWeightCol = set_weight_col
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setPredictionCol = set_prediction_col

    def fit(self, frame: Frame) -> "IsotonicRegressionModel":
        X = np.asarray(frame._column_values(self.features_col), np.float64)
        if X.ndim > 1:
            X = X[:, self.feature_index]
        y = np.asarray(frame._column_values(self.label_col), np.float64)
        mask = np.asarray(frame.mask)
        w = np.ones_like(y) if self.weight_col is None else \
            np.asarray(frame._column_values(self.weight_col), np.float64)
        x, y, w = X[mask], y[mask], w[mask]
        if x.size == 0:
            raise ValueError("IsotonicRegression: no valid rows")
        if not (np.all(np.isfinite(x)) and np.all(np.isfinite(y))):
            raise ValueError("IsotonicRegression: non-finite feature/label "
                             "in valid rows")
        if np.any(w < 0):
            raise ValueError("weights must be nonnegative")

        sign = 1.0 if self.isotonic else -1.0
        order = np.argsort(x, kind="stable")
        xs, ys, ws = x[order], sign * y[order], w[order]

        # aggregate duplicate feature values: weighted mean label (MLlib)
        uniq, start = np.unique(xs, return_index=True)
        wsum = np.add.reduceat(ws, start)
        ysum = np.add.reduceat(ws * ys, start)
        keep = wsum > 0
        bx = uniq[keep]
        bw = wsum[keep]
        by = ysum[keep] / bw

        # pool adjacent violators (weighted), classic stack formulation
        vals: list = []
        wts: list = []
        xs_lo: list = []
        xs_hi: list = []
        for xi, yi, wi in zip(bx, by, bw):
            vals.append(yi)
            wts.append(wi)
            xs_lo.append(xi)
            xs_hi.append(xi)
            while len(vals) > 1 and vals[-2] > vals[-1]:
                y2, w2 = vals.pop(), wts.pop()
                hi2 = xs_hi.pop()          # merged pool spans (lo1, hi2)
                xs_lo.pop()
                y1, w1 = vals.pop(), wts.pop()
                xs_hi.pop()
                lo1 = xs_lo.pop()
                vals.append((y1 * w1 + y2 * w2) / (w1 + w2))
                wts.append(w1 + w2)
                xs_lo.append(lo1)
                xs_hi.append(hi2)

        # MLlib keeps each pool's boundary pair (lo, hi) with the pooled
        # value at both ends, then interpolates linearly between pools
        boundaries: list = []
        predictions: list = []
        for lo, hi, v in zip(xs_lo, xs_hi, vals):
            boundaries.append(lo)
            predictions.append(v)
            if hi != lo:
                boundaries.append(hi)
                predictions.append(v)
        return IsotonicRegressionModel(
            np.asarray(boundaries, np.float64),
            sign * np.asarray(predictions, np.float64),
            {"features_col": self.features_col,
             "prediction_col": self.prediction_col,
             "feature_index": self.feature_index,
             "isotonic": self.isotonic})


@persistable
class IsotonicRegressionModel(Model):
    """Fitted step/piecewise-linear function: ``boundaries`` (ascending) and
    ``predictions``; transform is vectorized interpolation with constant
    extrapolation (exactly ``np.interp``'s contract, which matches MLlib's
    predictionForX)."""

    _persist_attrs = ("boundaries", "predictions", "_params")

    def __init__(self, boundaries, predictions, params=None):
        self.boundaries = np.asarray(boundaries, np.float64)
        self.predictions = np.asarray(predictions, np.float64)
        self._params = dict(params or {})

    def _p(self, k, default=None):
        return self._params.get(k, default)

    def _predict_array(self, x):
        return np.interp(np.asarray(x, np.float64), self.boundaries,
                         self.predictions)

    def transform(self, frame: Frame) -> Frame:
        X = np.asarray(frame._column_values(
            self._p("features_col", "features")), np.float64)
        if X.ndim > 1:
            X = X[:, self._p("feature_index", 0)]
        pred = self._predict_array(X)
        return frame.with_column(self._p("prediction_col", "prediction"),
                                 jnp.asarray(pred, float_dtype()))

    def predict(self, feature: float) -> float:
        return float(self._predict_array([float(feature)])[0])
