"""Word2Vec (MLlib ``org.apache.spark.ml.feature.Word2Vec`` — shipped by the
reference's mllib dependency, pom.xml:29-32).

TPU-first design — not a port of Spark's Hogwild-style async skip-gram:

* **Skip-gram with negative sampling (SGNS)**, the same objective family
  MLlib trains (MLlib uses hierarchical softmax; SGNS is the standard
  modern equivalent with identical embedding-quality semantics and a far
  better accelerator mapping: no per-node tree walks, just batched
  gathers + one dot per pair).
* **The entire training loop is ONE ``lax.scan``** over static-shape
  minibatches of (center, context) pairs. Each step: gather embeddings,
  draw K negatives from the unigram^0.75 table with ``jax.random``
  (counter-based, reproducible by seed), compute the sigmoid losses, and
  apply SGD via two ``segment_sum`` scatter-adds — synchronous and
  deterministic, vs Spark's racy Hogwild updates.
* **Mesh = synchronous data parallelism**: pair minibatches shard over the
  data axis and the two gradient scatters psum over ICI before the
  replicated update — the treeAggregate analogue per step.
* Pair generation (windowing) and vocab building are host-side one-time
  passes over the token lists (strings never touch the TPU — same rule as
  the rest of the text pipeline); ``transform`` averages word vectors per
  document (MLlib's Word2VecModel.transform), ``findSynonyms`` is one
  cosine matmul + top_k.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from .base import Estimator, Model, persistable
from .text import _obj_array, _token_col
from ..parallel.mesh import serialize_collectives


def _build_vocab(col, mask, min_count: int, max_vocab: int):
    """Host pass: vocabulary (count-desc, ties alphabetical) + counts."""
    docs = [t for t, m in zip(col, mask) if m and t is not None and len(t)]
    flat = [t for toks in docs for t in toks]
    if not flat:
        return [], np.zeros((0,), np.int64), docs
    uniq, counts = np.unique(np.asarray(flat), return_counts=True)
    keep = counts >= min_count
    uniq, counts = uniq[keep], counts[keep]
    order = np.lexsort((uniq, -counts))
    uniq, counts = uniq[order][:max_vocab], counts[order][:max_vocab]
    return [str(t) for t in uniq], counts.astype(np.int64), docs


def _build_pairs(docs, index: dict, window: int, seed: int,
                 max_sentence_length: int = 1000):
    """Host pass: all (center, context) skip-gram pairs with the word2vec
    convention of a per-center window size drawn uniformly from 1..window.
    Documents longer than ``max_sentence_length`` in-vocabulary tokens are
    chunked first (MLlib's maxSentenceLength), so no window spans a chunk
    boundary."""
    rng = np.random.default_rng(seed)
    centers, contexts = [], []
    for toks in docs:
        all_ids = [index[t] for t in toks if t in index]
        for s in range(0, len(all_ids), max_sentence_length):
            ids = all_ids[s: s + max_sentence_length]
            L = len(ids)
            if L < 2:
                continue
            win = rng.integers(1, window + 1, size=L)
            for i, c in enumerate(ids):
                lo = max(0, i - int(win[i]))
                hi = min(L, i + int(win[i]) + 1)
                for j in range(lo, hi):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
    return (np.asarray(centers, np.int32), np.asarray(contexts, np.int32))


@functools.lru_cache(maxsize=None)
def _sgns_fit_fn(vocab_size: int, dim: int, batch: int, steps: int,
                 negatives: int, lr0: float, mesh=None):
    """Jitted SGNS training scan, cached per static config.

    Signature: ``fit(centers, contexts, noise_cdf, key, U0, V0) ->
    (U, V, loss_history)`` where centers/contexts are (steps, batch)
    minibatch id matrices (sharded over the batch axis under a mesh),
    noise_cdf is the unigram^0.75 sampling CDF, and U/V are the input/
    output embedding matrices (replicated).
    """
    def step_loss(U, V, c_ids, o_ids, noise_cdf, key, lr, psum_axis=None):
        if psum_axis is not None:
            # distinct negatives per shard — a replicated key would make
            # every device draw the same uniforms (correlated samples)
            key = jax.random.fold_in(key, jax.lax.axis_index(psum_axis))
        u = U[c_ids]                                   # (B, dim)
        v_pos = V[o_ids]
        neg = jnp.searchsorted(
            noise_cdf,
            jax.random.uniform(key, (c_ids.shape[0],
                                     negatives))).astype(jnp.int32)
        v_neg = V[neg]                                 # (B, K, dim)

        pos_logit = jnp.sum(u * v_pos, axis=1)
        neg_logit = jnp.einsum("bd,bkd->bk", u, v_neg)
        # SGNS loss: −log σ(pos) − Σ log σ(−neg)
        loss = (jnp.mean(jax.nn.softplus(-pos_logit))
                + jnp.mean(jnp.sum(jax.nn.softplus(neg_logit), axis=1)))

        g_pos = jax.nn.sigmoid(pos_logit) - 1.0        # (B,)
        g_neg = jax.nn.sigmoid(neg_logit)              # (B, K)
        gu = g_pos[:, None] * v_pos + jnp.einsum("bk,bkd->bd", g_neg, v_neg)
        gv_pos = g_pos[:, None] * u
        gv_neg = g_neg[:, :, None] * u[:, None, :]     # (B, K, dim)

        dU = jax.ops.segment_sum(gu, c_ids, num_segments=vocab_size)
        all_v_ids = jnp.concatenate([o_ids, neg.reshape(-1)])
        all_gv = jnp.concatenate([gv_pos, gv_neg.reshape(-1, dim)])
        dV = jax.ops.segment_sum(all_gv, all_v_ids, num_segments=vocab_size)
        if psum_axis is not None:
            dU = jax.lax.psum(dU, psum_axis)
            dV = jax.lax.psum(dV, psum_axis)
            loss = jax.lax.pmean(loss, psum_axis)
        # full lr per PAIR (summed batch gradient), matching sequential
        # word2vec's effective step size — a 1/B mean would shrink each
        # pair's update by the batch size and stall learning
        return U - lr * dU, V - lr * dV, loss

    def core(centers, contexts, noise_cdf, key, U0, V0, psum_axis=None):
        def body(carry, xs):
            U, V, i = carry                  # int32 counter: a float32 one
            c_ids, o_ids = xs                # would freeze at 2^24 steps
            lr = lr0 * jnp.maximum(1.0 - i.astype(U0.dtype) / steps, 1e-2)
            k = jax.random.fold_in(key, i)
            U, V, loss = step_loss(U, V, c_ids, o_ids, noise_cdf, k, lr,
                                   psum_axis)
            return (U, V, i + 1), loss

        (U, V, _), losses = jax.lax.scan(
            body, (U0, V0, jnp.asarray(0, jnp.int32)),
            (centers, contexts))
        return U, V, losses

    if mesh is None:
        return jax.jit(lambda c, o, cdf, key, U0, V0: core(c, o, cdf, key,
                                                           U0, V0))

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    # minibatches shard on the batch (pair) axis; embeddings replicate
    return serialize_collectives(jax.jit(shard_map(
        lambda c, o, cdf, key, U0, V0: core(c, o, cdf, key, U0, V0,
                                            DATA_AXIS),
        mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P(None, DATA_AXIS), P(), P(), P(),
                  P()),
        out_specs=(P(), P(), P()))), mesh)


@persistable
class Word2Vec(Estimator):
    """MLlib ``Word2Vec`` builder surface: setVectorSize/setWindowSize/
    setMinCount/setMaxIter/setStepSize/setSeed/setMaxSentenceLength(+cols);
    plus ``num_negatives`` for the SGNS objective (see module docstring)."""

    _persist_attrs = ('vector_size', 'window_size', 'min_count', 'max_iter',
                      'step_size', 'num_negatives', 'batch_size',
                      'max_vocab_size', 'max_sentence_length', 'seed',
                      'input_col', 'output_col')

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 min_count: int = 5, max_iter: int = 1,
                 step_size: float = 0.025, num_negatives: int = 5,
                 batch_size: int = 1024, max_vocab_size: int = 262144,
                 max_sentence_length: int = 1000, seed: int = 0,
                 input_col: str = None, output_col: str = None):
        if vector_size < 1:
            raise ValueError("vector_size must be >= 1")
        if window_size < 1:
            raise ValueError("window_size must be >= 1")
        if max_sentence_length < 2:
            raise ValueError("max_sentence_length must be >= 2")
        self.vector_size = int(vector_size)
        self.window_size = int(window_size)
        self.min_count = int(min_count)
        self.max_iter = int(max_iter)
        self.step_size = float(step_size)
        self.num_negatives = int(num_negatives)
        self.batch_size = int(batch_size)
        self.max_vocab_size = int(max_vocab_size)
        self.max_sentence_length = int(max_sentence_length)
        self.seed = int(seed)
        self.input_col = input_col
        self.output_col = output_col

    def set_max_sentence_length(self, v):
        if v < 2:
            raise ValueError("max_sentence_length must be >= 2")
        self.max_sentence_length = int(v)
        return self

    setMaxSentenceLength = set_max_sentence_length

    def set_vector_size(self, v):
        if v < 1:
            raise ValueError("vector_size must be >= 1")
        self.vector_size = int(v)
        return self

    def set_window_size(self, v):
        if v < 1:
            raise ValueError("window_size must be >= 1")
        self.window_size = int(v)
        return self

    def set_min_count(self, v):
        self.min_count = int(v)
        return self

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    def set_step_size(self, v):
        self.step_size = float(v)
        return self

    def set_seed(self, v):
        self.seed = int(v)
        return self

    def set_input_col(self, v):
        self.input_col = v
        return self

    def set_output_col(self, v):
        self.output_col = v
        return self

    setVectorSize = set_vector_size
    setWindowSize = set_window_size
    setMinCount = set_min_count
    setMaxIter = set_max_iter
    setStepSize = set_step_size
    setSeed = set_seed
    setInputCol = set_input_col
    setOutputCol = set_output_col

    def fit(self, frame, mesh=None) -> "Word2VecModel":
        from ..parallel.mesh import normalize_mesh

        mesh = normalize_mesh(mesh)
        dt = np.dtype(float_dtype())
        col = _token_col(frame, self.input_col)
        mask = np.asarray(frame.mask)
        vocab, counts, docs = _build_vocab(col, mask, self.min_count,
                                           self.max_vocab_size)
        if not vocab:
            raise ValueError("Word2Vec: no tokens meet min_count in valid "
                             "rows")
        index = {t: i for i, t in enumerate(vocab)}
        centers, contexts = _build_pairs(docs, index, self.window_size,
                                         self.seed,
                                         self.max_sentence_length)
        V = len(vocab)
        dim = self.vector_size
        rng = np.random.default_rng(self.seed)

        if centers.size == 0:   # single-token docs only: random init model
            U = (rng.random((V, dim)) - 0.5) / dim
            return Word2VecModel(vocab, U.astype(dt), self._params_dict())

        # unigram^0.75 negative-sampling table as a CDF (word2vec standard)
        p = counts.astype(np.float64) ** 0.75
        noise_cdf = np.cumsum(p / p.sum()).astype(dt)

        B = self.batch_size
        ndev = 1 if mesh is None else mesh.devices.size
        B = max(ndev, (B // ndev) * ndev)   # batch divisible by shards
        n_pairs = centers.size
        steps_per_epoch = max(1, -(-n_pairs // B))
        steps = steps_per_epoch * max(1, self.max_iter)

        # shuffle + tile pairs into (steps, B) minibatch matrices
        perm = rng.permutation(n_pairs)
        idx = np.resize(perm, steps * B)
        c_mat = centers[idx].reshape(steps, B)
        o_mat = contexts[idx].reshape(steps, B)

        U0 = ((rng.random((V, dim)) - 0.5) / dim).astype(dt)
        V0 = np.zeros((V, dim), dt)

        fit_fn = _sgns_fit_fn(V, dim, B, steps, self.num_negatives,
                              self.step_size, mesh)
        args = [jnp.asarray(c_mat), jnp.asarray(o_mat),
                jnp.asarray(noise_cdf), jax.random.PRNGKey(self.seed),
                jnp.asarray(U0), jnp.asarray(V0)]
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import DATA_AXIS, shard_map

            shard = NamedSharding(mesh, P(None, DATA_AXIS))
            rep = NamedSharding(mesh, P())
            args = [jax.device_put(a, shard) for a in args[:2]] + \
                [jax.device_put(a, rep) for a in args[2:]]
        U, _, losses = jax.block_until_ready(fit_fn(*args))
        return Word2VecModel(vocab, np.asarray(U), self._params_dict(),
                             np.asarray(losses, np.float64).tolist())

    def _params_dict(self):
        return {k: getattr(self, k) for k in self._persist_attrs}


@persistable
class Word2VecModel(Model):
    """Word vectors + the MLlib surface: ``transform`` (per-document mean
    vector), ``getVectors`` (word → vector frame), ``findSynonyms``
    (cosine top-k — one matmul)."""

    _persist_attrs = ('vocabulary', 'vectors', '_params', 'loss_history')

    def __init__(self, vocabulary, vectors, params=None, loss_history=None):
        self.vocabulary = list(vocabulary)
        self.vectors = np.asarray(vectors)
        self._params = dict(params or {})
        self.loss_history = list(loss_history or [])
        self._build_index()

    def _post_load(self):
        self.vocabulary = list(self.vocabulary)
        self._build_index()

    def _build_index(self):
        self._index = {t: i for i, t in enumerate(self.vocabulary)}

    def _p(self, k, default=None):
        return self._params.get(k, default)

    @property
    def vector_size(self):
        return int(self.vectors.shape[1])

    def getVectorSize(self):     # PySpark surface: a METHOD, not an attr
        return self.vector_size

    def get_vectors(self):
        from ..frame import Frame

        return Frame({"word": np.asarray(self.vocabulary, object),
                      "vector": jnp.asarray(self.vectors, float_dtype())})

    getVectors = get_vectors

    def transform(self, frame):
        """Per-document mean of the word vectors (MLlib semantics); docs
        with no in-vocabulary token map to the zero vector."""
        col = _token_col(frame, self._p("input_col"))
        n = len(col)
        dim = self.vector_size
        # flattened gather + one segment-mean, no per-token Python math
        doc_ids, word_ids = [], []
        for i, toks in enumerate(col):
            if toks is None:
                continue
            for t in toks:
                j = self._index.get(t)
                if j is not None:
                    doc_ids.append(i)
                    word_ids.append(j)
        M = np.zeros((n, dim), np.dtype(float_dtype()))
        if word_ids:
            doc_ids = np.asarray(doc_ids)
            gathered = self.vectors[np.asarray(word_ids)]
            np.add.at(M, doc_ids, gathered)
            cnt = np.bincount(doc_ids, minlength=n).astype(M.dtype)
            M /= np.maximum(cnt, 1.0)[:, None]
        return frame.with_column(self._p("output_col"), jnp.asarray(M))

    def find_synonyms(self, word: str, num: int):
        """Top ``num`` nearest words by cosine similarity, as a Frame
        (word, similarity) — excludes the query word itself."""
        from ..frame import Frame

        j = self._index.get(word)
        if j is None:
            raise ValueError(f"word {word!r} not in vocabulary")
        W = jnp.asarray(self.vectors, float_dtype())
        norms = jnp.maximum(jnp.linalg.norm(W, axis=1), 1e-12)
        sims = (W @ W[j]) / (norms * norms[j])
        sims = sims.at[j].set(-jnp.inf)
        k = min(num, len(self.vocabulary) - 1)
        top_sims, top_idx = jax.lax.top_k(sims, k)
        top_idx = np.asarray(top_idx)
        return Frame({
            "word": np.asarray([self.vocabulary[i] for i in top_idx],
                               object),
            "similarity": np.asarray(top_sims, np.float64)})

    findSynonyms = find_synonyms
