"""MultilayerPerceptronClassifier (MLlib
``org.apache.spark.ml.classification.MultilayerPerceptronClassifier`` —
shipped by the reference's mllib dependency, pom.xml:29-32).

MLlib's MLPC is a fixed topology: sigmoid hidden layers + softmax output,
cross-entropy loss, trained with LBFGS over treeAggregate. Here the whole
network is a stack of MXU matmuls, the loss/gradient come from
``jax.value_and_grad`` over the batched forward (per-row reductions psum
over the data axis under a mesh — gradients flow through the collective
with correct SPMD semantics), and training is the shared full-batch Adam
``lax.scan`` (models/solvers.adam_scan) — one jitted program, zero host
round-trips.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from ..frame.frame import Frame
from .base import Estimator, Model, persistable
from ..parallel.mesh import serialize_collectives


def _mlp_forward(params, X):
    """Sigmoid hidden layers + linear output logits (softmax at the loss)."""
    h = X
    for i, (W, b) in enumerate(params):
        z = h @ W + b
        h = z if i == len(params) - 1 else jax.nn.sigmoid(z)
    return h


@functools.lru_cache(maxsize=None)
def _mlp_fit_fn(mesh, layers: tuple, max_iter: int, lr: float, seed: int):
    num_classes = layers[-1]

    def core(X, y, mask, axis=None):
        dt = X.dtype
        wm = mask.astype(dt)
        n = jnp.sum(wm)
        if axis is not None:
            n = jax.lax.psum(n, axis)
        Y1 = jax.nn.one_hot(y.astype(jnp.int32), num_classes,
                            dtype=dt) * wm[:, None]

        def objective(params):
            # invalid rows arrive zeroed (host-side) and pads are zero by
            # construction — no per-iteration re-masking needed. LOCAL
            # share only: psum_value_and_grad sums value+grad over the
            # mesh (grad *through* a psum is unreliable on legacy
            # shard_map; see solvers.psum_value_and_grad).
            logits = _mlp_forward(params, X)
            lse = jax.nn.logsumexp(logits, axis=1)
            ll = jnp.where(mask,
                           lse - jnp.sum(logits * Y1, axis=1), 0.0)
            return jnp.sum(ll) / n

        key = jax.random.PRNGKey(seed)
        params0 = []
        for i in range(len(layers) - 1):
            key, k1 = jax.random.split(key)
            fan_in, fan_out = layers[i], layers[i + 1]
            # Glorot-uniform init (MLlib's default weight init family)
            limit = jnp.sqrt(6.0 / (fan_in + fan_out)).astype(dt)
            W = jax.random.uniform(k1, (fan_in, fan_out), dt,
                                   -limit, limit)
            params0.append((W, jnp.zeros((fan_out,), dt)))

        from .solvers import adam_scan, psum_value_and_grad

        params, history = adam_scan(psum_value_and_grad(objective, axis),
                                    tuple(params0), max_iter, lr)
        return tuple(params), history

    if mesh is None:
        return jax.jit(lambda X, y, m: core(X, y, m))

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    return serialize_collectives(jax.jit(shard_map(
        lambda X, y, m: core(X, y, m, DATA_AXIS), mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P())), mesh)


@persistable
class MultilayerPerceptronClassifier(Estimator):
    """MLlib ``MultilayerPerceptronClassifier`` builder surface:
    setLayers/setMaxIter/setStepSize/setSeed(+cols). ``layers`` gives
    [input, hidden..., output] sizes; the output size is the class count."""

    _persist_attrs = ('layers', 'max_iter', 'step_size', 'seed',
                      'features_col', 'label_col', 'prediction_col',
                      'probability_col', 'raw_prediction_col')

    def __init__(self, layers: Sequence[int] = (), max_iter: int = 100,
                 step_size: float = 0.03, seed: int = 0,
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction",
                 probability_col: str = "probability",
                 raw_prediction_col: str = "rawPrediction"):
        self.layers = [int(v) for v in layers]
        self.max_iter = int(max_iter)
        self.step_size = float(step_size)
        self.seed = int(seed)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.probability_col = probability_col
        self.raw_prediction_col = raw_prediction_col

    def set_layers(self, v):
        self.layers = [int(x) for x in v]
        return self

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    def set_step_size(self, v):
        self.step_size = float(v)
        return self

    def set_seed(self, v):
        self.seed = int(v)
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setLayers = set_layers
    setMaxIter = set_max_iter
    setStepSize = set_step_size
    setSeed = set_seed
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setPredictionCol = set_prediction_col

    def fit(self, frame: Frame, mesh=None) \
            -> "MultilayerPerceptronClassificationModel":
        from ..parallel.distributed import pad_and_shard_rows
        from ..parallel.mesh import normalize_mesh

        mesh = normalize_mesh(mesh)
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(frame._column_values(self.label_col), np.float64)
        mask = np.asarray(frame.mask)
        yv = y[mask]
        if len(yv) == 0:
            raise ValueError("MultilayerPerceptronClassifier: no valid rows")
        if not np.all(np.isfinite(yv)) or np.any(yv < 0) \
                or np.any(yv != np.floor(yv)):
            raise ValueError("labels must be nonnegative integers 0..k-1")
        if not np.all(np.isfinite(X[mask])):
            raise ValueError("feature matrix has NaN/inf in valid rows")
        num_classes = int(yv.max()) + 1

        layers = list(self.layers)
        if not layers:
            layers = [X.shape[1], num_classes]
        if len(layers) < 2:
            raise ValueError("layers needs at least [input, output] sizes")
        if layers[0] != X.shape[1]:
            raise ValueError(f"layers[0]={layers[0]} != feature size "
                             f"{X.shape[1]}")
        if layers[-1] < num_classes:
            raise ValueError(f"layers[-1]={layers[-1]} < {num_classes} "
                             "observed classes")

        Xh = np.where(mask[:, None], X, 0.0)
        yh = np.where(mask, y, 0.0)
        Xd, yd, md = pad_and_shard_rows(mesh, Xh.astype(dt),
                                        yh.astype(dt), mask)
        fit_fn = _mlp_fit_fn(mesh, tuple(layers), self.max_iter,
                             self.step_size, self.seed)
        params, history = jax.block_until_ready(fit_fn(Xd, yd, md))
        weights = [(np.asarray(W, np.float64), np.asarray(b, np.float64))
                   for W, b in params]
        return MultilayerPerceptronClassificationModel(
            layers, weights, self._params_dict(),
            np.asarray(history, np.float64).tolist())

    def _params_dict(self):
        return {k: getattr(self, k) for k in self._persist_attrs}


@persistable
class MultilayerPerceptronClassificationModel(Model):
    """Fitted MLP: ``weights`` is the [(W, b), ...] stack; transform adds
    rawPrediction (logits), probability (softmax), prediction (argmax)."""

    _persist_attrs = ('layers', 'flat_weights', '_params', 'loss_history')

    def __init__(self, layers, weights=None, params=None,
                 loss_history=None, flat_weights=None):
        self.layers = [int(v) for v in layers]
        if weights is not None:
            self.flat_weights = {f"W{i}": np.asarray(W)
                                 for i, (W, _) in enumerate(weights)}
            self.flat_weights.update(
                {f"b{i}": np.asarray(b)
                 for i, (_, b) in enumerate(weights)})
        else:
            self.flat_weights = {k: np.asarray(v)
                                 for k, v in (flat_weights or {}).items()}
        self._params = dict(params or {})
        self.loss_history = list(loss_history or [])

    def _post_load(self):
        self.layers = [int(v) for v in self.layers]
        self.flat_weights = {k: np.asarray(v)
                             for k, v in self.flat_weights.items()}

    def _p(self, k, default=None):
        return self._params.get(k, default)

    @property
    def weights(self):
        n = len(self.layers) - 1
        return [(self.flat_weights[f"W{i}"], self.flat_weights[f"b{i}"])
                for i in range(n)]

    @property
    def num_features(self):
        return int(self.layers[0])

    numFeatures = num_features

    def _logits(self, X):
        Xd = jnp.asarray(X, float_dtype())
        if Xd.ndim == 1:
            Xd = Xd[:, None]
        params = [(jnp.asarray(W, Xd.dtype), jnp.asarray(b, Xd.dtype))
                  for W, b in self.weights]
        return _mlp_forward(params, Xd)

    def transform(self, frame: Frame) -> Frame:
        p = self._params
        logits = self._logits(frame._column_values(
            p.get("features_col", "features")))
        prob = jax.nn.softmax(logits, axis=1)
        pred = jnp.argmax(logits, axis=1).astype(float_dtype())
        out = frame.with_column(p.get("raw_prediction_col", "rawPrediction"),
                                logits)
        out = out.with_column(p.get("probability_col", "probability"), prob)
        return out.with_column(p.get("prediction_col", "prediction"), pred)

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.argmax(np.asarray(self._logits(x))[0]))
