"""Frequent pattern mining (MLlib ``org.apache.spark.ml.fpm.FPGrowth`` —
shipped by the reference's mllib dependency, pom.xml:29-32).

Design: FP-Growth mines variable-length string itemsets from transaction
lists — host-resident data by the framework's own rule (strings never
touch the TPU; same boundary as the tokenizers and the join planner's
string fallback). The classic FP-tree + conditional-pattern-base recursion
runs once per fit; rule generation and ``transform``'s subset matching are
vectorized over numpy object arrays where it pays. The parallelizable part
of PFP (per-item conditional trees) is embarrassingly independent — noted
for a multi-host split, but a single host mines typical basket data in
milliseconds, so no device path is invented for it.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Optional

import numpy as np

from .base import Estimator, Model, persistable
from .text import _obj_array


class _FPNode:
    __slots__ = ("item", "count", "parent", "children")

    def __init__(self, item, parent):
        self.item = item
        self.count = 0
        self.parent = parent
        self.children = {}


def _build_tree(transactions, counts, order):
    """FP-tree + per-item node lists from (filtered, ordered) transactions."""
    root = _FPNode(None, None)
    nodes = defaultdict(list)
    for t, c in zip(transactions, counts):
        node = root
        for item in t:
            child = node.children.get(item)
            if child is None:
                child = _FPNode(item, node)
                node.children[item] = child
                nodes[item].append(child)
            child.count += c
            node = child
    return root, nodes


def _mine(transactions, counts, min_count, suffix, out):
    """Recursive FP-growth over conditional pattern bases."""
    freq = defaultdict(int)
    for t, c in zip(transactions, counts):
        for item in t:
            freq[item] += c
    items = {i: f for i, f in freq.items() if f >= min_count}
    # least-frequent-first mining order (ties alphabetical for determinism)
    for item in sorted(items, key=lambda i: (items[i], i)):
        new_suffix = suffix + (item,)
        out[frozenset(new_suffix)] = items[item]
        # conditional pattern base for `item`
        order = {i: (items[i], i) for i in items}
        filtered = []
        fcounts = []
        for t, c in zip(transactions, counts):
            if item in t:
                kept = sorted((i for i in t if i in items and i != item),
                              key=lambda i: (-items[i], i))
                if kept:
                    filtered.append(tuple(kept))
                    fcounts.append(c)
        if filtered:
            _mine(filtered, fcounts, min_count, new_suffix, out)


@persistable
class FPGrowth(Estimator):
    """MLlib ``FPGrowth`` builder surface: setItemsCol/setMinSupport/
    setMinConfidence/setPredictionCol + ``fit(frame)``."""

    _persist_attrs = ('min_support', 'min_confidence', 'items_col',
                      'prediction_col')

    def __init__(self, min_support: float = 0.3,
                 min_confidence: float = 0.8, items_col: str = "items",
                 prediction_col: str = "prediction"):
        if not 0.0 < min_support <= 1.0:
            raise ValueError("min_support must be in (0, 1]")
        if not 0.0 <= min_confidence <= 1.0:
            raise ValueError("min_confidence must be in [0, 1]")
        self.min_support = float(min_support)
        self.min_confidence = float(min_confidence)
        self.items_col = items_col
        self.prediction_col = prediction_col

    def set_min_support(self, v):
        if not 0.0 < v <= 1.0:
            raise ValueError("min_support must be in (0, 1]")
        self.min_support = float(v)
        return self

    def set_min_confidence(self, v):
        if not 0.0 <= v <= 1.0:
            raise ValueError("min_confidence must be in [0, 1]")
        self.min_confidence = float(v)
        return self

    def set_items_col(self, v):
        self.items_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setMinSupport = set_min_support
    setMinConfidence = set_min_confidence
    setItemsCol = set_items_col
    setPredictionCol = set_prediction_col

    def fit(self, frame) -> "FPGrowthModel":
        col = frame._column_values(self.items_col)
        if not (isinstance(col, np.ndarray) and col.dtype == object):
            raise ValueError(f"column {self.items_col!r} must hold item "
                             "lists")
        mask = np.asarray(frame.mask)
        # MLlib: duplicate items within one transaction are an error;
        # we dedupe like most FPM implementations and document it
        txns = [tuple(dict.fromkeys(t)) for t, m in zip(col, mask)
                if m and t is not None and len(t)]
        n = len(txns)
        if n == 0:
            raise ValueError("FPGrowth: no valid transactions")
        min_count = max(1, int(np.ceil(self.min_support * n)))

        # first pass: global frequencies; filter + order transactions
        freq = defaultdict(int)
        for t in txns:
            for item in t:
                freq[item] += 1
        kept = {i: f for i, f in freq.items() if f >= min_count}
        ordered = []
        counts = []
        for t in txns:
            kt = sorted((i for i in t if i in kept),
                        key=lambda i: (-kept[i], i))
            if kt:
                ordered.append(tuple(kt))
                counts.append(1)

        itemsets: dict = {}
        _mine(ordered, counts, min_count, (), itemsets)
        return FPGrowthModel(
            [(sorted(s), int(c)) for s, c in sorted(
                itemsets.items(), key=lambda kv: (len(kv[0]),
                                                  sorted(kv[0])))],
            n, self.min_confidence,
            {"items_col": self.items_col,
             "prediction_col": self.prediction_col})


@persistable
class FPGrowthModel(Model):
    """Frequent itemsets + single-consequent association rules (MLlib's
    rule shape); ``transform`` predicts the union of fired consequents."""

    _persist_attrs = ('itemsets', 'num_transactions', 'min_confidence',
                      '_params')

    def __init__(self, itemsets, num_transactions, min_confidence,
                 params=None):
        # itemsets: list of (sorted item list, count)
        self.itemsets = [(list(s), int(c)) for s, c in itemsets]
        self.num_transactions = int(num_transactions)
        self.min_confidence = float(min_confidence)
        self._params = dict(params or {})
        self._build_rules()

    def _post_load(self):
        self.itemsets = [(list(s), int(c)) for s, c in self.itemsets]
        self._build_rules()

    def _build_rules(self):
        lookup = {frozenset(s): c for s, c in self.itemsets}
        self._rules = []
        n = max(self.num_transactions, 1)
        for s, c in self.itemsets:
            if len(s) < 2:
                continue
            fs = frozenset(s)
            for consequent in s:
                ante = fs - {consequent}
                ante_count = lookup.get(ante)
                if not ante_count:
                    continue
                conf = c / ante_count
                if conf >= self.min_confidence:
                    cons_count = lookup.get(frozenset([consequent]), 0)
                    lift = conf / (cons_count / n) if cons_count else np.nan
                    self._rules.append(
                        (sorted(ante), consequent, conf, lift, c / n))

    @property
    def freq_itemsets(self):
        from ..frame import Frame

        return Frame({
            "items": _obj_array([s for s, _ in self.itemsets]),
            "freq": np.asarray([c for _, c in self.itemsets], np.int64)})

    freqItemsets = freq_itemsets

    @property
    def association_rules(self):
        from ..frame import Frame

        return Frame({
            "antecedent": _obj_array([a for a, *_ in self._rules]),
            "consequent": _obj_array([[c] for _, c, *_ in self._rules]),
            "confidence": np.asarray([r[2] for r in self._rules]),
            "lift": np.asarray([r[3] for r in self._rules]),
            "support": np.asarray([r[4] for r in self._rules])})

    associationRules = association_rules

    def transform(self, frame):
        col = frame._column_values(self._p("items_col", "items"))
        out = []
        for t in col:
            if t is None:
                out.append(None)
                continue
            have = set(t)
            fired = []
            for ante, consequent, *_ in self._rules:
                if consequent not in have and set(ante) <= have \
                        and consequent not in fired:
                    fired.append(consequent)
            out.append(sorted(fired))
        return frame.with_column(self._p("prediction_col", "prediction"),
                                 _obj_array(out))

    def _p(self, k, default=None):
        return self._params.get(k, default)


# --- PrefixSpan ---------------------------------------------------------------
#
# MLlib ``PrefixSpan`` (mllib.fpm.PrefixSpan in the Spark 2.4 dependency,
# pom.xml:29-32; the ml-level findFrequentSequentialPatterns API landed in
# 3.0 — this class exposes that surface over the 2.4 algorithm). Sequential
# patterns over itemset sequences are host-resident string/object data by
# the framework's boundary rule (same as FPGrowth above); the classic
# pseudo-projection recursion runs on the host.


def _first_occurrence(seq, start_i, last_itemset, item, itemset_ext):
    """Earliest projection point for extending a pattern at itemset
    ``start_i`` (the current match position) with ``item``.

    ``itemset_ext``: the item joins the pattern's last itemset, so the
    matching itemset (searched from ``start_i`` on) must contain
    ``last_itemset + (item,)``. Sequence extension: ``item`` opens a new
    itemset strictly after ``start_i``. Returns (i, j) with j = offset
    just past ``item``, or None.
    """
    if itemset_ext:
        for i in range(start_i, len(seq)):
            s = seq[i]
            if item in s and all(x in s for x in last_itemset):
                return i, s.index(item) + 1
        return None
    for i in range(start_i + 1, len(seq)):
        s = seq[i]
        if item in s:
            return i, s.index(item) + 1
    return None


class PrefixSpan:
    """Sequential pattern mining (PrefixSpan, Pei et al. — the algorithm
    MLlib implements). ``find_frequent_sequential_patterns(frame)`` returns
    a Frame with ``sequence`` (list of itemsets) and ``freq`` columns,
    MLlib's output schema.

    A sequence is a list of itemsets; itemsets are unordered (stored
    sorted). Pattern growth uses canonical extensions — a new item either
    starts a new itemset ("sequence extension") or joins the last itemset
    with items greater than its current maximum ("itemset extension") —
    with pseudo-projection (first minimal occurrence) per sequence, which
    keeps support counting exact.
    """

    def __init__(self, min_support: float = 0.1,
                 max_pattern_length: int = 10,
                 max_local_proj_db_size: int = 32000000,
                 sequence_col: str = "sequence"):
        if not (0.0 <= min_support <= 1.0):
            raise ValueError("min_support must be in [0, 1]")
        if max_pattern_length < 1:
            raise ValueError("max_pattern_length must be >= 1")
        self.min_support = float(min_support)
        self.max_pattern_length = int(max_pattern_length)
        # accepted for API parity; a single host mines the whole projected
        # DB, so the mllib local/distributed split point is meaningless here
        self.max_local_proj_db_size = int(max_local_proj_db_size)
        self.sequence_col = sequence_col

    def set_min_support(self, v):
        if not (0.0 <= v <= 1.0):
            raise ValueError("min_support must be in [0, 1]")
        self.min_support = float(v)
        return self

    setMinSupport = set_min_support

    def set_max_pattern_length(self, v):
        if v < 1:
            raise ValueError("max_pattern_length must be >= 1")
        self.max_pattern_length = int(v)
        return self

    setMaxPatternLength = set_max_pattern_length

    def set_max_local_proj_db_size(self, v):
        self.max_local_proj_db_size = int(v)
        return self

    setMaxLocalProjDBSize = set_max_local_proj_db_size

    def set_sequence_col(self, v):
        self.sequence_col = v
        return self

    setSequenceCol = set_sequence_col

    def find_frequent_sequential_patterns(self, frame):
        import math

        raw = frame._column_values(self.sequence_col)
        valid = np.asarray(frame.mask)
        seqs = []
        for s, ok in zip(raw, valid):
            if not ok or s is None:           # masked slots never vote
                continue
            seqs.append(tuple(tuple(sorted(set(itemset))) for itemset in s))
        n = len(seqs)
        if n == 0:
            return _ps_result([], [])
        min_count = max(1, int(math.ceil(self.min_support * n)))
        max_len = self.max_pattern_length

        results = []

        def mine(pattern, pattern_items, projections):
            """``projections``: list of (seq_idx, i, j) — pattern's last
            itemset matched inside itemset ``i`` ending at offset ``j``."""
            if pattern_items >= max_len:
                return
            last = pattern[-1] if pattern else ()
            last_max = last[-1] if last else None
            # candidate support: each sequence votes once per (kind, item)
            counts = defaultdict(int)
            for (si, i, j) in projections:
                seq = seqs[si]
                seen = set()
                if last:
                    # itemset extensions: suffix of the matched itemset,
                    # or any later itemset containing last ∪ {x}
                    for x in seq[i][j:]:
                        seen.add((True, x))
                    for i2 in range(i + 1, len(seq)):
                        s2 = seq[i2]
                        if all(y in s2 for y in last):
                            for x in s2:
                                if x > last_max:
                                    seen.add((True, x))
                for i2 in range(i + 1, len(seq)):
                    for x in seq[i2]:
                        seen.add((False, x))
                for c in seen:
                    counts[c] += 1

            for (is_ext, item), c in sorted(
                    counts.items(), key=lambda kv: (kv[0][0], kv[0][1])):
                if c < min_count:
                    continue
                new_pattern = (pattern[:-1] + [last + (item,)] if is_ext
                               else pattern + [(item,)])
                proj = []
                for (si, i, j) in projections:
                    seq = seqs[si]
                    if is_ext:
                        # at the matched itemset the pattern's last itemset
                        # already holds; item must appear at/after offset j
                        if item in seq[i][j:]:
                            proj.append((si, i, seq[i].index(item) + 1))
                            continue
                        hit = _first_occurrence(seq, i + 1, last, item, True)
                    else:
                        hit = _first_occurrence(seq, i, (), item, False)
                    if hit is not None:
                        proj.append((si, hit[0], hit[1]))
                results.append(([list(p) for p in new_pattern], c))
                mine(new_pattern, pattern_items + 1, proj)

        # Root projections seed at a virtual itemset −1 so the sequence-
        # extension scans (which start at i+1) see itemset 0.
        mine([], 0, [(si, -1, 0) for si in range(n)])
        patterns = [r[0] for r in results]
        freqs = [r[1] for r in results]
        return _ps_result(patterns, freqs)

    findFrequentSequentialPatterns = find_frequent_sequential_patterns


def _ps_result(patterns, freqs):
    from ..frame import Frame

    return Frame({
        "sequence": _obj_array(patterns),
        "freq": np.asarray(freqs, np.int64),
    })
