"""AFTSurvivalRegression (MLlib
``org.apache.spark.ml.regression.AFTSurvivalRegression`` — shipped by the
reference's mllib dependency, pom.xml:29-32).

Weibull accelerated-failure-time model: ``log t = β₀ + xᵀβ + σ·ε`` with
ε Gumbel-distributed; censored rows (censor=0) contribute the survival
term of the likelihood, events (censor=1) the density term.

TPU-first: the negative log-likelihood and its gradient are ONE fused
masked reduction over rows (psum'd over the data axis under a mesh), and
the optimizer is a full-batch Adam ``lax.scan`` on (β, β₀, log σ) — the
whole fit is a single jitted program with zero host round-trips, playing
the role of MLlib's LBFGS-over-treeAggregate. Features are standardized
internally like the other linear fits (MLlib does the same for AFT).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from ..frame.frame import Frame
from .base import Estimator, Model, persistable
from ..parallel.mesh import serialize_collectives


class AftFit(NamedTuple):
    coefficients: jnp.ndarray
    intercept: jnp.ndarray
    scale: jnp.ndarray
    loss_history: jnp.ndarray


def _aft_core(X, logt, censor, mask, n, std, max_iter, lr, axis=None):
    """Adam on the mean Weibull-AFT negative log-likelihood.

    With ε = (log t − β₀ − xᵀβ)/σ and δ the event indicator:
        −ll_i = e^{ε_i} − δ_i·(ε_i − log σ)
    (the Gumbel density/survival split; MLlib's AFTAggregator computes the
    same quantity row-wise). All row reductions fuse into one psum'd
    vector under sharding.
    """
    dt = X.dtype
    d = X.shape[1]
    valid = std > 0
    sx = jnp.where(valid, std, 1.0)
    wm = mask.astype(dt)
    Xs = (X / sx) * wm[:, None]
    lt = logt * wm
    dl = censor * wm

    def reduce_(v):
        return jax.lax.psum(v, axis) if axis is not None else v

    def neg_ll(params):
        # LOCAL share of the likelihood: psum_value_and_grad reduces
        # value+grad over the mesh (grad through a psum is unreliable on
        # legacy shard_map; see solvers.psum_value_and_grad)
        beta, b0, logsig = params[:d], params[d], params[d + 1]
        sig = jnp.exp(logsig)
        eps = (lt - b0 * wm - Xs @ beta) / sig
        # masked rows: wm=0 ⇒ eps=0 ⇒ e^0=1 would leak — gate every term
        term = jnp.where(mask, jnp.exp(eps) - dl * (eps - logsig), 0.0)
        return jnp.sum(term) / n

    from .solvers import adam_scan, psum_value_and_grad

    p0 = jnp.zeros((d + 2,), dt)
    # init β₀ to mean log t (the σ=1, β=0 stationary point neighborhood)
    b0_init = reduce_(jnp.sum(lt)) / n
    p0 = p0.at[d].set(b0_init)

    p, history = adam_scan(psum_value_and_grad(neg_ll, axis), p0,
                           max_iter, lr)
    beta = jnp.where(valid, p[:d] / sx, 0.0)   # unscale to raw features
    return AftFit(beta, p[d], jnp.exp(p[d + 1]), history)


@functools.lru_cache(maxsize=None)
def _aft_fit_fn(mesh, max_iter: int, lr: float):
    """Jitted (and sharded) AFT fit, cached per (mesh, config)."""
    def stats_and_fit(X, logt, censor, mask, axis=None):
        from .classification import _feature_stats, _sharded_feature_stats

        n, std = _feature_stats(X, logt, mask) if axis is None \
            else _sharded_feature_stats(X, mask)
        return _aft_core(X, logt, censor, mask, n, std, max_iter, lr, axis)

    if mesh is None:
        return jax.jit(lambda X, lt, c, m: stats_and_fit(X, lt, c, m))

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    return serialize_collectives(jax.jit(shard_map(
        lambda X, lt, c, m: stats_and_fit(X, lt, c, m, DATA_AXIS),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS)),
        out_specs=P())), mesh)


@persistable
class AFTSurvivalRegression(Estimator):
    """MLlib ``AFTSurvivalRegression`` builder surface: setMaxIter/
    setFeaturesCol/setLabelCol/setCensorCol/setPredictionCol/
    setQuantileProbabilities/setQuantilesCol (+ a ``step_size`` knob for
    the Adam loop)."""

    _persist_attrs = ('max_iter', 'step_size', 'features_col', 'label_col',
                      'censor_col', 'prediction_col',
                      'quantile_probabilities', 'quantiles_col')

    def __init__(self, max_iter: int = 300, step_size: float = 0.1,
                 features_col: str = "features", label_col: str = "label",
                 censor_col: str = "censor",
                 prediction_col: str = "prediction",
                 quantile_probabilities=(0.01, 0.05, 0.1, 0.25, 0.5, 0.75,
                                         0.9, 0.95, 0.99),
                 quantiles_col: Optional[str] = None):
        self.max_iter = int(max_iter)
        self.step_size = float(step_size)
        self.features_col = features_col
        self.label_col = label_col
        self.censor_col = censor_col
        self.prediction_col = prediction_col
        self.quantile_probabilities = self._check_probs(
            quantile_probabilities)
        self.quantiles_col = quantiles_col

    @staticmethod
    def _check_probs(v):
        probs = tuple(float(q) for q in v)
        if not probs:
            raise ValueError("quantile probabilities must be non-empty")
        if any(not 0.0 < q < 1.0 for q in probs):
            raise ValueError("quantile probabilities must be in (0, 1)")
        return probs

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    def set_censor_col(self, v):
        self.censor_col = v
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_quantile_probabilities(self, v):
        self.quantile_probabilities = self._check_probs(v)
        return self

    def set_quantiles_col(self, v):
        self.quantiles_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    setMaxIter = set_max_iter
    setCensorCol = set_censor_col
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setQuantileProbabilities = set_quantile_probabilities
    setQuantilesCol = set_quantiles_col
    setPredictionCol = set_prediction_col

    def fit(self, frame: Frame, mesh=None) -> "AFTSurvivalRegressionModel":
        from ..parallel.distributed import pad_and_shard_rows
        from ..parallel.mesh import normalize_mesh

        mesh = normalize_mesh(mesh)
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        t = np.asarray(frame._column_values(self.label_col), np.float64)
        c = np.asarray(frame._column_values(self.censor_col), np.float64)
        mask = np.asarray(frame.mask)
        if mask.sum() == 0:
            raise ValueError("AFTSurvivalRegression: no valid rows")
        tv = t[mask]
        if not (np.all(np.isfinite(tv)) and np.all(tv > 0)):
            raise ValueError("survival times must be finite and > 0")
        cv = c[mask]
        if not np.all((cv == 0) | (cv == 1)):
            raise ValueError("censor column must be 0.0 or 1.0")
        if not np.all(np.isfinite(X[mask])):
            raise ValueError("feature matrix has NaN/inf in valid rows")

        # masked slots: zero features and log t (0 * NaN would poison)
        Xh = np.where(mask[:, None], X, 0.0)
        logt = np.where(mask, np.log(np.where(mask, t, 1.0)), 0.0)
        ch = np.where(mask, c, 0.0)
        Xd, ltd, cd, md = pad_and_shard_rows(
            mesh, Xh.astype(dt), logt.astype(dt), ch.astype(dt), mask)
        r = jax.block_until_ready(
            _aft_fit_fn(mesh, self.max_iter, self.step_size)(Xd, ltd, cd,
                                                             md))
        return AFTSurvivalRegressionModel(
            np.asarray(r.coefficients, np.float64), float(r.intercept),
            float(r.scale), self._params_dict(),
            np.asarray(r.loss_history, np.float64).tolist())

    def _params_dict(self):
        return {k: getattr(self, k) for k in self._persist_attrs}


@persistable
class AFTSurvivalRegressionModel(Model):
    """Fitted Weibull AFT: ``predict`` = exp(β₀ + xᵀβ) (MLlib's point
    prediction), ``predict_quantiles`` = exp(μ)·(−log(1−q))^σ."""

    _persist_attrs = ('coefficients', 'intercept', 'scale', '_params',
                      'loss_history')

    def __init__(self, coefficients, intercept, scale, params=None,
                 loss_history=None):
        self.coefficients = np.asarray(coefficients, np.float64)
        self.intercept = float(intercept)
        self.scale = float(scale)
        self._params = dict(params or {})
        self.loss_history = list(loss_history or [])

    def _p(self, k, default=None):
        return self._params.get(k, default)

    def _mu(self, X):
        Xd = jnp.asarray(X, float_dtype())
        if Xd.ndim == 1:
            Xd = Xd[:, None]
        return Xd @ jnp.asarray(self.coefficients, Xd.dtype) \
            + self.intercept

    def transform(self, frame: Frame) -> Frame:
        mu = self._mu(frame._column_values(
            self._p("features_col", "features")))
        out = frame.with_column(self._p("prediction_col", "prediction"),
                                jnp.exp(mu))
        qcol = self._p("quantiles_col")
        if qcol:
            qs = jnp.asarray(self._p("quantile_probabilities",
                                     (0.5,)), mu.dtype)
            q = jnp.exp(mu)[:, None] * \
                (-jnp.log1p(-qs))[None, :] ** self.scale
            out = out.with_column(qcol, q)
        return out

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.exp(np.asarray(self._mu(x))[0]))

    def predict_quantiles(self, features) -> np.ndarray:
        x = np.asarray(features, np.float64).reshape(1, -1)
        mu = float(np.asarray(self._mu(x))[0])
        qs = np.asarray(self._p("quantile_probabilities", (0.5,)))
        return np.exp(mu) * (-np.log1p(-qs)) ** self.scale

    predictQuantiles = predict_quantiles
