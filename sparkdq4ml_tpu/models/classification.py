"""LogisticRegression — binary elastic-net classifier, MLlib convention
(BASELINE.json config: "LogisticRegression binary classifier on DQ-filtered
rows"; the reference app itself has no classifier, so the API mirrors the
estimator surface its LinearRegression exercises at
`DataQuality4MachineLearningApp.java:120-151`).

TPU-first fit path: unlike the linear case (one Gramian suffices —
solvers.py), logistic loss needs per-iteration data passes. The whole FISTA
loop therefore runs inside ONE jitted ``lax.while_loop`` over the row-sharded data:
each iteration computes the local masked gradient and reduces the ``(d+2)``
gradient/loss vector with a single ``psum`` over the mesh — this is the true
per-iteration ``treeAggregate`` analogue (SURVEY.md §3.3), with the
coefficient "broadcast" implicit in SPMD replication and zero host syncs for
the entire optimization.

Numeric convention (MLlib LogisticRegression):

* features scaled by sample std (no centering — matches MLlib's
  sparsity-preserving choice); intercept fit unpenalized,
* mean log-loss objective; ``effectiveRegParam = regParam`` (no label
  scaling, unlike linear regression),
* with ``standardization=False`` the penalty lands on the raw coefficients:
  L1 weight ``1/σ_j``, L2 weight ``1/σ_j²``, as in the linear case.
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..config import float_dtype
from ..frame.frame import Frame
from ..parallel.mesh import DATA_AXIS, serialize_collectives, shard_map
from .base import (Estimator, Model, host_fetch, persistable, read_json,
                   write_json)
from .regression import _extract_xy
from .solvers import _soft


class LogisticFitResult(NamedTuple):
    coefficients: jnp.ndarray
    intercept: jnp.ndarray
    iterations: jnp.ndarray
    objective_history: jnp.ndarray
    converged: jnp.ndarray


def _feature_stats(X, y, mask):
    """Masked n, feature std (sample), for standardization — one pass."""
    w = mask.astype(X.dtype)
    n = jnp.sum(w)
    mean = (w @ X) / n
    var = (w @ (X * X)) / n - mean * mean
    denom = jnp.maximum(n - 1.0, 1.0)
    std = jnp.sqrt(jnp.clip(var * n / denom, 0.0))
    return n, std


def _sharded_feature_stats(X, mask):
    """Global masked n / sample std from inside shard_map — one fused psum
    of the [Σx, Σx², n] moment vector over the data axis."""
    w = mask.astype(X.dtype)
    parts = jnp.concatenate([w @ X, w @ (X * X), jnp.sum(w)[None]])
    parts = jax.lax.psum(parts, DATA_AXIS)
    d = X.shape[1]
    n = parts[2 * d]
    mean = parts[:d] / n
    var = parts[d: 2 * d] / n - mean * mean
    std = jnp.sqrt(jnp.clip(var * n / jnp.maximum(n - 1.0, 1.0), 0.0))
    return n, std


def _logistic_core(X, y, mask, reg_param, alpha, n, std,
                   max_iter, tol, fit_intercept, standardization, axis=None,
                   weights=None):
    """FISTA on mean log-loss over (possibly sharded) rows.

    When ``axis`` is set (inside shard_map), every per-row reduction is
    followed by a psum over that axis; n/std are passed in already global.
    ``weights``: optional per-row instance weights (MLlib weightCol); the
    default is the 0/1 mask. Margins always use the BOOLEAN mask — weights
    enter linearly through the per-row loss/gradient terms and ``n``.
    """
    dt = X.dtype
    d = X.shape[1]
    valid = std > 0
    sx = jnp.where(valid, std, 1.0)
    Xs = (X / sx) * mask.astype(dt)[:, None]   # standardized, masked rows
    yv = y.astype(dt) * mask.astype(dt)
    wm = mask.astype(dt)
    wv = wm if weights is None else weights.astype(dt)

    # penalty on raw coefficients when standardization=False: u1=1/sigma for
    # L1, u2=1/sigma^2 for L2 (see solvers._penalty_weights)
    u1 = jnp.ones((d,), dt) if standardization else jnp.where(valid, 1.0 / sx, 0.0)
    lam1 = alpha * reg_param * u1
    lam2 = (1.0 - alpha) * reg_param * (u1 if standardization else u1 * u1)

    def reduce_(v):
        return jax.lax.psum(v, axis) if axis is not None else v

    # Lipschitz bound: λmax(XᵀWX/n)/4 ≤ ‖√w·Xs‖_F²/(4n)
    sq = reduce_(jnp.sum(wv[:, None] * Xs * Xs))
    L = sq / (4.0 * n) + jnp.max(lam2, initial=0.0) + jnp.asarray(1e-12, dt)
    step = 1.0 / L

    def loss_grad(wb):
        w, b = wb[:d], wb[d]
        margin = Xs @ w + b * wm
        # stable log(1+exp(-z)) with z = (2y-1)*margin
        z = (2.0 * yv - wm) * margin
        ll = wv * jnp.logaddexp(0.0, -z)   # wv=0 zeroes masked rows
        p = jax.nn.sigmoid(margin)
        resid = (p - yv) * wv
        g_w = Xs.T @ resid
        g_b = jnp.sum(resid)
        packed = jnp.concatenate([g_w, jnp.array([g_b, jnp.sum(ll)])])
        packed = reduce_(packed)
        grad = packed[: d + 1] / n
        # ridge term belongs to the smooth part (L1 is handled by the prox)
        grad = grad.at[:d].add(lam2 * wb[:d])
        loss = packed[d + 1] / n
        if not fit_intercept:
            grad = grad.at[d].set(0.0)
        return loss, grad

    def objective(wb, loss):
        w = wb[:d]
        return loss + jnp.sum(lam1 * jnp.abs(w)) + 0.5 * jnp.sum(lam2 * w * w)

    def prox(cand):
        w_new = jnp.where(valid, _soft(cand[:d], step * lam1), 0.0)
        b_new = jnp.where(fit_intercept, cand[d], 0.0)
        return jnp.concatenate([w_new, b_new[None]])

    wb, done, iters, history = _fista_drive(loss_grad, objective, prox,
                                            step, d + 1, dt, max_iter, tol)
    coef = jnp.where(valid, wb[:d] / sx, 0.0)   # unscale to raw features
    intercept = wb[d]
    return LogisticFitResult(coef, intercept, iters, history, done)


def _logistic_newton_core(X, y, mask, reg_param, alpha, n, std,
                          max_iter, tol, fit_intercept, standardization,
                          axis=None, weights=None):
    """Damped Newton (IRLS) on mean log-loss — the L1-free fast path.

    Chosen automatically by ``LogisticRegression.fit`` when the penalty has
    no L1 part (``alpha`` is then 0 by construction and ignored here):
    Newton converges in ~5–10 iterations where FISTA needs its full budget,
    and each iteration is ONE fused pass — margin matvec, gradient, and the
    (d+1)² weighted Gramian Hessian (MXU-shaped) — psum'd once under a
    mesh (the per-iteration ``treeAggregate`` analogue, same as FISTA's).

    Robustness: the Hessian solve carries a tiny scaled diagonal jitter
    (separable unpenalized data drives p(1−p) → 0 and H toward singular),
    and each step is line-searched over {1, ½, ¼, ⅛}·δ — all four
    candidates evaluated in ONE batched matmul — keeping the objective
    monotone; when no candidate improves, the iterate stays put and the
    convergence latch closes. Same result contract as ``_logistic_core``
    (history length ``max_iter``+1, trailing entries frozen at the last
    objective).
    """
    del alpha  # L1-free by construction (router guarantees it)
    dt = X.dtype
    d = X.shape[1]
    valid = std > 0
    sx = jnp.where(valid, std, 1.0)
    wm = mask.astype(dt)
    Xs = (X / sx) * wm[:, None]
    yv = y.astype(dt) * wm
    wv = wm if weights is None else weights.astype(dt)
    Za = jnp.concatenate([Xs, wm[:, None]], axis=1)   # intercept column

    u1 = jnp.ones((d,), dt) if standardization \
        else jnp.where(valid, 1.0 / sx, 0.0)
    lam2 = reg_param * (u1 if standardization else u1 * u1)
    lam2_full = jnp.concatenate([lam2, jnp.zeros((1,), dt)])
    valid_full = jnp.concatenate([valid,
                                  jnp.full((1,), bool(fit_intercept))])

    def reduce_(v):
        return jax.lax.psum(v, axis) if axis is not None else v

    m = d + 1

    def stats(wb):
        """Gradient + Hessian at wb — one fused (psum'd) pass. (The loss
        is NOT computed here: the driver reads objectives only through
        ``batched_objective``, so packing a loss scalar would be dead
        O(n) work the psum forbids XLA from eliminating.)"""
        margin = Za @ wb
        p = jax.nn.sigmoid(margin)
        resid = (p - yv) * wv
        g = Za.T @ resid                                   # (m,)
        s = wv * p * (1.0 - p)
        H = (Za * s[:, None]).T @ Za                       # (m, m)
        packed = reduce_(jnp.concatenate([H.ravel(), g]))
        H = packed[:m * m].reshape(m, m) / n
        g = packed[m * m:] / n
        g = g + lam2_full * wb
        H = H + jnp.diag(lam2_full)
        g = jnp.where(valid_full, g, 0.0)
        H = jnp.where(valid_full[:, None] & valid_full[None, :], H,
                      jnp.eye(m, dtype=dt))
        return g, H

    def batched_objective(C):
        """Objectives of a (4, m) candidate stack in one fused pass."""
        margins = Za @ C.T                                 # (n, 4)
        z = (2.0 * yv - wm)[:, None] * margins
        ll = jnp.sum(wv[:, None] * jnp.logaddexp(0.0, -z), axis=0)  # (4,)
        ll = reduce_(ll) / n
        return ll + 0.5 * jnp.sum(lam2_full[None, :] * C * C, axis=1)

    wb, ok, iters, history = _newton_drive(stats, batched_objective, m,
                                           valid_full, dt, max_iter, tol)
    coef = jnp.where(valid, wb[:d] / sx, 0.0)
    intercept = wb[d]
    return LogisticFitResult(coef, intercept, iters, history, ok)


def _fista_drive(loss_grad, objective, prox, step, M, dt, max_iter, tol):
    """Shared Nesterov/FISTA driver (binary + softmax + SVC cores):
    momentum extrapolation, gradient-prox step, convergence latch, and
    objective-history bookkeeping in ONE place.

    ``loss_grad(wb) -> (loss, grad)`` is the (psum'd) smooth pass;
    ``objective(wb, loss)`` adds the nonsmooth/ridge terms;
    ``prox(cand) -> wb`` applies the proximal map + validity masking.

    while_loop, not scan: each iteration is two O(n·d) data passes, so a
    fit that converges at iteration k must stop paying for the remaining
    ``max_iter − k`` passes (a scan with a done-latch keeps computing
    them just to freeze the carry). History tail is pinned to the final
    objective after the loop — same decode contract as before.

    Returns ``(wb, converged, iterations, history)`` with ``history`` of
    length ``max_iter + 1`` (entry 0 = objective at zero).
    """
    wb0 = jnp.zeros((M,), dt)
    loss0, _ = loss_grad(wb0)
    obj0 = objective(wb0, loss0)
    hist0 = jnp.full((max_iter + 1,), obj0, dt)

    def cond(state):
        _, _, _, done, iters, _, _ = state
        return jnp.logical_and(iters < max_iter, ~done)

    def body(state):
        wb, wb_prev, t, _, iters, last_obj, hist = state
        tn = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        v = wb + ((t - 1.0) / tn) * (wb - wb_prev)
        _, grad = loss_grad(v)
        wb_new = prox(v - step * grad)
        loss_new, _ = loss_grad(wb_new)
        obj = objective(wb_new, loss_new)
        rel = jnp.abs(obj - last_obj) / jnp.maximum(jnp.abs(last_obj), 1e-12)
        done = rel < tol
        hist = hist.at[iters + 1].set(obj)
        return (wb_new, wb, tn, done, iters + 1, obj, hist)

    init = (wb0, wb0, jnp.asarray(1.0, dt), jnp.asarray(False),
            jnp.asarray(0, jnp.int32), obj0, hist0)
    wb, _, _, done, iters, last_obj, hist = jax.lax.while_loop(
        cond, body, init)
    history = jnp.where(jnp.arange(max_iter + 1) <= iters, hist, last_obj)
    return wb, done, iters, history


def _newton_drive(stats, batched_objective, M, valid_full, dt,
                  max_iter, tol):
    """Shared damped-Newton driver (binary + softmax cores): jittered
    Hessian solve, batched {1, ½, ¼, ⅛}·δ line search, convergence latch,
    and objective-history bookkeeping — in ONE place so the two solvers'
    convergence behavior stays identical by construction.

    ``stats(wb) -> (g, H)`` must be the regularized gradient/Hessian pass;
    ``batched_objective(C)`` the objectives of a (c, M) candidate stack.

    while_loop, not scan: each Newton iteration is HEAVY (Gramian Hessian
    + solve + batched line search), so converged fits must stop computing
    — a scan with a done-latch would burn the full max_iter budget of
    Hessians to freeze the result. History is written into a preallocated
    buffer; the unfilled tail is pinned to the final objective after the
    loop (same decode contract as FISTA's scan).

    Returns ``(wb, converged, iterations, history)`` with ``history`` of
    length ``max_iter + 1`` (entry 0 = objective at zero).
    """
    wb0 = jnp.zeros((M,), dt)
    # matvec-width pass only — stats(wb0) would psum a full discarded
    # Hessian just to read this scalar
    obj0 = batched_objective(wb0[None, :])[0]
    steps = jnp.asarray([1.0, 0.5, 0.25, 0.125], dt)
    hist0 = jnp.full((max_iter + 1,), obj0, dt)

    def cond(state):
        _, halt, _, iters, _, _ = state
        return jnp.logical_and(iters < max_iter, ~halt)

    def body(state):
        wb, _, _, iters, last_obj, hist = state
        g, H = stats(wb)
        # Scaled jitter keeps the solve usable when H is near-singular
        # (e.g. the unpenalized-softmax shift degeneracy). Scale by the
        # dtype's eps: an absolute 1e-9 is BELOW half-ulp of a float32
        # diagonal (~1e-8 at O(1) entries) and would be bit-for-bit inert.
        jitter = 100.0 * jnp.asarray(jnp.finfo(dt).eps, dt) * \
            (1.0 + jnp.max(jnp.abs(jnp.diag(H))))
        delta = jnp.linalg.solve(H + jitter * jnp.eye(M, dtype=dt), g)
        delta = jnp.where(valid_full, delta, 0.0)
        C = wb[None, :] - steps[:, None] * delta[None, :]  # (4, M)
        objs = batched_objective(C)
        objs = jnp.where(jnp.isfinite(objs), objs, jnp.inf)
        improving = objs < last_obj
        any_improving = jnp.any(improving)
        # first improving candidate (largest step), else stay put
        idx = jnp.argmax(improving)
        wb_new = jnp.where(any_improving, C[idx], wb)
        obj = jnp.where(any_improving, objs[idx], last_obj)
        rel = jnp.abs(obj - last_obj) / jnp.maximum(jnp.abs(last_obj), 1e-12)
        # Convergence: an accepted step whose relative decrease is < tol,
        # OR a stalled line search AT the optimum (gradient ~0 — at float
        # precision no candidate can improve there, the normal terminal
        # state for tiny tol). A stall with a LARGE gradient is a genuine
        # failure and must NOT report converged (sklearn's gtol analogue).
        gmax = jnp.max(jnp.abs(g))
        grad_small = gmax < 1e-4 * jnp.maximum(1.0, jnp.abs(last_obj))
        ok = jnp.logical_or(jnp.logical_and(rel < tol, any_improving),
                            jnp.logical_and(~any_improving, grad_small))
        halt = jnp.logical_or(ok, ~any_improving)
        hist = hist.at[iters + 1].set(obj)
        return (wb_new, halt, ok, iters + 1, obj, hist)

    init = (wb0, jnp.asarray(False), jnp.asarray(False),
            jnp.asarray(0, jnp.int32), obj0, hist0)
    wb, _, ok, iters, last_obj, hist = jax.lax.while_loop(cond, body, init)
    history = jnp.where(jnp.arange(max_iter + 1) <= iters, hist, last_obj)
    return wb, ok, iters, history


class SoftmaxFitResult(NamedTuple):
    coefficient_matrix: jnp.ndarray     # (K, d)
    intercept_vector: jnp.ndarray       # (K,)
    iterations: jnp.ndarray
    objective_history: jnp.ndarray
    converged: jnp.ndarray


def _softmax_core(X, y, mask, reg_param, alpha, n, std, num_classes,
                  max_iter, tol, fit_intercept, standardization, axis=None,
                  weights=None):
    """FISTA on the mean softmax cross-entropy over (possibly sharded) rows.

    MLlib ``family="multinomial"`` conventions: features scaled by sample
    std without centering; the (K, d) coefficient matrix penalized
    elementwise with the same elastic-net weights as the binary path; the
    K intercepts unpenalized. The whole loop is one ``lax.while_loop``
    (shared ``_fista_drive``) with a single fused ``(K·d + K + 1)`` psum
    per iteration when sharded — the per-iteration ``treeAggregate``
    analogue, exactly like the binary path.
    """
    dt = X.dtype
    d = X.shape[1]
    K = num_classes
    valid = std > 0
    sx = jnp.where(valid, std, 1.0)
    Xs = (X / sx) * mask.astype(dt)[:, None]   # standardized, masked rows
    wm = mask.astype(dt)
    wv = wm if weights is None else weights.astype(dt)
    Y1 = jax.nn.one_hot(y.astype(jnp.int32), K, dtype=dt) * wm[:, None]

    u1 = jnp.ones((d,), dt) if standardization \
        else jnp.where(valid, 1.0 / sx, 0.0)
    lam1 = alpha * reg_param * u1                       # (d,), same per class
    lam2 = (1.0 - alpha) * reg_param * (u1 if standardization else u1 * u1)

    def reduce_(v):
        return jax.lax.psum(v, axis) if axis is not None else v

    # Softmax Hessian w.r.t. margins is diag(p) − ppᵀ ⪯ ½·I, so
    # L ≤ ½‖Xs‖_F²/n (vs ¼ for the binary sigmoid).
    sq = reduce_(jnp.sum(wv[:, None] * Xs * Xs))
    L = 0.5 * sq / n + jnp.max(lam2, initial=0.0) + jnp.asarray(1e-12, dt)
    step = 1.0 / L

    m = K * d     # wb layout: [W.ravel() | b] with W (K, d), b (K,)

    def loss_grad(wb):
        W = wb[:m].reshape(K, d)
        b = wb[m:]
        margin = Xs @ W.T + b[None, :] * wm[:, None]        # (n, K)
        lse = jax.nn.logsumexp(margin, axis=1)
        ll = wv * jnp.where(mask, lse - jnp.sum(margin * Y1, axis=1), 0.0)
        p = jax.nn.softmax(margin, axis=1)
        resid = (p - Y1) * wv[:, None]                      # (n, K)
        g_W = resid.T @ Xs                                  # (K, d)
        g_b = jnp.sum(resid, axis=0)                        # (K,)
        packed = jnp.concatenate([g_W.ravel(), g_b, jnp.sum(ll)[None]])
        packed = reduce_(packed)
        grad = packed[: m + K] / n
        grad = grad.at[:m].add((lam2[None, :] * W).ravel())
        loss = packed[m + K] / n
        if not fit_intercept:
            grad = grad.at[m:].set(0.0)
        return loss, grad

    def objective(wb, loss):
        W = wb[:m].reshape(K, d)
        return (loss + jnp.sum(lam1[None, :] * jnp.abs(W))
                + 0.5 * jnp.sum(lam2[None, :] * W * W))

    lam1_full = jnp.concatenate([jnp.tile(lam1, K), jnp.zeros((K,), dt)])
    valid_full = jnp.concatenate([jnp.tile(valid, K),
                                  jnp.full((K,), fit_intercept)])

    def prox(cand):
        return jnp.where(valid_full, _soft(cand, step * lam1_full), 0.0)

    wb, done, iters, history = _fista_drive(loss_grad, objective, prox,
                                            step, m + K, dt, max_iter, tol)
    W = jnp.where(valid[None, :], wb[:m].reshape(K, d) / sx[None, :], 0.0)
    b = wb[m:]
    return SoftmaxFitResult(W, b, iters, history, done)


def _softmax_newton_core(X, y, mask, reg_param, alpha, n, std, num_classes,
                         max_iter, tol, fit_intercept, standardization,
                         axis=None, weights=None):
    """Damped Newton (IRLS) on mean softmax cross-entropy — the L1-free
    multinomial fast path (see ``_logistic_newton_core`` for the design;
    this is its K-class generalization).

    The softmax Hessian couples classes: block (k,l) is
    ``Σ_n s_nkl · za_n za_nᵀ`` with ``s_nkl = w_n (p_nk δ_kl − p_nk p_nl)``
    — built in ONE einsum over the batch (MXU-shaped contraction), psum'd
    once per iteration together with the gradient. The full
    ``(K(d+1))²`` system solves on device; the router caps ``K(d+1)`` so
    the solve stays trivial next to the data pass. For unpenalized fits
    the shift degeneracy (softmax invariance) makes H singular along the
    all-classes-shift direction — the scaled jitter handles it, and the
    caller's identifiability pivot (MLlib centering) fixes the gauge.
    """
    del alpha  # L1-free by construction (router guarantees it)
    dt = X.dtype
    d = X.shape[1]
    K = num_classes
    valid = std > 0
    sx = jnp.where(valid, std, 1.0)
    wm = mask.astype(dt)
    Xs = (X / sx) * wm[:, None]
    wv = wm if weights is None else weights.astype(dt)
    Y1 = jax.nn.one_hot(y.astype(jnp.int32), K, dtype=dt) * wm[:, None]
    Za = jnp.concatenate([Xs, wm[:, None]], axis=1)      # (n, d+1)

    u1 = jnp.ones((d,), dt) if standardization \
        else jnp.where(valid, 1.0 / sx, 0.0)
    lam2 = reg_param * (u1 if standardization else u1 * u1)    # (d,)
    # wb layout: (K, d+1) ravelled — [W | b] per class row
    lam2_row = jnp.concatenate([lam2, jnp.zeros((1,), dt)])    # (d+1,)
    lam2_full = jnp.tile(lam2_row, K)
    valid_row = jnp.concatenate([valid,
                                 jnp.full((1,), bool(fit_intercept))])
    valid_full = jnp.tile(valid_row, K)
    M = K * (d + 1)

    def reduce_(v):
        return jax.lax.psum(v, axis) if axis is not None else v

    def margins_of(Wb):
        """(n, K) margins for a (K, d+1) coefficient block."""
        return Za @ Wb.T

    def stats(wb):
        """Gradient + block Hessian at wb — one fused (psum'd) pass (the
        loss lives only in ``batched_objective``; see the binary core)."""
        Wb = wb.reshape(K, d + 1)
        margin = margins_of(Wb)
        p = jax.nn.softmax(margin, axis=1)
        resid = (p - Y1) * wv[:, None]                     # (n, K)
        g = (resid.T @ Za).ravel()                         # (K(d+1),)
        # block Hessian: S_nkl = wv_n (p_nk δ_kl − p_nk p_nl)
        S = wv[:, None, None] * (
            jnp.einsum("nk,kl->nkl", p, jnp.eye(K, dtype=dt))
            - p[:, :, None] * p[:, None, :])               # (n, K, K)
        H = jnp.einsum("nkl,ni,nj->kilj", S, Za, Za).reshape(M, M)
        packed = reduce_(jnp.concatenate([H.ravel(), g]))
        H = packed[:M * M].reshape(M, M) / n
        g = packed[M * M:] / n
        g = g + lam2_full * wb
        H = H + jnp.diag(lam2_full)
        g = jnp.where(valid_full, g, 0.0)
        H = jnp.where(valid_full[:, None] & valid_full[None, :], H,
                      jnp.eye(M, dtype=dt))
        return g, H

    def batched_objective(C):
        """(c,) objectives of a (c, M) candidate stack in one fused pass."""
        Wc = C.reshape(-1, K, d + 1)
        margins = jnp.einsum("nj,ckj->nck", Za, Wc)        # (n, c, K)
        lse = jax.nn.logsumexp(margins, axis=2)            # (n, c)
        fitted = jnp.einsum("nck,nk->nc", margins, Y1)
        ll = jnp.sum(wv[:, None] * jnp.where(mask[:, None],
                                             lse - fitted, 0.0), axis=0)
        ll = reduce_(ll) / n
        return ll + 0.5 * jnp.sum(lam2_full[None, :] * C * C, axis=1)

    wb, ok, iters, history = _newton_drive(stats, batched_objective, M,
                                           valid_full, dt, max_iter, tol)
    Wb = wb.reshape(K, d + 1)
    W = jnp.where(valid[None, :], Wb[:, :d] / sx[None, :], 0.0)
    b = Wb[:, d]
    return SoftmaxFitResult(W, b, iters, history, ok)


def _unpack_z(Z):
    """Split the packed design ``Z = [X, y, 1]·mask`` (pack_design layout).

    The pre-masked columns are exactly what the logistic core consumes —
    it only ever reads X, y masked, and ``w² = w`` for a boolean mask, so
    masked moments (w@(X·w) = w@X etc.) are unchanged.
    """
    d = Z.shape[1] - 2
    X = Z[:, :d]
    y = Z[:, d]
    mask = Z[:, d + 1] > 0
    return X, y, mask



def _unpack_zw(Z):
    """Split the weighted packed design ``Z = [X·m, y·m, w·m]``
    (pack_design_weighted layout): the last column carries the REAL
    instance weights (zero on masked rows), so the boolean mask is
    ``w > 0`` and the weights ride the same single buffer."""
    d = Z.shape[1] - 2
    w = Z[:, d + 1]
    return Z[:, :d], Z[:, d], w > 0, w


def _pack_logistic_result(r: "LogisticFitResult"):
    """One output buffer: [coef(d) | intercept | iters | converged | history]
    (same layout as the linear path; decode with
    distributed.unpack_fit_result)."""
    dt = r.coefficients.dtype
    scalars = jnp.stack([r.intercept.astype(dt), r.iterations.astype(dt),
                         r.converged.astype(dt)])
    return jnp.concatenate([r.coefficients, scalars,
                            r.objective_history.astype(dt)])


@functools.lru_cache(maxsize=None)
def fused_logistic_fit_packed(mesh: Optional[Mesh], max_iter: int, tol: float,
                              fit_intercept: bool, standardization: bool,
                              weighted: bool = False,
                              solver: str = "fista"):
    """One jitted program: stats pass + solver scan (+ per-iteration psum
    when sharded). Mirrors the linear path's ``fused_linear_fit_packed``,
    including its single-input/single-output dispatch discipline:
    ``fit(Z, hyper) -> flat`` with ``Z = pack_design(X, y, mask)`` and
    ``hyper = [regParam, elasticNetParam]``. With ``weighted=True`` the
    input is ``pack_design_weighted(X, y, mask, w)`` — the last column
    carries real instance weights (MLlib weightCol), and n/std/loss/grad
    are their weighted forms.

    ``solver``: "fista" (the general elastic-net path) or "newton" (damped
    IRLS — L1-free penalties only; ``LogisticRegression.fit`` routes to it
    automatically, see ``_logistic_newton_core``)."""
    core = {"fista": _logistic_core,
            "newton": _logistic_newton_core}[solver]

    def split(Z):
        if weighted:
            return _unpack_zw(Z)
        X, y, mask = _unpack_z(Z)
        return X, y, mask, None

    if mesh is None or mesh.devices.size <= 1:
        def fit(Z, hyper):
            X, y, mask, w = split(Z)
            n, std = _feature_stats(X, y, mask if w is None else w)
            return _pack_logistic_result(core(
                X, y, mask, hyper[0], hyper[1], n, std, max_iter,
                tol, fit_intercept, standardization, weights=w))
    else:
        def local(Z, hyper):
            X, y, mask, w = split(Z)
            n, std = _sharded_feature_stats(X, mask if w is None else w)
            return _pack_logistic_result(core(
                X, y, mask, hyper[0], hyper[1], n, std, max_iter,
                tol, fit_intercept, standardization, axis=DATA_AXIS,
                weights=w))

        fit = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P()),
            out_specs=P())

    return serialize_collectives(jax.jit(fit), mesh)


def _svc_core(X, y, mask, reg_param, n, std, max_iter, tol,
              fit_intercept, standardization, axis=None):
    """Accelerated gradient on the mean SQUARED hinge + L2 over (possibly
    sharded) rows — the MLlib ``LinearSVC`` role.

    MLlib minimizes the (subdifferentiable) hinge with OWLQN; the squared
    hinge is its smooth relative (sklearn's ``LinearSVC`` default), which
    maps onto the same zero-host-sync Nesterov ``lax.while_loop`` as the
    logistic path — one fused (d+2) psum per iteration when sharded.
    Decision boundaries agree with the hinge solution to test tolerance
    (asserted vs sklearn); conventions (std scaling without centering,
    unpenalized intercept, standardization-off 1/σ² penalty weights) match
    the logistic path / MLlib.
    """
    dt = X.dtype
    d = X.shape[1]
    valid = std > 0
    sx = jnp.where(valid, std, 1.0)
    Xs = (X / sx) * mask.astype(dt)[:, None]
    wm = mask.astype(dt)
    z = (2.0 * y.astype(dt) - 1.0) * wm         # ±1 labels, masked

    u1 = jnp.ones((d,), dt) if standardization \
        else jnp.where(valid, 1.0 / sx, 0.0)
    lam2 = reg_param * (u1 if standardization else u1 * u1)

    def reduce_(v):
        return jax.lax.psum(v, axis) if axis is not None else v

    # squared-hinge curvature ≤ 2 ⇒ L ≤ 2‖Xs‖_F²/n + max λ₂
    sq = reduce_(jnp.sum(Xs * Xs))
    L = 2.0 * sq / n + jnp.max(lam2, initial=0.0) + jnp.asarray(1e-12, dt)
    step = 1.0 / L

    def loss_grad(wb):
        w, b = wb[:d], wb[d]
        margin = Xs @ w + b * wm
        slack = jnp.maximum(0.0, wm - z * margin)   # masked rows: 0 − 0
        # d/dmargin ½slack² summed — resid drives both grad terms
        resid = -z * slack
        g_w = Xs.T @ resid
        g_b = jnp.sum(resid)
        packed = reduce_(jnp.concatenate(
            [g_w, jnp.array([g_b, jnp.sum(slack * slack)])]))
        grad = packed[: d + 1] * (2.0 / n)
        grad = grad.at[:d].add(lam2 * wb[:d])
        loss = packed[d + 1] / n
        if not fit_intercept:
            grad = grad.at[d].set(0.0)
        return loss, grad

    def objective(wb, loss):
        w = wb[:d]
        return loss + 0.5 * jnp.sum(lam2 * w * w)

    def prox(cand):
        return jnp.concatenate(
            [jnp.where(valid, cand[:d], 0.0),
             jnp.where(fit_intercept, cand[d], 0.0)[None]])

    wb, done, iters, history = _fista_drive(loss_grad, objective, prox,
                                            step, d + 1, dt, max_iter, tol)
    coef = jnp.where(valid, wb[:d] / sx, 0.0)
    return LogisticFitResult(coef, wb[d], iters, history, done)


@functools.lru_cache(maxsize=None)
def fused_svc_fit_packed(mesh: Optional[Mesh], max_iter: int, tol: float,
                         fit_intercept: bool, standardization: bool):
    """One jitted program for LinearSVC: stats pass + Nesterov scan
    (+ per-iteration psum when sharded); same single-input/single-output
    dispatch discipline as the logistic path. ``hyper = [regParam, 0]``
    (second slot reserved — the SVC penalty is L2-only, like MLlib)."""

    if mesh is None or mesh.devices.size <= 1:
        def fit(Z, hyper):
            X, y, mask = _unpack_z(Z)
            n, std = _feature_stats(X, y, mask)
            return _pack_logistic_result(_svc_core(
                X, y, mask, hyper[0], n, std, max_iter, tol,
                fit_intercept, standardization))
    else:
        def local(Z, hyper):
            X, y, mask = _unpack_z(Z)
            n, std = _sharded_feature_stats(X, mask)
            return _pack_logistic_result(_svc_core(
                X, y, mask, hyper[0], n, std, max_iter, tol,
                fit_intercept, standardization, axis=DATA_AXIS))

        fit = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P()),
            out_specs=P())

    return serialize_collectives(jax.jit(fit), mesh)


def _pack_softmax_result(r: "SoftmaxFitResult"):
    """One output buffer: [W.ravel() | b | iters | converged | history]."""
    dt = r.coefficient_matrix.dtype
    scalars = jnp.stack([r.iterations.astype(dt), r.converged.astype(dt)])
    return jnp.concatenate([r.coefficient_matrix.ravel(),
                            r.intercept_vector.astype(dt), scalars,
                            r.objective_history.astype(dt)])


def unpack_softmax_result(flat, num_classes: int, d: int):
    """Host-side decode of the packed softmax fit output."""
    flat = np.asarray(flat)
    m = num_classes * d
    return SoftmaxFitResult(
        coefficient_matrix=flat[:m].reshape(num_classes, d),
        intercept_vector=flat[m: m + num_classes],
        iterations=np.int32(flat[m + num_classes]),
        objective_history=flat[m + num_classes + 2:],
        converged=bool(flat[m + num_classes + 1]))


@functools.lru_cache(maxsize=None)
def fused_softmax_fit_packed(mesh: Optional[Mesh], num_classes: int,
                             max_iter: int, tol: float,
                             fit_intercept: bool, standardization: bool,
                             weighted: bool = False,
                             solver: str = "fista"):
    """Multinomial analogue of ``fused_logistic_fit_packed`` — same
    single-input/single-output dispatch discipline and per-iteration psum
    (and the same ``weighted`` / ``solver`` contracts; "newton" is the
    L1-free block-Hessian IRLS, see ``_softmax_newton_core``)."""
    core = {"fista": _softmax_core,
            "newton": _softmax_newton_core}[solver]

    def split(Z):
        if weighted:
            return _unpack_zw(Z)
        X, y, mask = _unpack_z(Z)
        return X, y, mask, None

    if mesh is None or mesh.devices.size <= 1:
        def fit(Z, hyper):
            X, y, mask, w = split(Z)
            n, std = _feature_stats(X, y, mask if w is None else w)
            return _pack_softmax_result(core(
                X, y, mask, hyper[0], hyper[1], n, std, num_classes,
                max_iter, tol, fit_intercept, standardization, weights=w))
    else:
        def local(Z, hyper):
            X, y, mask, w = split(Z)
            n, std = _sharded_feature_stats(X, mask if w is None else w)
            return _pack_softmax_result(core(
                X, y, mask, hyper[0], hyper[1], n, std, num_classes,
                max_iter, tol, fit_intercept, standardization,
                axis=DATA_AXIS, weights=w))

        fit = shard_map(
            local, mesh=mesh,
            in_specs=(P(DATA_AXIS), P()),
            out_specs=P())

    return serialize_collectives(jax.jit(fit), mesh)


@persistable
class LogisticRegression(Estimator):
    """Binary or multinomial logistic regression with elastic-net
    regularization (MLlib ``family`` semantics: auto / binomial /
    multinomial)."""

    weight_col = None    # back-compat default for pre-weightCol saves

    _persist_attrs = ("max_iter", "reg_param", "elastic_net_param", "tol",
                      "fit_intercept", "standardization", "threshold",
                      "family", "features_col", "label_col", "prediction_col",
                      "probability_col", "raw_prediction_col", "weight_col")

    def __init__(self, max_iter: int = 100, reg_param: float = 0.0,
                 elastic_net_param: float = 0.0, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True,
                 threshold: float = 0.5, family: str = "auto",
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction",
                 probability_col: str = "probability",
                 raw_prediction_col: str = "rawPrediction",
                 weight_col: Optional[str] = None):
        if family not in ("auto", "binomial", "multinomial"):
            raise ValueError(f"unknown family {family!r}")
        self.max_iter = max_iter
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization
        self.threshold = threshold
        self.family = family
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.probability_col = probability_col
        self.raw_prediction_col = raw_prediction_col
        self.weight_col = weight_col

    # fluent setters (snake + camel)
    def set_max_iter(self, v): self.max_iter = int(v); return self
    def set_reg_param(self, v): self.reg_param = float(v); return self
    def set_elastic_net_param(self, v): self.elastic_net_param = float(v); return self
    def set_tol(self, v): self.tol = float(v); return self
    def set_fit_intercept(self, v): self.fit_intercept = bool(v); return self
    def set_standardization(self, v): self.standardization = bool(v); return self
    def set_threshold(self, v): self.threshold = float(v); return self
    def set_features_col(self, v): self.features_col = v; return self
    def set_label_col(self, v): self.label_col = v; return self
    def set_weight_col(self, v): self.weight_col = v; return self

    def set_family(self, v):
        if v not in ("auto", "binomial", "multinomial"):
            raise ValueError(f"unknown family {v!r}")
        self.family = v
        return self

    setFamily = set_family

    setMaxIter = set_max_iter
    setRegParam = set_reg_param
    setElasticNetParam = set_elastic_net_param
    setTol = set_tol
    setFitIntercept = set_fit_intercept
    setStandardization = set_standardization
    setThreshold = set_threshold
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col
    setWeightCol = set_weight_col

    def get_reg_param(self): return self.reg_param
    def get_tol(self): return self.tol
    def get_threshold(self): return self.threshold

    getRegParam = get_reg_param
    getTol = get_tol
    getThreshold = get_threshold

    def _params_dict(self) -> dict:
        return {k: getattr(self, k) for k in (
            "max_iter", "reg_param", "elastic_net_param", "tol",
            "fit_intercept", "standardization", "threshold", "family",
            "features_col", "label_col", "prediction_col", "probability_col",
            "raw_prediction_col", "weight_col")}

    def fit(self, frame: Frame, mesh=None) -> "LogisticRegressionModel":
        if mesh is None:
            from ..session import TpuSession

            active = TpuSession.active()
            mesh = active.mesh if active is not None else None
        if mesh is not None and mesh.devices.size <= 1:
            mesh = None
        X, y, mask = _extract_xy(frame, self.features_col, self.label_col)

        yv = np.asarray(y)[np.asarray(mask)]
        if len(yv) == 0:
            raise ValueError("LogisticRegression: no valid rows")
        if np.any(yv < 0) or np.any(yv != np.floor(yv)):
            raise ValueError("labels must be nonnegative integers 0..k-1")
        num_classes = int(yv.max()) + 1
        family = self.family
        if family == "auto":
            family = "binomial" if num_classes <= 2 else "multinomial"
        if family == "binomial" and num_classes > 2:
            raise ValueError(
                f"binomial family requires binary labels, found "
                f"{num_classes} classes; use family='multinomial'")

        from ..config import float_dtype
        from ..parallel.distributed import (pack_design,
                                            pack_design_weighted,
                                            place_packed, unpack_fit_result)

        weighted = self.weight_col is not None
        if weighted:
            # masked rows' weight values never participate (see the
            # LinearRegression weightCol note): validate valid rows only,
            # zero the rest so a NaN payload cannot poison the packing
            w = frame._column_values(self.weight_col)
            # NaN fails >= too (silent NaN poisoning must raise instead)
            if not bool(np.all(np.asarray(w)[np.asarray(mask)] >= 0)):
                raise ValueError("weights must be nonnegative")
            w = jnp.where(mask, jnp.asarray(w, float_dtype()), 0.0)
            Zd = place_packed(pack_design_weighted(X, y, mask, w), mesh)
        else:
            Zd = place_packed(pack_design(X, y, mask), mesh)
        hyper = jnp.asarray([self.reg_param, self.elastic_net_param],
                            float_dtype())

        if family == "multinomial":
            K = max(num_classes, 2)
            # Same routing as the binary path: L1-free penalties take the
            # block-Hessian Newton solver; the K(d+1) cap keeps the
            # on-device solve trivial next to the per-iteration data pass.
            l1_free = (self.elastic_net_param == 0.0
                       or self.reg_param == 0.0)
            sm_solver = "newton" if (l1_free
                                     and K * (X.shape[1] + 1) <= 256) \
                else "fista"
            from ..utils import observability as _obs
            from ..utils.profiling import counters as _counters

            with _obs.fit_span("fit.logistic_regression",
                               fused_softmax_fit_packed,
                               family="multinomial", classes=K,
                               rows=int(X.shape[0]),
                               features=int(X.shape[1]),
                               solver=sm_solver, max_iter=self.max_iter,
                               shards=(mesh.devices.size if mesh is not None
                                       else 1)) as s:
                fit_fn = fused_softmax_fit_packed(mesh, K, self.max_iter,
                                                  self.tol,
                                                  self.fit_intercept,
                                                  self.standardization,
                                                  weighted=weighted,
                                                  solver=sm_solver)
                result = unpack_softmax_result(fit_fn(Zd, hyper), K,
                                               X.shape[1])
                _counters.increment("solver.fits")
                _counters.increment("solver.iterations",
                                    int(result.iterations))
                s.set(iterations=int(result.iterations),
                      converged=bool(result.converged))
            W = np.asarray(result.coefficient_matrix, np.float64)
            b = np.asarray(result.intercept_vector, np.float64)
            # Identifiability pivot (MLlib convention): the softmax loss is
            # invariant to a per-feature shift across classes; intercepts
            # are never penalized so they are always centered, coefficients
            # only when the fit was unpenalized.
            if self.fit_intercept:
                b = b - b.mean()
            if self.reg_param == 0.0:
                W = W - W.mean(axis=0, keepdims=True)
            result = SoftmaxFitResult(W, b, result.iterations,
                                      result.objective_history,
                                      result.converged)
            model = LogisticRegressionModel(
                coefficient_matrix=W, intercept_vector=b,
                params=self._params_dict())
            model._summary_source = (frame, result)
            return model

        # Solver routing (framework upgrade, solution-identical): the
        # elastic-net general case runs FISTA; an L1-free penalty
        # (elasticNetParam==0 or regParam==0 — incl. MLlib's defaults)
        # runs damped Newton/IRLS, which converges in ~5-10 fused
        # iterations instead of FISTA's O(100). Capped at d<=256 so the
        # per-iteration (d+1)^2 Hessian psum + host-free solve stays cheap.
        l1_free = (self.elastic_net_param == 0.0 or self.reg_param == 0.0)
        solver = "newton" if (l1_free and X.shape[1] <= 256) else "fista"
        from ..utils import observability as _obs
        from ..utils.profiling import counters as _counters

        with _obs.fit_span("fit.logistic_regression",
                           fused_logistic_fit_packed,
                           family="binomial", classes=num_classes,
                           rows=int(X.shape[0]), features=int(X.shape[1]),
                           solver=solver, max_iter=self.max_iter,
                           shards=(mesh.devices.size if mesh is not None
                                   else 1)) as s:
            fit_fn = fused_logistic_fit_packed(mesh, self.max_iter, self.tol,
                                               self.fit_intercept,
                                               self.standardization,
                                               weighted=weighted,
                                               solver=solver)
            result = LogisticFitResult(
                *unpack_fit_result(fit_fn(Zd, hyper), X.shape[1]))
            _counters.increment("solver.fits")
            _counters.increment("solver.iterations", int(result.iterations))
            s.set(iterations=int(result.iterations),
                  converged=bool(result.converged))
        model = LogisticRegressionModel(
            coefficients=np.asarray(result.coefficients),
            intercept=float(result.intercept),
            params=self._params_dict())
        model._summary_source = (frame, result)
        return model


@persistable
class LogisticRegressionModel(Model):
    """Fitted logistic model. Binary fits expose ``coefficients`` /
    ``intercept``; multinomial fits expose ``coefficient_matrix`` (K, d) /
    ``intercept_vector`` (K,) — accessing the vector accessors on a
    multinomial model raises, exactly like MLlib."""

    def __init__(self, coefficients: Optional[np.ndarray] = None,
                 intercept: float = 0.0, params: Optional[dict] = None,
                 coefficient_matrix: Optional[np.ndarray] = None,
                 intercept_vector: Optional[np.ndarray] = None):
        if coefficient_matrix is not None:
            self._matrix = np.asarray(coefficient_matrix)
            self._intercepts = np.asarray(intercept_vector, np.float64)
            self._binary = False
        else:
            self._matrix = None
            self._intercepts = None
            self._binary = True
            self._coefficients = np.asarray(coefficients)
            self._intercept = float(intercept)
        self._params = dict(params or {})
        self._training_summary = None
        self._summary_source = None

    @property
    def is_multinomial(self) -> bool:
        return not self._binary

    @property
    def coefficients(self) -> np.ndarray:
        if not self._binary:
            raise RuntimeError(
                "coefficients is undefined for a multinomial model; "
                "use coefficient_matrix")
        return self._coefficients

    @property
    def intercept(self) -> float:
        if not self._binary:
            raise RuntimeError(
                "intercept is undefined for a multinomial model; "
                "use intercept_vector")
        return self._intercept

    @property
    def coefficient_matrix(self) -> np.ndarray:
        if self._binary:
            return self._coefficients[None, :]
        return self._matrix

    coefficientMatrix = coefficient_matrix

    @property
    def intercept_vector(self) -> np.ndarray:
        if self._binary:
            return np.asarray([self._intercept])
        return self._intercepts

    interceptVector = intercept_vector

    @property
    def num_classes(self) -> int:
        return 2 if self._binary else int(self._matrix.shape[0])

    numClasses = num_classes

    @property
    def num_features(self) -> int:
        return int(self.coefficient_matrix.shape[1])

    @property
    def threshold(self) -> float:
        return self._params.get("threshold", 0.5)

    def _margin(self, X):
        return X @ jnp.asarray(self.coefficients, X.dtype) + self.intercept

    def _margins_multi(self, X):
        W = jnp.asarray(self._matrix, X.dtype)
        b = jnp.asarray(self._intercepts, X.dtype)
        return X @ W.T + b[None, :]

    def transform(self, frame: Frame) -> Frame:
        """Append rawPrediction (margin), probability, and prediction columns
        — MLlib's classifier transform contract."""
        p = self._params
        X = jnp.asarray(frame._column_values(p.get("features_col", "features")),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        if not self._binary:
            raw = self._margins_multi(X)
            prob = jax.nn.softmax(raw, axis=1)
            pred = jnp.argmax(raw, axis=1).astype(float_dtype())
            out = frame.with_column(
                p.get("raw_prediction_col", "rawPrediction"), raw)
            out = out.with_column(p.get("probability_col", "probability"),
                                  prob)
            return out.with_column(p.get("prediction_col", "prediction"),
                                   pred)
        margin = self._margin(X)
        prob = jax.nn.sigmoid(margin)
        pred = (prob > self.threshold).astype(float_dtype())
        out = frame.with_column(p.get("raw_prediction_col", "rawPrediction"), margin)
        out = out.with_column(p.get("probability_col", "probability"), prob)
        return out.with_column(p.get("prediction_col", "prediction"), pred)

    def predict_raw(self, features):
        v = np.asarray(features, np.float64).reshape(-1)
        if not self._binary:
            return self._matrix.astype(np.float64) @ v + self._intercepts
        return float(v @ self.coefficients.astype(np.float64) + self.intercept)

    def predict_probability(self, features):
        raw = self.predict_raw(features)
        if not self._binary:
            e = np.exp(raw - raw.max())
            return e / e.sum()
        return float(1.0 / (1.0 + np.exp(-raw)))

    predictProbability = predict_probability

    def predict(self, features) -> float:
        if not self._binary:
            return float(np.argmax(self.predict_raw(features)))
        return 1.0 if self.predict_probability(features) > self.threshold else 0.0

    @property
    def summary(self):
        if self._training_summary is None:
            if self._summary_source is None:
                raise RuntimeError("model was not fit with summary (loaded model?)")
            frame, result = self._summary_source
            if self._binary:
                self._training_summary = \
                    BinaryLogisticRegressionTrainingSummary(self, frame,
                                                            result)
            else:
                self._training_summary = \
                    LogisticRegressionTrainingSummary(self, frame, result)
        return self._training_summary

    @property
    def has_summary(self) -> bool:
        return self._training_summary is not None or self._summary_source is not None

    hasSummary = has_summary

    def evaluate(self, frame: Frame):
        if not self._binary:
            return LogisticRegressionSummary(self, frame)
        return BinaryLogisticRegressionSummary(self, frame)

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        write_json(os.path.join(path, "metadata.json"), {
            "class": "LogisticRegressionModel",
            "multinomial": not self._binary,
            "intercept": (self._intercept if self._binary
                          # dqlint: ok(host-sync): _intercepts is the host
                          # numpy copy materialized at fit time
                          else self._intercepts.tolist()),
            "params": self._params,
        })
        np.save(os.path.join(path, "coefficients.npy"),
                self._coefficients if self._binary else self._matrix)

    @classmethod
    def load(cls, path: str) -> "LogisticRegressionModel":
        meta = read_json(os.path.join(path, "metadata.json"))
        if meta.get("class") != "LogisticRegressionModel":
            raise ValueError(f"not a LogisticRegressionModel checkpoint: {path}")
        coef = np.load(os.path.join(path, "coefficients.npy"))
        if meta.get("multinomial"):
            return cls(coefficient_matrix=coef,
                       intercept_vector=np.asarray(meta["intercept"]),
                       params=meta.get("params"))
        return cls(coef, meta["intercept"], meta.get("params"))

    # Pipeline-persistence hooks (base.save_stage/load_stage dispatch here).
    def _save_to_dir(self, path: str) -> None:
        self.save(path)

    @classmethod
    def _load_from_dir(cls, path: str, meta: dict):
        return cls.load(path)


class BinaryLogisticRegressionSummary:
    """Evaluation over a frame's valid rows: accuracy, ROC, areaUnderROC."""

    def __init__(self, model: LogisticRegressionModel, frame: Frame):
        self._model = model
        pred_frame = model.transform(frame)
        d = pred_frame.to_pydict()
        p = model._params
        self._label = d[p.get("label_col", "label")].astype(np.float64)
        self._prob = d[p.get("probability_col", "probability")].astype(np.float64)
        self._pred = d[p.get("prediction_col", "prediction")].astype(np.float64)
        self._predictions_frame = pred_frame

    @property
    def predictions(self) -> Frame:
        return self._predictions_frame

    @property
    def accuracy(self) -> float:
        return float(np.mean(self._pred == self._label))

    @property
    def area_under_roc(self) -> float:
        """Exact AUC — delegates to the shared O(n log n) helper."""
        from .evaluation import area_under_roc

        return area_under_roc(self._label, self._prob)

    areaUnderROC = area_under_roc

    @property
    def roc(self) -> Frame:
        """(FPR, TPR) curve frame, MLlib's ``summary.roc()`` analogue."""
        from .evaluation import roc_points

        fpr, tpr = roc_points(self._label, self._prob)
        return Frame({"FPR": fpr, "TPR": tpr})

    @property
    def pr(self) -> Frame:
        """(recall, precision) curve, MLlib's ``summary.pr()``."""
        from .evaluation import pr_points

        _, precision, recall = pr_points(self._label, self._prob)
        return Frame({"recall": np.r_[0.0, recall],
                      "precision": np.r_[1.0, precision]})

    def _by_threshold(self, metric: str) -> Frame:
        from .evaluation import pr_points

        thr, precision, recall = pr_points(self._label, self._prob)
        if metric == "precision":
            vals = precision
        elif metric == "recall":
            vals = recall
        else:
            denom = np.maximum(precision + recall, 1e-30)
            vals = 2.0 * precision * recall / denom
        return Frame({"threshold": thr, metric: vals})

    @property
    def precision_by_threshold(self) -> Frame:
        return self._by_threshold("precision")

    precisionByThreshold = precision_by_threshold

    @property
    def recall_by_threshold(self) -> Frame:
        return self._by_threshold("recall")

    recallByThreshold = recall_by_threshold

    @property
    def f_measure_by_threshold(self) -> Frame:
        return self._by_threshold("F-Measure")

    fMeasureByThreshold = f_measure_by_threshold


class BinaryLogisticRegressionTrainingSummary(BinaryLogisticRegressionSummary):
    def __init__(self, model, frame, result: LogisticFitResult):
        super().__init__(model, frame)
        self._iterations = int(result.iterations)
        hist = np.asarray(result.objective_history, np.float64)
        self._objective_history = hist[: self._iterations + 1]

    @property
    def total_iterations(self) -> int:
        return self._iterations

    totalIterations = total_iterations

    @property
    def objective_history(self) -> np.ndarray:
        return self._objective_history

    objectiveHistory = objective_history


class LogisticRegressionSummary:
    """Multiclass evaluation over a frame's valid rows — MLlib's
    ``LogisticRegressionSummary``: accuracy, per-label precision/recall/F,
    weighted averages."""

    def __init__(self, model: "LogisticRegressionModel", frame: Frame):
        self._model = model
        pred_frame = model.transform(frame)
        d = pred_frame.to_pydict()
        p = model._params
        self._label = np.asarray(d[p.get("label_col", "label")], np.float64)
        self._pred = np.asarray(d[p.get("prediction_col", "prediction")],
                                np.float64)
        self._predictions_frame = pred_frame
        self._k = model.num_classes
        self._confusion_cache = None

    @property
    def predictions(self) -> Frame:
        return self._predictions_frame

    @property
    def labels(self) -> np.ndarray:
        return np.arange(self._k, dtype=np.float64)

    @property
    def accuracy(self) -> float:
        return float(np.mean(self._pred == self._label))

    def _confusion(self):
        if self._confusion_cache is None:
            k = self._k
            pred_i = self._pred.astype(np.int64)
            true_i = self._label.astype(np.int64)
            tp = np.bincount(pred_i[pred_i == true_i],
                             minlength=k)[:k].astype(np.float64)
            pred_c = np.bincount(pred_i, minlength=k)[:k].astype(np.float64)
            true_c = np.bincount(true_i, minlength=k)[:k].astype(np.float64)
            self._confusion_cache = (tp, pred_c, true_c)
        return self._confusion_cache

    @property
    def precision_by_label(self) -> np.ndarray:
        tp, pred_c, _ = self._confusion()
        return np.where(pred_c > 0, tp / np.maximum(pred_c, 1), 0.0)

    precisionByLabel = precision_by_label

    @property
    def recall_by_label(self) -> np.ndarray:
        tp, _, true_c = self._confusion()
        return np.where(true_c > 0, tp / np.maximum(true_c, 1), 0.0)

    recallByLabel = recall_by_label

    @property
    def f_measure_by_label(self) -> np.ndarray:
        p, r = self.precision_by_label, self.recall_by_label
        return np.where(p + r > 0, 2 * p * r / np.maximum(p + r, 1e-300), 0.0)

    fMeasureByLabel = f_measure_by_label

    def _weights(self):
        _, _, true_c = self._confusion()
        return true_c / max(true_c.sum(), 1.0)

    @property
    def weighted_precision(self) -> float:
        return float(self._weights() @ self.precision_by_label)

    weightedPrecision = weighted_precision

    @property
    def weighted_recall(self) -> float:
        return float(self._weights() @ self.recall_by_label)

    weightedRecall = weighted_recall

    @property
    def weighted_f_measure(self) -> float:
        return float(self._weights() @ self.f_measure_by_label)

    weightedFMeasure = weighted_f_measure


class LogisticRegressionTrainingSummary(LogisticRegressionSummary):
    def __init__(self, model, frame, result: "SoftmaxFitResult"):
        super().__init__(model, frame)
        self._iterations = int(result.iterations)
        hist = np.asarray(result.objective_history, np.float64)
        self._objective_history = hist[: self._iterations + 1]

    @property
    def total_iterations(self) -> int:
        return self._iterations

    totalIterations = total_iterations

    @property
    def objective_history(self) -> np.ndarray:
        return self._objective_history

    objectiveHistory = objective_history


# ---------------------------------------------------------------------------
# LinearSVC (MLlib org.apache.spark.ml.classification.LinearSVC)
# ---------------------------------------------------------------------------

@persistable
class LinearSVC(Estimator):
    """MLlib ``LinearSVC``: linear support-vector classifier, L2 penalty,
    binary 0/1 labels. Squared-hinge objective on device (see
    :func:`_svc_core`); builder surface mirrors MLlib
    (setMaxIter/setRegParam/setTol/setFitIntercept/setStandardization/
    setThreshold + the column setters)."""

    _persist_attrs = ("max_iter", "reg_param", "tol", "fit_intercept",
                      "standardization", "threshold", "features_col",
                      "label_col", "prediction_col", "raw_prediction_col")

    def __init__(self, max_iter: int = 100, reg_param: float = 0.0,
                 tol: float = 1e-6, fit_intercept: bool = True,
                 standardization: bool = True, threshold: float = 0.0,
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction",
                 raw_prediction_col: str = "rawPrediction"):
        self.max_iter = int(max_iter)
        self.reg_param = float(reg_param)
        self.tol = float(tol)
        self.fit_intercept = bool(fit_intercept)
        self.standardization = bool(standardization)
        self.threshold = float(threshold)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.raw_prediction_col = raw_prediction_col

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    def set_reg_param(self, v):
        self.reg_param = float(v)
        return self

    def set_tol(self, v):
        self.tol = float(v)
        return self

    def set_fit_intercept(self, v):
        self.fit_intercept = bool(v)
        return self

    def set_standardization(self, v):
        self.standardization = bool(v)
        return self

    def set_threshold(self, v):
        self.threshold = float(v)
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    setMaxIter = set_max_iter
    setRegParam = set_reg_param
    setTol = set_tol
    setFitIntercept = set_fit_intercept
    setStandardization = set_standardization
    setThreshold = set_threshold
    setFeaturesCol = set_features_col
    setLabelCol = set_label_col

    def fit(self, frame: Frame, mesh=None) -> "LinearSVCModel":
        from ..parallel.mesh import normalize_mesh

        if mesh is None:
            from ..session import TpuSession

            active = TpuSession.active()
            mesh = active.mesh if active is not None else None
        mesh = normalize_mesh(mesh)
        X, y, mask = _extract_xy(frame, self.features_col, self.label_col)
        yv = np.asarray(y)[np.asarray(mask)]
        if len(yv) == 0:
            raise ValueError("LinearSVC: no valid rows")
        if not np.all((yv == 0) | (yv == 1)):
            raise ValueError("LinearSVC requires binary 0/1 labels")

        from ..parallel.distributed import (pack_design, place_packed,
                                            unpack_fit_result)

        Zd = place_packed(pack_design(X, y, mask), mesh)
        fit_fn = fused_svc_fit_packed(mesh, self.max_iter, self.tol,
                                      self.fit_intercept,
                                      self.standardization)
        hyper = jnp.asarray([self.reg_param, 0.0], float_dtype())
        r = unpack_fit_result(fit_fn(Zd, hyper), X.shape[1])
        iters = int(r.iterations)
        # truncate the scan's padded tail (post-convergence repeats), the
        # LogisticRegressionTrainingSummary convention
        history = np.asarray(r.objective_history,
                             np.float64)[: iters + 1].tolist()
        return LinearSVCModel(np.asarray(r.coefficients),
                              float(r.intercept),
                              self._params_dict(),
                              objective_history=history,
                              iterations=iters)

    def _params_dict(self):
        return {k: getattr(self, k) for k in self._persist_attrs}


@persistable
class LinearSVCModel(Model):
    """Fitted linear SVC: ``rawPrediction`` = [−margin, margin];
    ``prediction`` thresholds the margin at ``threshold`` (MLlib)."""

    _persist_attrs = ("coefficients", "intercept", "_params",
                      "objective_history", "iterations")

    def __init__(self, coefficients, intercept, params=None,
                 objective_history=None, iterations=0):
        self.coefficients = np.asarray(coefficients)
        self.intercept = float(intercept)
        self._params = dict(params or {})
        self.objective_history = list(objective_history or [])
        self.iterations = int(iterations)

    def _p(self, k, default=None):
        return self._params.get(k, default)

    @property
    def num_features(self):
        return int(self.coefficients.shape[0])

    numFeatures = num_features
    getThreshold = lambda self: self._p("threshold", 0.0)

    def _margin(self, X):
        Xd = jnp.asarray(X, float_dtype())
        if Xd.ndim == 1:
            Xd = Xd[:, None]
        return Xd @ jnp.asarray(self.coefficients, Xd.dtype) + self.intercept

    def transform(self, frame: Frame) -> Frame:
        m = self._margin(frame._column_values(
            self._p("features_col", "features")))
        raw = jnp.stack([-m, m], axis=1)
        pred = (m > self._p("threshold", 0.0)).astype(float_dtype())
        out = frame.with_column(
            self._p("raw_prediction_col", "rawPrediction"), raw)
        return out.with_column(self._p("prediction_col", "prediction"),
                               pred)

    def predict(self, features) -> float:
        x = np.asarray(features, np.float64).reshape(1, -1)
        return float(np.asarray(self._margin(x))[0]
                     > self._p("threshold", 0.0))


# ---------------------------------------------------------------------------
# NaiveBayes (MLlib org.apache.spark.ml.classification.NaiveBayes)
# ---------------------------------------------------------------------------

def _nb_sufficient_stats(X, y, w, num_classes: int, psum_axis=None):
    """Per-class label counts and feature sums — one masked one-hot matmul
    (MXU), the whole NaiveBayes 'fit pass' in a single fused kernel.
    ``psum_axis`` reduces the (k,) + (k, d) statistics over the mesh's
    data axis (the treeAggregate analogue, SURVEY.md §3.3)."""
    onehot = jax.nn.one_hot(y.astype(jnp.int32), num_classes,
                            dtype=X.dtype) * w[:, None]    # (n, k)
    class_count = jnp.sum(onehot, axis=0)                  # (k,)
    feat_sum = onehot.T @ X                                # (k, d)
    if psum_axis is not None:
        class_count, feat_sum = jax.lax.psum((class_count, feat_sum),
                                             psum_axis)
    return class_count, feat_sum


@functools.lru_cache(maxsize=None)
def _nb_stats_fn(mesh, num_classes: int):
    """Jitted (and, under a mesh, shard_map'd) NaiveBayes statistics pass,
    cached per (mesh, k)."""
    if mesh is None:
        # close over num_classes — jit would trace a partial-bound int
        return jax.jit(
            lambda X, y, w: _nb_sufficient_stats(X, y, w, num_classes))

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    return serialize_collectives(jax.jit(shard_map(
        lambda X, y, w: _nb_sufficient_stats(X, y, w, num_classes,
                                             DATA_AXIS),
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P()))), mesh)


@persistable
class NaiveBayes(Estimator):
    """MLlib ``NaiveBayes``: multinomial (default) or bernoulli model with
    Laplace ``smoothing`` (default 1.0). Labels must be 0..k-1 doubles (the
    StringIndexer convention); multinomial requires nonnegative features,
    bernoulli requires 0/1 features — both validated like Spark.

    TPU-first: the entire fit is one one-hot matmul for the per-class
    sufficient statistics (no per-row loop), and prediction is
    ``pi + X @ thetaᵀ`` — a single MXU matmul batched over rows."""

    weight_col = None    # back-compat default for pre-weightCol saves

    _persist_attrs = ('smoothing', 'model_type', 'features_col', 'label_col',
                      'prediction_col', 'probability_col',
                      'raw_prediction_col', 'weight_col')

    def __init__(self, smoothing: float = 1.0, model_type: str = "multinomial",
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction",
                 probability_col: str = "probability",
                 raw_prediction_col: str = "rawPrediction",
                 weight_col: Optional[str] = None):
        if model_type not in ("multinomial", "bernoulli"):
            raise ValueError(f"model_type={model_type!r}")
        if smoothing < 0:
            raise ValueError("smoothing must be >= 0")
        self.smoothing = float(smoothing)
        self.model_type = model_type
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.probability_col = probability_col
        self.raw_prediction_col = raw_prediction_col
        self.weight_col = weight_col

    def set_smoothing(self, v):
        if v < 0:
            raise ValueError("smoothing must be >= 0")
        self.smoothing = float(v)
        return self

    setSmoothing = set_smoothing

    def set_model_type(self, v):
        if v not in ("multinomial", "bernoulli"):
            raise ValueError(f"model_type={v!r}")
        self.model_type = v
        return self

    setModelType = set_model_type

    def set_features_col(self, v):
        self.features_col = v
        return self

    setFeaturesCol = set_features_col

    def set_label_col(self, v):
        self.label_col = v
        return self

    setLabelCol = set_label_col

    def set_weight_col(self, v):
        self.weight_col = v
        return self

    setWeightCol = set_weight_col

    def fit(self, frame: Frame, mesh=None) -> "NaiveBayesModel":
        from ..parallel.mesh import normalize_mesh

        mesh = normalize_mesh(mesh)
        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(frame._column_values(self.label_col), dt)
        mask = np.asarray(frame.mask)
        yv = y[mask]
        if len(yv) == 0:
            raise ValueError("NaiveBayes: no valid rows")
        if np.any(yv < 0) or np.any(yv != np.floor(yv)):
            raise ValueError("labels must be nonnegative integers 0..k-1")
        num_classes = int(yv.max()) + 1
        Xv = X[mask]
        if self.model_type == "multinomial":
            if not np.all(Xv >= 0):   # NaN fails >= too (Spark rejects it)
                raise ValueError("multinomial NaiveBayes requires "
                                 "nonnegative features")
        else:
            if not np.all((Xv == 0) | (Xv == 1)):
                raise ValueError("bernoulli NaiveBayes requires 0/1 features")

        from ..parallel.distributed import pad_and_shard_rows

        Xh = X if self.model_type == "multinomial" else (X > 0).astype(dt)
        # masked slots may hold NaN features/labels (dropna/filter keep
        # values in place); zero them — 0-weight × NaN would still poison
        # the stats matmul (0 * NaN = NaN)
        Xh = np.where(mask[:, None], Xh, 0.0)
        yh = np.where(mask, y, 0.0)
        row_w = mask.astype(dt)
        if self.weight_col is not None:
            # instance weights (MLlib weightCol): the per-class sufficient
            # statistics are one weighted one-hot matmul, so weights slot
            # straight into the row-weight vector; masked slots stay 0
            w = np.asarray(frame._column_values(self.weight_col), dt)
            if not np.all(w[mask] >= 0):   # NaN fails >= too
                raise ValueError("weights must be nonnegative")
            row_w = np.where(mask, w, 0.0).astype(dt)
        Xd, yd, wd = pad_and_shard_rows(mesh, Xh, yh, row_w)
        class_count, feat_sum = _nb_stats_fn(mesh, num_classes)(Xd, yd, wd)
        class_count = np.asarray(class_count, np.float64)
        feat_sum = np.asarray(feat_sum, np.float64)
        lam = self.smoothing
        n = class_count.sum()
        pi = np.log(class_count + lam) - np.log(n + num_classes * lam)
        if self.model_type == "multinomial":
            # log P(feature j | class c), normalized over the feature axis
            row_tot = feat_sum.sum(axis=1, keepdims=True)
            theta = np.log(feat_sum + lam) \
                - np.log(row_tot + lam * X.shape[1])
        else:
            # log P(x_j = 1 | class c); the complement handled at predict
            theta = np.log(feat_sum + lam) \
                - np.log(class_count[:, None] + 2.0 * lam)
        return NaiveBayesModel(pi, theta, self.model_type,
                               self._params_dict())

    def _params_dict(self):
        return {k: getattr(self, k) for k in (
            "smoothing", "model_type", "features_col", "label_col",
            "prediction_col", "probability_col", "raw_prediction_col",
            "weight_col")}


@persistable
class NaiveBayesModel(Model):
    """``pi`` (k,) log class priors; ``theta`` (k, d) log feature
    likelihoods. Prediction is one matmul; bernoulli adds the complement
    term exactly as MLlib's BernoulliNB does."""

    _persist_attrs = ('pi', 'theta', 'model_type', '_params')

    def __init__(self, pi, theta, model_type, params=None):
        self.pi = np.asarray(pi)
        self.theta = np.asarray(theta)
        self.model_type = model_type
        self._params = dict(params or {})

    @property
    def num_classes(self):
        return int(self.pi.shape[0])

    numClasses = num_classes

    @property
    def num_features(self):
        return int(self.theta.shape[1])

    numFeatures = num_features

    def _raw(self, X):
        pi = jnp.asarray(self.pi, X.dtype)
        theta = jnp.asarray(self.theta, X.dtype)
        if self.model_type == "multinomial":
            return pi + X @ theta.T
        Xb = (X > 0).astype(X.dtype)
        neg = jnp.log1p(-jnp.exp(jnp.minimum(theta, -1e-7)))   # log(1-p)
        return pi + jnp.sum(neg, axis=1) + Xb @ (theta - neg).T

    def transform(self, frame: Frame) -> Frame:
        p = self._params
        X = jnp.asarray(frame._column_values(p.get("features_col",
                                                   "features")),
                        float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        raw = self._raw(X)
        prob = jax.nn.softmax(raw, axis=1)
        pred = jnp.argmax(raw, axis=1).astype(float_dtype())
        out = frame.with_column(p.get("raw_prediction_col", "rawPrediction"),
                                raw)
        out = out.with_column(p.get("probability_col", "probability"), prob)
        return out.with_column(p.get("prediction_col", "prediction"), pred)

    def predict(self, features) -> float:
        x = jnp.asarray(np.asarray(features,
                                   np.dtype(float_dtype())).reshape(1, -1))
        return float(host_fetch(jnp.argmax(self._raw(x), axis=1))[0])


# ---------------------------------------------------------------------------
# OneVsRest (MLlib org.apache.spark.ml.classification.OneVsRest)
# ---------------------------------------------------------------------------

@persistable
class OneVsRest(Estimator):
    """MLlib ``OneVsRest``: reduce multiclass to k independent binary fits
    of any binary classifier estimator. The k fits are embarrassingly
    parallel and share the feature matrix already resident in HBM."""

    def __init__(self, classifier=None, features_col: str = "features",
                 label_col: str = "label",
                 prediction_col: str = "prediction"):
        self.classifier = classifier
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col

    def set_classifier(self, v):
        self.classifier = v
        return self

    setClassifier = set_classifier

    # composite persistence: the inner classifier is itself a stage
    def _save_to_dir(self, path: str) -> None:
        from .base import save_stage

        write_json(os.path.join(path, "metadata.json"),
                   {"class": "OneVsRest",
                    "features_col": self.features_col,
                    "label_col": self.label_col,
                    "prediction_col": self.prediction_col,
                    "has_classifier": self.classifier is not None})
        if self.classifier is not None:
            save_stage(self.classifier, os.path.join(path, "classifier"))

    @classmethod
    def _load_from_dir(cls, path: str, meta: dict) -> "OneVsRest":
        from .base import load_stage

        clf = load_stage(os.path.join(path, "classifier")) \
            if meta.get("has_classifier") else None
        return cls(clf, meta["features_col"], meta["label_col"],
                   meta["prediction_col"])

    def fit(self, frame: Frame, mesh=None) -> "OneVsRestModel":
        if self.classifier is None:
            raise ValueError("OneVsRest: classifier not set")
        import copy
        import inspect

        y = np.asarray(frame._column_values(self.label_col), np.float64)
        mask = np.asarray(frame.mask)
        yv = y[mask]
        if len(yv) == 0:
            raise ValueError("OneVsRest: no valid rows")
        if np.any(yv < 0) or np.any(yv != np.floor(yv)):
            raise ValueError("labels must be nonnegative integers 0..k-1")
        k = int(yv.max()) + 1
        models = []
        for c in range(k):
            binary = frame.with_column(
                self.label_col,
                jnp.asarray((y == c).astype(np.dtype(float_dtype()))))
            est = copy.deepcopy(self.classifier)
            if hasattr(est, "set_features_col"):
                est.set_features_col(self.features_col)
            if hasattr(est, "set_label_col"):
                est.set_label_col(self.label_col)
            # pass mesh only to estimators whose fit accepts it (a bare
            # try/except would swallow TypeErrors raised inside fit)
            if "mesh" in inspect.signature(est.fit).parameters:
                models.append(est.fit(binary, mesh=mesh))
            else:
                models.append(est.fit(binary))
        return OneVsRestModel(models, self.features_col,
                              self.prediction_col)


@persistable
class OneVsRestModel(Model):
    """k fitted binary models; prediction = argmax of their scores (the
    probability-of-positive column when available, else rawPrediction)."""

    def __init__(self, models, features_col="features",
                 prediction_col="prediction"):
        self.models = list(models)
        self.features_col = features_col
        self.prediction_col = prediction_col

    @property
    def num_classes(self):
        return len(self.models)

    numClasses = num_classes

    def _scores(self, frame: Frame):
        cols = []
        for m in self.models:
            out = m.transform(frame)
            p = getattr(m, "_params", {})
            prob_col = p.get("probability_col", "probability")
            raw_col = p.get("raw_prediction_col", "rawPrediction")
            name = prob_col if prob_col in out.columns else raw_col
            v = jnp.asarray(out._column_values(name))
            if v.ndim == 2:   # [P(neg), P(pos)] or [-margin, margin]
                v = v[:, -1]
            cols.append(v)
        return jnp.stack(cols, axis=1)

    def transform(self, frame: Frame) -> Frame:
        scores = self._scores(frame)
        pred = jnp.argmax(scores, axis=1).astype(float_dtype())
        return frame.with_column(self.prediction_col, pred)

    def _save_to_dir(self, path: str) -> None:
        import os

        from .base import save_stage, write_json

        write_json(os.path.join(path, "metadata.json"),
                   {"class": "OneVsRestModel",
                    "n": len(self.models),
                    "features_col": self.features_col,
                    "prediction_col": self.prediction_col})
        for i, m in enumerate(self.models):
            save_stage(m, os.path.join(path, f"model_{i}"))

    @classmethod
    def _load_from_dir(cls, path: str, meta: dict) -> "OneVsRestModel":
        import os

        from .base import load_stage

        models = [load_stage(os.path.join(path, f"model_{i}"))
                  for i in range(meta["n"])]
        return cls(models, meta["features_col"], meta["prediction_col"])
