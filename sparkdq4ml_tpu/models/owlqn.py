"""Orthant-Wise Limited-memory Quasi-Newton (OWL-QN) — MLlib's actual L1
solver (breeze OWLQN behind ``LinearRegression.fit``, SURVEY.md §3.3 step 2),
reimplemented on sufficient statistics inside ``lax.scan``.

The smooth part is the standardized quadratic ``f(w) = ½wᵀGw − bᵀw (+ ridge)``
from :mod:`.solvers`, so gradients are matvecs on the replicated ``(d,d)``
statistics — no data passes, no host syncs. L-BFGS two-loop recursion uses a
fixed-size rolling history (static shapes); the orthant machinery is:

* pseudo-gradient: subgradient choice that is steepest among valid ones,
* direction projection: zero components whose sign disagrees with the
  steepest-descent direction,
* orthant projection in the line search: iterates may not cross their orthant.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .solvers import FitResult, Moments, _penalty_weights, unpack_moments

_HISTORY = 10          # L-BFGS memory (breeze default for OWLQN is 10)
_LS_STEPS = 20         # max backtracking halvings per iteration


def _pseudo_gradient(w, g, lam1):
    """Steepest valid subgradient of f + λ1‖w‖₁."""
    at_zero = w == 0.0
    pg_nonzero = g + lam1 * jnp.sign(w)
    down = g + lam1   # right derivative at 0
    up = g - lam1     # left derivative at 0
    pg_zero = jnp.where(down < 0.0, down, jnp.where(up > 0.0, up, 0.0))
    return jnp.where(at_zero, pg_zero, pg_nonzero)


@functools.partial(jax.jit, static_argnames=("max_iter", "fit_intercept",
                                             "standardization"))
def owlqn_solve(A: jnp.ndarray, reg_param, elastic_net_param,
                max_iter: int = 100, tol: float = 1e-6,
                fit_intercept: bool = True,
                standardization: bool = True) -> FitResult:
    m = unpack_moments(A, fit_intercept=fit_intercept)
    dt = A.dtype
    d = m.b.shape[0]
    eff = jnp.asarray(reg_param, dt) / jnp.where(m.std_y > 0, m.std_y, 1.0)
    alpha = jnp.asarray(elastic_net_param, dt)
    u1, u2 = _penalty_weights(m, standardization)
    lam1 = alpha * eff * u1
    lam2 = (1.0 - alpha) * eff * u2

    def smooth_grad(w):
        return m.G @ w - m.b + lam2 * w

    def objective(w):
        f = 0.5 * (m.yy - 2.0 * jnp.dot(m.b, w) + w @ m.G @ w)
        return f + jnp.sum(lam1 * jnp.abs(w)) + 0.5 * jnp.sum(lam2 * w * w)

    def two_loop(pg, S, Y, rho, k):
        """L-BFGS two-loop on the rolling (S, Y) history.

        Logical pair j lives in slot j % _HISTORY; the live pairs are
        j = k−1 … max(0, k−_HISTORY). The backward pass must visit them
        newest→oldest and the forward pass oldest→newest, so slot order is
        computed from k (a plain 9..0 sweep would interleave stale and fresh
        pairs once the buffer wraps past k = _HISTORY).
        """
        order = jnp.arange(_HISTORY)                    # 0 = newest
        slots = (k - 1 - order) % _HISTORY              # newest→oldest slots
        valid = order < jnp.minimum(k, _HISTORY)

        def bwd(carry, t):
            q, alphas = carry
            i, slot = t
            a = jnp.where(valid[i], rho[slot] * jnp.dot(S[slot], q), 0.0)
            q = q - a * Y[slot]
            return (q, alphas.at[i].set(a)), None

        (q, alphas), _ = jax.lax.scan(
            bwd, (pg, jnp.zeros((_HISTORY,), dt)), (order, slots))
        # Initial Hessian scaling γ = sᵀy/yᵀy of the newest pair
        newest = (k - 1) % _HISTORY
        sy = jnp.dot(S[newest], Y[newest])
        yy_ = jnp.dot(Y[newest], Y[newest])
        gamma = jnp.where((k > 0) & (yy_ > 0), sy / jnp.maximum(yy_, 1e-30), 1.0)
        r = gamma * q

        def fwd(r, t):
            i, slot = t
            beta = jnp.where(valid[i], rho[slot] * jnp.dot(Y[slot], r), 0.0)
            r = r + jnp.where(valid[i], 1.0, 0.0) * (alphas[i] - beta) * S[slot]
            return r, None

        r, _ = jax.lax.scan(fwd, r, (order[::-1], slots[::-1]))
        return r

    def body(state, _):
        w, g, fval, S, Y, rho, k, done, iters = state
        pg = _pseudo_gradient(w, g, lam1)
        direction = -two_loop(pg, S, Y, rho, k)
        # Project: direction must agree with −pg componentwise
        direction = jnp.where(direction * pg < 0.0, direction, 0.0)
        # Orthant for the line search: sign(w), or sign(−pg) where w == 0
        xi = jnp.where(w != 0.0, jnp.sign(w), jnp.sign(-pg))
        deriv = jnp.dot(pg, direction)

        def ls_body(carry, _):
            step, best_w, best_f, found = carry
            cand = w + step * direction
            cand = jnp.where(cand * xi < 0.0, 0.0, cand)  # orthant projection
            fc = objective(cand)
            ok = jnp.logical_and(jnp.logical_not(found),
                                 fc <= fval + 1e-4 * step * deriv)
            best_w = jnp.where(ok, cand, best_w)
            best_f = jnp.where(ok, fc, best_f)
            found = jnp.logical_or(found, ok)
            return (step * 0.5, best_w, best_f, found), None

        init_step = jnp.where(k == 0, 1.0 / jnp.maximum(
            jnp.linalg.norm(direction), 1e-12), 1.0).astype(dt)
        (_, w_new, f_new, found), _ = jax.lax.scan(
            ls_body, (init_step, w, fval, jnp.asarray(False)), None,
            length=_LS_STEPS)

        g_new = smooth_grad(w_new)
        s = w_new - w
        yv = g_new - g
        sy = jnp.dot(s, yv)
        slot = k % _HISTORY
        keep = jnp.logical_and(found, sy > 1e-30)
        S2 = jnp.where(keep, S.at[slot].set(s), S)
        Y2 = jnp.where(keep, Y.at[slot].set(yv), Y)
        rho2 = jnp.where(keep, rho.at[slot].set(1.0 / jnp.maximum(sy, 1e-30)), rho)
        k2 = k + jnp.where(keep, 1, 0)

        rel = jnp.abs(f_new - fval) / jnp.maximum(jnp.abs(fval), 1e-12)
        now_done = jnp.logical_or(done,
                                  jnp.logical_or(rel < tol,
                                                 jnp.logical_not(found)))
        w_out = jnp.where(done, w, w_new)
        g_out = jnp.where(done, g, g_new)
        f_out = jnp.where(done, fval, f_new)
        iters_out = iters + jnp.where(done, 0, 1).astype(jnp.int32)
        return (w_out, g_out, f_out,
                jnp.where(done, S, S2), jnp.where(done, Y, Y2),
                jnp.where(done, rho, rho2), jnp.where(done, k, k2),
                now_done, iters_out), f_out

    w0 = jnp.zeros((d,), dt)
    f0 = objective(w0)
    init = (w0, smooth_grad(w0), f0,
            jnp.zeros((_HISTORY, d), dt), jnp.zeros((_HISTORY, d), dt),
            jnp.zeros((_HISTORY,), dt), jnp.asarray(0, jnp.int32),
            jnp.asarray(False), jnp.asarray(0, jnp.int32))
    (w, _, _, _, _, _, _, done, iters), history = jax.lax.scan(
        body, init, None, length=max_iter)

    w = jnp.where(m.valid, w, 0.0)
    sx = jnp.where(m.valid, m.std_x, 1.0)
    sy_ = jnp.where(m.std_y > 0, m.std_y, 1.0)
    coef = jnp.where(m.valid, w * sy_ / sx, 0.0)
    intercept = (m.mean_y - jnp.dot(coef, m.mean_x)) if fit_intercept \
        else jnp.asarray(0.0, dt)
    history = jnp.concatenate([f0[None], history])
    return FitResult(coef, intercept, iters, history, done)
