"""ALS collaborative filtering (MLlib ``org.apache.spark.ml.recommendation``
— shipped by the reference's mllib dependency, pom.xml:29-32).

TPU-first design — not a port of Spark's block-partitioned ALS:

* Each half-step solves every user's (or item's) k×k ridge system AT ONCE:
  the per-user normal matrices ``Σ v_i v_iᵀ`` are one ``segment_sum`` over
  the ratings' factor outer products, and the solves are one *batched*
  ``jnp.linalg.solve`` over all users — XLA turns both into large fused
  batch ops. Spark instead shuffles factor blocks between executors per
  step; here the whole alternation loop is a single jitted ``lax.scan``
  with zero host round-trips.
* Regularization follows Spark's ALS-WR convention: λ scaled by each
  user's/item's rating count (``regParam * n_u``).
* ``recommend_for_all_users`` is one ``U @ Vᵀ`` MXU matmul + ``top_k``.

Implicit feedback (``implicit_prefs=True``) follows Hu–Koren–Volinsky:
preference ``p = [r > 0]``, confidence ``c = 1 + α·|r|``. The TPU trick is
the same one the paper exploits: ``YᵀY`` over ALL items is one (k×k) matmul
shared by every user, and only the sparse correction
``Σ (c−1)·y yᵀ`` runs through a ``segment_sum`` — so the half-step stays
two segment_sums + one batched solve, independent of the dense n_users×n_items
preference matrix that is never materialized.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype
from ..frame import Frame
from .base import Estimator, Model, persistable
from ..parallel.mesh import serialize_collectives


def _als_half_step(factors_other, idx_self, idx_other, ratings, n_self,
                   rank, reg, w=None, psum_axis=None):
    """Solve all of one side's factors given the other side's.

    For every entity e on the solving side:
        (Σ_{r∈R(e)} v_r v_rᵀ + λ·n_e·I) x_e = Σ_{r∈R(e)} rating_r · v_r
    computed as two segment_sums + one batched solve.

    ``w`` (nnz,) 0/1 weights let zero-padded rating slots drop out of every
    statistic; ``psum_axis`` reduces the local segment statistics over the
    mesh's data axis (the treeAggregate→psum contract of SURVEY.md §3.3)
    before the replicated batched solve — Spark's ALS instead shuffles
    factor blocks between executors per half-step.
    """
    V = factors_other[idx_other]                       # (nnz, k)
    ww = jnp.ones_like(ratings) if w is None else w
    outer = (V[:, :, None] * V[:, None, :]) * ww[:, None, None]
    A = jax.ops.segment_sum(outer, idx_self, num_segments=n_self)
    b = jax.ops.segment_sum(V * (ratings * ww)[:, None], idx_self,
                            num_segments=n_self)
    cnt = jax.ops.segment_sum(ww, idx_self, num_segments=n_self)
    if psum_axis is not None:
        A = jax.lax.psum(A, psum_axis)
        b = jax.lax.psum(b, psum_axis)
        cnt = jax.lax.psum(cnt, psum_axis)
    eye = jnp.eye(rank, dtype=V.dtype)
    # ALS-WR: λ scaled by the entity's rating count; entities with no
    # ratings get the identity system → zero factors
    lam = reg * jnp.maximum(cnt, 1.0)
    A = A + lam[:, None, None] * eye
    x = jnp.linalg.solve(A, b[:, :, None])[:, :, 0]
    return jnp.where(cnt[:, None] > 0, x, 0.0)


def _implicit_half_step(factors_other, idx_self, idx_other, ratings,
                        n_self, rank, reg, alpha, w=None, psum_axis=None):
    """HKV implicit half-step: for every entity e on the solving side

        (YᵀY + Σ_{r∈R(e)} (c_r − 1)·v_r v_rᵀ + λI) x_e
            = Σ_{r∈R(e)} c_r·p_r·v_r

    with ``c = 1 + α|r|`` and ``p = [r > 0]``. ``YᵀY`` is one dense (k, k)
    MXU matmul shared across entities; the corrections are segment_sums
    over the observed entries only.

    Under sharding, ``factors_other`` is replicated so ``YᵀY`` needs no
    collective — only the sparse corrections psum over ``psum_axis``.
    """
    V = factors_other[idx_other]                       # (nnz, k)
    YtY = factors_other.T @ factors_other              # (k, k), shared
    ww = jnp.ones_like(ratings) if w is None else w
    c1 = alpha * jnp.abs(ratings)                      # c − 1
    p = (ratings > 0).astype(V.dtype)
    outer = (V[:, :, None] * V[:, None, :]) * (c1 * ww)[:, None, None]
    A_extra = jax.ops.segment_sum(outer, idx_self, num_segments=n_self)
    b = jax.ops.segment_sum(V * ((1.0 + c1) * p * ww)[:, None], idx_self,
                            num_segments=n_self)
    cnt = jax.ops.segment_sum(ww, idx_self, num_segments=n_self)
    if psum_axis is not None:
        A_extra = jax.lax.psum(A_extra, psum_axis)
        b = jax.lax.psum(b, psum_axis)
        cnt = jax.lax.psum(cnt, psum_axis)
    eye = jnp.eye(rank, dtype=V.dtype)
    A = YtY[None, :, :] + A_extra + reg * eye
    x = jnp.linalg.solve(A, b[:, :, None])[:, :, 0]
    return jnp.where(cnt[:, None] > 0, x, 0.0)


def _psum_mean(num, den, psum_axis):
    if psum_axis is not None:
        num = jax.lax.psum(num, psum_axis)
        den = jax.lax.psum(den, psum_axis)
    return num / jnp.maximum(den, 1.0)


@functools.lru_cache(maxsize=None)
def _implicit_fit_fn(rank, max_iter, reg, alpha, n_users, n_items,
                     mesh=None):
    def core(u_idx, i_idx, ratings, w, U0, V0, psum_axis):
        p = (ratings > 0).astype(U0.dtype)
        c = 1.0 + alpha * jnp.abs(ratings)

        def body(carry, _):
            U, V = carry
            U = _implicit_half_step(V, u_idx, i_idx, ratings, n_users,
                                    rank, reg, alpha, w, psum_axis)
            V = _implicit_half_step(U, i_idx, u_idx, ratings, n_items,
                                    rank, reg, alpha, w, psum_axis)
            # confidence-weighted preference loss over observed entries
            # (the unobserved-zeros term is monitoring-only, not recomputed)
            pred = jnp.sum(U[u_idx] * V[i_idx], axis=1)
            loss = _psum_mean(jnp.sum(w * c * (p - pred) ** 2),
                              jnp.sum(w), psum_axis)
            return (U, V), loss

        (U, V), history = jax.lax.scan(body, (U0, V0), None, length=max_iter)
        return U, V, history

    return _jit_als_fit(core, mesh)


@functools.lru_cache(maxsize=None)
def _als_fit_fn(rank, max_iter, reg, n_users, n_items, mesh=None):
    def core(u_idx, i_idx, ratings, w, U0, V0, psum_axis):
        def body(carry, _):
            U, V = carry
            U = _als_half_step(V, u_idx, i_idx, ratings, n_users, rank,
                               reg, w, psum_axis)
            V = _als_half_step(U, i_idx, u_idx, ratings, n_items, rank,
                               reg, w, psum_axis)
            # loss (for the scan output): masked squared error
            pred = jnp.sum(U[u_idx] * V[i_idx], axis=1)
            mse = _psum_mean(jnp.sum(w * (ratings - pred) ** 2),
                             jnp.sum(w), psum_axis)
            return (U, V), mse
        (U, V), history = jax.lax.scan(body, (U0, V0), None, length=max_iter)
        return U, V, history

    return _jit_als_fit(core, mesh)


def _jit_als_fit(core, mesh):
    """Jit ``core`` either directly or as a shard_map over the ratings
    (nnz) axis: the factor matrices stay replicated, the per-entry
    statistics psum over ICI — the whole alternation loop remains one
    jitted scan with zero host round-trips, now per device."""
    if mesh is None:
        return jax.jit(lambda u, i, r, w, U0, V0: core(u, i, r, w, U0, V0,
                                                       None))

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    fn = shard_map(
        lambda u, i, r, w, U0, V0: core(u, i, r, w, U0, V0, DATA_AXIS),
        mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(), P()),
        out_specs=(P(), P(), P()))
    return serialize_collectives(jax.jit(fn), mesh)


@persistable
class ALS(Estimator):
    """MLlib ``ALS`` builder surface: setRank/setMaxIter/setRegParam/
    setUserCol/setItemCol/setRatingCol/setColdStartStrategy/setSeed."""

    _persist_attrs = ('rank', 'max_iter', 'reg_param', 'user_col',
                      'item_col', 'rating_col', 'prediction_col',
                      'cold_start_strategy', 'implicit_prefs', 'alpha',
                      'seed')

    def __init__(self, rank: int = 10, max_iter: int = 10,
                 reg_param: float = 0.1, user_col: str = "user",
                 item_col: str = "item", rating_col: str = "rating",
                 prediction_col: str = "prediction",
                 cold_start_strategy: str = "nan",
                 implicit_prefs: bool = False, alpha: float = 1.0,
                 seed: int = 0):
        if alpha < 0:
            raise ValueError("alpha must be >= 0")
        if rank < 1:
            raise ValueError("rank must be >= 1")
        if cold_start_strategy not in ("nan", "drop"):
            raise ValueError(f"cold_start_strategy={cold_start_strategy!r}")
        self.rank = int(rank)
        self.max_iter = int(max_iter)
        self.reg_param = float(reg_param)
        self.user_col = user_col
        self.item_col = item_col
        self.rating_col = rating_col
        self.prediction_col = prediction_col
        self.cold_start_strategy = cold_start_strategy
        self.implicit_prefs = bool(implicit_prefs)
        self.alpha = float(alpha)
        self.seed = int(seed)

    def set_implicit_prefs(self, v):
        self.implicit_prefs = bool(v)
        return self

    setImplicitPrefs = set_implicit_prefs

    def set_alpha(self, v):
        if v < 0:
            raise ValueError("alpha must be >= 0")
        self.alpha = float(v)
        return self

    setAlpha = set_alpha

    def set_rank(self, v):
        if v < 1:
            raise ValueError("rank must be >= 1")
        self.rank = int(v)
        return self

    setRank = set_rank

    def set_max_iter(self, v):
        self.max_iter = int(v)
        return self

    setMaxIter = set_max_iter

    def set_reg_param(self, v):
        self.reg_param = float(v)
        return self

    setRegParam = set_reg_param

    def set_user_col(self, v):
        self.user_col = v
        return self

    setUserCol = set_user_col

    def set_item_col(self, v):
        self.item_col = v
        return self

    setItemCol = set_item_col

    def set_rating_col(self, v):
        self.rating_col = v
        return self

    setRatingCol = set_rating_col

    def set_cold_start_strategy(self, v):
        if v not in ("nan", "drop"):
            raise ValueError(f"cold_start_strategy={v!r}")
        self.cold_start_strategy = v
        return self

    setColdStartStrategy = set_cold_start_strategy

    def set_seed(self, v):
        self.seed = int(v)
        return self

    setSeed = set_seed

    def fit(self, frame: Frame, mesh=None) -> "ALSModel":
        from ..parallel.mesh import normalize_mesh

        dt = np.dtype(float_dtype())
        mask = np.asarray(frame.mask)
        if mask.sum() == 0:
            raise ValueError("ALS: no valid rows")
        mesh = normalize_mesh(mesh)
        users = np.asarray(frame._column_values(self.user_col))[mask]
        items = np.asarray(frame._column_values(self.item_col))[mask]
        ratings = np.asarray(frame._column_values(self.rating_col),
                             dt)[mask]
        if not np.all(np.isfinite(ratings)):
            raise ValueError("ALS: rating column has NaN/inf in valid rows")

        # dense id maps (hosts the analogue of Spark's in/out block mapping)
        u_ids, u_idx = np.unique(np.asarray(users, np.int64),
                                 return_inverse=True)
        i_ids, i_idx = np.unique(np.asarray(items, np.int64),
                                 return_inverse=True)
        n_users, n_items = len(u_ids), len(i_ids)

        rng = np.random.default_rng(self.seed)
        # Spark seeds factors with scaled |N(0,1)|; plain N(0,1)/sqrt(k)
        # reaches the same optimum on this convex-per-block problem
        U0 = (rng.normal(size=(n_users, self.rank)) / np.sqrt(self.rank)) \
            .astype(dt)
        V0 = (rng.normal(size=(n_items, self.rank)) / np.sqrt(self.rank)) \
            .astype(dt)

        if self.implicit_prefs:
            fit_fn = _implicit_fit_fn(self.rank, self.max_iter,
                                      self.reg_param, self.alpha,
                                      n_users, n_items, mesh)
        else:
            fit_fn = _als_fit_fn(self.rank, self.max_iter, self.reg_param,
                                 n_users, n_items, mesh)

        # shard the ratings (nnz) axis; zero-weight pad slots never vote
        from ..parallel.distributed import pad_and_shard_rows

        args = pad_and_shard_rows(mesh, np.asarray(u_idx, np.int32),
                                  np.asarray(i_idx, np.int32), ratings,
                                  np.ones_like(ratings))
        if mesh is None:
            factors = (jnp.asarray(U0), jnp.asarray(V0))
        else:
            from ..parallel.mesh import replicated_sharding

            rep = replicated_sharding(mesh)
            factors = (jax.device_put(U0, rep), jax.device_put(V0, rep))
        U, V, history = jax.block_until_ready(fit_fn(*args, *factors))
        # dqlint: ok(host-sync): id vocabularies are host numpy
        # (np.unique over the input ids), not device arrays
        return ALSModel(np.asarray(U), np.asarray(V), u_ids.tolist(),
                        i_ids.tolist(), self._params_dict(),
                        np.asarray(history, np.float64).tolist())

    def _params_dict(self):
        return {k: getattr(self, k) for k in self._persist_attrs}


@persistable
class ALSModel(Model):
    """User/item factor matrices + the MLlib surface: ``transform`` (rating
    prediction per (user, item) row), ``recommendForAllUsers/Items`` (one
    MXU matmul + top_k), ``userFactors``/``itemFactors`` frames."""

    _persist_attrs = ('user_factors_arr', 'item_factors_arr', 'user_ids',
                      'item_ids', '_params', 'loss_history')

    def __init__(self, user_factors, item_factors, user_ids, item_ids,
                 params=None, loss_history=None):
        self.user_factors_arr = np.asarray(user_factors)
        self.item_factors_arr = np.asarray(item_factors)
        self.user_ids = list(user_ids)
        self.item_ids = list(item_ids)
        self._params = dict(params or {})
        self.loss_history = list(loss_history or [])
        self._build_index()

    def _post_load(self):
        self.user_ids = list(self.user_ids)
        self.item_ids = list(self.item_ids)
        self._build_index()

    def _build_index(self):
        self._u_map = {int(u): i for i, u in enumerate(self.user_ids)}
        self._i_map = {int(v): i for i, v in enumerate(self.item_ids)}

    @property
    def rank(self):
        return int(self.user_factors_arr.shape[1])

    def _p(self, key, default=None):
        return self._params.get(key, default)

    @property
    def user_factors(self) -> Frame:
        return Frame({"id": np.asarray(self.user_ids, np.int64),
                      "features": jnp.asarray(self.user_factors_arr,
                                              float_dtype())})

    userFactors = user_factors

    @property
    def item_factors(self) -> Frame:
        return Frame({"id": np.asarray(self.item_ids, np.int64),
                      "features": jnp.asarray(self.item_factors_arr,
                                              float_dtype())})

    itemFactors = item_factors

    def transform(self, frame: Frame) -> Frame:
        users = np.asarray(frame._column_values(self._p("user_col", "user")),
                           np.int64)
        items = np.asarray(frame._column_values(self._p("item_col", "item")),
                           np.int64)
        u_pos = np.asarray([self._u_map.get(int(u), -1) for u in users])
        i_pos = np.asarray([self._i_map.get(int(v), -1) for v in items])
        known = (u_pos >= 0) & (i_pos >= 0)
        U = jnp.asarray(self.user_factors_arr, float_dtype())
        V = jnp.asarray(self.item_factors_arr, float_dtype())
        pred = jnp.sum(U[jnp.asarray(np.where(known, u_pos, 0))] *
                       V[jnp.asarray(np.where(known, i_pos, 0))], axis=1)
        pred = jnp.where(jnp.asarray(known), pred,
                         jnp.asarray(np.nan, pred.dtype))
        out = frame.with_column(self._p("prediction_col", "prediction"),
                                pred)
        if self._p("cold_start_strategy", "nan") == "drop":
            out = out.filter(jnp.asarray(known))
        return out

    def predict(self, user: int, item: int) -> float:
        u = self._u_map.get(int(user))
        v = self._i_map.get(int(item))
        if u is None or v is None:
            return float("nan")
        return float(self.user_factors_arr[u] @ self.item_factors_arr[v])

    def _recommend(self, F_for, F_items, ids_for, ids_items, num: int,
                   col_for: str, col_items: str) -> Frame:
        scores = jnp.asarray(F_for, float_dtype()) @ \
            jnp.asarray(F_items, float_dtype()).T
        k = min(num, scores.shape[1])
        top_scores, top_idx = jax.lax.top_k(scores, k)    # (n, k)
        top_idx = np.asarray(top_idx)
        top_scores = np.asarray(top_scores)
        ids_items_arr = np.asarray(ids_items, np.int64)
        recs = np.empty(len(ids_for), dtype=object)
        for i in range(len(ids_for)):
            recs[i] = [(int(ids_items_arr[j]), float(s))
                       for j, s in zip(top_idx[i], top_scores[i])]
        return Frame({col_for: np.asarray(ids_for, np.int64),
                      "recommendations": recs})

    def recommend_for_all_users(self, num_items: int) -> Frame:
        """Top ``num_items`` items per user — one U @ Vᵀ matmul + top_k."""
        return self._recommend(self.user_factors_arr, self.item_factors_arr,
                               self.user_ids, self.item_ids, num_items,
                               self._p("user_col", "user"), "item")

    recommendForAllUsers = recommend_for_all_users

    def recommend_for_all_items(self, num_users: int) -> Frame:
        return self._recommend(self.item_factors_arr, self.user_factors_arr,
                               self.item_ids, self.user_ids, num_users,
                               self._p("item_col", "item"), "user")

    recommendForAllItems = recommend_for_all_items
