"""GeneralizedLinearRegression: MLlib's IRLS GLM
(org.apache.spark.ml.regression.GeneralizedLinearRegression — shipped by the
reference's mllib dependency, pom.xml:29-32; the reference app itself fits
plain LinearRegression, `DataQuality4MachineLearningApp.java:120-126`).

Families × links (Spark's support table): gaussian (identity, log, inverse),
binomial (logit, probit, cloglog), poisson (log, identity, sqrt), gamma
(inverse, identity, log). Optional L2 ``reg_param`` and a ``weight_col``.

TPU-first: each IRLS iteration is a weighted-least-squares solve whose
normal matrix ``XᵀWX`` and moment ``XᵀWz`` are ONE fused masked matmul over
the row-sharded data (psum over ICI under a mesh — the per-iteration
``treeAggregate`` of Spark's IRLS, SURVEY.md §3.3) followed by a tiny
(d+1)² host-free ``linalg.solve``. The entire iteration loop runs inside a
single ``jit``'d ``lax.while_loop`` — zero host round-trips, vs. Spark's
two RPC barriers per IRLS step.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.stats import norm as _jnorm
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import float_dtype
from ..frame import Frame
from ..parallel.mesh import DATA_AXIS, serialize_collectives, shard_map
from .base import Estimator, Model, host_fetch, persistable

_FAMILY_LINKS = {
    "gaussian": ("identity", "log", "inverse"),
    "binomial": ("logit", "probit", "cloglog"),
    "poisson": ("log", "identity", "sqrt"),
    "gamma": ("inverse", "identity", "log"),
    # tweedie accepts any power link; validated separately
    "tweedie": (),
}
_DEFAULT_LINK = {"gaussian": "identity", "binomial": "logit",
                 "poisson": "log", "gamma": "inverse"}
_EPS = 1e-12


def _power_link(lp: float):
    """Tweedie power link g(μ)=μ^lp (lp=0 ⇒ log), MLlib's ``linkPower``.

    lp 1 / −1 reduce to identity / inverse exactly; other powers clamp to
    the positive domain (fractional powers of a negative η are undefined).
    """
    if lp == 0.0:
        return (lambda mu: jnp.log(jnp.maximum(mu, _EPS)), jnp.exp,
                lambda eta: jnp.exp(eta))
    if lp == 1.0:
        return (lambda mu: mu, lambda eta: eta,
                lambda eta: jnp.ones_like(eta))
    if lp == -1.0:
        return (lambda mu: 1.0 / mu, lambda eta: 1.0 / eta,
                lambda eta: -1.0 / (eta * eta))
    inv_p = 1.0 / lp
    # Fractional powers need a positive η domain. The floor must be far
    # above denormal range: η clamped to 1e-12 with inv_p = −2 would give
    # μ = 1e24 and IRLS weights ~ η⁻³ = 1e36, overflowing float32 matmuls
    # to inf → NaN solves. 1e-3 keeps every derived quantity f32-finite
    # while being far below any realistic linear-predictor magnitude.
    floor = 1e-3
    return (lambda mu: jnp.maximum(mu, _EPS) ** lp,
            lambda eta: jnp.maximum(eta, floor) ** inv_p,
            lambda eta: inv_p * jnp.maximum(eta, floor) ** (inv_p - 1.0))


# -- link functions: eta = g(mu); inv: mu = g⁻¹(eta); deriv: dmu/deta --------

def _link_fns(link: str):
    if link == "identity":
        return (lambda mu: mu, lambda eta: eta,
                lambda eta: jnp.ones_like(eta))
    if link == "log":
        return (lambda mu: jnp.log(jnp.maximum(mu, _EPS)), jnp.exp,
                lambda eta: jnp.exp(eta))
    if link == "logit":
        inv = jax.nn.sigmoid
        return (lambda mu: jnp.log(mu / (1.0 - mu)), inv,
                lambda eta: inv(eta) * (1.0 - inv(eta)))
    if link == "inverse":
        return (lambda mu: 1.0 / mu, lambda eta: 1.0 / eta,
                lambda eta: -1.0 / (eta * eta))
    if link == "sqrt":
        return (jnp.sqrt, lambda eta: eta * eta, lambda eta: 2.0 * eta)
    if link == "probit":
        return (_jnorm.ppf, _jnorm.cdf, _jnorm.pdf)
    if link == "cloglog":
        return (lambda mu: jnp.log(-jnp.log1p(-mu)),
                lambda eta: -jnp.expm1(-jnp.exp(eta)),
                lambda eta: jnp.exp(eta - jnp.exp(eta)))
    if link.startswith("power(") and link.endswith(")"):
        return _power_link(float(link[6:-1]))
    raise ValueError(f"unknown link {link!r}")


def _tweedie_power(family: str):
    """``"tweedie:<p>"`` → p, else None (the string keeps family usable as
    an lru_cache key for the jitted fit builders)."""
    if family.startswith("tweedie:"):
        return float(family.split(":", 1)[1])
    return None


def _variance_fn(family: str):
    p = _tweedie_power(family)
    if p is not None:
        if p == 0.0:
            return lambda mu: jnp.ones_like(mu)
        return lambda mu: jnp.maximum(mu, _EPS) ** p
    return {"gaussian": lambda mu: jnp.ones_like(mu),
            "binomial": lambda mu: mu * (1.0 - mu),
            "poisson": lambda mu: mu,
            "gamma": lambda mu: mu * mu}[family]


def _clip_mu(family: str, mu):
    if family == "binomial":
        return jnp.clip(mu, _EPS, 1.0 - _EPS)
    if family in ("poisson", "gamma"):
        return jnp.maximum(mu, _EPS)
    p = _tweedie_power(family)
    if p is not None and p != 0.0:
        # two-sided: the upper cap keeps μ^p and the IRLS weights finite in
        # float32 when the power link wanders toward its domain boundary
        return jnp.clip(mu, _EPS, 1e8)
    return mu


def _unit_deviance(family: str, y, mu):
    """Elementwise per-row deviance contribution (before weighting)."""
    p = _tweedie_power(family)
    if p is not None:
        if p == 0.0:
            family = "gaussian"
        elif p == 1.0:
            family = "poisson"
        elif p == 2.0:
            family = "gamma"
        else:
            # general Tweedie deviance (p ≠ 1, 2); y = 0 is fine for
            # 1 < p < 2 (both y-terms vanish)
            yp = jnp.maximum(y, 0.0)
            t1 = jnp.where(yp > 0,
                           yp ** (2.0 - p) / ((1.0 - p) * (2.0 - p)), 0.0)
            t2 = y * mu ** (1.0 - p) / (1.0 - p)
            t3 = mu ** (2.0 - p) / (2.0 - p)
            return 2.0 * (t1 - t2 + t3)
    if family == "gaussian":
        return (y - mu) ** 2
    if family == "binomial":
        yl = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, _EPS) / mu), 0.0)
        ol = jnp.where(y < 1, (1 - y) * jnp.log(
            jnp.maximum(1 - y, _EPS) / (1 - mu)), 0.0)
        return 2.0 * (yl + ol)
    if family == "poisson":
        t = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, _EPS) / mu), 0.0)
        return 2.0 * (t - (y - mu))
    # gamma
    r = jnp.maximum(y, _EPS) / mu
    return 2.0 * (-jnp.log(r) + (y - mu) / mu)


def _deviance(family: str, y, mu, w):
    """Per-family deviance, weight-summed (Spark/R convention)."""
    return jnp.sum(w * _unit_deviance(family, y, mu))


class GlmFit(NamedTuple):
    beta: jnp.ndarray          # (d+1,) — [coefficients..., intercept slot]
    iterations: jnp.ndarray
    converged: jnp.ndarray
    deviance: jnp.ndarray
    xtwx: jnp.ndarray          # final weighted normal matrix (for std errors)


def _build_fit(mesh, family: str, link: str, max_iter: int, tol: float,
               reg_param: float, fit_intercept: bool):
    link_f, link_inv, dmu_deta = _link_fns(link)
    var_f = _variance_fn(family)

    def wls_stats(X1, y, w, off, beta):
        # w == 0 marks masked rows and shard padding; their y may be NaN and
        # their eta may push the inverse link to ±inf, so every statistic is
        # sanitized through jnp.where (0 * NaN would poison the matmuls).
        # ``off`` is the fixed offset added to the linear predictor
        # (MLlib's offsetCol); the WLS regresses (z − off) on X.
        valid = w > 0
        eta = X1 @ beta + off
        mu = jnp.where(valid, _clip_mu(family, link_inv(eta)), 1.0)
        yv = jnp.where(valid, y, 1.0)   # yv == mu == 1 ⇒ zero unit deviance
        d = jnp.where(valid, dmu_deta(eta), 1.0)
        d = jnp.where(jnp.abs(d) < _EPS, jnp.sign(d) * _EPS + (d == 0) * _EPS,
                      d)
        z = jnp.where(valid, eta - off + (yv - mu) / d, 0.0)
        ww = jnp.where(valid, w * d * d / jnp.maximum(var_f(mu), _EPS), 0.0)
        Xw = X1 * ww[:, None]
        return X1.T @ Xw, Xw.T @ z, _deviance(family, yv, mu, w)

    if mesh is not None:
        def sharded_stats(X1, y, w, off, beta):
            a, b, dev = wls_stats(X1, y, w, off, beta)
            return (jax.lax.psum(a, DATA_AXIS), jax.lax.psum(b, DATA_AXIS),
                    jax.lax.psum(dev, DATA_AXIS))

        stats = shard_map(
            sharded_stats, mesh=mesh,
            in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                      P(DATA_AXIS), P()),
            out_specs=(P(), P(), P()))
    else:
        stats = wls_stats

    def fit(X1, y, w, off, beta0):
        p = X1.shape[1]
        ridge = jnp.eye(p, dtype=X1.dtype) * reg_param
        if fit_intercept:
            ridge = ridge.at[p - 1, p - 1].set(0.0)  # never penalize intercept

        def body(carry):
            beta, _, it, _, _ = carry
            xtwx, xtwz, dev = stats(X1, y, w, off, beta)
            new = jnp.linalg.solve(xtwx + ridge, xtwz)
            delta = jnp.max(jnp.abs(new - beta)) / \
                jnp.maximum(jnp.max(jnp.abs(new)), 1.0)
            return (new, dev, it + 1, delta, xtwx)

        def cond(carry):
            _, _, it, delta, _ = carry
            return jnp.logical_and(it < max_iter, delta > tol)

        init = (beta0, jnp.asarray(jnp.inf, X1.dtype),
                jnp.asarray(0, jnp.int32), jnp.asarray(jnp.inf, X1.dtype),
                jnp.zeros((p, p), X1.dtype))
        beta, _, iters, delta, _ = jax.lax.while_loop(cond, body, init)
        # final pass: deviance + XᵀWX at the converged beta
        xtwx, _, dev = stats(X1, y, w, off, beta)
        return GlmFit(beta, iters, delta <= tol, dev, xtwx)

    return serialize_collectives(jax.jit(fit), mesh)


@functools.lru_cache(maxsize=None)
def _fit_cached(mesh, family, link, max_iter, tol, reg_param, fit_intercept):
    return _build_fit(mesh, family, link, max_iter, tol, reg_param,
                      fit_intercept)


@persistable
class GeneralizedLinearRegression(Estimator):
    """MLlib ``GeneralizedLinearRegression`` builder surface:
    setFamily/setLink/setMaxIter/setTol/setRegParam/setFitIntercept/
    setWeightCol/setFeaturesCol/setLabelCol/setPredictionCol/
    setLinkPredictionCol + ``fit(frame[, mesh])``."""

    _persist_attrs = ('family', 'link', 'max_iter', 'tol', 'reg_param',
                      'fit_intercept', 'features_col', 'label_col',
                      'prediction_col', 'link_prediction_col', 'weight_col',
                      'offset_col', 'variance_power', 'link_power')

    def __init__(self, family: str = "gaussian", link: Optional[str] = None,
                 max_iter: int = 25, tol: float = 1e-6,
                 reg_param: float = 0.0, fit_intercept: bool = True,
                 features_col: str = "features", label_col: str = "label",
                 prediction_col: str = "prediction",
                 link_prediction_col: Optional[str] = None,
                 weight_col: Optional[str] = None,
                 offset_col: Optional[str] = None,
                 variance_power: float = 0.0,
                 link_power: Optional[float] = None):
        family = family.lower()
        if family not in _FAMILY_LINKS:
            raise ValueError(f"unknown family {family!r} "
                             f"(supported: {sorted(_FAMILY_LINKS)})")
        if family == "tweedie":
            # MLlib: the tweedie link is the power link, configured via
            # linkPower (default 1 − variancePower), never via ``link``
            if link is not None:
                raise ValueError("tweedie uses link_power, not link")
            if 0.0 < variance_power < 1.0:
                raise ValueError("variance_power must be 0 or >= 1 "
                                 "(no Tweedie distribution exists in (0,1))")
            if link_power is None:
                link_power = 1.0 - variance_power
            link = f"power({float(link_power)})"
        else:
            if link_power is not None:
                raise ValueError("link_power is only valid for the tweedie "
                                 "family")
            link = link.lower() if link else _DEFAULT_LINK[family]
            if link not in _FAMILY_LINKS[family]:
                raise ValueError(
                    f"link {link!r} not supported by family "
                    f"{family!r} (supported: {_FAMILY_LINKS[family]})")
        if reg_param < 0:
            raise ValueError("reg_param must be >= 0")
        self.family = family
        self.link = link
        self.max_iter = int(max_iter)
        self.tol = float(tol)
        self.reg_param = float(reg_param)
        self.fit_intercept = bool(fit_intercept)
        self.features_col = features_col
        self.label_col = label_col
        self.prediction_col = prediction_col
        self.link_prediction_col = link_prediction_col
        self.weight_col = weight_col
        self.offset_col = offset_col
        self.variance_power = float(variance_power)
        self.link_power = (None if link_power is None else float(link_power))

    def _family_key(self) -> str:
        """Family string for the jitted-fit cache (tweedie carries its
        variance power so the builder closes over it)."""
        if self.family == "tweedie":
            return f"tweedie:{self.variance_power}"
        return self.family

    def _set(self, name, v):
        setattr(self, name, v)
        return self

    def _reinit(self, family, link, variance_power=None, link_power=None):
        """Re-run __init__ to re-validate a family/link combination while
        preserving every other configured parameter."""
        if variance_power is None:
            variance_power = self.variance_power
        return GeneralizedLinearRegression.__init__(
            self, family, link, self.max_iter, self.tol, self.reg_param,
            self.fit_intercept, self.features_col, self.label_col,
            self.prediction_col, self.link_prediction_col, self.weight_col,
            self.offset_col, variance_power, link_power) or self

    def set_family(self, v):
        v = v.lower()
        if v == "tweedie":
            return self._reinit(v, None, link_power=self.link_power)
        return self._reinit(v, self.link if v == self.family else None)

    setFamily = set_family

    def set_link(self, v):
        return self._reinit(self.family, v)

    setLink = set_link

    def set_variance_power(self, v):
        return self._reinit("tweedie", None, variance_power=float(v),
                            link_power=self.link_power)

    setVariancePower = set_variance_power

    def set_link_power(self, v):
        return self._reinit("tweedie", None, link_power=float(v))

    setLinkPower = set_link_power

    def set_offset_col(self, v):
        return self._set("offset_col", v)

    setOffsetCol = set_offset_col

    def set_max_iter(self, v):
        return self._set("max_iter", int(v))

    setMaxIter = set_max_iter

    def set_tol(self, v):
        return self._set("tol", float(v))

    setTol = set_tol

    def set_reg_param(self, v):
        if v < 0:
            raise ValueError("reg_param must be >= 0")
        return self._set("reg_param", float(v))

    setRegParam = set_reg_param

    def set_fit_intercept(self, v):
        return self._set("fit_intercept", bool(v))

    setFitIntercept = set_fit_intercept

    def set_weight_col(self, v):
        return self._set("weight_col", v)

    setWeightCol = set_weight_col

    def set_features_col(self, v):
        return self._set("features_col", v)

    setFeaturesCol = set_features_col

    def set_label_col(self, v):
        return self._set("label_col", v)

    setLabelCol = set_label_col

    def set_link_prediction_col(self, v):
        return self._set("link_prediction_col", v)

    setLinkPredictionCol = set_link_prediction_col

    def _validate_y(self, y):
        if self.family == "binomial":
            if not np.all((y[~np.isnan(y)] >= 0) & (y[~np.isnan(y)] <= 1)):
                raise ValueError("binomial family requires labels in [0, 1]")
        elif self.family == "poisson":
            if not np.all(y[~np.isnan(y)] >= 0):
                raise ValueError("poisson family requires nonnegative labels")
        elif self.family == "gamma":
            if not np.all(y[~np.isnan(y)] > 0):
                raise ValueError("gamma family requires positive labels")
        elif self.family == "tweedie":
            p = self.variance_power
            if 1.0 <= p < 2.0:
                if not np.all(y[~np.isnan(y)] >= 0):
                    raise ValueError("tweedie with 1 <= variance_power < 2 "
                                     "requires nonnegative labels")
            elif p >= 2.0:
                if not np.all(y[~np.isnan(y)] > 0):
                    raise ValueError("tweedie with variance_power >= 2 "
                                     "requires positive labels")

    def fit(self, frame: Frame, mesh=None) -> "GeneralizedLinearRegressionModel":
        if mesh is None:
            from ..session import TpuSession

            active = TpuSession.active()
            mesh = active.mesh if active is not None else None
        if mesh is not None and mesh.devices.size <= 1:
            mesh = None

        dt = np.dtype(float_dtype())
        X = np.asarray(frame._column_values(self.features_col), dt)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(frame._column_values(self.label_col), dt)
        mask = np.asarray(frame.mask)
        if mask.sum() == 0:
            raise ValueError("GeneralizedLinearRegression: no valid rows")
        self._validate_y(y[mask])
        prior_w = np.ones_like(y)
        if self.weight_col is not None:
            prior_w = np.asarray(frame._column_values(self.weight_col), dt)
        w = np.where(mask, prior_w, 0.0).astype(dt)
        off = np.zeros_like(y)
        if self.offset_col is not None:
            off = np.where(mask, np.asarray(
                frame._column_values(self.offset_col), dt), 0.0).astype(dt)
        d = X.shape[1]

        # intercept carried as a final all-ones column (dropped when
        # fit_intercept=False by zero-weighting its ridge row is wrong —
        # instead simply omit the column)
        X1 = np.concatenate([X, np.ones((X.shape[0], 1), dt)], axis=1) \
            if self.fit_intercept else X
        p = X1.shape[1]

        # family-standard starting point: one IRLS step from mu0
        ym = y[mask]
        wm = w[mask]
        mu_bar = float(np.sum(ym * wm) / max(wm.sum(), 1e-12))
        beta0 = np.zeros((p,), dt)
        if self.fit_intercept:
            link_f, _, _ = _link_fns(self.link)
            positive = self.family in ("poisson", "gamma") or (
                self.family == "tweedie" and self.variance_power != 0.0)
            mu0 = {"binomial": min(max(mu_bar, 0.01), 0.99)}.get(
                self.family, max(mu_bar, 0.1) if positive else mu_bar)
            beta0[p - 1] = float(host_fetch(link_f(jnp.asarray(mu0, dt))))

        from ..parallel.distributed import pad_and_shard_rows

        X1d, yd, wd, offd = pad_and_shard_rows(mesh, X1, y, w, off)

        fit_fn = _fit_cached(mesh, self._family_key(), self.link,
                             self.max_iter, self.tol, self.reg_param,
                             self.fit_intercept)
        res = jax.block_until_ready(fit_fn(X1d, yd, wd, offd,
                                           jnp.asarray(beta0)))
        beta = np.asarray(res.beta, np.float64)
        coef = beta[:d] if self.fit_intercept else beta
        intercept = float(beta[d]) if self.fit_intercept else 0.0

        model = GeneralizedLinearRegressionModel(
            coefficients=coef.copy(), intercept=intercept,
            params=self._params_dict())
        model._fit_info = {
            "deviance": float(res.deviance),
            "iterations": int(res.iterations),
            "converged": bool(res.converged),
            "xtwx": np.asarray(res.xtwx, np.float64),
            "frame": frame,
        }
        return model

    def _params_dict(self):
        d = {k: getattr(self, k) for k in self._persist_attrs}
        # the model/summary helpers key deviance/variance/link math off the
        # params dict; the encoded family carries the tweedie power
        d["family"] = self._family_key()
        return d


@persistable
class GeneralizedLinearRegressionModel(Model):
    _persist_attrs = ('coefficients', 'intercept', '_params')
    _fit_info = None  # load_stage bypasses __init__; summary absent then

    def __init__(self, coefficients, intercept, params=None):
        self.coefficients = np.asarray(coefficients)
        self.intercept = float(intercept)
        self._params = dict(params or {})
        self._fit_info = None

    @property
    def num_features(self):
        return int(self.coefficients.shape[0])

    numFeatures = num_features

    def _p(self, key, default=None):
        return self._params.get(key, default)

    def _eta(self, X):
        return X @ jnp.asarray(self.coefficients, X.dtype) + self.intercept

    def transform(self, frame: Frame) -> Frame:
        X = jnp.asarray(frame._column_values(
            self._p("features_col", "features")), float_dtype())
        if X.ndim == 1:
            X = X[:, None]
        eta = self._eta(X)
        oc = self._p("offset_col")
        if oc:
            # missing offset column must fail loudly (predictions would
            # silently be off by the exposure factor) — MLlib does the same
            eta = eta + jnp.asarray(frame._column_values(oc), eta.dtype)
        _, link_inv, _ = _link_fns(self._p("link", "identity"))
        out = frame.with_column(self._p("prediction_col", "prediction"),
                                link_inv(eta))
        lp = self._p("link_prediction_col")
        if lp:
            out = out.with_column(lp, eta)
        return out

    def predict(self, features) -> float:
        x = jnp.asarray(np.asarray(features, np.dtype(float_dtype()))
                        .reshape(1, -1))
        _, link_inv, _ = _link_fns(self._p("link", "identity"))
        return float(np.asarray(link_inv(self._eta(x)))[0])

    @property
    def summary(self) -> "GlmTrainingSummary":
        if self._fit_info is None:
            raise ValueError("summary is only available on the model "
                             "returned by fit() (not after load())")
        return GlmTrainingSummary(self, self._fit_info)

    @property
    def has_summary(self):
        return self._fit_info is not None

    hasSummary = has_summary


class GlmTrainingSummary:
    """MLlib ``GeneralizedLinearRegressionTrainingSummary``: deviance, null
    deviance, dispersion, AIC, residuals, coefficient standard errors /
    t-values / p-values (Wald; normal for binomial+poisson, t for
    gaussian+gamma — Spark's convention)."""

    def __init__(self, model, info):
        self._m = model
        self._info = info
        self._frame = info["frame"]
        self._cache: dict = {}  # summary is immutable after fit; memoize
        # the data extraction and dispersion so chained properties
        # (p_values → t_values → std errors → dispersion) do one data pass

    @property
    def deviance(self) -> float:
        return self._info["deviance"]

    @property
    def num_iterations(self) -> int:
        return self._info["iterations"]

    numIterations = num_iterations

    @property
    def converged(self) -> bool:
        return self._info["converged"]

    def _xyw(self):
        if "xyw" in self._cache:
            return self._cache["xyw"]
        m = self._m
        dt = np.float64
        X = np.asarray(self._frame._column_values(
            m._p("features_col", "features")), dt)
        if X.ndim == 1:
            X = X[:, None]
        y = np.asarray(self._frame._column_values(
            m._p("label_col", "label")), dt)
        mask = np.asarray(self._frame.mask)
        w = np.ones_like(y)
        if m._p("weight_col"):
            w = np.asarray(self._frame._column_values(m._p("weight_col")), dt)
        self._cache["xyw"] = (X[mask], y[mask], w[mask])
        return self._cache["xyw"]

    def _offset(self):
        """Offset over the training rows (zeros unless offset_col set)."""
        if "offset" in self._cache:
            return self._cache["offset"]
        mask = np.asarray(self._frame.mask)
        oc = self._m._p("offset_col")
        if oc:
            off = np.asarray(self._frame._column_values(oc),
                             np.float64)[mask]
        else:
            off = np.zeros(int(mask.sum()), np.float64)
        self._cache["offset"] = off
        return off

    def _mu(self):
        """Fitted means over the training rows (memoized — always derived
        from the cached _xyw features, so the cache is safe by
        construction)."""
        if "mu" in self._cache:
            return self._cache["mu"]
        X, _, _ = self._xyw()
        _, link_inv, _ = _link_fns(self._m._p("link"))
        eta = X @ self._m.coefficients + self._m.intercept + self._offset()
        self._cache["mu"] = host_fetch(_clip_mu(self._m._p("family"),
                                                link_inv(jnp.asarray(eta))))
        return self._cache["mu"]

    @property
    def degrees_of_freedom(self) -> int:
        X, _, _ = self._xyw()
        p = self._m.num_features + (1 if self._m._p("fit_intercept", True)
                                    else 0)
        return int(len(X) - p)

    degreesOfFreedom = degrees_of_freedom

    @property
    def residual_degree_of_freedom_null(self) -> int:
        X, _, _ = self._xyw()
        return int(len(X) - (1 if self._m._p("fit_intercept", True) else 0))

    residualDegreeOfFreedomNull = residual_degree_of_freedom_null

    @property
    def dispersion(self) -> float:
        family = self._m._p("family")
        if family in ("binomial", "poisson"):
            return 1.0
        if "dispersion" in self._cache:
            return self._cache["dispersion"]
        X, y, w = self._xyw()
        mu = self._mu()
        var = host_fetch(_variance_fn(family)(jnp.asarray(mu)))
        pearson = np.sum(w * (y - mu) ** 2 / np.maximum(var, _EPS))
        self._cache["dispersion"] = float(
            pearson / max(self.degrees_of_freedom, 1))
        return self._cache["dispersion"]

    @property
    def null_deviance(self) -> float:
        X, y, w = self._xyw()
        family = self._m._p("family")
        link = self._m._p("link")
        off = self._offset()
        _, link_inv, _ = _link_fns(link)
        if np.any(off != 0.0):
            # with an offset the null model's linear predictor is
            # β₀ + offset_i (row-varying) — an intercept-only IRLS fit
            if self._m._p("fit_intercept", True):
                link_f, _, _ = _link_fns(link)
                mu_bar = float(np.sum(y * w) / max(w.sum(), _EPS))
                b0 = float(host_fetch(link_f(jnp.asarray(
                    _clip_mu(family, jnp.asarray(mu_bar, jnp.float64))))))
                fit_fn = _fit_cached(None, family, link, 50, 1e-10, 0.0,
                                     False)
                ones = jnp.ones((len(y), 1), jnp.float64)
                res = fit_fn(ones, jnp.asarray(y), jnp.asarray(w),
                             jnp.asarray(off), jnp.asarray([b0]))
                return float(res.deviance)
            mu0 = host_fetch(_clip_mu(family, link_inv(jnp.asarray(off))))
        elif self._m._p("fit_intercept", True):
            mu0 = np.full_like(y, np.sum(y * w) / w.sum())
        else:
            mu0 = np.full_like(y, float(host_fetch(link_inv(
                jnp.asarray(0.0, jnp.float64)))))
        mu0 = host_fetch(_clip_mu(family, jnp.asarray(mu0)))
        return float(host_fetch(_deviance(family, jnp.asarray(y),
                                          jnp.asarray(mu0),
                                          jnp.asarray(w))))

    nullDeviance = null_deviance

    def residuals(self, residuals_type: str = "deviance") -> Frame:
        """deviance | pearson | working | response residual column."""
        X, y, w = self._xyw()
        family = self._m._p("family")
        mu = self._mu()
        if residuals_type == "response":
            r = y - mu
        elif residuals_type == "pearson":
            var = host_fetch(_variance_fn(family)(jnp.asarray(mu)))
            r = (y - mu) * np.sqrt(w) / np.sqrt(np.maximum(var, _EPS))
        elif residuals_type == "working":
            _, _, dmu = _link_fns(self._m._p("link"))
            link_f, _, _ = _link_fns(self._m._p("link"))
            eta = host_fetch(link_f(jnp.asarray(mu)))
            d = host_fetch(dmu(jnp.asarray(eta)))
            r = (y - mu) / np.where(np.abs(d) < _EPS, _EPS, d)
        elif residuals_type == "deviance":
            unit = host_fetch(_unit_deviance(family, jnp.asarray(y),
                                             jnp.asarray(mu))) * w
            r = np.sign(y - mu) * np.sqrt(np.maximum(unit, 0.0))
        else:
            raise ValueError(f"unknown residuals type {residuals_type!r}")
        return Frame({f"{residuals_type}Residuals": r})

    @property
    def aic(self) -> float:
        X, y, w = self._xyw()
        family = self._m._p("family")
        if _tweedie_power(family) is not None:
            # the Tweedie log-likelihood has no closed form for general
            # variance powers; Spark likewise refuses AIC for tweedie
            raise ValueError("AIC is not supported for the tweedie family")
        mu = self._mu()
        n = len(y)
        p = self._m.num_features + (1 if self._m._p("fit_intercept", True)
                                    else 0)
        if family == "gaussian":
            rss = np.sum(w * (y - mu) ** 2)
            ll = -0.5 * n * (np.log(2 * np.pi * rss / n) + 1)
            return float(-2 * ll + 2 * (p + 1))   # +1 for the variance
        if family == "binomial":
            ll = np.sum(w * (y * np.log(mu) + (1 - y) * np.log(1 - mu)))
            return float(-2 * ll + 2 * p)
        if family == "poisson":
            from scipy.special import gammaln

            ll = np.sum(w * (y * np.log(np.maximum(mu, _EPS)) - mu
                             - gammaln(y + 1)))
            return float(-2 * ll + 2 * p)
        # gamma: profile the shape via the dispersion estimate
        from scipy.special import gammaln

        disp = max(self.dispersion, _EPS)
        a = 1.0 / disp
        ll = np.sum(w * (a * np.log(a * y / np.maximum(mu, _EPS))
                         - a * y / np.maximum(mu, _EPS)
                         - np.log(np.maximum(y, _EPS)) - gammaln(a)))
        return float(-2 * ll + 2 * (p + 1))

    @property
    def coefficient_standard_errors(self):
        if self._m._p("reg_param", 0.0) > 0:
            # The Wald covariance pinv(XtWX)·φ is only valid for the
            # unpenalized MLE; Spark likewise refuses these stats for
            # regularized fits.
            raise ValueError(
                "standard errors are not available for regularized fits "
                "(reg_param > 0); refit with reg_param=0 for Wald inference")
        cov = np.linalg.pinv(self._info["xtwx"]) * self.dispersion
        return np.sqrt(np.clip(np.diag(cov), 0.0, None))

    coefficientStandardErrors = coefficient_standard_errors

    @property
    def t_values(self):
        se = self.coefficient_standard_errors
        beta = np.r_[self._m.coefficients, self._m.intercept] \
            if self._m._p("fit_intercept", True) else self._m.coefficients
        return beta / np.where(se == 0, np.inf, se)

    tValues = t_values

    @property
    def p_values(self):
        from scipy import stats as sstats

        t = np.abs(self.t_values)
        if self._m._p("family") in ("binomial", "poisson"):
            return 2.0 * (1.0 - sstats.norm.cdf(t))
        return 2.0 * sstats.t.sf(t, max(self.degrees_of_freedom, 1))

    pValues = p_values
