"""Estimator/Transformer/Model/Pipeline base classes (the MLlib ``ml``
pipeline contracts that ``VectorAssembler`` and ``LinearRegression``
implement — `DataQuality4MachineLearningApp.java:110-126` uses exactly the
Transformer and Estimator halves)."""

from __future__ import annotations

import json
import os
from typing import Sequence


class Transformer:
    def transform(self, frame):
        raise NotImplementedError

    def __call__(self, frame):
        return self.transform(frame)


class Estimator:
    def fit(self, frame):
        raise NotImplementedError


class Model(Transformer):
    pass


class Pipeline(Estimator):
    """Chain of stages; each Estimator stage is fit on the running frame and
    replaced by its Model."""

    def __init__(self, stages: Sequence = ()):
        self._stages = list(stages)

    def set_stages(self, stages: Sequence) -> "Pipeline":
        self._stages = list(stages)
        return self

    setStages = set_stages

    def get_stages(self):
        return list(self._stages)

    getStages = get_stages

    def fit(self, frame) -> "PipelineModel":
        fitted = []
        cur = frame
        for stage in self._stages:
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                cur = model.transform(cur)
            else:
                fitted.append(stage)
                cur = stage.transform(cur)
        return PipelineModel(fitted)


class PipelineModel(Model):
    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def transform(self, frame):
        cur = frame
        for stage in self.stages:
            cur = stage.transform(cur)
        return cur


def write_json(path: str, obj) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, default=float)


def read_json(path: str):
    with open(path) as f:
        return json.load(f)
