"""Estimator/Transformer/Model/Pipeline base classes (the MLlib ``ml``
pipeline contracts that ``VectorAssembler`` and ``LinearRegression``
implement — `DataQuality4MachineLearningApp.java:110-126` uses exactly the
Transformer and Estimator halves), plus the generic stage-persistence layer
(MLlib's MLWritable/MLReadable analogue; SURVEY.md §5 "Checkpoint / resume"
— a capability upgrade over the reference, which never saves models).

Persistence model: every stage class declares ``_persist_attrs`` (the
attributes that fully determine it) and registers itself with
``@persistable``; ``save_stage``/``load_stage`` write/read one JSON file per
stage (numpy arrays embedded with a dtype tag). ``Pipeline`` and
``PipelineModel`` save stages into numbered subdirectories.
"""

from __future__ import annotations

import json
import os
from typing import Sequence

import numpy as np

_STAGE_REGISTRY: dict[str, type] = {}


def host_fetch(x) -> np.ndarray:
    """THE sanctioned device→host pull for model accessor APIs
    (``predict(features)`` single points, ``compute_cost``, summary
    statistics): one counted ``frame.host_sync`` per call, host numpy
    out. Every such accessor is host-returning by contract, so the
    transfer is inherent — what the standing ROADMAP constraint requires
    is that it be *counted*, so EXPLAIN ANALYZE and the span layer's
    per-op sync deltas see it (dqlint's ``host-sync`` rule pins the
    discipline statically)."""
    from ..utils.profiling import counters

    counters.increment("frame.host_sync")
    return np.asarray(x)


def persistable(cls):
    """Class decorator: register for name-based load_stage resolution."""
    _STAGE_REGISTRY[cls.__name__] = cls
    return cls


def _to_jsonable(v):
    if isinstance(v, np.ndarray):
        dt = "object" if v.dtype == object else str(v.dtype)
        # dqlint: ok(host-sync): isinstance-narrowed to host numpy —
        # persistence serializes the host copies stored on the stage
        return {"__ndarray__": v.tolist(), "dtype": dt}
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, dict):
        return {k: _to_jsonable(x) for k, x in v.items()}
    return v


def _from_jsonable(v):
    if isinstance(v, dict) and "__ndarray__" in v:
        dt = v["dtype"]
        return np.asarray(v["__ndarray__"],
                          object if dt == "object" else np.dtype(dt))
    if isinstance(v, dict):
        return {k: _from_jsonable(x) for k, x in v.items()}
    return v


def save_stage(stage, path: str) -> None:
    """Persist one stage (transformer/estimator/model) to ``path/``."""
    if hasattr(stage, "_save_to_dir"):  # composite stages (Pipeline, ...)
        stage._save_to_dir(path)
        return
    attrs = getattr(stage, "_persist_attrs", None)
    if attrs is None:
        raise TypeError(f"{type(stage).__name__} is not persistable "
                        f"(no _persist_attrs)")
    payload = {"class": type(stage).__name__,
               "data": {k: _to_jsonable(getattr(stage, k)) for k in attrs}}
    write_json(os.path.join(path, "stage.json"), payload)


def load_stage(path: str):
    """Load any persisted stage; dispatches on the recorded class name."""
    meta_path = os.path.join(path, "stage.json")
    if not os.path.exists(meta_path):  # composite stage directory
        comp = read_json(os.path.join(path, "metadata.json"))
        cls = _STAGE_REGISTRY.get(comp["class"])
        if cls is None or not hasattr(cls, "_load_from_dir"):
            raise ValueError(f"unknown composite stage {comp['class']!r}")
        return cls._load_from_dir(path, comp)
    meta = read_json(meta_path)
    cls = _STAGE_REGISTRY.get(meta["class"])
    if cls is None:
        raise ValueError(f"unknown stage class {meta['class']!r}; known: "
                         f"{sorted(_STAGE_REGISTRY)}")
    obj = cls.__new__(cls)
    for k, v in meta["data"].items():
        setattr(obj, k, _from_jsonable(v))
    post = getattr(obj, "_post_load", None)
    if post is not None:
        post()
    return obj


class _Persist:
    """save()/load() surface shared by all stage kinds."""

    def save(self, path: str) -> None:
        save_stage(self, path)

    def write(self):  # MLlib: model.write().overwrite().save(path)
        return _Writer(self)

    @classmethod
    def load(cls, path: str):
        obj = load_stage(path)
        if not isinstance(obj, cls):
            raise TypeError(f"{path} holds a {type(obj).__name__}, "
                            f"not a {cls.__name__}")
        return obj

    read = load


class _Writer:
    def __init__(self, stage):
        self._stage = stage

    def overwrite(self) -> "_Writer":
        return self

    def save(self, path: str) -> None:
        save_stage(self._stage, path)


class Transformer(_Persist):
    def transform(self, frame):
        raise NotImplementedError

    def __call__(self, frame):
        return self.transform(frame)


class Estimator(_Persist):
    def fit(self, frame):
        raise NotImplementedError


class Model(Transformer):
    pass


@persistable
class Pipeline(Estimator):
    """Chain of stages; each Estimator stage is fit on the running frame and
    replaced by its Model."""

    def __init__(self, stages: Sequence = ()):
        self._stages = list(stages)

    def _save_to_dir(self, path: str) -> None:
        write_json(os.path.join(path, "metadata.json"),
                   {"class": type(self).__name__,
                    "n_stages": len(self._stages)})
        for i, st in enumerate(self._stages):
            save_stage(st, os.path.join(path, f"stage_{i:02d}"))

    @classmethod
    def _load_from_dir(cls, path: str, meta: dict):
        stages = [load_stage(os.path.join(path, f"stage_{i:02d}"))
                  for i in range(meta["n_stages"])]
        return cls(stages)

    def set_stages(self, stages: Sequence) -> "Pipeline":
        self._stages = list(stages)
        return self

    setStages = set_stages

    def get_stages(self):
        return list(self._stages)

    getStages = get_stages

    def fit(self, frame) -> "PipelineModel":
        fitted = []
        cur = frame
        for stage in self._stages:
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                cur = model.transform(cur)
            else:
                fitted.append(stage)
                cur = stage.transform(cur)
        return PipelineModel(fitted)


@persistable
class PipelineModel(Model):
    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def transform(self, frame):
        cur = frame
        for stage in self.stages:
            cur = stage.transform(cur)
        return cur

    def _save_to_dir(self, path: str) -> None:
        write_json(os.path.join(path, "metadata.json"),
                   {"class": type(self).__name__,
                    "n_stages": len(self.stages)})
        for i, st in enumerate(self.stages):
            save_stage(st, os.path.join(path, f"stage_{i:02d}"))

    @classmethod
    def _load_from_dir(cls, path: str, meta: dict):
        return cls([load_stage(os.path.join(path, f"stage_{i:02d}"))
                    for i in range(meta["n_stages"])])


def write_json(path: str, obj) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, default=float)


def read_json(path: str):
    with open(path) as f:
        return json.load(f)
