"""Elastic-net linear solvers on sufficient statistics — the MLlib
``LinearRegression.train`` replacement, designed TPU-first.

MLlib's fit (SURVEY.md §3.3) is: one ``treeAggregate`` pass for feature/label
moments, then OWLQN iterations where every step broadcasts coefficients,
computes per-partition gradients row-by-row, and reduces over netty RPC — two
executor barriers per iteration.

The TPU design collapses all data passes into **one augmented Gramian**:
``A = ZᵀZ`` with ``Z = [X, y, 1] · mask`` — a single fused masked matmul on
the MXU (+ one ``psum`` over the mesh when sharded; see
``parallel/distributed.py``). Every quantity the solver needs — counts, means,
sample variances, the centered/standardized Gram matrix ``G``, the correlation
vector ``b``, and the label energy — unpacks from ``A`` on device. The whole
iteration loop (FISTA proximal gradient, or orthant-wise L-BFGS) then runs on
the tiny replicated ``(d×d)`` statistics inside one ``lax.scan`` — zero host
round-trips, zero per-iteration data passes, vs. Spark's 40×2 RPC barriers
(SURVEY.md §6 "Hard parts").

Numeric convention (validated against SURVEY.md §2.3 golden tables):

* sample std (n−1 denominator) for features and label (MLlib summarizer),
* solve in standardized space: ``x̂ = (x − x̄)/σ_x``, ``ŷ = (y − ȳ)/σ_y``
  (centering is implicit — it happens in the moment algebra, never on data),
* ``effectiveRegParam = regParam/σ_y``; L1/L2 split by ``elasticNetParam``,
* with ``standardization=False`` the penalty lands on the *raw* coefficients:
  L1 weight ``1/σ_xj``, L2 weight ``1/σ_xj²`` (MLlib semantics),
* unscale: ``w_j = ŵ_j σ_y/σ_xj``; ``intercept = ȳ − w·x̄``.
"""

from __future__ import annotations

import functools
import threading
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class Moments(NamedTuple):
    """Unpacked sufficient statistics (all device scalars/vectors)."""
    n: jnp.ndarray           # valid-row count
    mean_x: jnp.ndarray      # (d,)
    mean_y: jnp.ndarray      # ()
    std_x: jnp.ndarray       # (d,) sample std
    std_y: jnp.ndarray       # ()
    G: jnp.ndarray           # (d,d) standardized (centered) Gram / n
    b: jnp.ndarray           # (d,)  standardized X'y / n
    yy: jnp.ndarray          # ()    standardized y'y / n  (≈ (n-1)/n)
    valid: jnp.ndarray       # (d,) bool — feature has nonzero variance


class FitResult(NamedTuple):
    coefficients: jnp.ndarray      # (d,) original scale
    intercept: jnp.ndarray         # ()
    iterations: jnp.ndarray        # () int32 — solver iterations run
    objective_history: jnp.ndarray  # (max_iter+1,) scaled-objective trace
    converged: jnp.ndarray         # () bool


def augmented_gram(X: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """One-pass masked statistics: ``A = ZᵀZ``, ``Z = [X, y, 1]·mask``.

    Shape ``(d+2, d+2)``. This is the entire data touch of a linear fit — the
    ``treeAggregate`` analogue, as one MXU matmul per shard. With
    ``config.pallas`` enabled, dispatches to the row-streaming Pallas kernel
    (``ops/pallas_kernels.py``); default is the XLA expression below.
    """
    from ..ops import pallas_kernels

    if pallas_kernels.dispatch_to_pallas(X, y, mask):
        return pallas_kernels.masked_gram_pallas(X, y, mask)
    w = mask.astype(X.dtype)
    ones = jnp.ones_like(y)
    Z = jnp.concatenate([X, y[:, None], ones[:, None]], axis=1) * w[:, None]
    return Z.T @ Z


def unpack_moments(A: jnp.ndarray, fit_intercept: bool = True) -> Moments:
    """A → means/stds/standardized Gram. Pure device algebra, no data."""
    d = A.shape[0] - 2
    n = A[d + 1, d + 1]
    sum_x = A[:d, d + 1]
    sum_y = A[d, d + 1]
    mean_x = sum_x / n
    mean_y = sum_y / n
    # Centered second moments (always centered for std computation)
    Cxx = A[:d, :d] - n * jnp.outer(mean_x, mean_x)
    Cxy = A[:d, d] - n * mean_x * mean_y
    Cyy = A[d, d] - n * mean_y * mean_y
    denom = jnp.maximum(n - 1.0, 1.0)
    var_x = jnp.clip(jnp.diag(Cxx), 0.0) / denom
    var_y = jnp.clip(Cyy, 0.0) / denom
    std_x = jnp.sqrt(var_x)
    std_y = jnp.sqrt(var_y)
    valid = std_x > 0
    sx = jnp.where(valid, std_x, 1.0)
    sy = jnp.where(std_y > 0, std_y, 1.0)
    if not fit_intercept:
        # MLlib without intercept: no centering in the objective (std still
        # computed from centered moments above).
        Cxx = A[:d, :d]
        Cxy = A[:d, d]
        Cyy = A[d, d]
    G = Cxx / (n * jnp.outer(sx, sx))
    b = jnp.where(valid, Cxy / (n * sx * sy), 0.0)
    yy = Cyy / (n * sy * sy)
    # Zero out invalid (constant) features so they never move off 0.
    G = jnp.where(jnp.outer(valid, valid), G, jnp.where(
        jnp.eye(d, dtype=bool), 1.0, 0.0))
    return Moments(n, mean_x, mean_y, std_x, std_y, G, b, yy, valid)


def _penalty_weights(m: Moments, standardization: bool):
    """Per-feature multipliers (u1 for L1, u2 for L2) in standardized space.

    With ``standardization=False`` the penalty applies to the *raw*
    coefficient ``w_raw = ŵ/σ``: ``|w_raw| = |ŵ|/σ`` gives u1 = 1/σ, while
    ``w_raw² = ŵ²/σ²`` gives u2 = 1/σ² (MLlib's L2Regularization divides by
    std twice)."""
    if standardization:
        ones = jnp.ones_like(m.std_x)
        return ones, ones
    sx = jnp.where(m.valid, m.std_x, 1.0)
    u1 = jnp.where(m.valid, 1.0 / sx, 0.0)
    return u1, u1 * u1


def _objective(w, m: Moments, lam1, lam2):
    f = 0.5 * (m.yy - 2.0 * jnp.dot(m.b, w) + w @ m.G @ w)
    return f + jnp.sum(lam1 * jnp.abs(w)) + 0.5 * jnp.sum(lam2 * w * w)


def _soft(x, t):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


@functools.partial(jax.jit, static_argnames=("max_iter", "fit_intercept",
                                             "standardization",
                                             "record_history"))
def fista_solve(A: jnp.ndarray, reg_param, elastic_net_param,
                max_iter: int = 100, tol: float = 1e-6,
                fit_intercept: bool = True,
                standardization: bool = True,
                record_history: bool = True) -> FitResult:
    """Accelerated proximal gradient (FISTA) on the standardized objective.

    Reaches the same optimum as MLlib's OWLQN on the convex elastic net
    (parity is defined on the solution, SURVEY.md §7 "Hard parts"); the whole
    loop is one ``lax.scan`` with static shapes. ``objective_history[0]`` is
    the loss at w=0 (≈0.5), matching MLlib's convention of recording the
    initial objective.

    ``record_history=False`` drops the per-iteration objective trace
    (the returned history holds only the initial objective) — callers
    that solve many throwaway cells (the fused CV grid) skip the wasted
    stacking. The trace itself is accumulated in the scan CARRY with an
    explicit int32 ``dynamic_update_index_in_dim`` rather than as a
    stacked scan output: the stacking machinery's update indices come
    out mixed s64/s32 under x64, which the jax-0.4.x SPMD partitioner
    rejects whenever the solve lands inside a sharded program (the fused
    CV refit). Identical trace, partitioner-safe on every jax this
    framework supports.
    """
    m = unpack_moments(A, fit_intercept=fit_intercept)
    dt = A.dtype
    d = m.b.shape[0]
    eff = jnp.asarray(reg_param, dt) / jnp.where(m.std_y > 0, m.std_y, 1.0)
    alpha = jnp.asarray(elastic_net_param, dt)
    u1, u2 = _penalty_weights(m, standardization)
    lam1 = alpha * eff * u1
    lam2 = (1.0 - alpha) * eff * u2
    # Lipschitz bound: ‖G‖₂ ≤ ‖G‖_F for PSD G; + max ridge term.
    L = jnp.linalg.norm(m.G) + jnp.max(lam2, initial=0.0) + jnp.asarray(1e-12, dt)
    step = 1.0 / L

    w0 = jnp.zeros((d,), dt)
    obj0 = _objective(w0, m, lam1, lam2)
    hist0 = jnp.zeros((max_iter if record_history else 0,), dt)

    def body(state, i):
        w, w_prev, t, done, iters, last_obj, hist = state
        tn = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        v = w + ((t - 1.0) / tn) * (w - w_prev)
        grad = m.G @ v - m.b + lam2 * v
        w_new = _soft(v - step * grad, step * lam1)
        w_new = jnp.where(m.valid, w_new, 0.0)
        obj = _objective(w_new, m, lam1, lam2)
        # MLlib-style relative-improvement convergence test
        rel = jnp.abs(obj - last_obj) / jnp.maximum(jnp.abs(last_obj), 1e-12)
        now_done = jnp.logical_or(done, rel < tol)
        w_out = jnp.where(done, w, w_new)
        w_prev_out = jnp.where(done, w_prev, w)
        t_out = jnp.where(done, t, tn)
        obj_out = jnp.where(done, last_obj, obj)
        iters_out = iters + jnp.where(done, 0, 1).astype(jnp.int32)
        if record_history:
            hist = jax.lax.dynamic_update_index_in_dim(hist, obj_out, i, 0)
        return (w_out, w_prev_out, t_out, now_done, iters_out, obj_out,
                hist), None

    init = (w0, w0, jnp.asarray(1.0, dt), jnp.asarray(False),
            jnp.asarray(0, jnp.int32), obj0, hist0)
    (w, _, _, done, iters, _, hist), _ = jax.lax.scan(
        body, init, jnp.arange(max_iter, dtype=jnp.int32))

    sx = jnp.where(m.valid, m.std_x, 1.0)
    sy = jnp.where(m.std_y > 0, m.std_y, 1.0)
    coef = jnp.where(m.valid, w * sy / sx, 0.0)
    intercept = (m.mean_y - jnp.dot(coef, m.mean_x)) if fit_intercept else jnp.asarray(0.0, dt)
    history = (jnp.concatenate([obj0[None], hist]) if record_history
               else obj0[None])
    return FitResult(coef, intercept, iters, history, done)


@functools.partial(jax.jit, static_argnames=("fit_intercept", "standardization"))
def normal_solve(A: jnp.ndarray, reg_param, elastic_net_param=0.0,
                 fit_intercept: bool = True,
                 standardization: bool = True) -> FitResult:
    """Closed-form (normal-equations) path — MLlib's ``solver="normal"``,
    valid when there is no L1 term. One small Cholesky solve on device."""
    m = unpack_moments(A, fit_intercept=fit_intercept)
    dt = A.dtype
    d = m.b.shape[0]
    eff = jnp.asarray(reg_param, dt) / jnp.where(m.std_y > 0, m.std_y, 1.0)
    lam2 = (1.0 - jnp.asarray(elastic_net_param, dt)) * eff * _penalty_weights(m, standardization)[1]
    H = m.G + jnp.diag(lam2)
    w = jnp.linalg.solve(H, m.b)
    w = jnp.where(m.valid, w, 0.0)
    sx = jnp.where(m.valid, m.std_x, 1.0)
    sy = jnp.where(m.std_y > 0, m.std_y, 1.0)
    coef = jnp.where(m.valid, w * sy / sx, 0.0)
    intercept = (m.mean_y - jnp.dot(coef, m.mean_x)) if fit_intercept else jnp.asarray(0.0, dt)
    history = jnp.zeros((1,), dt)
    return FitResult(coef, intercept, jnp.asarray(0, jnp.int32), history,
                     jnp.asarray(True))


def resolve_solver(solver: str, reg_param: float, elastic_net_param: float) -> str:
    """Map MLlib's ``solver`` param to a concrete solver name, with
    ``auto`` semantics: normal equations when no L1 term is active, else the
    iterative proximal path."""
    has_l1 = (reg_param > 0.0) and (elastic_net_param > 0.0)
    if solver == "normal" or (solver == "auto" and not has_l1):
        if has_l1:
            raise ValueError("solver='normal' cannot apply an L1 penalty")
        return "normal"
    if solver in ("auto", "fista", "proximal"):
        return "fista"
    if solver in ("owlqn", "l-bfgs", "lbfgs"):
        return "owlqn"
    raise ValueError(f"unknown solver {solver!r}")


def downgrade_solver(solver_name: str, reg_param: float,
                     elastic_net_param: float) -> Optional[str]:
    """The resilience ladder's solver downgrade (``utils.recovery``):
    an iterative solver (``owlqn``/``fista``) that keeps failing degrades
    to the closed-form ``normal`` path — but only when no L1 term is
    active (normal equations cannot express the L1 penalty, exactly
    MLlib's restriction). Returns ``None`` when no downgrade exists."""
    has_l1 = (reg_param > 0.0) and (elastic_net_param > 0.0)
    if solver_name in ("owlqn", "fista") and not has_l1:
        return "normal"
    return None


def solve(A: jnp.ndarray, reg_param: float, elastic_net_param: float,
          max_iter: int, tol: float, fit_intercept: bool, standardization: bool,
          solver: str = "auto") -> FitResult:
    """Solver dispatch on a precomputed Gramian (see :func:`resolve_solver`).

    Host-level dispatch boundary, so it carries the ``solver`` fault-site
    hooks (``utils.faults``): a scheduled device error raises here before
    the jitted solve, and a scheduled NaN poisons the returned statistics
    — both exercised by the resilience suite. No-ops without a plan.
    """
    from ..utils import faults as _faults
    from ..utils import observability as _obs
    from ..utils.profiling import counters

    _faults.inject("solver")
    name = resolve_solver(solver, reg_param, elastic_net_param)
    counters.increment(f"solver.{name}_calls")
    _record_solver_example(name, A, reg_param, elastic_net_param,
                           max_iter, tol, fit_intercept, standardization)
    with _obs.span("solver.solve", cat="solver", solver=name,
                   features=int(A.shape[0]) - 2, max_iter=max_iter):
        if name == "normal":
            result = normal_solve(A, reg_param, elastic_net_param,
                                  fit_intercept=fit_intercept,
                                  standardization=standardization)
        elif name == "fista":
            result = fista_solve(A, reg_param, elastic_net_param,
                                 max_iter=max_iter, tol=tol,
                                 fit_intercept=fit_intercept,
                                 standardization=standardization)
        else:
            from .owlqn import owlqn_solve

            result = owlqn_solve(A, reg_param, elastic_net_param,
                                 max_iter=max_iter, tol=tol,
                                 fit_intercept=fit_intercept,
                                 standardization=standardization)
    return _faults.corrupt("solver", result)


def _jit_entry_size(fn) -> Optional[int]:
    """Compiled-program count of a ``jax.jit`` entry point (private-ish
    ``_cache_size`` API — None when unavailable, never an error)."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None


#: Abstract example calling conventions of the solver jit entry points,
#: keyed by a stable program key (solver name + Gramian spec + statics).
#: Recorded at the ``solve()`` dispatch boundary (shape/dtype metadata
#: only) so the program auditor can re-trace "the solver programs this
#: process actually serves" without guessing shapes. Bounded: one entry
#: per distinct (solver, shape, statics) signature.
_SOLVER_EXAMPLES: dict[str, tuple] = {}
_SOLVER_EXAMPLES_LOCK = threading.Lock()
_SOLVER_EXAMPLES_MAX = 64


def _record_solver_example(name: str, A, reg_param, elastic_net_param,
                           max_iter, tol, fit_intercept,
                           standardization) -> None:
    if name not in ("fista", "normal"):
        return            # owlqn is not a single jit entry point
    shape = tuple(getattr(A, "shape", ()))
    dtype = getattr(A, "dtype", None)
    if len(shape) != 2 or dtype is None:
        return
    key = (f"{name}_solve|A={shape[0]}x{shape[1]}:{np.dtype(dtype).str}"
           f"|maxIter={max_iter}|intercept={bool(fit_intercept)}"
           f"|std={bool(standardization)}")
    with _SOLVER_EXAMPLES_LOCK:
        if key in _SOLVER_EXAMPLES \
                or len(_SOLVER_EXAMPLES) >= _SOLVER_EXAMPLES_MAX:
            return
        aspec = jax.ShapeDtypeStruct(shape, dtype)
        if name == "normal":
            args = (aspec, float(reg_param), float(elastic_net_param))
            kwargs = {"fit_intercept": bool(fit_intercept),
                      "standardization": bool(standardization)}
            fn = normal_solve
        else:
            args = (aspec, float(reg_param), float(elastic_net_param))
            kwargs = {"max_iter": int(max_iter), "tol": float(tol),
                      "fit_intercept": bool(fit_intercept),
                      "standardization": bool(standardization)}
            fn = fista_solve
        _SOLVER_EXAMPLES[key] = (fn, args, kwargs)


def solver_program_handles() -> list:
    """Registry callback (CACHES.register_programs): the solver jit
    entry points at every calling convention this process dispatched.
    The variant re-traces at the next feature count — solver-loop
    structure must not depend on the Gramian size."""
    from ..utils import observability as _obs

    with _SOLVER_EXAMPLES_LOCK:
        items = list(_SOLVER_EXAMPLES.items())
    out = []
    for key, (fn, args, kwargs) in items:
        a = args[0]

        def wider(extra):
            return jax.ShapeDtypeStruct(
                (a.shape[0] + extra, a.shape[1] + extra), a.dtype)

        out.append(_obs.ProgramHandle(
            "solver", key, fn,
            args=args, kwargs=kwargs,
            # two fresh widths compared against each other (never the
            # possibly trace-cached recorded shape)
            variants={"shape": [((wider(1),) + args[1:], kwargs),
                                ((wider(2),) + args[1:], kwargs)]},
            mesh=None, guarded=None, meta={}))
    return out


def solver_cache_stats() -> dict:
    """Registry callback (observability.CACHES): compiled-program counts
    of the solver jit entry points plus the per-solver call counters —
    ``session.cache_report()['solver']``."""
    from ..utils.profiling import counters

    with _SOLVER_EXAMPLES_LOCK:
        entries = [{"key": k[:160], "program_key": k}
                   for k in _SOLVER_EXAMPLES]
    stats: dict = {
        "kind": "jax.jit entry points (sufficient-statistics solvers)",
        "programs": {"fista_solve": _jit_entry_size(fista_solve),
                     "normal_solve": _jit_entry_size(normal_solve)},
        "entries": entries,
    }
    calls = {name: counters.get(f"solver.{name}_calls")
             for name in ("fista", "normal", "owlqn")}
    stats["calls"] = {k: v for k, v in calls.items() if v}
    stats["fits"] = counters.get("solver.fits")
    stats["trace_hits"] = counters.get("jit.trace_hit")
    stats["trace_misses"] = counters.get("jit.trace_miss")
    return stats


def _register_cache_stats() -> None:
    from ..utils import observability as _obs

    _obs.CACHES.register("solver", solver_cache_stats)
    _obs.CACHES.register_programs("solver", solver_program_handles)


_register_cache_stats()


def psum_value_and_grad(local_objective, axis):
    """``value_and_grad`` for a data-parallel objective inside shard_map:
    differentiate the LOCAL objective, then explicitly ``psum`` both the
    value and every gradient leaf.

    Mathematically identical to ``value_and_grad(psum(local))`` — grad is
    linear — but robust across shard_map implementations: differentiating
    *through* a psum relies on replication tracking that the legacy
    ``check_rep`` machinery gets silently wrong when the check is off
    (which it must be: the old checker cannot traverse the while/scan
    loops every solver here uses; see ``parallel.mesh.shard_map``). Any
    replicated term in the local objective (regularizers on replicated
    params) must be pre-divided by the shard count so the psum restores
    it exactly once.

    ``axis=None`` returns plain ``jax.value_and_grad`` — the single-device
    path pays nothing.
    """
    vg = jax.value_and_grad(local_objective)
    if axis is None:
        return vg

    def vg_psum(params):
        v, g = vg(params)
        return (jax.lax.psum(v, axis),
                jax.tree_util.tree_map(lambda t: jax.lax.psum(t, axis), g))
    return vg_psum


def adam_scan(value_and_grad, params0, max_iter: int, lr: float,
              grad_mask=None, b1: float = 0.9, b2: float = 0.999,
              eps: float = 1e-8):
    """Full-batch Adam (bias-corrected) as ONE ``lax.scan`` over a params
    pytree — the shared optimizer of the non-Gramian fits (Weibull AFT,
    factorization machines). ``value_and_grad(params) -> (loss, grads)``;
    ``grad_mask`` optionally transforms the gradient pytree (e.g. zeroing
    frozen parameter groups). Returns (params, loss_history).
    """
    leaves = jax.tree_util.tree_leaves(params0)
    dt = leaves[0].dtype
    m0 = jax.tree_util.tree_map(jnp.zeros_like, params0)

    def body(state, i):
        p, m, v = state
        loss, g = value_and_grad(p)
        if grad_mask is not None:
            g = grad_mask(g)
        m = jax.tree_util.tree_map(lambda a, b_: b1 * a + (1 - b1) * b_,
                                   m, g)
        v = jax.tree_util.tree_map(
            lambda a, b_: b2 * a + (1 - b2) * b_ * b_, v, g)
        t = i + 1
        p = jax.tree_util.tree_map(
            lambda p_, m_, v_: p_ - lr * (m_ / (1 - b1 ** t))
            / (jnp.sqrt(v_ / (1 - b2 ** t)) + eps), p, m, v)
        return (p, m, v), loss

    (params, _, _), history = jax.lax.scan(
        body, (params0, m0, m0), jnp.arange(max_iter, dtype=dt))
    return params, history


def huber_fit(X, y, mask, epsilon: float = 1.35, reg_param: float = 0.0,
              fit_intercept: bool = True, max_iter: int = 500,
              tol: float = 1e-8, standardization: bool = True):
    """MLlib's ``loss="huber"`` robust regression: joint minimization of
    Huber's concomitant-scale objective (Owen 2007 — the same objective
    sklearn's HuberRegressor and Spark's HuberAggregator use)

        L(beta, sigma) = sum_i m_i (sigma + H_eps(r_i / sigma) * sigma)
                         + reg_param * ||beta||^2,   r_i = y_i - x_i.b - c

    over (beta, intercept, log sigma) with full-batch Adam inside one
    jitted ``lax.while_loop`` — the robust loss has no Gramian
    sufficient statistic, so unlike the squared-error path this
    revisits the rows every iteration (still one fused device program,
    zero host round-trips). Initialized from the OLS solution.
    Returns (coefficients, intercept, sigma, iterations, history).
    """
    import jax

    fdt = jnp.asarray(X).dtype
    X = jnp.asarray(X)
    y = jnp.asarray(y, fdt)
    # callers pass the Gramian-convention mask (bool, or sqrt(w) when a
    # weightCol is set); the robust objective weights rows LINEARLY, so
    # square it — a no-op for booleans, exactly w for weighted fits
    m = jnp.square(jnp.asarray(mask, fdt))
    n = jnp.maximum(jnp.sum(m), 1.0)
    d = X.shape[1]

    # OLS warm start via the existing Gramian machinery (which expects
    # the sqrt-convention mask, i.e. the caller's original)
    A = augmented_gram(X, y, jnp.asarray(mask, fdt))
    moments = unpack_moments(A, fit_intercept)
    # MLlib penalizes the STANDARDIZED coefficients when
    # standardization=True: beta_std_j = beta_j * std_j
    pen_scale = (jnp.asarray(moments.std_x, fdt) if standardization
                 else jnp.ones((d,), fdt))
    ols = normal_solve(A, 0.0, 0.0, fit_intercept=fit_intercept)
    b0 = jnp.asarray(ols.coefficients, fdt)
    c0 = jnp.asarray(ols.intercept, fdt)
    r0 = (y - X @ b0 - c0) * m
    s0 = jnp.log(jnp.maximum(jnp.sqrt(jnp.sum(r0 * r0) / n), 1e-6))

    eps = jnp.asarray(epsilon, fdt)

    def objective(params):
        b, c, ls = params
        sigma = jnp.exp(ls)
        r = (y - X @ b - (c if fit_intercept else 0.0)) / sigma
        # H(z) = z^2 inside, 2*eps|z| - eps^2 outside — the convention
        # sklearn's HuberRegressor optimizes (Owen 2007 eq. 1), so the
        # fitted scale_ cross-checks directly
        h = jnp.where(jnp.abs(r) <= eps, r * r,
                      2.0 * eps * jnp.abs(r) - eps * eps)
        # MLlib cost: (1/n) sum(loss) + regParam * 0.5 ||b_std||^2 —
        # scaled through by n so the loss term stays a plain sum
        return (jnp.sum(m * (sigma + h * sigma))
                + reg_param * n * 0.5 * jnp.sum((b * pen_scale) ** 2))

    grad = jax.grad(objective)

    def step(state):
        i, params, mom, vel, _prev, obj = state
        g = grad(params)
        t = (i + 1).astype(fdt)
        lr = 0.05 * jnp.minimum(1.0, 10.0 / t)   # mild decay
        mom = jax.tree.map(lambda a, b_: 0.9 * a + 0.1 * b_, mom, g)
        vel = jax.tree.map(lambda a, b_: 0.999 * a + 0.001 * b_ * b_,
                           vel, g)
        mhat = jax.tree.map(lambda a: a / (1 - 0.9 ** t), mom)
        vhat = jax.tree.map(lambda a: a / (1 - 0.999 ** t), vel)
        params = jax.tree.map(
            lambda p, mh, vh: p - lr * mh / (jnp.sqrt(vh) + 1e-9),
            params, mhat, vhat)
        new_obj = objective(params)
        return (i + 1, params, mom, vel, obj, new_obj)

    def cont(state):
        i, _p, _m, _v, prev, obj = state
        return jnp.logical_and(i < max_iter,
                               jnp.abs(prev - obj) > tol * (1 + jnp.abs(obj)))

    params0 = (b0, c0, s0)
    zeros = jax.tree.map(jnp.zeros_like, params0)
    state = (jnp.asarray(0), params0, zeros, zeros,
             jnp.asarray(jnp.inf, fdt), objective(params0))
    i, (b, c, ls), _, _, _, obj = jax.lax.while_loop(cont, step, state)
    return b, (c if fit_intercept else jnp.asarray(0.0, fdt)), \
        jnp.exp(ls), i, obj
