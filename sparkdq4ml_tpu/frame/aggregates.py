"""Aggregations: global (device, mask-weighted) and grouped (device-first).

Design note: global aggregates (``df.agg``, ``describe``) are masked device
reductions — one fused kernel per call, honoring the validity mask exactly
like the fit statistics. Grouped aggregation over NUMERIC keys and the
compilable aggregate family lowers to ONE jitted device program
(``ops/segments.py``: on-device lexicographic key sort + segment-boundary
discovery + ``segment_*`` reductions) whose only host sync is the final
group count. Everything outside that surface — string keys, host-object
aggregates (``collect_list``, ``percentile_approx``, the two-column
family), grouped-map UDFs — takes the original host boundary: group
discovery with numpy lexsort and vectorized per-group numpy reductions,
the same "gather at the boundary, never in the compute path" rule as
``Frame.to_pydict``. ``spark.groupedExec.enabled=false`` restores the
host path for everything (bit-identical results either way).
"""

from __future__ import annotations

import builtins
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from ..ops.expressions import Expr

_AGGS = ("count", "sum", "avg", "mean", "min", "max", "stddev", "variance",
         "stddev_pop", "var_pop", "median", "mode", "percentile_approx",
         "count_distinct", "sum_distinct", "collect_list", "collect_set",
         "first", "last", "skewness", "kurtosis",
         "corr", "covar_samp", "covar_pop", "max_by", "min_by")
# two-column aggregates (Spark's F.corr(a, b), max_by(x, ord))
_TWO_COL = ("corr", "covar_samp", "covar_pop", "max_by", "min_by")
# windowed form exists only for the running aggregates (as in Spark ≤2.x SQL)
_WINDOWABLE = ("count", "sum", "avg", "min", "max")


def _dict_aggs(d: dict) -> list:
    """PySpark's dict form: ``agg({'col': 'fn'})`` → AggExpr list with
    Spark's generated ``fn(col)`` output names ('*' allowed for count)."""
    out = []
    for col, fn in d.items():
        out.append(AggExpr(fn, None if col == "*" else col))
    return out


class AggExpr:
    """An aggregate over a column, e.g. ``F.avg("price")`` or SQL ``AVG(price)``."""

    def __init__(self, fn: str, column: Optional[str],
                 alias: Optional[str] = None,
                 column2: Optional[str] = None,
                 ignore_nulls: bool = False,
                 param=None):
        fn = fn.lower()
        if fn not in _AGGS:
            raise ValueError(f"unknown aggregate {fn!r} (supported: {_AGGS})")
        self.fn = "avg" if fn == "mean" else fn
        if self.fn in _TWO_COL:
            if column is None or column2 is None:
                raise ValueError(f"{self.fn}(col1, col2) takes two columns")
        elif column2 is not None:
            raise ValueError(f"{self.fn}() takes one column")
        self.column = column  # None = count(*)
        self.column2 = column2
        self.ignore_nulls = bool(ignore_nulls)  # first/last only
        self.param = param                       # percentile_approx only
        self._alias = alias

    def alias(self, name: str) -> "AggExpr":
        return AggExpr(self.fn, self.column, name, self.column2,
                       self.ignore_nulls, self.param)

    @property
    def name(self) -> str:
        if self._alias:
            return self._alias
        if self.fn == "count" and self.column is None:
            return "count"
        if self.fn in _TWO_COL:
            return f"{self.fn}({self.column}, {self.column2})"
        if self.fn in ("count_distinct", "sum_distinct"):
            return f"{self.fn.split('_')[0]}(DISTINCT {self.column})"
        if self.fn in ("first", "last") and self.ignore_nulls:
            # Spark encodes the flag in the name ("first(x, true)");
            # also keeps the two variants from colliding in one agg() call
            return f"{self.fn}({self.column}, true)"
        if self.fn == "percentile_approx":
            return f"percentile_approx({self.column}, {self.param})"
        target = "1" if self.column is None else self.column
        return f"{self.fn}({target})"

    def __repr__(self):
        return self.name

    def over(self, spec) -> "Expr":
        """Bind as a window aggregate: ``F.sum("x").over(Window...)``.
        Running aggregates plus ``first``/``last`` (→ the
        first_value/last_value window forms) have windowed shapes."""
        from .window import window_agg

        if self.fn in ("first", "last"):
            if self.ignore_nulls:
                raise ValueError(f"windowed {self.fn}() does not support "
                                 "ignoreNulls")
            expr = window_agg(f"{self.fn}_value", self.column).over(spec)
            return expr.alias(self._alias) if self._alias else expr
        if self.fn not in _WINDOWABLE:
            raise ValueError(f"windowed {self.fn}() is not supported")
        expr = window_agg(self.fn, self.column).over(spec)
        return expr.alias(self._alias) if self._alias else expr


class AggOfExpr(AggExpr):
    """An aggregate over an EXPRESSION (``sum(price * qty)``): the
    expression materializes as a temp device column just before
    aggregation (one fused pass), then aggregates like any column.
    Constructed by the SQL parser and the fluent constructors when given
    an Expr instead of a name."""

    def __init__(self, fn: str, expr, alias: Optional[str] = None):
        fn = fn.lower()
        fn = "avg" if fn == "mean" else fn
        if fn not in _AGGS or fn in _TWO_COL:
            raise ValueError(
                f"aggregate {fn!r} does not take an expression argument")
        self.fn = fn
        self.expr = expr
        self.column = None
        self.column2 = None
        self.ignore_nulls = False
        self.param = None
        self._alias = alias

    def alias(self, name: str) -> "AggOfExpr":
        return AggOfExpr(self.fn, self.expr, name)

    @property
    def name(self) -> str:
        return self._alias if self._alias else f"{self.fn}({self.expr})"

    def over(self, spec):
        raise ValueError(
            "windowed aggregates over expressions are not supported — "
            "materialize the expression with withColumn first")


def materialize_agg_exprs(frame, aggs):
    """Expression-argument aggregates → temp columns + plain AggExprs.
    Returns (frame, rewritten aggs); shared by every aggregation entry
    (global, grouped, pivoted, rollup/cube)."""
    out = []
    for i, a in enumerate(aggs):
        if isinstance(a, AggOfExpr):
            tmp = f"__aggarg_{i}"
            frame = frame.with_column(tmp, a.expr)
            out.append(AggExpr(a.fn, tmp, alias=a.name))
        else:
            out.append(a)
    return frame, out


# functions-module-style constructors (org.apache.spark.sql.functions).
# Each accepts a column NAME or (like PySpark) a column EXPRESSION —
# F.sum(col("p") * 2) — which routes through AggOfExpr materialization.
def _agg_or_expr(fn: str, col):
    if isinstance(col, Expr):
        from ..ops.expressions import Col
        if isinstance(col, Col):
            return AggExpr(fn, col.name)
        return AggOfExpr(fn, col)
    return AggExpr(fn, col)


def count(col: Optional[str] = None) -> AggExpr:
    if isinstance(col, Expr):
        return _agg_or_expr("count", col)
    return AggExpr("count", None if col in (None, "*") else col)


def sum(col) -> AggExpr:       # noqa: A001 - mirrors Spark's name
    return _agg_or_expr("sum", col)


def avg(col) -> AggExpr:
    return _agg_or_expr("avg", col)


mean = avg


def min(col) -> AggExpr:       # noqa: A001
    return _agg_or_expr("min", col)


def max(col) -> AggExpr:       # noqa: A001
    return _agg_or_expr("max", col)


def stddev(col) -> AggExpr:
    return _agg_or_expr("stddev", col)


def variance(col) -> AggExpr:
    return _agg_or_expr("variance", col)


def stddev_pop(col: str) -> AggExpr:
    return AggExpr("stddev_pop", col)


def var_pop(col: str) -> AggExpr:
    return AggExpr("var_pop", col)


def median(col: str) -> AggExpr:
    return AggExpr("median", col)


def mode(col: str) -> AggExpr:
    return AggExpr("mode", col)


def percentile_approx(col: str, percentage: float,
                      accuracy: int = 10000) -> AggExpr:
    """Spark's approximate percentile; this engine computes the EXACT
    nearest-rank order statistic (groups are host-resident, the sort is
    cheaper than a sketch), so ``accuracy`` is accepted for API
    compatibility and the answer has zero error."""
    if not 0.0 <= float(percentage) <= 1.0:
        raise ValueError(f"percentage must be in [0, 1], got {percentage}")
    return AggExpr("percentile_approx", col, param=float(percentage))


def count_distinct(col: str) -> AggExpr:
    return AggExpr("count_distinct", col)


countDistinct = count_distinct


def approx_count_distinct(col: str, rsd: float = 0.05) -> AggExpr:
    """Spark's HLL sketch bounds executor memory; this engine's groups are
    host-resident so the EXACT count is cheaper than a sketch — ``rsd``
    is accepted for API compatibility and the answer has zero error."""
    if not 0.0 < rsd < 1.0:
        raise ValueError(f"rsd must be in (0, 1), got {rsd}")
    return AggExpr("count_distinct", col,
                   alias=f"approx_count_distinct({col})")


approxCountDistinct = approx_count_distinct


def sum_distinct(col: str) -> AggExpr:
    return AggExpr("sum_distinct", col)


sumDistinct = sum_distinct


def collect_list(col: str) -> AggExpr:
    return AggExpr("collect_list", col)


def collect_set(col: str) -> AggExpr:
    return AggExpr("collect_set", col)


def first(col: str, ignorenulls: bool = False) -> AggExpr:
    return AggExpr("first", col, ignore_nulls=ignorenulls)


def last(col: str, ignorenulls: bool = False) -> AggExpr:
    return AggExpr("last", col, ignore_nulls=ignorenulls)


def skewness(col: str) -> AggExpr:
    return AggExpr("skewness", col)


def kurtosis(col: str) -> AggExpr:
    return AggExpr("kurtosis", col)


def corr(col1: str, col2: str) -> AggExpr:
    return AggExpr("corr", col1, column2=col2)


def covar_samp(col1: str, col2: str) -> AggExpr:
    return AggExpr("covar_samp", col1, column2=col2)


def covar_pop(col1: str, col2: str) -> AggExpr:
    return AggExpr("covar_pop", col1, column2=col2)


def _group_plan(key_cols: list[np.ndarray], n: int):
    """Null-safe lexicographic group discovery shared by groupBy/pivot:
    returns (order, group_starts, group_ends) over the n rows. Delegates key
    decomposition to window._key_parts/_neq so None string keys don't crash
    lexsort and NaN float keys form one group, exactly like window
    partitioning."""
    from .window import _key_parts, _neq

    parts_list = [_key_parts(np.asarray(k)) for k in key_cols]
    # np.lexsort: primary key LAST → reverse keys, and components within one
    lex = [comp for parts in reversed(parts_list)
           for comp in reversed(parts)]
    order = np.lexsort(lex) if lex else np.arange(n)
    boundary = np.zeros(len(order), bool)
    if len(order):
        boundary[0] = True
    for parts in parts_list:
        for comp in parts:
            boundary[1:] |= _neq(comp[order])
    starts = np.flatnonzero(boundary)
    ends = np.r_[starts[1:], len(order)]
    return order, starts, ends


def _drop_nulls(values: np.ndarray) -> np.ndarray:
    if values.dtype == object:
        return values[np.asarray([x is not None for x in values], bool)]
    if np.issubdtype(values.dtype, np.floating):
        return values[~np.isnan(values)]
    return values


def _np_agg(fn: str, values: np.ndarray, ignore_nulls: bool = False,
            param=None):
    if fn in ("first", "last"):
        # Spark's first/last default ignoreNulls=false: the raw first/last
        # row value, null included
        v = _drop_nulls(values) if ignore_nulls else values
        if len(v) == 0:
            return float("nan")
        return v[0] if fn == "first" else v[-1]
    values = _drop_nulls(values)  # SQL semantics: aggregates skip nulls
    if fn == "count":
        return len(values)
    if fn == "count_distinct":
        return len(set(values.tolist()))
    if fn == "collect_list":
        return list(values.tolist())
    if fn == "collect_set":
        # first-appearance order (Spark's order is unspecified)
        return list(dict.fromkeys(values.tolist()))
    if len(values) == 0:
        return float("nan")
    if fn == "sum":
        return values.sum()
    if fn == "sum_distinct":
        return np.asarray(list(set(values.tolist()))).sum()
    if fn == "avg":
        return float(np.mean(values))
    if fn == "min":
        return values.min()
    if fn == "max":
        return values.max()
    if fn == "stddev":
        return float(np.std(values, ddof=1)) if len(values) > 1 else float("nan")
    if fn == "variance":
        return float(np.var(values, ddof=1)) if len(values) > 1 else float("nan")
    if fn == "stddev_pop":
        return float(np.std(values, ddof=0))
    if fn == "var_pop":
        return float(np.var(values, ddof=0))
    if fn == "median":
        return float(np.median(np.asarray(values, np.float64)))
    if fn == "mode":
        # most frequent value; ties break to the smallest (deterministic —
        # Spark leaves tie order unspecified)
        uniq, cnt = np.unique(np.asarray(values), return_counts=True)
        return uniq[np.lexsort((uniq, -cnt))[0]]
    if fn == "percentile_approx":
        # exact nearest-rank order statistic: the smallest value whose
        # cumulative rank >= ceil(p*n) (Spark's convention — e.g.
        # p=0.5 over [1, 5] is 1, not 5). Spark's sketch bounds memory;
        # the exact sort here is cheaper and has zero error.
        v = np.sort(np.asarray(values, np.float64))
        p = float(param if param is not None else 0.5)
        idx = builtins.max(int(np.ceil(p * len(v))) - 1, 0)
        return float(v[builtins.min(idx, len(v) - 1)])
    if fn in ("skewness", "kurtosis"):
        # Spark: population moments; kurtosis is EXCESS kurtosis
        v = np.asarray(values, np.float64)
        m2 = np.mean((v - v.mean()) ** 2)
        if m2 == 0:
            return float("nan")
        if fn == "skewness":
            return float(np.mean((v - v.mean()) ** 3) / m2 ** 1.5)
        return float(np.mean((v - v.mean()) ** 4) / m2 ** 2 - 3.0)
    raise ValueError(fn)


def _np_agg2(fn: str, a: np.ndarray, b: np.ndarray):
    """Two-column aggregates over pairwise non-null rows (SQL semantics)."""
    if fn in ("max_by", "min_by"):
        # value of a at the extreme of b (Spark max_by/min_by): only rows
        # with a null ORDERING are ignored — the selected VALUE returns
        # as-is, NULL included (Spark returns NULL when the row at the
        # extreme ordering has a null value; ADVICE.md #3). The value may
        # be any type (string max_by is the idiomatic use) and passes
        # through unconverted.
        a = np.asarray(a)
        bb = np.asarray(b, np.float64)
        ok = ~np.isnan(bb)
        if not ok.any():
            return None if a.dtype == object else float("nan")
        sel = np.flatnonzero(ok)
        pick = sel[int(np.argmax(bb[sel])) if fn == "max_by"
                   else int(np.argmin(bb[sel]))]
        v = a[pick]
        return v if a.dtype == object else float(v)
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    ok = ~(np.isnan(a) | np.isnan(b))
    a, b = a[ok], b[ok]
    n = len(a)
    if fn == "covar_pop":
        return float(np.mean((a - a.mean()) * (b - b.mean()))) if n else float("nan")
    if n < 2:
        return float("nan")
    if fn == "covar_samp":
        return float(((a - a.mean()) * (b - b.mean())).sum() / (n - 1))
    if fn == "corr":
        sa, sb = a.std(), b.std()
        if sa == 0 or sb == 0:
            return float("nan")
        return float(np.mean((a - a.mean()) * (b - b.mean())) / (sa * sb))
    raise ValueError(fn)


_DEVICE_AGGS = ("count", "sum", "avg", "min", "max", "stddev", "variance")


def _one_slot_obj(value):
    arr = np.empty(1, dtype=object)
    arr[0] = value
    return arr


def global_agg(frame, aggs: list[AggExpr]):
    """Masked device reductions over the whole frame → 1-row Frame.
    The order-/set-valued aggregates (collect_*, first/last, distinct,
    corr family, higher moments) take the host boundary like grouped
    aggregation — their outputs are host objects by nature."""
    from .frame import Frame

    mask = frame.mask
    w = mask.astype(jnp.float32)
    out = {}
    # (name, nonnull_count, value, null_result) for the aggregates whose
    # empty-input NULL decision is deferred to ONE host sync after the loop
    deferred: list = []
    for agg in aggs:
        if agg.fn == "count" and agg.column is None:
            out[agg.name] = jnp.sum(mask, dtype=jnp.int32)[None]
            continue
        if agg.fn in _TWO_COL:
            m = np.asarray(mask)
            a = np.asarray(frame._column_values(agg.column))[m]
            b = np.asarray(frame._column_values(agg.column2))[m]
            out[agg.name] = np.asarray([_np_agg2(agg.fn, a, b)])
            continue
        if agg.fn not in _DEVICE_AGGS:
            m = np.asarray(mask)
            vals = np.asarray(frame._column_values(agg.column))[m]
            res = _np_agg(agg.fn, vals, agg.ignore_nulls, agg.param)
            # list results AND non-numeric scalars (first/last of a string
            # column) must stay object slots — np.asarray would mint a
            # unicode array the device column layer rejects
            host_obj = (agg.fn in ("collect_list", "collect_set")
                        or vals.dtype == object)
            out[agg.name] = (_one_slot_obj(res) if host_obj
                             else np.asarray([res]))
            continue
        col = frame._column_values(agg.column)
        if isinstance(col, np.ndarray) and col.dtype == object:
            # string column: host path (count only meaningful)
            vals = col[np.asarray(mask)]
            out[agg.name] = np.asarray([_np_agg(agg.fn, vals)])
            continue
        v = jnp.asarray(col)
        if agg.fn in ("count", "sum") and jnp.issubdtype(v.dtype, jnp.integer):
            # exact integer arithmetic on host (Spark widens SUM to long;
            # a float32 device accumulation would round/saturate)
            vals = np.asarray(v)[np.asarray(mask)]
            out[agg.name] = np.asarray(
                [len(vals) if agg.fn == "count" else int(vals.sum(dtype=np.int64))],
                dtype=np.int64)
            continue
        vf = v.astype(jnp.float64 if v.dtype == jnp.float64 else jnp.float32)
        wf = w.astype(vf.dtype)
        # SQL semantics: aggregates over a column skip nulls (NaN)
        null = jnp.isnan(vf)
        valid = jnp.logical_and(mask, jnp.logical_not(null))
        wf = wf * jnp.logical_not(null).astype(vf.dtype)
        nv = jnp.sum(wf)
        vf = jnp.where(null, 0.0, vf)
        nan = jnp.asarray(jnp.nan, vf.dtype)
        # SQL NULL results over zero non-null rows (Spark): keyed on the
        # non-null ROW COUNT, not the weight sum (a zero weight sum over
        # non-null rows must yield 0.0 from sum(), ADVICE.md #5), and the
        # decision is deferred — one host sync after the loop instead of
        # an eager float() per aggregate.
        cnt = jnp.sum(valid, dtype=jnp.int32)
        if agg.fn == "count":
            out[agg.name] = cnt[None]
        elif agg.fn == "avg":
            out[agg.name] = (jnp.sum(vf * wf) / nv)[None]
        elif agg.fn == "sum":
            out[agg.name] = None  # placeholder keeps the column order
            deferred.append((agg.name, cnt, jnp.sum(vf * wf)[None],
                             nan[None]))
        elif agg.fn == "min":
            big = jnp.asarray(jnp.inf, vf.dtype)
            out[agg.name] = None
            deferred.append((agg.name, cnt, jnp.min(
                jnp.where(valid, vf, big)).astype(v.dtype)[None],
                nan[None]))
        elif agg.fn == "max":
            small = jnp.asarray(-jnp.inf, vf.dtype)
            out[agg.name] = None
            deferred.append((agg.name, cnt, jnp.max(
                jnp.where(valid, vf, small)).astype(v.dtype)[None],
                nan[None]))
        else:  # stddev / variance: sample (n-1); NaN when n < 2 (Spark)
            mu = jnp.sum(vf * wf) / nv
            ss = jnp.sum(wf * (vf - mu) ** 2)
            var = jnp.where(nv > 1.0, ss / jnp.maximum(nv - 1.0, 1.0),
                            jnp.asarray(jnp.nan, vf.dtype))
            out[agg.name] = (var if agg.fn == "variance" else jnp.sqrt(var))[None]
    if deferred:
        # the ONE deferred device->host pull per agg call (all empty-input
        # verdicts batch into a single stacked transfer) — counted, so the
        # span layer and EXPLAIN ANALYZE see it (dqlint host-sync)
        from ..utils.profiling import counters

        counters.increment("frame.host_sync")
        counts = np.asarray(jnp.stack([c for _, c, _, _ in deferred]))
        for (name, _, val, nanv), c in zip(deferred, counts):
            out[name] = val if int(c) > 0 else nanv
    return Frame(out)


class _AggShortcuts:
    """The RelationalGroupedDataset terminal shortcuts, shared by the
    grouped, pivoted, and rollup/cube frames — each delegates to
    ``self.agg``."""

    def count(self):
        return self.agg(AggExpr("count", None))

    def sum(self, *cols: str):
        return self.agg(*[AggExpr("sum", c) for c in cols])

    def avg(self, *cols: str):
        return self.agg(*[AggExpr("avg", c) for c in cols])

    mean = avg

    def min(self, *cols: str):
        return self.agg(*[AggExpr("min", c) for c in cols])

    def max(self, *cols: str):
        return self.agg(*[AggExpr("max", c) for c in cols])


class GroupedFrame(_AggShortcuts):
    """Result of ``Frame.group_by`` — terminal agg methods mirror Spark's
    ``RelationalGroupedDataset``."""

    def __init__(self, frame, keys: list[str]):
        if not keys:
            raise ValueError("group_by requires at least one key column")
        self._frame = frame
        self._keys = keys
        for k in keys:
            frame._column_values(k)  # validate early

    def apply_in_pandas(self, func, schema):
        """Spark 3's ``groupBy(...).applyInPandas(fn, schema)``: the
        grouped-map UDF. Each group materializes as a pandas DataFrame on
        the host, ``func`` maps it to a new DataFrame, and the pieces
        concatenate into one Frame cast to the DDL ``schema``. This is
        the escape hatch for per-group logic the fused aggregate path
        cannot express — it pays the host boundary once per group, so
        keep it off hot paths (the vectorized agg() stays the fast lane).
        """
        import pandas as pd

        from .csv import parse_ddl_schema
        from .frame import Frame

        fields = parse_ddl_schema(schema) if isinstance(schema, str) \
            else list(schema)
        pdf = self._frame.to_pandas()
        if len(pdf) == 0:
            groups = []
        else:
            groups = [g.reset_index(drop=True)
                      for _, g in pdf.groupby(self._keys, sort=True,
                                              dropna=False)]
        outs = []
        for g in groups:
            out = func(g)
            if not isinstance(out, pd.DataFrame):
                raise TypeError("applyInPandas function must return a "
                                f"pandas DataFrame, got {type(out).__name__}")
            outs.append(out)
        names = [n for n, _ in fields]
        if outs:
            cat = pd.concat(outs, ignore_index=True)
            missing = [n for n in names if n not in cat.columns]
            if missing:
                raise ValueError(f"applyInPandas output is missing schema "
                                 f"columns {missing}")
            data = {n: cat[n].to_numpy() for n in names}
        else:
            data = {n: np.asarray([], np.float64) for n in names}
        frame = Frame(data)
        for name, tname in fields:
            frame = frame.with_column(
                name, frame.col(name).cast(tname))
        return frame

    applyInPandas = apply_in_pandas

    def agg(self, *aggs: Union[AggExpr, str]):
        from .frame import Frame

        if len(aggs) == 1 and isinstance(aggs[0], dict):
            aggs = tuple(_dict_aggs(aggs[0]))
        agg_list = []
        for a in aggs:
            if isinstance(a, str):
                a = AggExpr(a, None)
            agg_list.append(a)
        if not agg_list:
            raise ValueError("agg() needs at least one aggregate")
        frame_src, agg_list = materialize_agg_exprs(self._frame, agg_list)

        # Device-resident path first (ops/segments.py): one jitted
        # segment-reduce program, one host sync (the group count). Any
        # ineligible plan (string keys, host-object aggs) or internal
        # failure falls back to the host path below via the shared
        # try_device protocol — the optimization layer must never change
        # results.
        from ..ops import segments

        out = segments.try_device(
            "grouped_agg",
            lambda: segments.grouped_agg(frame_src, self._keys, agg_list))
        if out is not None:
            return out

        d = frame_src.to_pydict()  # host boundary: one gather
        key_cols = [np.asarray(d[k]) for k in self._keys]
        order, group_starts, group_ends = _group_plan(
            key_cols, len(key_cols[0]) if key_cols else 0)
        if len(order) == 0:
            data = {k: [] for k in self._keys}
            data.update({a.name: [] for a in agg_list})
            return Frame(data)

        data: dict[str, list] = {k: [] for k in self._keys}
        for a in agg_list:
            data[a.name] = []
        for s, e in zip(group_starts, group_ends):
            idx = order[s:e]
            for k, kc in zip(self._keys, key_cols):
                data[k].append(kc[idx[0]])
            for a in agg_list:
                if a.fn == "count" and a.column is None:
                    data[a.name].append(len(idx))
                elif a.fn in _TWO_COL:
                    data[a.name].append(_np_agg2(
                        a.fn, np.asarray(d[a.column])[idx],
                        np.asarray(d[a.column2])[idx]))
                else:
                    data[a.name].append(_np_agg(
                        a.fn, np.asarray(d[a.column])[idx], a.ignore_nulls,
                        a.param))
        # list-valued aggregate columns must stay ragged object arrays
        for a in agg_list:
            if a.fn in ("collect_list", "collect_set"):
                from .frame import list_column

                data[a.name] = list_column(data[a.name])
        return Frame(data)

    def pivot(self, pivot_col: str, values=None) -> "PivotedFrame":
        """``groupBy(keys).pivot(col[, values]).agg(...)`` — rotate the
        distinct values of ``pivot_col`` into output columns (Spark's
        RelationalGroupedDataset.pivot). When ``values`` is omitted the
        distinct values are discovered from the data and sorted, as Spark
        does; passing them explicitly skips that pass and fixes the column
        order."""
        self._frame._column_values(pivot_col)
        return PivotedFrame(self._frame, self._keys, pivot_col, values)



class PivotedFrame(_AggShortcuts):
    """Result of ``GroupedFrame.pivot`` — terminal agg methods produce one
    output column per (pivot value × aggregate), Spark column naming:
    just the value for a single aggregate, ``value_aggname`` for several."""

    def __init__(self, frame, keys: list[str], pivot_col: str, values):
        self._frame = frame
        self._keys = keys
        self._pivot_col = pivot_col
        self._values = list(values) if values is not None else None

    def agg(self, *aggs: Union[AggExpr, str]):
        from .frame import Frame

        agg_list = [AggExpr(a, None) if isinstance(a, str) else a
                    for a in aggs]
        if not agg_list:
            raise ValueError("agg() needs at least one aggregate")
        frame_src, agg_list = materialize_agg_exprs(self._frame, agg_list)

        d = frame_src.to_pydict()  # host boundary: one gather
        pcol = np.asarray(d[self._pivot_col])
        if self._values is None:
            uniq = [x for x in set(pcol.tolist()) if x is not None]
            try:
                values = sorted(uniq)       # natural order (Spark parity)
            except TypeError:
                # mixed incomparable types (e.g. int + str): group by type,
                # natural order within each type
                values = sorted(uniq, key=lambda x: (str(type(x)), x))
        else:
            values = self._values

        key_cols = [np.asarray(d[k]) for k in self._keys]
        order, group_starts, group_ends = _group_plan(key_cols, len(pcol))

        # Output names are precomputed, de-colliding against group keys AND
        # each other (two pivot values may stringify identically, 1 vs "1").
        taken = set(self._keys)
        names: dict[tuple, str] = {}
        for vi, v in enumerate(values):
            for ai, a in enumerate(agg_list):
                base = str(v) if len(agg_list) == 1 else f"{v}_{a.name}"
                while base in taken:
                    base += "_pivot"
                taken.add(base)
                names[(vi, ai)] = base

        agg_arrays = {a.column: np.asarray(d[a.column])
                      for a in agg_list if a.column is not None}
        agg_arrays.update({a.column2: np.asarray(d[a.column2])
                           for a in agg_list if a.column2 is not None})

        data: dict[str, list] = {k: [] for k in self._keys}
        for nm in names.values():
            data[nm] = []
        for s, e in zip(group_starts, group_ends):
            idx = order[s:e]
            for k, kc in zip(self._keys, key_cols):
                data[k].append(kc[idx[0]])
            grp_pivot = pcol[idx]
            for vi, v in enumerate(values):
                sub = idx[np.asarray([x == v for x in grp_pivot], bool)]
                for ai, a in enumerate(agg_list):
                    if a.fn == "count" and a.column is None:
                        data[names[(vi, ai)]].append(len(sub))
                    elif len(sub) == 0:
                        # no rows for this cell → null (Spark), even for
                        # COUNT over a column (Spark yields null there too)
                        data[names[(vi, ai)]].append(float("nan"))
                    elif a.fn in _TWO_COL:
                        data[names[(vi, ai)]].append(_np_agg2(
                            a.fn, agg_arrays[a.column][sub],
                            agg_arrays[a.column2][sub]))
                    else:
                        data[names[(vi, ai)]].append(_np_agg(
                            a.fn, agg_arrays[a.column][sub], a.ignore_nulls,
                            a.param))
        from .frame import list_column

        for (vi, ai), nm in names.items():
            if agg_list[ai].fn in ("collect_list", "collect_set"):
                data[nm] = list_column(data[nm])
        return Frame(data)



class MultiGroupedFrame(_AggShortcuts):
    """``Frame.rollup``/``Frame.cube`` — aggregate at several grouping
    levels and union the results, Spark's subtotal semantics: key columns
    absent from a level come back null. Output key columns are nullable
    and therefore host object columns (None in subtotal rows) — keeping
    integer keys EXACT; a NaN filler would silently promote int keys to
    the device float dtype and corrupt values past its mantissa."""

    def __init__(self, frame, keys: list[str], levels: list[tuple]):
        if not keys:
            raise ValueError("rollup/cube require at least one key column")
        self._frame = frame
        self._keys = keys
        self._levels = levels
        for k in keys:
            frame._column_values(k)  # validate early

    def agg(self, *aggs: Union[AggExpr, str]):
        from .frame import Frame

        agg_list = [AggExpr(a, None) if isinstance(a, str) else a
                    for a in aggs]
        if not agg_list:
            raise ValueError("agg() needs at least one aggregate")

        frame_src, agg_list = materialize_agg_exprs(self._frame, agg_list)
        # One pass per level; a single concatenate per column at the end.
        key_parts: dict[str, list] = {k: [] for k in self._keys}
        agg_parts: dict[str, list] = {a.name: [] for a in agg_list}
        for kept in self._levels:
            if kept:
                out = GroupedFrame(frame_src, list(kept)).agg(*agg_list)
            else:
                out = global_agg(frame_src, agg_list)
            d = out.to_pydict()
            n = len(next(iter(d.values()))) if d else 0
            for k in self._keys:
                if k in d:
                    key_parts[k].append(np.asarray(d[k], object))
                else:
                    filler = np.empty(n, dtype=object)  # None slots
                    filler.fill(None)
                    key_parts[k].append(filler)
            for a in agg_list:
                agg_parts[a.name].append(np.asarray(d[a.name]))

        data: dict = {}
        for k in self._keys:
            data[k] = np.concatenate(key_parts[k])
        for a in agg_list:
            parts = agg_parts[a.name]
            if any(p.dtype == object for p in parts):
                parts = [np.asarray(p, object) for p in parts]
            data[a.name] = np.concatenate(parts)
        return Frame(data)


def rollup_levels(keys: list[str]) -> list[tuple]:
    """Prefixes, longest first, down to the grand total: Spark ROLLUP."""
    return [tuple(keys[:i]) for i in range(len(keys), -1, -1)]


def cube_levels(keys: list[str]) -> list[tuple]:
    """Every key subset (kept in key order), by descending size: CUBE."""
    import itertools as _it

    out = []
    for r in range(len(keys), -1, -1):
        out.extend(_it.combinations(keys, r))
    return out
