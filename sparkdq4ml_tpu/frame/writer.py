"""CSV writer — the ``df.write`` half of the data-loader capability
(checkpointing a cleaned frame back to storage; the reference pipeline is a
pure function of its input CSV, so frame persistence + deterministic re-run
is the lineage/recovery analogue of SURVEY.md §5 "Failure detection")."""

from __future__ import annotations

import os

import numpy as np


def _format_value(v) -> str:
    if v is None:
        return ""
    if isinstance(v, (np.floating, float)):
        if np.isnan(v):
            return ""
        return np.format_float_positional(np.float64(v), unique=True, trim="0")
    if isinstance(v, (np.bool_, bool)):
        return "true" if v else "false"
    if isinstance(v, (np.integer, int)):
        return str(int(v))
    return str(v)


def _escape(s: str, delimiter: str, quote: str = '"') -> str:
    if delimiter in s or quote in s or "\n" in s or "\r" in s:
        return quote + s.replace(quote, quote * 2) + quote
    return s


def write_csv(frame, path: str, header: bool = False,
              delimiter: str = ",") -> None:
    d = frame.to_pydict()  # valid rows only — masked slots never persist
    names = frame.columns
    lines = []
    if header:
        lines.append(delimiter.join(_escape(n, delimiter) for n in names))
    n = len(next(iter(d.values()))) if d else 0
    for i in range(n):
        lines.append(delimiter.join(
            _escape(_format_value(d[name][i]), delimiter) for name in names))
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w", newline="") as f:
        f.write("\n".join(lines) + ("\n" if lines else ""))


class DataFrameWriter:
    """Builder mirroring ``df.write.format("csv").option(...).save(path)``."""

    def __init__(self, frame):
        self._frame = frame
        self._format = "csv"
        self._options: dict[str, str] = {}
        self._mode = "errorifexists"

    def format(self, fmt: str) -> "DataFrameWriter":
        self._format = fmt.lower()
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[key.lower()] = str(value)
        return self

    def mode(self, mode: str) -> "DataFrameWriter":
        if mode.lower() not in ("overwrite", "errorifexists", "error"):
            raise ValueError(f"unsupported write mode {mode!r}")
        self._mode = mode.lower()
        return self

    def save(self, path: str) -> None:
        if self._format not in ("csv", "json", "parquet"):
            raise ValueError(
                f"unsupported format {self._format!r} (csv, json, "
                "or parquet)")
        if os.path.exists(path) and self._mode == "errorifexists":
            raise FileExistsError(
                f"{path} exists (use .mode('overwrite') to replace)")
        if self._format == "parquet":
            from .parquet import write_parquet

            write_parquet(
                self._frame, path,
                compression=self._options.get("compression", "snappy"))
            return
        if self._format == "json":
            from .jsonl import write_json

            write_json(self._frame, path)
            return
        header = self._options.get("header", "false").lower() in ("true", "1")
        delimiter = self._options.get("sep", self._options.get("delimiter", ","))
        write_csv(self._frame, path, header=header, delimiter=delimiter)

    def csv(self, path: str) -> None:
        self.save(path)

    def json(self, path: str) -> None:
        self.format("json").save(path)

    def parquet(self, path: str) -> None:
        self.format("parquet").save(path)
