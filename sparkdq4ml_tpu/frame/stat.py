"""``Frame.stat`` — Spark's ``DataFrameStatFunctions`` equivalent.

Thematically this is the reference's own subject: its second DQ rule is a
*price correlation* plausibility check (`PriceCorrelationDataQualityService
.java:5-10`), and Spark users inspect exactly these statistics
(``df.stat.corr("guest", "price")``) when designing such rules.

All statistics are mask-weighted single-pass device reductions — filtered
rows never contribute (SURVEY.md §7 "Masked-filter semantics")."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import float_dtype


@jax.jit
def _corr_cov(a, b, w):
    """Mask-weighted Pearson correlation and sample covariance, one pass."""
    n = jnp.sum(w)
    ma = jnp.sum(a * w) / n
    mb = jnp.sum(b * w) / n
    da = (a - ma) * w
    db = (b - mb) * w
    cov = jnp.sum(da * db) / jnp.maximum(n - 1.0, 1.0)
    va = jnp.sum(da * da) / jnp.maximum(n - 1.0, 1.0)
    vb = jnp.sum(db * db) / jnp.maximum(n - 1.0, 1.0)
    denom = jnp.sqrt(va * vb)
    corr = jnp.where(denom > 0, cov / denom, jnp.nan)
    return corr, cov


class FrameStatFunctions:
    def __init__(self, frame):
        self._frame = frame

    def _pair(self, col1: str, col2: str):
        dt = float_dtype()
        a = jnp.asarray(self._frame._column_values(col1), dt)
        b = jnp.asarray(self._frame._column_values(col2), dt)
        w = self._frame.mask.astype(dt)
        return a, b, w

    def corr(self, col1: str, col2: str, method: str = "pearson") -> float:
        """Pearson (or Spearman rank) correlation of two numeric columns."""
        from ..utils.profiling import counters

        a, b, w = self._pair(col1, col2)
        if method == "spearman":
            a, b = _rank(a, w), _rank(b, w)
        elif method != "pearson":
            raise ValueError(f"unknown correlation method {method!r}")
        counters.increment("frame.host_sync")  # device scalar → float
        return float(_corr_cov(a, b, w)[0])

    def cov(self, col1: str, col2: str) -> float:
        """Sample covariance (n−1 denominator, like Spark)."""
        from ..utils.profiling import counters

        a, b, w = self._pair(col1, col2)
        counters.increment("frame.host_sync")  # device scalar → float
        return float(_corr_cov(a, b, w)[1])

    def approx_quantile(self, col: str, probabilities, relative_error=0.0):
        """Quantiles of a numeric column. Spark sketches (Greenwald-Khanna)
        to bound executor memory; here an exact device sort is both cheaper
        and exact at any size XLA can sort, so ``relative_error`` is
        accepted for API compatibility and ignored."""
        from ..utils.profiling import counters

        a = jnp.asarray(self._frame._column_values(col), float_dtype())
        counters.increment("frame.host_sync")  # mask + column pull, one batch
        keep = np.asarray(self._frame.mask)
        vals = np.sort(np.asarray(a)[keep])
        if len(vals) == 0:
            return [float("nan") for _ in np.atleast_1d(probabilities)]
        qs = [float(vals[min(int(p * len(vals)), len(vals) - 1)])
              for p in np.atleast_1d(probabilities)]
        return qs

    approxQuantile = approx_quantile

    def crosstab(self, col1: str, col2: str):
        """Contingency table of two columns (Spark's ``stat.crosstab``)."""
        from .frame import Frame

        d = self._frame.to_pydict()
        a = [str(v) for v in d[col1]]
        b = [str(v) for v in d[col2]]
        rows = sorted(set(a))
        cols = sorted(set(b))
        counts = {(x, y): 0 for x in rows for y in cols}
        for x, y in zip(a, b):
            counts[(x, y)] += 1
        data = {f"{col1}_{col2}": np.asarray(rows, dtype=object)}
        for y in cols:
            data[y] = np.asarray([counts[(x, y)] for x in rows], np.int64)
        return Frame(data)

    def sample_by(self, col: str, fractions: dict, seed: int = 0):
        """Stratified Bernoulli sample without replacement
        (Spark ``stat.sampleBy``): each row whose ``col`` value appears in
        ``fractions`` is kept with that stratum's probability; strata
        absent from ``fractions`` sample at 0. Mask-composed — shapes stay
        static and column arrays are shared, like ``Frame.sample``."""
        import jax.numpy as jnp

        for k, f in fractions.items():
            if not 0.0 <= f <= 1.0:
                raise ValueError(
                    f"fraction for stratum {k!r} must be in [0, 1], got {f}")
        vals = self._frame._column_values(col)
        if vals.dtype != object:
            from ..utils.profiling import counters

            counters.increment("frame.host_sync")  # device stratum pull
        vals_h = (np.asarray(vals, object) if vals.dtype == object
                  else np.asarray(vals))
        rng = np.random.default_rng(seed)
        u = rng.random(len(vals_h))
        frac = np.asarray([fractions.get(v, 0.0) for v in vals_h.tolist()])
        keep = jnp.asarray(u < frac)
        return self._frame._with(
            mask=jnp.logical_and(self._frame.mask, keep))

    sampleBy = sample_by

    def freq_items(self, cols, support: float = 0.01):
        """Per-column items with frequency ≥ support (Spark ``freqItems``)."""
        from .frame import Frame

        d = self._frame.to_pydict()
        out = {}
        n = max(len(next(iter(d.values()))), 1) if d else 1
        for c in cols:
            vals, counts = np.unique(np.asarray([str(v) for v in d[c]]),
                                     return_counts=True)
            keep = [v for v, k in zip(vals, counts) if k / n >= support]
            out[c + "_freqItems"] = np.asarray([keep], dtype=object)
        return Frame(out)

    freqItems = freq_items


def _rank(x, w):
    """Average ranks of the valid entries (invalid slots get rank 0 and are
    zero-weighted by the caller anyway)."""
    xn = np.asarray(x)
    keep = np.asarray(w) > 0
    import scipy.stats  # available via sklearn dependency

    ranks = np.zeros_like(xn)
    ranks[keep] = scipy.stats.rankdata(xn[keep])
    return jnp.asarray(ranks, x.dtype)
