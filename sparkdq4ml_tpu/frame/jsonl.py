"""JSON-lines reader/writer — Spark's default ``json`` source format (one
object per line; ``multiLine=true`` reads a single top-level JSON array).

Schema is inferred the Spark way: the column set is the union of keys over
all records; a column whose values are all integral reads as int, any
float promotes to double, any string/bool/nested value makes it a host
object column; missing keys are null (NaN numeric / None object). Nested
objects and arrays stay as host Python objects (the engine's string-side
boundary — scalars live in HBM, structure stays on the host), where Spark
would infer struct/array types.
"""

from __future__ import annotations

import json
import math
import os

import numpy as np

from ..config import float_dtype
from .frame import Frame, list_column


def _records_from_file(path: str, multi_line: bool) -> list[dict]:
    with open(path, "r", encoding="utf-8") as f:
        if multi_line:
            data = json.load(f)
            if not isinstance(data, list):
                raise ValueError(
                    "multiLine json must be a top-level array of objects")
            records = data
        else:
            records = []
            for line in f:
                line = line.strip()
                if not line:
                    continue
                records.append(json.loads(line))
    for r in records:
        if not isinstance(r, dict):
            raise ValueError(f"json record is not an object: {r!r}")
    return records


def read_json(path: str, multi_line: bool = False) -> Frame:
    records = _records_from_file(path, multi_line)
    names: list[str] = []
    for r in records:
        for k in r:
            if k not in names:
                names.append(k)

    data = {}
    for name in names:
        vals = [r.get(name) for r in records]
        kinds = set()
        for v in vals:
            if v is None:
                continue
            if isinstance(v, bool):
                kinds.add("bool")
            elif isinstance(v, int):
                kinds.add("int")
            elif isinstance(v, float):
                kinds.add("float")
            elif isinstance(v, str):
                kinds.add("str")
            else:
                kinds.add("object")
        if kinds <= {"int"} and all(v is not None for v in vals):
            try:
                data[name] = np.asarray(vals, np.int64)
            except OverflowError:
                # valid-JSON integers past int64: promote like a float col
                data[name] = np.asarray([float(v) for v in vals],
                                        np.dtype(float_dtype()))
        elif kinds <= {"int", "float"}:
            data[name] = np.asarray(
                [math.nan if v is None else float(v) for v in vals],
                np.dtype(float_dtype()))   # honor engine dtype (as CSV does)
        elif kinds <= {"bool"} and all(v is not None for v in vals):
            data[name] = np.asarray(vals, bool)
        else:
            data[name] = list_column(vals)
    return Frame(data)


def write_json(frame, path: str) -> None:
    """One JSON object per line, valid rows only; NaN → null (Spark
    writes nulls, and NaN is this engine's numeric null)."""
    d = frame.to_pydict()
    names = frame.columns
    n = len(next(iter(d.values()))) if d else 0
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)

    def conv(v):
        if v is None:
            return None
        if isinstance(v, (np.floating, float)):
            # NaN/±Inf have no JSON representation → null, at EVERY depth
            return float(v) if math.isfinite(v) else None
        if isinstance(v, (np.bool_, bool)):
            return bool(v)
        if isinstance(v, (np.integer, int)):
            return int(v)
        if isinstance(v, np.ndarray):
            return [conv(x) for x in v.tolist()]
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        return v

    with open(path, "w", encoding="utf-8") as f:
        for i in range(n):
            f.write(json.dumps({name: conv(d[name][i]) for name in names})
                    + "\n")
