"""Columnar Frame: the framework's ``Dataset<Row>`` equivalent.

Design (SURVEY.md §7 step 1, TPU-first):

* a Frame is a dict of named columns — device arrays of shape ``(n,)`` (scalar
  columns) or ``(n, d)`` (vector columns, e.g. VectorAssembler output) — plus
  a boolean **validity mask** of shape ``(n,)``.
* ``filter`` ANDs into the mask instead of gathering rows, so every array keeps
  a static shape and everything downstream stays jit/XLA-friendly. All
  reductions (count, means, fit statistics) are mask-weighted; the golden DQ
  row counts (SURVEY.md §2.3: 40→34→24 etc.) are the regression tests that the
  mask never leaks.
* Spark's lazy DAG is deliberately **not** replicated: XLA's jit tracing and
  fusion provide the equivalent optimization, so eager column ops are the
  idiomatic design (SURVEY.md §7 preamble).

String columns are host-side numpy object arrays (TPUs do not hold strings);
numeric columns live in device memory.

Covers the Dataset API surface the reference app exercises:
``withColumnRenamed`` (`DataQuality4MachineLearningApp.java:58-59`),
``withColumn`` + ``callUDF`` (`:68-69,86-87`), ``show``/``printSchema``
(`:63,72-73,81-83,93-95,114-115`), temp views + SQL filtering (`:76-78,88-90`),
label-column copy (`:101`).
"""

from __future__ import annotations

import io
import logging
import threading
from typing import Iterable, Mapping, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, float_dtype, int_dtype
from ..ops.expressions import Col, Expr, spark_type_name
from ..utils.debug import ensure_backend
from ..utils.observability import op_span
from ..utils.profiling import counters

logger = logging.getLogger("sparkdq4ml_tpu.frame")

# Pipeline flushes serialize PER FRAME (``Frame._lock``): frames were
# thread-safe-immutable before the lazy layer, and must stay observably
# so — but that is a per-object invariant, and a frame's flush touches
# only its own ``_data_store``/``_mask_store``/``_pending`` (stores are
# immutable snapshots; sibling frames replaying a shared prefix each
# publish their OWN result). A global flush lock would also serialize
# UNRELATED frames' flushes across serving workers — exactly the
# overlap the cross-request coalescer (serve/coalesce.py) exists to
# exploit: its batch leader holds its frame's lock through the hold
# window, and followers must be able to reach their own dispatches
# meanwhile. Inside a frame's lock, stores publish BEFORE _pending
# clears, so the unlocked fast-path check in the _data/_mask getters
# can never see "no pending" with stale stores. Concurrency of the
# device work itself needs no global lock: unsharded programs are
# single-device (thread-safe jit dispatch), and sharded flushes
# serialize on the collective lock (parallel/mesh.py) like every other
# multi-device program. _LOCK_FILL guards only the lazy per-frame lock
# creation — frames are minted on every op, so the hot construction
# paths must not pay an RLock allocation each.
_LOCK_FILL = threading.Lock()


def _is_device_error(e: BaseException) -> bool:
    """The retryable device-fault class of the flush ladder: exactly what
    a real XLA fault (OOM, interconnect reset) or an injected
    ``pipeline_flush:device_error`` surfaces as."""
    return isinstance(e, jax.errors.JaxRuntimeError)


ColumnLike = Union[Expr, jnp.ndarray, np.ndarray, Sequence]


def list_column(items) -> np.ndarray:
    """PUBLIC constructor for a ragged list column (token lists, item
    baskets): a 1-D object array with one list per row. ``np.asarray``
    would collapse equal-length lists into a 2-D array; explicit slot
    assignment keeps the ragged shape. Use with ``Frame({...: list_column(
    rows)})`` for any Tokenizer/Word2Vec/FPGrowth-style input."""
    arr = np.empty(len(items), dtype=object)
    for i, it in enumerate(items):
        arr[i] = it
    return arr


def _is_string_col(arr) -> bool:
    return isinstance(arr, np.ndarray) and arr.dtype == object


def lexsort_keys(arrays, ascending, nulls_first):
    """THE lexsort component construction for row ordering — shared by the
    host ``Frame.sort`` path and the grouped engine's CPU sort plan
    (``ops/segments._host_sort_plan``), so null placement and direction
    semantics cannot drift between them.

    ``arrays`` are per-key numpy arrays (original key order); returns the
    ``np.lexsort`` key list. Per key, appended last = higher priority:
    the null flag partitions each key before its values order within
    (False sorts first, so nulls-first wants nulls=False). Default null
    placement (``nulls_first[i] is None``) is Spark's: first ascending,
    last descending. NaN is the numeric null; None the string null;
    descending string keys are not supported (raises)."""
    keys = []
    for k, a, nf in zip(reversed(arrays), reversed(ascending),
                        reversed(nulls_first)):
        if nf is None:
            nf = a                 # Spark default: asc→first, desc→last
        k = np.asarray(k)
        if k.dtype == object:
            if not a:
                raise ValueError("descending sort on string columns is "
                                 "not supported")
            null_flag = np.asarray([x is None for x in k], bool)
            keys.append(np.asarray([x if x is not None else "" for x in k],
                                   dtype=object))
        else:
            if k.dtype == np.bool_:
                k = k.astype(np.int8)   # numpy forbids unary minus on bool
            null_flag = np.isnan(k) if np.issubdtype(
                k.dtype, np.floating) else np.zeros(len(k), bool)
            v = -k if not a else k
            # NaN would float to the end inside lexsort regardless of
            # the flag key; neutralize it so the flag alone decides
            keys.append(np.where(null_flag, 0.0, v)
                        if null_flag.any() else v)
        keys.append(~null_flag if nf else null_flag)
    return keys


def _vector_join_plan(lcols, rcols, li, ri, how, build_left=False):
    """Vectorized hash-join *plan* for all-numeric keys — (lpairs, rpairs)
    row-index arrays, or None when ineligible (non-finite float keys, or
    integers float64 can't hold exactly).

    ``build_left`` (cost-based optimizer hint, inner joins only): sort
    the LEFT side instead of the right — the win when the left is the
    small side (the default plan's stable argsort runs over the right).
    Emission stays bit-identical: inner-join emission order IS the
    (left, right)-lexicographic pair order (left rows ascend, each with
    its right matches ascending), so the swapped plan's pairs
    re-canonicalize with one lexsort.

    The Spark analogue of this step is the driver's shuffle planning; the
    dict-based fallback in :meth:`Frame.join` is interpreter-bound at ~10⁶
    rows, while this path is pure numpy: single-key joins use the float64
    key values directly as sortable ids, multi-key joins assign integer
    group ids with ONE lexsort over the concatenated rows (np.unique(axis=0)
    would be ~5× slower), then one stable argsort of the right ids +
    run-length-encoded binary-search group lookups — emitting pairs in
    exactly the fallback's order (left rows in order, each with its right
    matches in right order; unmatched right rows appended in order for
    right/outer).

    Micro-bench (this machine, 10⁶-row inner join, int keys, ~1 match/row):
    dict plan ~2.0 s, this plan ~0.6 s (3.5×); the gap widens with match
    multiplicity since pair emission here is ``np.repeat``, not ``list.append``.
    """
    if build_left and how == "inner":
        swapped = _vector_join_plan(rcols, lcols, ri, li, "inner")
        if swapped is None:
            return None
        r_sw, l_sw = swapped          # swapped call: "left" = our right
        order = np.lexsort((r_sw, l_sw))   # primary: true left index
        return (l_sw[order].astype(np.int64),
                r_sw[order].astype(np.int64))

    def to64(c):
        c64 = c.astype(np.float64)
        if np.issubdtype(c.dtype, np.floating):
            return c64, bool(np.isfinite(c64).all())
        # integer keys: require an exact float64 round-trip (>2^53 ids lose
        # precision and could alias distinct keys)
        return c64, bool(np.array_equal(c64.astype(c.dtype), c))

    conv = [to64(c) for c in lcols + rcols]
    if not all(ok for _, ok in conv):
        return None
    k = len(lcols)
    nl = li.size

    if k == 1:
        # single key: the float64 values themselves are the sortable ids
        lid, rid = conv[0][0], conv[1][0]
    else:
        # multi-key: group ids via one lexsort over the concatenated rows
        # (np.unique(axis=0)'s void-view sort is ~5× slower than this)
        cols = [np.concatenate([conv[j][0], conv[k + j][0]])
                for j in range(k)]
        perm = np.lexsort(cols[::-1])
        newg = np.zeros(perm.size, bool)
        if perm.size:
            newg[0] = True
            for c in cols:
                cs = c[perm]
                newg[1:] |= cs[1:] != cs[:-1]
        inv = np.empty(perm.size, np.int64)
        inv[perm] = np.cumsum(newg) - 1
        lid, rid = inv[:nl], inv[nl:]
    order = np.argsort(rid, kind="stable")      # groups keep right order
    rid_sorted = rid[order]
    # run-length encode the sorted right keys: one binary search into the
    # distinct values + O(1) group offset/count lookups (two full
    # searchsorted calls over all rows would dominate the plan otherwise)
    if rid_sorted.size:
        bound = np.empty(rid_sorted.size, bool)
        bound[0] = True
        bound[1:] = rid_sorted[1:] != rid_sorted[:-1]
        gstart = np.nonzero(bound)[0]
        gvals = rid_sorted[gstart]
        gcnt = np.diff(np.append(gstart, rid_sorted.size))
        pos = np.minimum(np.searchsorted(gvals, lid), gvals.size - 1)
        hit = gvals[pos] == lid
        start = np.where(hit, gstart[pos], 0)
        counts = np.where(hit, gcnt[pos], 0)
    else:
        start = np.zeros(lid.size, np.int64)
        counts = np.zeros(lid.size, np.int64)

    if how == "left_semi":
        hit = counts > 0
        return li[hit], ri[order[start[hit]]]
    if how == "left_anti":
        miss = counts == 0
        return li[miss], np.full(int(miss.sum()), -1, np.int64)

    ecounts = counts
    if how in ("left", "outer"):                # unmatched left → one -1 row
        ecounts = np.maximum(counts, 1)
    total = int(ecounts.sum())
    lp = np.repeat(li, ecounts)
    group_first = np.cumsum(ecounts) - ecounts
    within = np.arange(total) - np.repeat(group_first, ecounts)
    flat = np.repeat(start, ecounts) + within
    if order.size:
        rp = ri[order[np.minimum(flat, order.size - 1)]]
    else:
        rp = np.full(total, -1, np.int64)
    if how in ("left", "outer"):
        rp = np.where(np.repeat(counts == 0, ecounts), -1, rp)

    if how in ("right", "outer"):               # append unmatched right rows
        lid_sorted = np.sort(lid)
        if lid_sorted.size:
            pos = np.searchsorted(lid_sorted, rid)
            matched = (pos < lid_sorted.size) & \
                (lid_sorted[np.minimum(pos, lid_sorted.size - 1)] == rid)
        else:
            matched = np.zeros(rid.size, bool)
        extra = ri[~matched]
        lp = np.concatenate([lp, np.full(extra.size, -1, np.int64)])
        rp = np.concatenate([rp, extra])
    return lp.astype(np.int64), rp.astype(np.int64)


def _as_column(values, n: Optional[int] = None):
    """Coerce raw values into a column array (device array, or host object array)."""
    if isinstance(values, np.ndarray) and values.dtype == object:
        arr = values
    elif isinstance(values, np.ndarray) and values.dtype.kind in ("U", "S"):
        # numpy unicode/bytes arrays are string columns: host object array
        arr = values.astype(object)
    elif isinstance(values, (jnp.ndarray, np.ndarray)):
        arr = jnp.asarray(values)
    else:
        values = list(values)
        if values and any(isinstance(v, str) for v in values):
            arr = np.asarray(values, dtype=object)
        else:
            np_arr = np.asarray(values)
            if np_arr.dtype == object:
                # e.g. [None, "a"] (null-first string groups) — host column
                arr = np_arr
            else:
                if np_arr.dtype == np.float64:
                    np_arr = np_arr.astype(np.dtype(float_dtype()))
                elif np_arr.dtype == np.int64:
                    np_arr = np_arr.astype(np.dtype(int_dtype()))
                arr = jnp.asarray(np_arr)
    if n is not None and arr.shape[0] != n:
        raise ValueError(f"column length {arr.shape[0]} != frame length {n}")
    return arr


class Frame:
    """Immutable columnar frame with a validity mask (see module docstring).

    Pipeline compiler (``ops/compiler.py``): consecutive *compilable*
    ``with_column``/``with_columns``/``filter`` calls do not dispatch one
    XLA computation each — they accumulate as pending steps
    (``_pending``) and materialize as ONE jitted program at the first
    read of ``_data``/``_mask`` (any action, aggregation, sort, join,
    fit, or host boundary). ``select`` fuses its own projection
    expressions into the same program. Externally frames stay immutable
    and eager-equivalent: the flush is a cache fill, semantics are
    bit-identical, and ``config.pipeline = False``
    (``spark.pipeline.enabled``) restores the exact per-op eager path.
    """

    _alias: Optional[str] = None  # set by .alias(); not inherited by _with
    _pending: tuple = ()          # deferred pipeline steps (see _defer)
    _flush_lock = None            # per-frame flush serializer (see _lock)
    # Row-shard layout descriptor (parallel/shard.py ShardedStore), or
    # None for the single-device layout. A sharded frame's columns/mask
    # are global arrays padded to devices×bucket slots with a False mask
    # tail, laid out row-sharded over the mesh; masked-slot semantics
    # make every consumer correct unchanged, while the flush path lowers
    # pending steps as ONE shard_map program. Propagates through
    # _with/_defer (same layout); ops that rebuild a compact Frame
    # (sort, join, groupBy output, explode, union) return single-device
    # frames — re-shard at the next ingest/explicit shard_frame call.
    _shard = None

    # _data/_mask are flush-on-read properties so EVERY consumer — frame
    # methods, aggregates, models, tests poking internals — sees the
    # materialized state without knowing the pipeline layer exists.
    @property
    def _data(self) -> dict:
        if self._pending:
            self._flush()
        return self._data_store

    @_data.setter
    def _data(self, value: dict) -> None:
        self._data_store = value

    @property
    def _mask(self):
        if self._pending:
            self._flush()
        return self._mask_store

    @_mask.setter
    def _mask(self, value) -> None:
        self._mask_store = value

    def __init__(self, columns: Mapping[str, ColumnLike], mask=None):
        # Library-boundary liveness: a Frame built WITHOUT a TpuSession is
        # the first jnp touch in direct-library use, and on a wedged
        # tunneled-TPU box an unguarded first touch hangs PJRT init
        # forever. ensure_backend probes + bounds that first init exactly
        # like session start does, and is a single cached global read on
        # every call after the first (and when a backend is already up).
        ensure_backend()
        self._data: dict[str, object] = {}
        n = None
        for name, values in columns.items():
            arr = _as_column(values, n)
            n = arr.shape[0] if n is None else n
            self._data[name] = arr
        self._n = 0 if n is None else int(n)
        if mask is None:
            self._mask = jnp.ones((self._n,), dtype=jnp.bool_)
        else:
            self._mask = jnp.asarray(mask, jnp.bool_)
            if self._mask.shape != (self._n,):
                raise ValueError("mask shape mismatch")

    # -- construction ------------------------------------------------------
    @classmethod
    def from_rows(cls, rows: Iterable[Sequence], names: Sequence[str]) -> "Frame":
        rows = list(rows)  # an exhausted iterator must still yield named cols
        cols = list(zip(*rows)) if rows else [[] for _ in names]
        return cls({name: list(vals) for name, vals in zip(names, cols)})

    def _with(self, data=None, mask=None) -> "Frame":
        f = Frame.__new__(Frame)
        f._data = dict(self._data if data is None else data)
        f._mask = self._mask if mask is None else mask
        f._n = self._n
        f._shard = self._shard
        return f

    # -- pipeline compiler plumbing (ops/compiler.py) ----------------------
    def _lock(self):
        """This frame's flush serializer, created on first need (every
        frame op mints frames — the construction paths must not pay an
        RLock each). Reentrant: the flush ladder re-enters through eager
        replay on the same frame. Per-frame by design — see the
        _LOCK_FILL comment at module top."""
        lk = self._flush_lock
        if lk is None:
            with _LOCK_FILL:
                lk = self._flush_lock
                if lk is None:
                    lk = self._flush_lock = threading.RLock()
        return lk

    def _defer(self, step) -> "Frame":
        """New frame sharing this one's base columns/mask with ``step``
        appended to the pending pipeline. Flush never mutates a shared
        store in place, so sharing is safe; compilable steps are pure, so
        sibling frames replaying a shared prefix stay correct."""
        f = Frame.__new__(Frame)
        with self._lock():
            # consistent (stores, pending) snapshot: racing a concurrent
            # flush of this frame unlocked could pair the POST-flush
            # stores with the PRE-flush step list — the child would then
            # double-apply every step. The PARENT's lock is the right
            # one (it serializes this read against the parent's own
            # flush); the child lazily mints its own.
            f._data_store = self._data_store
            f._mask_store = self._mask_store
            f._pending = self._pending + (step,)
            f._shard = self._shard
        f._n = self._n
        return f

    def _pending_names(self) -> list[str]:
        names: list[str] = []
        for s in self._pending:
            if s[0] == "with_column":
                names.append(s[1])
            elif s[0] == "with_columns":
                names.extend(n for n, _ in s[1])
        return names

    def _pipe_schema(self):
        # lazy: only columns the checked expression references get a
        # dtype probe — deferral stays O(expr), not O(frame width)
        from ..ops.compiler import LazySchema

        return LazySchema(self._data_store, self._pending_names())

    def _can_defer(self, *exprs) -> bool:
        if not config.pipeline or self._n == 0:
            return False
        from ..ops.compiler import is_compilable

        schema = self._pipe_schema()
        return all(isinstance(e, Expr) and is_compilable(e, schema)
                   for e in exprs)

    def _flush(self) -> None:
        """Materialize the pending pipeline steps as one compiled program
        (or, on any compiler failure, by eager per-op replay — the
        optimization layer must never change results).

        ``_pending`` is cleared only AFTER a successful materialization:
        if even the eager replay raises (a genuinely bad expression), the
        exception propagates with the steps intact, so every subsequent
        read raises the same error instead of silently serving the
        pre-op frame state. Flushes serialize on this frame's own lock
        (``_lock`` — per frame, so UNRELATED frames' flushes overlap and
        the serving tier's coalescer can rendezvous them) and
        publish the new stores BEFORE clearing ``_pending`` — a reader
        racing the unlocked getter fast-path either re-enters here (and
        finds nothing left to do) or sees the fully flushed state; never
        stale stores, never a double-applied step.

        Degradation ladder (ISSUE 11): a DEVICE fault inside the fused
        dispatch — a real ``XlaRuntimeError``, or an injected
        ``pipeline_flush`` fault from ``utils.faults`` — routes through
        :meth:`_flush_ladder` (retry via the PR-1 recovery engine, then
        eager per-op replay, counted ``pipeline.fault_fallback``); steps
        stay in ``_pending`` until a rung succeeds, so a failed rung can
        never half-apply. With no fault plan installed the extra cost is
        one ``is None`` check (test-pinned)."""
        from ..ops.compiler import PipelineError, run_pipeline
        from ..utils import faults as _faults

        with self._lock():
            steps = self._pending
            if not steps:
                return
            try:
                new_data, new_mask, _ = run_pipeline(
                    self._data_store, self._mask_store, self._n, steps,
                    shard=self._shard)
                if _faults.active() is not None:   # chaos armed
                    # Surface async-dispatched device faults INSIDE this
                    # try while chaos is armed (jax dispatch is async; an
                    # unsynced fault would otherwise raise at a later
                    # host read, past the ladder, with _pending already
                    # cleared). The no-chaos path deliberately keeps the
                    # flush un-synced — one sync per flush would
                    # serialize the async pipeline; a real accelerator
                    # fault then surfaces at the consumer's first host
                    # read as a failed (never silently wrong) query, and
                    # the SERVING tier's requeue ladder still catches it
                    # there (JaxRuntimeError is its retryable class).
                    jax.block_until_ready((new_data, new_mask))
                    new_data, new_mask = self._chaos_validate(
                        steps, new_data, new_mask)
            except PipelineError as e:
                logger.debug("pipeline flush fell back to eager replay: %s",
                             e)
                new_data, new_mask = self._eager_replay(steps)
            except Exception as e:
                if not _is_device_error(e):
                    raise
                new_data, new_mask = self._flush_ladder(
                    steps, first_cause=e)
            self._data_store = new_data
            self._mask_store = new_mask
            self._pending = ()

    def _chaos_validate(self, steps, new_data, new_mask):
        """NaN-corruption arm of the ``pipeline_flush`` ladder — runs only
        under an installed fault plan with a ``nan`` spec at this site.
        The produced columns are corrupted through ``faults.corrupt`` (a
        flaky-transfer model) and checked with ``check_finite``; a
        detected poisoning re-runs the whole flush through the resilient
        ladder. The finiteness check is sound for the chaos suite's own
        workloads (PR-1 convention: chaos tests detect their own injected
        NaNs); workloads whose flush outputs legitimately carry NaN take
        the ladder's extra replays but keep their correct eager result."""
        from ..utils import faults as _faults

        plan = _faults.active()
        if plan is None or not plan._has("pipeline_flush", ("nan",)):
            return new_data, new_mask
        from ..utils import recovery as _rec

        new_data, changed = self._corrupt_changed(new_data)
        if _rec.check_finite(changed):
            return new_data, new_mask
        # rung "dispatch" = the pre-ladder flush attempt, distinct from
        # the ladder's own rung="primary" retry events (no double-log)
        _rec.RECOVERY_LOG.record("pipeline_flush", "retry", attempt=1,
                                 rung="dispatch",
                                 cause="non-finite result")
        return self._flush_ladder(steps)

    def _corrupt_changed(self, new_data):
        """The one corrupt-merge step of the nan arm, shared by the first
        flush (:meth:`_chaos_validate`) and the ladder's retries: corrupt
        the columns this flush PRODUCED (identity vs the pre-flush store)
        and merge any poisoning back. Returns ``(new_data, changed)`` —
        ``changed`` is the validation target."""
        from ..utils import faults as _faults

        changed = {k: v for k, v in new_data.items()
                   if v is not self._data_store.get(k)}
        poisoned = _faults.corrupt("pipeline_flush", changed)
        if poisoned is not changed:
            new_data = {**new_data, **poisoned}
            changed = poisoned
        return new_data, changed

    def _flush_ladder(self, steps, first_cause=None):
        """The ``pipeline_flush`` degradation ladder: retry the fused
        program under ``recovery.resilient_call`` (per-site
        ``spark.recovery.pipeline_flush.*`` policy), then degrade one
        level to eager per-op replay (``pipeline.fault_fallback``) — a
        fault costs one rung, never the query. Runs under this frame's
        flush lock (held by the caller), so chaos-path backoff sleeps
        briefly serialize THIS frame's other flushes — bounded by the
        retry policy; unrelated frames are unaffected."""
        from ..ops.compiler import PipelineError, run_pipeline
        from ..utils import faults as _faults
        from ..utils import recovery as _rec
        from ..utils.profiling import counters

        plan = _faults.active()
        nan_armed = plan is not None and plan._has("pipeline_flush",
                                                   ("nan",))
        shard_store = self._shard
        site = "pipeline_flush" if shard_store is None else "shard_flush"

        def fused():
            new_data, new_mask, _ = run_pipeline(
                self._data_store, self._mask_store, self._n, steps,
                shard=shard_store)
            if not nan_armed:
                return new_data, new_mask, None
            new_data, changed = self._corrupt_changed(new_data)
            return new_data, new_mask, changed

        degraded: list = []

        def gather():
            # shard_flush ladder rung 2 ("a device fault on one shard"):
            # re-place the columns single-device and replay the SAME
            # steps through the unsharded fused program; the frame drops
            # its sharded layout (the caller below) — a fault costs this
            # frame its distribution, never the query.
            from ..parallel.shard import gather_store

            counters.increment("pipeline.shard_gather")
            data, mask = gather_store(self)
            new_data, new_mask, _ = run_pipeline(data, mask, self._n,
                                                 steps)
            degraded.append(True)
            if not nan_armed:
                return new_data, new_mask, None
            new_data, changed = self._corrupt_changed(new_data)
            return new_data, new_mask, changed

        def eager():
            counters.increment("pipeline.fault_fallback")
            d, m = self._eager_replay(steps)
            return d, m, None

        validate = ((lambda out: out[2] is None
                     or _rec.check_finite(out[2]))
                    if nan_armed else None)
        if first_cause is not None:
            # the PRE-ladder dispatch that failed — rung "dispatch", so a
            # persistent fault's ladder retries (rung "primary") never
            # read as duplicates of this event
            _rec.RECOVERY_LOG.record(
                site, "retry", attempt=1, rung="dispatch",
                cause=f"{type(first_cause).__name__}: {first_cause}")
        fallbacks = ((("gather", gather),) if shard_store is not None
                     else ()) + (("eager", eager),)
        try:
            new_data, new_mask, _ = _rec.resilient_call(
                fused, site=site, validate=validate,
                fallbacks=fallbacks)
            if degraded:
                self._shard = None
            return new_data, new_mask
        except PipelineError:
            # structural compile failure inside the ladder: eager replay
            # is the answer on every path
            d, m, _ = eager()
            return d, m

    def _eager_replay(self, steps):
        """Apply pipeline steps through the eager code paths (fallback)."""
        f = self._with(data=self._data_store, mask=self._mask_store)
        for s in steps:
            if s[0] == "with_column":
                f = f._with_column_eager(s[1], s[2])
            elif s[0] == "with_columns":
                f = f._with_columns_eager(dict(s[1]))
            else:
                f = f._filter_eager(s[1])
        return f._data_store, f._mask_store

    # -- basic introspection ----------------------------------------------
    @property
    def columns(self) -> list[str]:
        if not self._pending:
            return list(self._data_store)
        # pending with_column targets are columns too — WITHOUT forcing a
        # flush (column-name introspection is not a materialization point)
        out = list(self._data_store)
        seen = set(out)
        for n in self._pending_names():
            if n not in seen:
                seen.add(n)
                out.append(n)
        return out

    @property
    def num_slots(self) -> int:
        """Physical row slots (including masked-out rows). Static under jit."""
        return self._n

    @property
    def mask(self) -> jnp.ndarray:
        return self._mask

    def dtypes(self) -> list[tuple[str, str]]:
        return [(name, spark_type_name(np.dtype(arr.dtype)) if not _is_string_col(arr)
                 else "string") for name, arr in self._data.items()]

    def schema_string(self) -> str:
        """``printSchema`` text, matching Spark's output shape."""
        out = io.StringIO()
        out.write("root\n")
        for name, arr in self._data.items():
            if _is_string_col(arr):
                tname = "string"
            elif arr.ndim == 2:
                tname = "vector"
            else:
                tname = spark_type_name(np.dtype(arr.dtype))
            out.write(f" |-- {name}: {tname} (nullable = true)\n")
        return out.getvalue()

    def print_schema(self) -> None:
        print(self.schema_string(), end="")

    printSchema = print_schema  # Spark-style alias

    # -- column access -----------------------------------------------------
    def _column_values(self, name: str):
        try:
            return self._data[name]
        except KeyError:
            raise KeyError(
                f"no column {name!r}; columns: {self.columns}") from None

    def col(self, name: str) -> Col:
        # raise early on unknown names, like Spark's analyzer — a name
        # check, not a value read, so a pending pipeline stays pending
        if name not in self.columns:
            raise KeyError(
                f"no column {name!r}; columns: {self.columns}")
        return Col(name)

    def __getitem__(self, name: str) -> Col:
        return self.col(name)

    def _eval(self, expr_or_values):
        if isinstance(expr_or_values, Expr):
            return expr_or_values.eval(self)
        if self._shard is not None:
            # raw columns sized to the TRUE row count (what a caller who
            # never heard of sharding naturally provides) pad + place
            # into the sharded layout; slot-length arrays pass through
            arr = _as_column(expr_or_values)
            if arr.shape[0] == self._shard.rows and \
                    self._shard.rows != self._n:
                from ..parallel.shard import place_column

                return place_column(arr, self._shard)
            if arr.shape[0] != self._n:
                raise ValueError(f"column length {arr.shape[0]} != frame "
                                 f"length {self._shard.rows} (sharded "
                                 f"slots {self._n})")
            return arr
        return _as_column(expr_or_values, self._n)

    # -- transformations (each returns a new Frame) ------------------------
    # Observability: the op_span decorator is a no-op (one flag read) until
    # spark.observability.enabled turns the tracer on; then each decorated
    # op records a span with rows in/out (static shapes — never a device
    # read, so the "no host syncs" hygiene of the fused paths holds).
    @op_span("frame.with_column")
    def with_column(self, name: str, values: ColumnLike) -> "Frame":
        """``withColumn`` — add or replace a column from an expression/array.

        A compilable expression defers into the fused pipeline (one XLA
        program per chain at the next materialization point) instead of
        dispatching its own computation; see the class docstring."""
        if isinstance(values, Expr) and self._can_defer(values):
            return self._defer(("with_column", name, values))
        return self._with_column_eager(name, values)

    def _with_column_eager(self, name: str, values: ColumnLike) -> "Frame":
        data = dict(self._data)
        data[name] = self._eval(values)
        return self._with(data=data)

    withColumn = with_column

    def with_column_renamed(self, old: str, new: str) -> "Frame":
        """``withColumnRenamed`` — no-op if ``old`` is absent (Spark semantics)."""
        if old not in self._data:
            return self
        data = {(new if k == old else k): v for k, v in self._data.items()}
        return self._with(data=data)

    withColumnRenamed = with_column_renamed

    def with_columns_renamed(self, mapping: Mapping[str, str]) -> "Frame":
        """Spark 3.4's ``withColumnsRenamed`` — batch rename; absent keys
        are no-ops (same semantics as the single-column form).

        A rename target that collides with a surviving column raises:
        Spark would produce duplicate column names, which this engine's
        dict-backed frame cannot represent — silently keeping one of the
        two (the old behavior) lost data with no error (ADVICE.md #4).
        Swaps (``{'a': 'b', 'b': 'a'}``) remain legal: the collision test
        only counts columns that keep their name."""
        renamed_away = {k for k, new in mapping.items()
                        if k in self._data and new != k}
        data: dict = {}
        for k, v in self._data.items():
            nk = mapping.get(k, k)
            if nk in data or (nk != k and nk in self._data
                              and nk not in renamed_away):
                raise ValueError(
                    f"withColumnsRenamed: rename target {nk!r} collides "
                    "with an existing column; the engine cannot hold "
                    "duplicate column names (rename or drop the other "
                    f"{nk!r} first)")
            data[nk] = v
        return self._with(data=data)

    withColumnsRenamed = with_columns_renamed

    def transform(self, func, *args, **kwargs) -> "Frame":
        """Spark's ``df.transform(fn)`` — chainable function application:
        ``df.transform(clean).transform(label)`` reads pipeline-style."""
        out = func(self, *args, **kwargs)
        if not isinstance(out, Frame):
            raise TypeError("transform function must return a Frame, got "
                            f"{type(out).__name__}")
        return out

    def unpivot(self, ids, values=None, variable_column_name: str = "variable",
                value_column_name: str = "value") -> "Frame":
        """Spark 3.4's ``unpivot``/``melt``: wide → long. ``ids`` stay as
        identifier columns; each of ``values`` (default: every non-id
        numeric column) contributes one output row per input row, tagged
        with its column name. Row-major like Spark: input row 0's value
        columns first, then row 1's. Host-side reshape at the boundary —
        the long result lands as fresh device columns."""
        ids = [ids] if isinstance(ids, str) else list(ids)
        if values is None:
            values = [c for c in self.columns if c not in ids]
        values = [values] if isinstance(values, str) else list(values)
        if not values:
            raise ValueError("unpivot requires at least one value column")
        for c in ids + values:
            if c not in self.columns:
                raise ValueError(f"unpivot column {c!r} is not a column")
        d = self.to_pydict()
        n = len(next(iter(d.values()))) if d else 0
        k = len(values)
        data: dict = {}
        for c in ids:
            col = np.asarray(d[c])
            data[c] = (np.repeat(col, k) if col.dtype != object
                       else np.asarray([x for x in col for _ in range(k)],
                                       dtype=object))
        data[variable_column_name] = np.asarray(values * n, dtype=object) \
            if n else np.asarray([], dtype=object)
        vals = np.column_stack(
            [np.asarray(d[c], np.float64) for c in values]) \
            if n else np.zeros((0, k))
        data[value_column_name] = vals.ravel()
        return Frame(data)

    melt = unpivot

    @op_span("frame.select")
    def select(self, *exprs: Union[str, Expr]) -> "Frame":
        from ..ops.expressions import Alias, Explode, JsonTuple

        # flatten list/tuple items so `select(df.colRegex("`x.*`"))` works
        flat = []
        for e in exprs:
            if isinstance(e, (list, tuple)):
                flat.extend(e)
            else:
                flat.append(e)
        exprs = tuple(flat)
        # Spark allows ONE generator (explode) per select: resolve the
        # scalar columns first, then expand rows at the host boundary.
        # Only a bare Explode or an Alias over one counts — any other
        # wrapper (Cast(Explode), arithmetic) falls through to eval(),
        # whose generator error explains the restriction.
        gens = [e for e in exprs if isinstance(e, Explode)
                or (isinstance(e, Alias) and isinstance(e.child, Explode))]
        if len(gens) > 1:
            raise ValueError("only one explode() per select (Spark rule)")
        # Fused select+filter: compilable projection expressions evaluate
        # inside ONE compiled program together with any pending
        # with_column/filter steps (the SQL SELECT-list + WHERE hot path).
        pre = self._precompute_select(exprs, gens)
        data: dict[str, object] = {}
        for e in exprs:
            if isinstance(e, str):
                if e == "*":
                    data.update(self._data)
                    continue
                e = Col(e)
            # identity, not `in`: Expr.__eq__ builds a BinOp (truthy), so
            # membership tests over Expr lists must never use ==
            if any(e is g for g in gens):
                continue
            if isinstance(e, JsonTuple):
                # multi-column generator: no row multiplication, so it
                # expands inline (c0…cN) unlike the explode family
                data.update(e.columns(self))
                continue
            if id(e) in pre:
                data[e.name] = pre[id(e)]
                continue
            data[e.name] = e.eval(self)
        if not gens:
            return self._with(data=data)
        g = gens[0]
        inner = g if isinstance(g, Explode) else g.child
        src_vals = inner.source_values(self)
        # a temp slot keeps an explicitly-selected source column (or one
        # pulled in via '*') in the output, like Spark
        tmp = "__explode_source__"
        while tmp in data:
            tmp += "_"
        return self._with(data={**data, tmp: src_vals}).explode(
            tmp, g.name, keep_nulls=inner.outer,
            position_col="pos" if inner.with_position else None)

    def _precompute_select(self, exprs, gens) -> dict:
        """Evaluate compilable select expressions (plus any pending
        pipeline steps) in one compiled program; returns ``{id(expr):
        array}`` for the loop in :meth:`select` to consume. Empty dict ⇒
        nothing fused (caller falls through to per-expression eval, which
        flushes pending steps on first `_data` read)."""
        if not config.pipeline or self._n == 0:
            return {}
        from ..ops.compiler import (PipelineError, is_compilable,
                                    run_pipeline)

        from ..ops.expressions import JsonTuple

        schema = self._pipe_schema()
        cand = [e for e in exprs
                if isinstance(e, Expr) and not isinstance(e, JsonTuple)
                and not any(e is g for g in gens)
                and not isinstance(e, Col)          # plain refs are free
                and is_compilable(e, schema)]
        # Fusing pays when a pending chain flushes anyway or when >= 2
        # expressions share one program; a lone expression on a clean
        # frame costs the same either way — keep it eager.
        if not cand or (not self._pending and len(cand) < 2):
            return {}
        extra = [(f"__sel_{i}", e) for i, e in enumerate(cand)]
        with self._lock():
            steps = self._pending
            try:
                new_data, new_mask, extras = run_pipeline(
                    self._data_store, self._mask_store, self._n, steps,
                    extra, shard=self._shard)
            except PipelineError as e:
                logger.debug("fused select fell back to eager: %s", e)
                return {}
            except Exception as e:
                if not _is_device_error(e):
                    raise
                # device fault in the fused select: defer to the eager
                # path (per-expression eval, whose first _data read
                # re-enters the _flush ladder if the fault persists)
                from ..utils.recovery import RECOVERY_LOG

                RECOVERY_LOG.record(
                    "pipeline_flush", "fallback", rung="select",
                    cause=f"{type(e).__name__}: {e}",
                    detail="fused select deferred to eager evaluation")
                return {}
            # stores BEFORE pending — same publish ordering as _flush
            self._data_store = new_data
            self._mask_store = new_mask
            self._pending = ()
        return {id(e): extras[f"__sel_{i}"] for i, e in enumerate(cand)}

    @op_span("frame.explode")
    def explode(self, column: str, output_col: str = None,
                keep_nulls: bool = False,
                position_col: str = None) -> "Frame":
        """Spark's ``explode``: one output row per element of a list cell.

        Row multiplication is inherently dynamic-shaped, so this is a host
        boundary like join/groupBy (the "gather at the boundary" rule):
        lengths gather once, scalar columns ``np.repeat``, and the result
        is a compact new Frame. Null/empty cells drop their row (Spark's
        ``explode``); ``keep_nulls=True`` gives ``explode_outer`` (one
        null-element row instead)."""
        arr = self._data.get(column)
        if arr is None:
            raise ValueError(f"no column {column!r}")
        if not _is_string_col(arr):
            raise ValueError("explode() expects an array column (e.g. "
                             "split() or collect_list() output)")
        from ..ops.expressions import _require_array_cells

        _require_array_cells(arr, "explode")  # a str cell would silently
        # produce zero rows otherwise (plain string columns are object
        # arrays too)
        out_name = output_col or column
        idx = np.nonzero(self._host_mask())[0]
        cells = np.asarray(arr, object)[idx]
        lens = np.asarray([
            (len(c) if isinstance(c, (list, tuple, np.ndarray)) else 0)
            if c is not None else 0 for c in cells], np.int64)
        if keep_nulls:
            rep = np.maximum(lens, 1)
        else:
            rep = lens
        src = np.repeat(idx, rep)
        values = []
        positions = []
        for c, ln in zip(cells, lens):
            if ln:
                values.extend(list(c))
                positions.extend(range(ln))
            elif keep_nulls:
                values.append(None)
                positions.append(None)     # posexplode_outer: null pos
        src_dev = jnp.asarray(src) if len(src) else None  # ONE transfer
        data: dict[str, object] = {}
        for name, col_arr in self._data.items():
            if name == column:
                continue
            if _is_string_col(col_arr):
                data[name] = np.asarray(col_arr, object)[src]
            else:
                data[name] = jnp.take(jnp.asarray(col_arr),
                                      src_dev, axis=0) \
                    if len(src) else jnp.asarray(col_arr)[:0]
        # element dtype from the NON-NULL values: numeric lists land on
        # device; strings (or an all-null result, which must not flip a
        # string column to float NaN) stay host
        non_null = [v for v in values if v is not None]
        if non_null and all(isinstance(v, (int, float, np.floating,
                                           np.integer)) for v in non_null):
            data[out_name] = jnp.asarray(np.asarray(
                [np.nan if v is None else float(v) for v in values],
                np.float64), float_dtype())
        else:
            out = np.empty(len(values), object)
            for i, v in enumerate(values):
                out[i] = v
            data[out_name] = out
        if position_col is not None:
            if position_col in data:
                raise ValueError(
                    f"position column {position_col!r} collides with an "
                    "existing output column")
            if any(p is None for p in positions):
                pos_arr = jnp.asarray(np.asarray(
                    [np.nan if p is None else float(p) for p in positions],
                    np.float64), float_dtype())
            else:
                pos_arr = jnp.asarray(np.asarray(positions, np.int32))
            # Spark's posexplode order is (pos, col): rebuild with the
            # position column right before the value column
            ordered: dict[str, object] = {}
            for k, v in data.items():
                if k == out_name:
                    ordered[position_col] = pos_arr
                ordered[k] = v
            data = ordered
        return Frame(data)

    def drop(self, *names: str) -> "Frame":
        data = {k: v for k, v in self._data.items() if k not in names}
        return self._with(data=data)

    @op_span("frame.filter")
    def filter(self, condition: Union[Expr, jnp.ndarray]) -> "Frame":
        """AND a predicate into the validity mask (static shapes preserved).

        SQL three-valued logic: a NULL predicate (NaN in this engine's
        float encoding — e.g. ``array_contains`` over a null cell) drops
        the row, exactly like Spark's WHERE. A bare ``NaN.astype(bool)``
        would be True and silently keep null rows.

        A compilable predicate defers into the fused pipeline — the mask
        AND lands inside the same compiled program as the column
        expressions it rides with."""
        if isinstance(condition, Expr) and self._can_defer(condition):
            return self._defer(("filter", condition))
        return self._filter_eager(condition)

    def _filter_eager(self, condition: Union[Expr, jnp.ndarray]) -> "Frame":
        from ..ops.expressions import predicate_keep_mask

        cond = condition.eval(self) if isinstance(condition, Expr) else jnp.asarray(condition)
        keep = predicate_keep_mask(cond)
        return self._with(mask=jnp.logical_and(self._mask, keep))

    where = filter

    def limit(self, n: int) -> "Frame":
        keep = jnp.cumsum(self._mask.astype(jnp.int32)) <= n
        return self._with(mask=jnp.logical_and(self._mask, keep))

    def offset(self, n: int) -> "Frame":
        """Skip the first ``n`` valid rows (SQL OFFSET; Spark 3.4's
        ``df.offset``) — a mask update like ``limit``, no data movement."""
        keep = jnp.cumsum(self._mask.astype(jnp.int32)) > n
        return self._with(mask=jnp.logical_and(self._mask, keep))

    @op_span("frame.union")
    def union(self, other: "Frame") -> "Frame":
        if self.columns != other.columns:
            raise ValueError("union requires identical column lists")
        data = {}
        for name in self.columns:
            a, b = self._data[name], other._data[name]
            if _is_string_col(a) or _is_string_col(b):
                data[name] = np.concatenate([np.asarray(a, object), np.asarray(b, object)])
            else:
                data[name] = jnp.concatenate([jnp.asarray(a), jnp.asarray(b)])
        f = Frame(data)
        f._mask = jnp.concatenate([self._mask, other._mask])
        return f

    unionAll = union  # Spark 2.x alias (deprecated there, kept for parity)

    def union_by_name(self, other: "Frame",
                      allow_missing_columns: bool = False) -> "Frame":
        """``unionByName`` — union resolving columns by name, not position.
        With ``allow_missing_columns`` the asymmetric columns null-fill."""
        if allow_missing_columns:
            both = list(dict.fromkeys(self.columns + other.columns))

            def widen(frame):
                out = frame
                for name in both:
                    if name not in frame.columns:
                        ref_arr = (other if name in other.columns
                                   else self)._data[name]
                        if _is_string_col(ref_arr):
                            fill = np.full((frame.num_slots,), None,
                                           dtype=object)
                        else:
                            fill = jnp.full((frame.num_slots,), jnp.nan,
                                            float_dtype())
                        out = out.with_column(name, fill)
                return out.select(*both)

            return widen(self).union(widen(other))
        if set(self.columns) != set(other.columns):
            raise ValueError(
                f"unionByName: column sets differ {self.columns} vs "
                f"{other.columns}; pass allow_missing_columns=True")
        return self.union(other.select(*self.columns))

    unionByName = union_by_name

    _NULL_KEY = "\0__null__"  # NaN stand-in so null rows hash/compare equal

    def _keyed_rows(self):
        """One host gather → [(hashable null-safe key, row), ...]. NaN (the
        engine's null) maps to a sentinel so null rows match each other, as
        Spark's null-safe set ops do."""
        def norm(x):
            if isinstance(x, np.ndarray):                 # vector cell
                return tuple(norm(v) for v in x.tolist())
            if hasattr(x, "item"):
                x = x.item()
            if isinstance(x, float) and x != x:
                return Frame._NULL_KEY
            return x

        rows = self.collect()
        return [(tuple(norm(x) for x in r), r) for r in rows]

    def select_expr(self, *exprs: str) -> "Frame":
        """Spark's ``selectExpr``: SQL expression strings evaluated over
        this frame (same grammar as ``session.sql``'s select list — CAST,
        arithmetic, functions, aliases, ``*``), via a scratch catalog so
        no temp view leaks."""
        from ..sql.catalog import Catalog
        from ..sql.parser import execute

        cat = Catalog()
        cat.register("__this__", self)
        return execute(
            f"SELECT {', '.join(exprs)} FROM __this__", catalog=cat)

    selectExpr = select_expr

    def col_regex(self, pattern: str) -> list:
        """Spark's ``colRegex``: column expressions whose names match the
        (Java-style, backtick-quoted allowed) regex — pass the result
        straight to ``select`` (it flattens lists)."""
        import re as _re

        pat = pattern.strip()
        if pat.startswith("`") and pat.endswith("`"):
            pat = pat[1:-1]
        rx = _re.compile(pat)
        return [Col(c) for c in self.columns if rx.fullmatch(c)]

    colRegex = col_regex

    @property
    def schema(self) -> list[tuple[str, str]]:
        """``[(name, spark_type_name)]`` — the engine's schema form (the
        ``StructType`` analogue; same pairs as ``dtypes()``)."""
        return self.dtypes()

    @property
    def na(self) -> "_NAFunctions":
        """``df.na`` accessor (Spark ``DataFrameNaFunctions``):
        ``na.fill`` / ``na.drop`` / ``na.replace``."""
        return _NAFunctions(self)

    def intersect(self, other: "Frame") -> "Frame":
        """Distinct rows present in both frames (SQL INTERSECT, null-safe)."""
        if self.columns != other.columns:
            raise ValueError("intersect requires identical column lists")
        theirs = {k for k, _ in other._keyed_rows()}
        seen = set()
        rows = []
        for key, row in self._keyed_rows():
            if key in theirs and key not in seen:
                seen.add(key)
                rows.append(row)
        return Frame.from_rows(rows, self.columns)

    def except_all(self, other: "Frame") -> "Frame":
        """Rows of self not in other, preserving duplicates (EXCEPT ALL)."""
        if self.columns != other.columns:
            raise ValueError("exceptAll requires identical column lists")
        from collections import Counter

        budget = Counter(k for k, _ in other._keyed_rows())
        rows = []
        for key, row in self._keyed_rows():
            if budget[key] > 0:
                budget[key] -= 1
            else:
                rows.append(row)
        return Frame.from_rows(rows, self.columns)

    exceptAll = except_all

    def intersect_all(self, other: "Frame") -> "Frame":
        """Rows present in both frames, preserving duplicate counts
        (SQL INTERSECT ALL — each row appears min(count_self, count_other)
        times, null-safe like ``intersect``)."""
        if self.columns != other.columns:
            raise ValueError("intersectAll requires identical column lists")
        from collections import Counter

        budget = Counter(k for k, _ in other._keyed_rows())
        rows = []
        for key, row in self._keyed_rows():
            if budget[key] > 0:
                budget[key] -= 1
                rows.append(row)
        return Frame.from_rows(rows, self.columns)

    intersectAll = intersect_all

    def subtract(self, other: "Frame") -> "Frame":
        """Distinct rows of self not in other (SQL EXCEPT [DISTINCT])."""
        if self.columns != other.columns:
            raise ValueError("subtract requires identical column lists")
        theirs = {k for k, _ in other._keyed_rows()}
        seen = set()
        rows = []
        for key, row in self._keyed_rows():
            if key not in theirs and key not in seen:
                seen.add(key)
                rows.append(row)
        return Frame.from_rows(rows, self.columns)

    def replace(self, to_replace, value=None, subset=None) -> "Frame":
        """``df.replace`` — substitute exact values in [subset] columns.
        Accepts a scalar pair, a list + scalar, or a {old: new} dict."""
        if isinstance(to_replace, dict):
            mapping = to_replace
        elif isinstance(to_replace, (list, tuple)):
            if isinstance(value, (list, tuple)):  # PySpark list-to-list form
                if len(value) != len(to_replace):
                    raise ValueError(
                        f"replace: value list length {len(value)} != "
                        f"to_replace length {len(to_replace)}")
                mapping = dict(zip(to_replace, value))
            else:
                mapping = {v: value for v in to_replace}
        else:
            mapping = {to_replace: value}
        cols = subset if subset is not None else self.columns
        data = dict(self._data)
        for name in cols:
            arr = self._data[name]
            if _is_string_col(arr):
                str_map = {k: v for k, v in mapping.items()
                           if isinstance(k, str)}
                if str_map:
                    data[name] = np.asarray(
                        [str_map.get(x, x) for x in arr], dtype=object)
            else:
                num_map = {k: v for k, v in mapping.items()
                           if isinstance(k, (int, float))
                           and not isinstance(k, bool)}
                if num_map:
                    src = jnp.asarray(arr)  # converted ONCE; matches test
                    col = src               # against the original values
                    # replacing with None (null) or a float widens ints
                    if any(v is None or isinstance(v, float)
                           for v in num_map.values()) \
                            and not jnp.issubdtype(col.dtype, jnp.floating):
                        col = col.astype(float_dtype())
                    for old, new in num_map.items():
                        if new is None:
                            new = float("nan")
                        col = jnp.where(src == old,
                                        jnp.asarray(new, col.dtype), col)
                    data[name] = col
        return self._with(data=data)

    def with_columns(self, cols_map: Mapping[str, ColumnLike]) -> "Frame":
        """``withColumns`` — add/replace several columns at once. Every
        expression resolves against the *input* frame (Spark semantics), so
        a map that replaces a column and references it elsewhere sees the
        original values.

        When every expression is compilable the whole batch defers as ONE
        pipeline step — N expressions in one compiled program."""
        items = tuple(cols_map.items())
        if items and self._can_defer(*[v for _, v in items]):
            return self._defer(("with_columns", items))
        return self._with_columns_eager(cols_map)

    def _with_columns_eager(self, cols_map: Mapping[str, ColumnLike]) \
            -> "Frame":
        evaluated = {name: self._eval(values)
                     for name, values in cols_map.items()}
        data = dict(self._data)
        data.update(evaluated)
        return self._with(data=data)

    withColumns = with_columns

    def to_df(self, *names: str) -> "Frame":
        """``toDF`` — rename all columns positionally. Duplicate names are
        rejected (the columnar dict cannot represent them, unlike Spark)."""
        if len(names) != len(self.columns):
            raise ValueError(f"toDF expects {len(self.columns)} names, "
                             f"got {len(names)}")
        if len(set(names)) != len(names):
            raise ValueError(f"toDF names must be unique, got {list(names)}")
        data = {new: self._data[old]
                for new, old in zip(names, self.columns)}
        return self._with(data=data)

    toDF = to_df

    def summary(self, *stats: str) -> "Frame":
        """Spark's ``summary``: describe + percentiles. Default statistics:
        count, mean, stddev, min, 25%, 50%, 75%, max."""
        from .aggregates import AggExpr, global_agg

        if not stats:
            stats = ("count", "mean", "stddev", "min", "25%", "50%", "75%",
                     "max")
        cols = [name for name, arr in self._data.items()
                if not _is_string_col(arr) and arr.ndim == 1]
        data: dict[str, object] = {
            "summary": np.asarray(list(stats), dtype=object)}
        m = self._host_mask()
        plain = [s for s in stats if not s.endswith("%")]
        for c in cols:
            vals = np.asarray(self._data[c], np.float64)[m]
            vals = vals[~np.isnan(vals)]
            agg_row = {}
            if plain:  # one batched device reduction per column (cf describe)
                aggs = [AggExpr({"mean": "avg"}.get(s, s), c).alias(s)
                        for s in plain]
                d = global_agg(self, aggs).to_pydict()
                agg_row = {s: d[s][0] for s in plain}
            out = []
            for s in stats:
                if s.endswith("%"):
                    q = float(s[:-1]) / 100.0
                    out.append(str(np.quantile(vals, q)) if len(vals)
                               else "NaN")
                else:
                    out.append(str(agg_row[s]))
            data[c] = np.asarray(out, dtype=object)
        return Frame(data)

    def sample(self, fraction: float, seed: int = 0,
               with_replacement: bool = False) -> "Frame":
        """Row sample. Without replacement: Bernoulli mask — shapes stay
        static and the column arrays are shared. With replacement: Poisson
        counts per valid row (Spark's semantics; ``fraction`` is the
        expected copy count and may exceed 1), materialized by ONE gather
        into a NEW frame — this breaks mask/array sharing with the source
        and the result's row count is data-dependent."""
        if with_replacement:
            if fraction < 0.0:
                raise ValueError(f"fraction must be >= 0, got {fraction}")
            rng = np.random.default_rng(seed)
            counts = rng.poisson(fraction, self.num_slots)
            counts = np.where(self._host_mask(), counts, 0)
            idx = np.repeat(np.arange(self.num_slots), counts)
            data = {}
            for name, arr in self._data.items():
                if _is_string_col(arr):
                    data[name] = np.asarray(arr, object)[idx]
                else:
                    data[name] = jnp.take(jnp.asarray(arr),
                                          jnp.asarray(idx), axis=0)
            return Frame(data)
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")
        rng = np.random.default_rng(seed)
        keep = jnp.asarray(rng.random(self.num_slots) < fraction)
        return self._with(mask=jnp.logical_and(self._mask, keep))

    def random_split(self, weights: Sequence[float],
                     seed: int = 0) -> list["Frame"]:
        """Split rows into disjoint frames with the given relative weights —
        ``df.randomSplit([0.8, 0.2], seed)``, the MLlib train/test idiom.
        Each split shares the column arrays; only the masks differ."""
        w = np.asarray(weights, np.float64)
        if w.ndim != 1 or len(w) < 1 or np.any(w < 0) or w.sum() == 0:
            raise ValueError(f"invalid split weights {weights!r}")
        edges = np.cumsum(w / w.sum())
        rng = np.random.default_rng(seed)
        u = rng.random(self.num_slots)
        out = []
        lo = 0.0
        for hi in edges:
            pick = jnp.asarray((u >= lo) & (u < hi))
            out.append(self._with(mask=jnp.logical_and(self._mask, pick)))
            lo = hi
        return out

    randomSplit = random_split

    @op_span("frame.cache")
    def cache(self) -> "Frame":
        """Materialize and pin: flush any pending fused pipeline, then
        ``block_until_ready`` every device column and the validity mask.
        JAX dispatch is async — without the block, timing code around
        ``cache()`` would measure enqueue, not compute; this makes
        ``cache()`` the honest timing boundary bench.py treats it as
        (Spark parity: after ``cache().count()`` the data IS resident)."""
        arrs = [jnp.asarray(arr) for arr in self._data.values()
                if not _is_string_col(arr)]
        jax.block_until_ready(arrs + [self._mask])
        counters.increment("frame.cache")
        return self

    persist = cache

    def unpersist(self, blocking: bool = False) -> "Frame":
        return self

    def repartition(self, num_partitions: int, *cols) -> "Frame":
        """No-op for API parity: a device-mesh engine has no partition
        count — distribution happens at fit time via ``mesh=`` sharding,
        not by reshaping the frame."""
        return self

    def coalesce(self, num_partitions: int) -> "Frame":
        return self

    def hint(self, name: str, *parameters) -> "Frame":
        """No-op for API parity (broadcast/shuffle hints steer Spark's
        planner; XLA owns that choice here)."""
        return self

    def checkpoint(self, eager: bool = True) -> "Frame":
        """No-op for API parity: the frame IS materialized (eager engine);
        there is no lineage to truncate."""
        return self

    localCheckpoint = checkpoint
    local_checkpoint = checkpoint

    def alias(self, name: str) -> "Frame":
        """Record a frame alias (Spark ``alias``). Join disambiguation by
        alias-qualified columns is not supported — rename columns instead
        (``with_column_renamed``). Like Spark's (a plan-node property),
        the alias applies to THIS frame object; derived frames don't
        inherit it."""
        out = self._with()
        out._alias = name
        return out

    def explain(self, extended: bool = False, analyze: bool = False) -> None:
        """Describe the physical representation (the eager-engine analogue
        of Spark's plan dump): columns, dtypes, placement, mask stats.

        ``analyze=True`` additionally EXECUTES the frame's pending fused
        pipeline under a per-query stats collector and appends the
        measured flush profile — one line per recorded span (wall ms,
        rows, compile-vs-cache-hit verdict, host syncs, peak device
        bytes) plus the query-level counter deltas. An already-
        materialized frame reports an empty analyze section (nothing left
        to execute) — the informative call site is right after building a
        lazy op chain."""
        print(self.explain_string(extended=extended, analyze=analyze))

    def explain_string(self, extended: bool = False,
                       analyze: bool = False) -> str:
        """The text :meth:`explain` prints (testable surface)."""
        analyzed: list[str] = []
        if analyze:
            # run BEFORE the physical description below reads _data/_mask
            # (its count() would silently flush the pending steps outside
            # the measurement window)
            from ..config import config as _config
            from ..utils import observability as _obs
            from ..utils.logging import format_kv

            with _obs.query_stats(
                    sample_memory=_config.explain_memory) as qs:
                jax.block_until_ready(self._mask)   # flush + honest wait
            analyzed.append("== Analyzed ==")
            for s in qs.spans:
                attrs = {k: v for k, v in s.attrs.items() if v is not None}
                kv = format_kv(dur_ms=round((s.dur_us or 0) / 1e3, 3),
                               **attrs)
                analyzed.append(f"  {s.name}" + (f"  {kv}" if kv else ""))
            delta = qs.counter_delta()
            if delta:
                analyzed.append("  counters: " + format_kv(**delta))
            if not qs.spans:
                analyzed.append("  (nothing pending — frame already "
                                "materialized)")
        n_valid = self.count()
        lines = ["== Physical Frame =="]
        lines.append(f"row slots: {self.num_slots} (valid: {n_valid}, "
                     f"masked: {self.num_slots - n_valid})")
        if self._shard is not None:
            st = self._shard
            lines.append(
                f"layout: row-sharded over {st.devices} device(s), "
                f"{st.bucket} slot(s)/shard, rows/shard="
                f"{st.shard_counts()}")
        for name in self.columns:
            arr = self._data[name]
            kind = ("host/object" if _is_string_col(arr)
                    else f"device/{jnp.asarray(arr).dtype}")
            lines.append(f"  {name}: {kind}")
        if extended:
            devs = {getattr(d, "platform", "?")
                    for c in self._data.values() if hasattr(c, "devices")
                    for d in c.devices()}
            lines.append(f"devices: {sorted(devs) or ['host']}")
            lines.append("execution: eager columnar; filters are validity-"
                         "mask AND; XLA fuses expression chains under jit")
        return "\n".join(lines + analyzed)

    # -- actions -----------------------------------------------------------
    def count(self) -> int:
        """Number of valid (unmasked) rows."""
        # dqlint: ok(host-sync): deliberately NOT a counted frame host
        # boundary — the seed contract, pinned by test_explain
        # TestDisabledModeNoOp (count() is the no-op-path probe there;
        # counting it would make the probe self-invalidating)
        return int(jnp.sum(self._mask))

    def is_empty(self) -> bool:
        return self.count() == 0

    def _host_mask(self) -> np.ndarray:
        counters.increment("frame.host_sync")
        return np.asarray(self._mask)

    @op_span("frame.to_pydict", cat="action")
    def to_pydict(self, limit: Optional[int] = None) -> dict[str, np.ndarray]:
        """Materialize valid rows on host (the gather happens here, once, at
        the host boundary — never inside the compute path).

        All device→host transfers batch into ONE ``jax.device_get`` of
        the column dict (mask included when no ``limit`` trims it first)
        instead of one sync per column; each batch counts as a
        ``frame.host_sync`` in ``profiling.counters``.

        ``limit`` gathers only the first N valid rows — ``take``/``show``
        use it so peeking at a large device-resident frame does not transfer
        the whole dataset.
        """
        if limit is not None:
            # the limit cut needs the mask on host BEFORE slicing columns:
            # one tiny mask sync, then one batched sync of the prefixes
            m = self._host_mask()
            keep = np.cumsum(m) <= limit
            m = m & keep
            upto = int(np.argmax(~keep)) if not keep.all() else len(m)
            m = m[:upto]
            device = {name: jnp.asarray(arr)[: len(m)]
                      for name, arr in self._data.items()
                      if not _is_string_col(arr)}
            pulled = jax.device_get(device) if device else {}
            if device:
                counters.increment("frame.host_sync")
        else:
            mask_key = "__mask__"
            while mask_key in self._data:       # paranoid name collision
                mask_key += "_"
            device = {name: arr for name, arr in self._data.items()
                      if not _is_string_col(arr)}
            device[mask_key] = self._mask
            pulled = jax.device_get(device)     # ONE batched transfer
            counters.increment("frame.host_sync")
            m = np.asarray(pulled.pop(mask_key), bool)
        out = {}
        for name, arr in self._data.items():
            host = pulled[name] if name in pulled else arr[: len(m)]
            out[name] = np.asarray(host)[m]
        return out

    def collect(self, limit: Optional[int] = None) -> list[tuple]:
        d = self.to_pydict(limit)
        cols = [d[name] for name in self.columns]
        return [tuple(row) for row in zip(*cols)] if cols else []

    def take(self, n: int) -> list[tuple]:
        return self.collect(limit=n)

    def head(self, n: int = 1):
        rows = self.take(n)
        return rows if n != 1 else (rows[0] if rows else None)

    def first(self):
        return self.head(1)

    def tail(self, n: int) -> list[tuple]:
        """Last ``n`` valid rows (Spark ``tail``)."""
        rows = self.collect()
        return rows[-n:] if n > 0 else []

    def to_pandas(self):
        """Materialize as a pandas DataFrame (Spark ``toPandas``): string
        columns stay object dtype, numeric columns keep the engine's
        device dtypes, and vector columns (2D, e.g. an assembled
        ``features``) become per-row arrays in an object column — the
        shape Spark's toPandas gives vector UDTs."""
        import pandas as pd

        d = self.to_pydict()
        out = {}
        for k, v in d.items():
            arr = np.asarray(v) if not _is_string_col(v) else v
            if getattr(arr, "ndim", 1) > 1:
                col = np.empty(len(arr), dtype=object)
                for i in range(len(arr)):
                    col[i] = np.asarray(arr[i])
                arr = col
            out[k] = arr
        return pd.DataFrame(out, columns=self.columns)

    toPandas = to_pandas

    def to_json(self) -> list[str]:
        """One JSON object string per valid row (Spark ``toJSON``; a list,
        not an RDD — this engine has no lazy distributed collection).
        NaN/None become JSON null; numpy scalars coerce to Python."""
        import json
        import math

        def _coerce(v):
            if v is None:
                return None
            if isinstance(v, (np.floating, float)):
                f = float(v)
                return None if math.isnan(f) else f
            if isinstance(v, (np.integer, int)):
                return int(v)
            if isinstance(v, (np.bool_, bool)):
                return bool(v)
            if isinstance(v, np.ndarray):
                return [_coerce(x) for x in v.tolist()]
            return v

        cols = self.columns
        return [json.dumps({c: _coerce(v) for c, v in zip(cols, row)})
                for row in self.collect()]

    toJSON = to_json

    def foreach(self, f) -> None:
        """Apply ``f`` to every valid row host-side (Spark ``foreach`` —
        eager here, no executors)."""
        for row in self.collect():
            f(row)

    def foreach_partition(self, f) -> None:
        """Apply ``f`` to an iterator over all valid rows (Spark
        ``foreachPartition``; this engine is one partition)."""
        f(iter(self.collect()))

    foreachPartition = foreach_partition

    # -- display -----------------------------------------------------------
    def _format_cell(self, v, truncate: int) -> str:
        if isinstance(v, (np.floating, float)):
            if np.isnan(v):
                s = "NaN"
            elif isinstance(v, np.floating):
                # shortest round-trip repr at the column's own precision, so
                # float32 23.1 prints "23.1" (as Spark's double toString would)
                s = np.format_float_positional(v, unique=True, trim="0")
            else:
                s = repr(float(v))
        elif isinstance(v, (np.bool_, bool)):
            s = "true" if v else "false"
        elif isinstance(v, (np.integer, int)):
            s = str(int(v))
        elif isinstance(v, np.ndarray):  # vector cell, shown Spark-style: [40.0]
            s = "[" + ",".join(
                np.format_float_positional(x, unique=True, trim="0")
                if isinstance(x, np.floating) else str(x) for x in v) + "]"
        elif v is None:
            s = "null"
        else:
            s = str(v)
        if truncate > 0 and len(s) > truncate:
            s = s[: truncate - 3] + "..." if truncate > 3 else s[:truncate]
        return s

    def show_string(self, n: int = None, truncate: Union[bool, int] = True) -> str:
        """Spark-format ASCII table (right-aligned cells, +---+ borders,
        ``only showing top N rows`` footer)."""
        if n is None:
            n = config.default_show_rows
        tr = 20 if truncate is True else (0 if truncate is False else int(truncate))
        total = int(self._host_mask().sum())
        d = self.to_pydict(limit=n)  # gather only what is displayed
        names = self.columns
        rows = []
        shown = len(next(iter(d.values()))) if d else 0
        for i in range(shown):
            rows.append([self._format_cell(d[name][i], tr) for name in names])
        headers = [name if tr <= 0 or len(name) <= tr else name[: tr - 3] + "..."
                   for name in names]
        widths = [max([len(h)] + [len(r[j]) for r in rows]) for j, h in enumerate(headers)]
        sep = "+" + "+".join("-" * w for w in widths) + "+"
        out = [sep, "|" + "|".join(h.rjust(w) for h, w in zip(headers, widths)) + "|", sep]
        for r in rows:
            out.append("|" + "|".join(c.rjust(w) for c, w in zip(r, widths)) + "|")
        out.append(sep)
        text = "\n".join(out) + "\n"
        if total > n:
            text += f"only showing top {n} rows\n"
        return text

    def show(self, n: int = None, truncate: Union[bool, int] = True) -> None:
        print(self.show_string(n, truncate))

    def __repr__(self):
        fields = ", ".join(f"{name}: {t}" for name, t in self.dtypes())
        return f"Frame[{fields}]"

    # -- aggregation / reshaping ------------------------------------------
    def group_by(self, *keys: str):
        """``groupBy`` — returns a GroupedFrame with agg/count/avg/... ."""
        from .aggregates import GroupedFrame

        return GroupedFrame(self, list(keys))

    groupBy = group_by

    def map_in_pandas(self, func, schema):
        """Spark 3's ``mapInPandas(fn, schema)``: ``func`` receives an
        iterator of pandas DataFrame batches (one batch here — the frame
        is already fully resident) and yields output batches, concatenated
        and cast to the DDL ``schema``. Host-boundary escape hatch like
        ``applyInPandas``; the fused column path remains the fast lane."""
        import pandas as pd

        from .csv import parse_ddl_schema

        fields = parse_ddl_schema(schema) if isinstance(schema, str) \
            else list(schema)
        outs = [b for b in func(iter([self.to_pandas()]))]
        for b in outs:
            if not isinstance(b, pd.DataFrame):
                raise TypeError("mapInPandas function must yield pandas "
                                f"DataFrames, got {type(b).__name__}")
        names = [n for n, _ in fields]
        if outs:
            cat = pd.concat(outs, ignore_index=True)
            missing = [n for n in names if n not in cat.columns]
            if missing:
                raise ValueError(f"mapInPandas output is missing schema "
                                 f"columns {missing}")
            data = {n: cat[n].to_numpy() for n in names}
        else:
            data = {n: np.asarray([], np.float64) for n in names}
        out = Frame(data)
        for name, tname in fields:
            out = out.with_column(name, out.col(name).cast(tname))
        return out

    mapInPandas = map_in_pandas

    def rollup(self, *keys: str):
        """``rollup`` — hierarchical subtotals: every key prefix plus the
        grand total, absent keys null (Spark ROLLUP)."""
        from .aggregates import MultiGroupedFrame, rollup_levels

        return MultiGroupedFrame(self, list(keys), rollup_levels(list(keys)))

    def cube(self, *keys: str):
        """``cube`` — subtotals for EVERY key subset (Spark CUBE)."""
        from .aggregates import MultiGroupedFrame, cube_levels

        return MultiGroupedFrame(self, list(keys), cube_levels(list(keys)))

    @op_span("frame.agg")
    def agg(self, *aggs):
        """Global aggregates (no grouping): masked device reductions.
        Accepts AggExprs, bare fn names, or PySpark's dict form
        (``agg({'v': 'avg'})``)."""
        from .aggregates import (AggExpr, _dict_aggs, global_agg,
                                 materialize_agg_exprs)

        if len(aggs) == 1 and isinstance(aggs[0], dict):
            aggs = tuple(_dict_aggs(aggs[0]))
        agg_list = [a if isinstance(a, AggExpr) else AggExpr(a, None)
                    for a in aggs]
        frame, agg_list = materialize_agg_exprs(self, agg_list)
        return global_agg(frame, agg_list)

    @op_span("frame.sort")
    def sort(self, *cols, ascending=True) -> "Frame":
        """``orderBy`` — reorders valid rows (host argsort at the boundary),
        dropping masked slots (the result is compact). Columns may be
        names, ``Col``s, or ``col.asc()``/``col.desc()`` (+
        ``*_nulls_first/last``) sort markers — a marker's direction and
        null placement override ``ascending`` for that column. Default
        null placement is Spark's: nulls first ascending, last
        descending (NaN is the numeric null)."""
        from ..ops.expressions import SortOrder

        if not cols:
            raise ValueError("sort requires at least one column")
        asc = ([ascending] * len(cols) if isinstance(ascending, bool)
               else list(ascending))
        if len(asc) != len(cols):
            raise ValueError("ascending list must match columns")
        nulls_first: list = [None] * len(cols)
        resolved = []
        for i, c in enumerate(cols):
            if isinstance(c, SortOrder):
                name = c.name
                asc[i] = c.ascending
                nulls_first[i] = c.nulls_first
            elif isinstance(c, str):
                name = c
            else:
                name = c.name  # Col / aliased expr
            if name not in self.columns:
                raise ValueError(
                    f"sort key {name!r} is not a column of this frame "
                    "(sorting by a computed expression is not supported — "
                    "add it with with_column first)")
            resolved.append(name)
        cols = resolved
        # Device path (ops/segments.py): numeric sort keys compute the
        # permutation on device (jax.lax.sort) and gather payload with
        # jnp.take — one host sync (the valid-row count) instead of the
        # full round trip. String keys / failures take the host lexsort.
        from ..ops import segments

        out = segments.try_device(
            "sort", lambda: segments.device_sort(self, cols, asc,
                                                 nulls_first))
        if out is not None:
            return out
        d = self.to_pydict()
        order = np.lexsort(lexsort_keys([d[c] for c in cols], asc,
                                        nulls_first))
        return Frame({name: (vals[order] if vals.dtype == object
                             else np.asarray(vals)[order])
                      for name, vals in d.items()})

    orderBy = sort
    # one partition: sorting "within partitions" IS a total sort here
    sortWithinPartitions = sort
    sort_within_partitions = sort
    order_by = sort

    @op_span("frame.distinct")
    def distinct(self) -> "Frame":
        """Unique valid rows (result compact, order of first occurrence).
        Null-safe like Spark: null rows equal each other, so duplicates
        with NaN/None cells collapse too. All-numeric frames dedup on
        device (ops/segments.py: one sort + boundary program, one host
        sync); any string column falls back to the host row walk."""
        from ..ops import segments

        out = segments.try_device(
            "distinct", lambda: segments.device_unique(self, self.columns))
        if out is not None:
            return out
        seen = set()
        out = []
        for key, r in self._keyed_rows():
            if key not in seen:
                seen.add(key)
                out.append(r)
        return Frame.from_rows(out, self.columns)

    @op_span("frame.drop_duplicates")
    def drop_duplicates(self, subset=None) -> "Frame":
        """Spark ``dropDuplicates``: with ``subset``, keep the FIRST valid
        row per distinct key combination (all columns retained); without,
        identical to :meth:`distinct`."""
        if subset is None:
            return self.distinct()
        if isinstance(subset, str):
            subset = [subset]
        for c in subset:
            if c not in self.columns:
                raise ValueError(f"dropDuplicates column {c!r} not found")
        # Numeric 1-D subset keys dedup on device (same kernel as
        # distinct); vector-cell keys stay host-side — the host path
        # treats NaN components of a vector cell as distinct (NaN != NaN
        # inside the tuple key) while scalar NaN keys fold, and the
        # device kernel implements only the scalar fold.
        if all(getattr(self._data.get(c), "ndim", 1) == 1
               for c in subset):
            from ..ops import segments

            out = segments.try_device(
                "drop_duplicates",
                lambda: segments.device_unique(self, subset))
            if out is not None:
                return out
        idx = np.nonzero(self._host_mask())[0]
        seen = set()
        keep = []
        keycols = [np.asarray(self._column_values(c)) for c in subset]
        if any(not _is_string_col(self._data[c]) for c in subset):
            counters.increment("frame.host_sync")  # device key-column pull

        def cell_key(cell):
            a = np.asarray(cell)
            if a.ndim:
                return tuple(a.ravel().tolist())
            x = a.item() if hasattr(a, "item") else cell
            # NaN = SQL NULL throughout this engine: null keys form ONE
            # group (NaN != NaN would keep every null-key duplicate)
            if isinstance(x, float) and x != x:
                return None
            return x

        for pos in idx:
            key = tuple(cell_key(k[pos]) for k in keycols)
            if key not in seen:
                seen.add(key)
                keep.append(pos)
        keep_idx = np.asarray(keep, np.int64)
        keep_dev = jnp.asarray(keep_idx)  # one host→device transfer, not
        data = {}                         # one per gathered column
        for name in self.columns:
            arr = self._data[name]
            if _is_string_col(arr):
                data[name] = np.asarray(arr, dtype=object)[keep_idx]
            else:
                data[name] = jnp.take(jnp.asarray(arr), keep_dev, axis=0)
        return Frame(data)

    dropDuplicates = drop_duplicates

    @op_span("frame.join")
    def join(self, other: "Frame", on, how: str = "inner",
             build: Optional[str] = None,
             est: Optional[tuple] = None) -> "Frame":
        """Relational join on key column(s) present in both frames.

        ``how``: ``inner`` | ``left`` | ``right`` | ``outer``/``full`` |
        ``left_semi`` | ``left_anti`` | ``cross``. Key columns appear once in
        the result (Spark's ``USING`` semantics); a non-key column name
        present on both sides keeps the left column and surfaces the right
        one as ``<name>_right`` (explicit, instead of Spark's ambiguous
        duplicate).

        ``build="left"`` (cost-based optimizer hint, inner joins only):
        plan the hash join building from the LEFT side — the win when
        the left is the small side. Result is bit-identical, emission
        order included (see ``_vector_join_plan``); any other value or
        join type ignores the hint.

        ``est=(left_rows, right_rows)`` (adaptive execution input,
        ``sql/adaptive.py``): the optimizer's pre-execution row
        estimates for the two sides. When ``spark.aqe.enabled`` and the
        OBSERVED valid-row counts drift past ``spark.aqe.driftFactor``,
        the build side re-decides mid-query and a small-enough observed
        build side skips the hash-partition shuffle (both transforms
        bit-identical by construction). ``None`` (or AQE off) keeps the
        static plan.

        Design: only valid (mask=True) rows participate. The match *plan*
        (row-index pairs) is computed host-side with a hash join — the
        analogue of Spark's driver/shuffle planning, and unavoidable for
        host-resident string keys — while column *materialization* is device
        gathers (``jnp.take``), so numeric data never leaves HBM. Unmatched
        slots in outer joins fill with NaN (numeric, int promotes to float)
        or None (string).
        """
        how = how.lower().replace("fullouter", "outer").replace("full", "outer")
        valid = ("inner", "left", "right", "outer", "left_semi", "left_anti",
                 "cross")
        if how not in valid:
            raise ValueError(f"unknown join type {how!r}; expected one of {valid}")
        build_left = build == "left" and how == "inner"
        keys = [on] if isinstance(on, str) else list(on or [])
        if how != "cross":
            if not keys:
                raise ValueError("join requires `on` key column(s)")
            for k in keys:
                if k not in self.columns or k not in other.columns:
                    raise ValueError(f"join key {k!r} must exist in both frames")

        li = np.nonzero(self._host_mask())[0]
        ri = np.nonzero(other._host_mask())[0]

        # Adaptive re-planning (sql/adaptive.py): the host plan already
        # holds both sides' TRUE valid-row counts — zero extra syncs —
        # so when either side drifted past spark.aqe.driftFactor from
        # the optimizer's estimate, the build side re-decides from the
        # observed counts, and an observed build side under
        # spark.aqe.broadcastThreshold bytes skips the hash-partition
        # shuffle entirely (the partitioned plan reproduces the
        # unpartitioned emission order exactly, so skipping it is the
        # identity transform). One conf read when AQE is off; a cold
        # estimate (est None) changes nothing.
        aqe_skip_shuffle = False
        if est is not None and how == "inner" and config.aqe_enabled:
            from ..sql import adaptive as _aqe

            left_est, right_est = est
            if _aqe.drift(left_est, li.size) \
                    or _aqe.drift(right_est, ri.size):
                want_left = li.size * _aqe.BUILD_RATIO <= ri.size
                if want_left != build_left and _aqe.guard("build-flip"):
                    _aqe.record(
                        "build-flip",
                        f"join build={'left' if want_left else 'right'}"
                        f" (observed {li.size} vs {ri.size} rows)",
                        est_before=(left_est if want_left
                                    else right_est),
                        est_after=(int(li.size) if want_left
                                   else int(ri.size)))
                    build_left = want_left
                store_hint = (self._shard if self._shard is not None
                              else other._shard)
                if store_hint is not None and \
                        max(li.size, ri.size) >= int(config.shard_min_rows):
                    b_rows = int(min(li.size, ri.size))
                    b_frame = self if li.size <= ri.size else other
                    b_bytes = b_rows * _aqe.row_nbytes(b_frame)
                    if b_bytes <= int(config.aqe_broadcast_threshold) \
                            and _aqe.guard("broadcast"):
                        _aqe.record(
                            "broadcast",
                            "hash-partition Exchange skipped (observed"
                            f" build side {b_rows} rows ~{b_bytes} B "
                            "fits spark.aqe.broadcastThreshold)",
                            est_before=(left_est if li.size <= ri.size
                                        else right_est),
                            est_after=b_rows)
                        aqe_skip_shuffle = True

        if how == "cross":
            lpairs = np.repeat(li, len(ri))
            rpairs = np.tile(ri, len(li))
        elif ri.size == 0:
            # Empty group table (right side has zero valid rows): the
            # plan is fully determined without building one — inner /
            # right / semi match nothing, left / outer / anti keep every
            # left row (null-filled right columns via the -1 sentinel).
            # Guarding here keeps the searchsorted clamp in
            # _vector_join_plan (gvals.size - 1) unreachable at size 0.
            if how in ("inner", "right", "left_semi"):
                lpairs = np.empty(0, np.int64)
                rpairs = np.empty(0, np.int64)
            else:                       # left / outer / left_anti
                lpairs = li.astype(np.int64)
                rpairs = np.full(li.size, -1, np.int64)
        else:
            # key columns materialize ONCE; the vector plan and the dict
            # fallback share them (a plan bail-out must not re-read).
            # Each side's device-key pull counts as one host sync batch.
            for fr in (self, other):
                if any(not _is_string_col(fr._data[k]) for k in keys):
                    counters.increment("frame.host_sync")
            lraw = [np.asarray(self._column_values(k))[li] for k in keys]
            rraw = [np.asarray(other._column_values(k))[ri] for k in keys]
            plan = None
            if all(not _is_string_col(self._data[k])
                   and not _is_string_col(other._data[k]) for k in keys):
                # Hash-partition shuffle lowering (sharded frames, above
                # the spark.shard.minRows host-fallback bound): the plan
                # computes per key-hash partition and merges back into
                # the exact unpartitioned emission order — the Exchange
                # EXPLAIN renders. Any partition bail-out (inexact keys)
                # falls through to the single plan below.
                store = self._shard if self._shard is not None \
                    else other._shard
                planner = ((lambda *a: _vector_join_plan(
                    *a, build_left=True)) if build_left
                    else _vector_join_plan)
                if store is not None and not aqe_skip_shuffle and \
                        max(li.size, ri.size) >= int(config.shard_min_rows):
                    from ..parallel.shard import partitioned_join_plan

                    plan = partitioned_join_plan(
                        planner, lraw, rraw, li, ri, how,
                        store.devices)
                if plan is None:
                    plan = planner(lraw, rraw, li, ri, how)
            if plan is not None:
                lpairs, rpairs = plan
            elif build_left:
                # hinted build-from-left dict plan (string keys): build
                # the table over the small left side, probe with the
                # right, and re-canonicalize to the default plan's
                # (left, right)-lexicographic inner emission order
                ltable: dict = {}
                lkeys = list(zip(*[c.tolist() for c in lraw]))
                for pos, kt in zip(li, lkeys):
                    ltable.setdefault(kt, []).append(pos)
                rkeys = list(zip(*[c.tolist() for c in rraw]))
                lp, rp = [], []
                for rpos, kt in zip(ri, rkeys):
                    for lpos in ltable.get(kt, ()):
                        lp.append(lpos)
                        rp.append(rpos)
                lpairs = np.asarray(lp, np.int64)
                rpairs = np.asarray(rp, np.int64)
                order = np.lexsort((rpairs, lpairs))
                lpairs, rpairs = lpairs[order], rpairs[order]
            else:
                rkeys = list(zip(*[c.tolist() for c in rraw]))
                table: dict = {}
                for pos, kt in zip(ri, rkeys):
                    table.setdefault(kt, []).append(pos)
                lkeys = list(zip(*[c.tolist() for c in lraw]))
                lp, rp = [], []
                matched_r = set()
                for pos, kt in zip(li, lkeys):
                    hits = table.get(kt)
                    if hits:
                        if how == "left_anti":
                            continue
                        if how == "left_semi":
                            lp.append(pos)
                            rp.append(hits[0])
                            continue
                        for rpos in hits:
                            lp.append(pos)
                            rp.append(rpos)
                            matched_r.add(rpos)
                    elif how in ("left", "outer", "left_anti"):
                        lp.append(pos)
                        rp.append(-1)
                if how in ("right", "outer"):
                    for pos in ri:
                        if pos not in matched_r:
                            lp.append(-1)
                            rp.append(pos)
                lpairs = np.asarray(lp, np.int64)
                rpairs = np.asarray(rp, np.int64)

        def gather(frame, idx, fill_missing):
            """Materialize frame columns at idx; idx == -1 ⇒ null fill."""
            missing = idx < 0
            safe = np.where(missing, 0, idx)
            safe_dev = jnp.asarray(safe)       # ONE host→device transfer
            miss_dev = (jnp.asarray(missing)   # shared across all columns
                        if fill_missing and missing.any() else None)
            out = {}
            if frame.num_slots == 0 and len(idx):
                # gathering from an EMPTY side (e.g. left join against an
                # empty right frame): every idx is -1; jnp.take from a
                # zero-length axis raises, so synthesize the null columns
                for name in frame.columns:
                    arr = frame._data[name]
                    if _is_string_col(arr):
                        out[name] = np.full(len(idx), None, dtype=object)
                    else:
                        a = jnp.asarray(arr)
                        out[name] = jnp.full((len(idx),) + a.shape[1:],
                                             jnp.nan, float_dtype())
                return out
            for name in frame.columns:
                arr = frame._data[name]
                if _is_string_col(arr):
                    col = arr[safe]
                    if fill_missing and missing.any():
                        col = col.copy()
                        col[missing] = None
                    out[name] = col
                else:
                    col = jnp.take(jnp.asarray(arr), safe_dev, axis=0)
                    if miss_dev is not None:
                        if not np.issubdtype(np.dtype(col.dtype), np.floating):
                            col = col.astype(float_dtype())
                        nan = jnp.asarray(np.nan, col.dtype)
                        col = jnp.where(
                            miss_dev[(...,) + (None,) * (col.ndim - 1)],
                            nan, col)
                    out[name] = col
            return out

        left_cols = gather(self, lpairs, how in ("right", "outer"))
        if how in ("left_semi", "left_anti"):
            return Frame(left_cols)
        right_cols = gather(other, rpairs, how in ("left", "outer", "left_anti"))
        data = dict(left_cols)
        if how in ("right", "outer") and lpairs.size and (lpairs < 0).any():
            # USING semantics: one key column, coalesced from the non-null
            # side (rows appended for unmatched right rows have lpairs == -1).
            miss = lpairs < 0
            for k in keys:
                lk, rk = data[k], right_cols[k]
                if _is_string_col(lk) or _is_string_col(rk):
                    data[k] = np.where(miss, np.asarray(rk, dtype=object),
                                       np.asarray(lk, dtype=object))
                else:
                    data[k] = jnp.where(jnp.asarray(miss),
                                        jnp.asarray(rk).astype(lk.dtype), lk)
        for name, col in right_cols.items():
            if name in keys:
                continue
            out_name = name + "_right" if name in data else name
            data[out_name] = col
        return Frame(data)

    def cross_join(self, other: "Frame") -> "Frame":
        return self.join(other, on=None, how="cross")

    crossJoin = cross_join

    def dropna(self, how="any", thresh=None, subset=None) -> "Frame":
        """Mask out null rows (Spark ``dropna`` / ``na.drop`` signature:
        ``how`` "any"|"all", ``thresh`` = minimum non-null count which
        overrides ``how``, ``subset`` = columns considered). NaN (float) /
        None (string) count as null; stays static-shaped like ``filter``.
        A list first argument is accepted as a legacy positional
        ``subset``."""
        if isinstance(how, (list, tuple)):
            subset, how = list(how), "any"
        if how not in ("any", "all"):
            raise ValueError(f"how={how!r}; expected 'any' or 'all'")
        cols = subset if subset is not None else self.columns
        nonnull = jnp.zeros((self._n,), jnp.int32)
        for name in cols:
            arr = self._column_values(name)
            if _is_string_col(arr):
                ok = jnp.asarray([x is not None for x in arr])
            elif np.issubdtype(np.dtype(arr.dtype), np.floating):
                flat_nan = jnp.isnan(arr)
                if flat_nan.ndim > 1:
                    flat_nan = flat_nan.any(axis=tuple(range(1, flat_nan.ndim)))
                ok = jnp.logical_not(flat_nan)
            else:
                ok = jnp.ones((self._n,), jnp.bool_)  # ints have no null
            nonnull = nonnull + ok.astype(jnp.int32)
        if thresh is not None:
            keep = nonnull >= int(thresh)
        elif how == "all":
            keep = nonnull > 0
        else:
            keep = nonnull == len(cols)
        return self._with(mask=jnp.logical_and(self._mask, keep))

    def fillna(self, value, subset=None) -> "Frame":
        """Replace NaN/None with ``value`` in [subset] columns. A dict
        ``value`` maps column -> fill value per column (Spark's common
        ``na.fill({'col': 0.0})`` form; ``subset`` is ignored then, like
        Spark)."""
        if isinstance(value, dict):
            out = self
            for name, v in value.items():
                out = out.fillna(v, subset=[name])
            return out
        cols = subset if subset is not None else self.columns
        data = dict(self._data)
        for name in cols:
            arr = self._data[name]
            if _is_string_col(arr):
                if isinstance(value, str):
                    data[name] = np.asarray(
                        [value if x is None else x for x in arr], dtype=object)
            elif np.issubdtype(np.dtype(arr.dtype), np.floating) and \
                    isinstance(value, (int, float)):
                data[name] = jnp.where(jnp.isnan(arr),
                                       jnp.asarray(value, arr.dtype), arr)
        return self._with(data=data)

    def describe(self, *cols: str) -> "Frame":
        """Spark's ``describe``: count/mean/stddev/min/max summary rows.
        String columns describe like Spark's — non-null count and
        lexicographic min/max, with null mean/stddev cells."""
        from .aggregates import AggExpr, global_agg

        if not cols:
            cols = tuple(name for name, arr in self._data.items()
                         if arr.ndim == 1)
        stats = ["count", "mean", "stddev", "min", "max"]
        fns = [{"mean": "avg"}.get(s, s) for s in stats]
        data: dict[str, object] = {"summary": np.asarray(stats, dtype=object)}
        m = self._host_mask()
        for c in cols:
            arr = self._data[c]
            if _is_string_col(arr):
                vals = [x for x in np.asarray(arr, object)[m]
                        if x is not None]
                data[c] = np.asarray(
                    [str(len(vals)), None, None,
                     (min(vals) if vals else None),
                     (max(vals) if vals else None)], dtype=object)
                continue
            aggs = [AggExpr(fn, c).alias(fn) for fn in fns]
            row = global_agg(self, aggs).to_pydict()  # one sync per column
            data[c] = np.asarray([str(row[fn][0]) for fn in fns], dtype=object)
        return Frame(data)

    # -- statistics --------------------------------------------------------
    @property
    def stat(self):
        """``df.stat`` — corr/cov/approxQuantile/crosstab/freqItems
        (Spark's DataFrameStatFunctions)."""
        from .stat import FrameStatFunctions

        return FrameStatFunctions(self)

    def corr(self, col1: str, col2: str, method: str = "pearson") -> float:
        return self.stat.corr(col1, col2, method)

    def cov(self, col1: str, col2: str) -> float:
        return self.stat.cov(col1, col2)

    # -- writer ------------------------------------------------------------
    @property
    def write(self):
        """``df.write.format("csv").option("header", True).save(path)``."""
        from .writer import DataFrameWriter

        return DataFrameWriter(self)

    def to_csv(self, path: str, header: bool = False,
               delimiter: str = ",") -> None:
        from .writer import write_csv

        write_csv(self, path, header=header, delimiter=delimiter)

    # -- temp views --------------------------------------------------------
    def create_or_replace_temp_view(self, name: str) -> None:
        """Register this frame in the session catalog for SQL access
        (`DataQuality4MachineLearningApp.java:76,88`)."""
        from ..sql.catalog import default_catalog

        default_catalog().register(name, self)

    createOrReplaceTempView = create_or_replace_temp_view

    def create_temp_view(self, name: str) -> None:
        """``createTempView`` — like the or-replace form but raises if
        the name is taken (Spark's TempTableAlreadyExistsException)."""
        from ..sql.catalog import default_catalog

        cat = default_catalog()
        if cat.table_exists(name):
            raise ValueError(f"temp view {name!r} already exists "
                             "(use createOrReplaceTempView)")
        cat.register(name, self)

    createTempView = create_temp_view


class _NAFunctions:
    """``df.na`` accessor (Spark ``DataFrameNaFunctions``) — thin verbs
    over the frame's own null handling: ``fill`` -> ``fillna``,
    ``drop`` -> ``dropna``, ``replace`` -> ``replace``."""

    def __init__(self, frame: "Frame"):
        self._frame = frame

    def fill(self, value, subset=None) -> "Frame":
        return self._frame.fillna(value, subset=subset)

    def drop(self, how="any", thresh=None, subset=None) -> "Frame":
        return self._frame.dropna(how=how, thresh=thresh, subset=subset)

    def replace(self, to_replace, value=None, subset=None) -> "Frame":
        return self._frame.replace(to_replace, value=value, subset=subset)
