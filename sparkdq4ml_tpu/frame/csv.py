"""CSV reader with schema inference — the data-loader capability.

Replaces the engine's CSV source the reference invokes with
``inferSchema=true, header=false`` (`DataQuality4MachineLearningApp.java:53-55`).
Must-have behavior (SURVEY.md §2.2):

* **universal newline handling including bare CR** — all three reference
  datasets are CR-terminated (``\\r`` only, no LF); a naive ``\\n`` split reads
  one giant record,
* default column names ``_c0, _c1, …`` when ``header=False``,
* type inference producing integer/long/double/boolean/string in that order of
  preference; empty fields are nulls (NaN in float columns — int columns with
  nulls promote to double, a documented deviation from Spark's boxed nulls).

Parsing happens on host (strings never touch the TPU); inferred numeric
columns are uploaded once as device arrays. A native C++ tokenizer (the
Univocity-parser analogue in the data-loader role) is used for large files
when available — see ``sparkdq4ml_tpu/frame/native_csv.py``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ..config import float_dtype, int_dtype
from .frame import Frame

_NULL_STRINGS = {""}
_TRUE = {"true", "TRUE", "True"}
_FALSE = {"false", "FALSE", "False"}


def split_records(text: str) -> list[str]:
    r"""Split on \r\n, \r, or \n; drop blank records (Spark skips blank lines).

    Quote-UNaware — only safe when the text contains no quote character;
    :func:`parse_csv_text` routes quoted input through the stateful scanner
    so record separators inside quoted fields stay literal.
    """
    text = text.replace("\r\n", "\n").replace("\r", "\n")
    return [line for line in text.split("\n") if line.strip() != ""]


def _parse_quoted_text(text: str, delimiter: str, quote: str) -> list[list[str]]:
    r"""Single-pass stateful tokenizer for text containing quotes: record
    separators (\r\n, \r, \n) and delimiters inside quoted fields are
    literal content; ``""`` inside quotes is an escaped quote (RFC 4180 —
    the Univocity behavior behind the reference's CSV options,
    `DataQuality4MachineLearningApp.java:53-55`)."""
    rows: list[list[str]] = []
    row: list[str] = []
    buf: list[str] = []
    quoted_field = False   # current field had quotes (never blank-skipped)
    in_q = False
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if in_q:
            if c == quote:
                if i + 1 < n and text[i + 1] == quote:
                    buf.append(quote)
                    i += 1
                else:
                    in_q = False
            else:
                buf.append(c)
        elif c == quote:
            in_q = True
            quoted_field = True
        elif c == delimiter:
            row.append("".join(buf))
            buf = []
        elif c in ("\r", "\n"):
            if c == "\r" and i + 1 < n and text[i + 1] == "\n":
                i += 1
            row.append("".join(buf))
            buf = []
            if len(row) > 1 or row[0].strip() != "" or quoted_field:
                rows.append(row)      # blank lines are skipped (Spark)
            row = []
            quoted_field = False
        else:
            buf.append(c)
        i += 1
    if buf or row or quoted_field:   # a lone quoted "" is still a record
        row.append("".join(buf))
        if len(row) > 1 or row[0].strip() != "" or quoted_field:
            rows.append(row)
    return rows


def parse_csv_text(text: str, delimiter: str = ",",
                   quote: str = '"') -> list[list[str]]:
    """Tokenize a whole CSV text into rows of fields.

    Quote-free text (the reference datasets) takes the allocation-light
    split path; any quote routes through the stateful scanner so embedded
    record separators parse correctly.
    """
    if quote and quote in text:
        return _parse_quoted_text(text, delimiter, quote)
    return [r.split(delimiter) for r in split_records(text)]


def split_fields(record: str, delimiter: str = ",", quote: str = '"') -> list[str]:
    """Tokenize one record with RFC-4180 quoting — a thin wrapper over the
    same scanner :func:`parse_csv_text` uses (one quote state machine to
    maintain, not two)."""
    if quote not in record:
        return record.split(delimiter)
    rows = _parse_quoted_text(record, delimiter, quote)
    return rows[0] if rows else [""]


def _try_int(s: str) -> Optional[int]:
    try:
        return int(s)
    except ValueError:
        return None


def _try_float(s: str) -> Optional[float]:
    try:
        return float(s)
    except ValueError:
        return None


def _is_null_field(v: str) -> bool:
    """Whitespace-only (incl. empty) fields are nulls for numeric/boolean
    typing — Spark's univocity parser trims unquoted fields by default
    (ignoreLeading/TrailingWhiteSpace), so "  " reads as empty -> null.
    The native tokenizer (parse_span) agrees. String columns keep the
    narrower exact-"" rule so "  " survives as a value there."""
    return v in _NULL_STRINGS or not v.strip()


def infer_column(values: Sequence[str]):
    """Infer one column's type and parse it.

    Preference order integer → long → double → boolean → string, matching the
    Spark CSV inferrer's ladder. Returns a numpy array (object dtype for
    strings).
    """
    non_null = [v for v in values if not _is_null_field(v)]
    has_null = len(non_null) != len(values)

    if non_null and all(_try_int(v) is not None for v in non_null):
        ints = [int(v) for v in non_null]
        if not has_null:
            lo, hi = min(ints), max(ints)
            dt = np.dtype(int_dtype()) if -(2**31) <= lo and hi < 2**31 else np.int64
            return np.asarray([int(v) for v in values], dtype=dt)
        # int column with nulls promotes to double + NaN
        return np.asarray([np.nan if _is_null_field(v) else float(v)
                           for v in values], dtype=np.dtype(float_dtype()))
    if non_null and all(_try_float(v) is not None for v in non_null):
        return np.asarray([np.nan if _is_null_field(v) else float(v)
                           for v in values], dtype=np.dtype(float_dtype()))
    if non_null and all(v in _TRUE or v in _FALSE for v in non_null) and not has_null:
        return np.asarray([v in _TRUE for v in values], dtype=np.bool_)
    return np.asarray([v if v not in _NULL_STRINGS else None for v in values],
                      dtype=object)


_MODES = ("PERMISSIVE", "DROPMALFORMED", "FAILFAST")


def parse_ddl_schema(ddl: str) -> list:
    """Parse a Spark DDL schema string (``"a INT, b DOUBLE, s STRING"``)
    into [(name, type_name)]; type names are validated against the
    engine's Spark type-name table."""
    from ..ops.expressions import resolve_type_name

    fields = []
    for part in ddl.split(","):
        toks = part.split()
        if len(toks) != 2:
            raise ValueError(
                f"bad DDL field {part.strip()!r} (expected 'name TYPE')")
        name, type_name = toks
        resolve_type_name(type_name)          # raises on unknown types
        fields.append((name, type_name.lower()))
    return fields


def _cast_column(values: list, type_name: str):
    """Cast raw CSV strings to a declared Spark type; unparseable or null
    cells become null (Spark PERMISSIVE), which for integral columns
    promotes the column to float (the engine's nullable-numeric form)."""
    if type_name == "string":
        return np.asarray([v if v not in _NULL_STRINGS else None
                           for v in values], dtype=object)
    if type_name == "boolean":
        out = [None if _is_null_field(v)
               else v.strip().lower() == "true" for v in values]
        if any(v is None for v in out):
            return np.asarray([np.nan if v is None else float(v)
                               for v in out])
        return np.asarray(out, bool)
    floats = np.empty(len(values), np.float64)
    any_null = False
    for i, v in enumerate(values):
        try:
            floats[i] = float(v)
        except (TypeError, ValueError):
            floats[i] = np.nan
            any_null = True
    if type_name in ("int", "integer", "long"):
        if not any_null and np.all(floats == np.floor(floats)):
            dt = np.int64 if type_name == "long" else np.int32
            return floats.astype(dt)
        return floats          # nullable integral → float column
    from ..config import float_dtype

    return floats.astype(np.float32 if type_name == "float"
                         else np.dtype(float_dtype()))


def read_csv(path: str, header: bool = False, infer_schema: bool = True,
             delimiter: str = ",", engine: str = "auto",
             quote: str = '"', mode: str = "PERMISSIVE",
             schema=None) -> Frame:
    """Load a CSV file into a Frame.

    ``engine``: "python" (pure host parser), "native" (C++ tokenizer), or
    "auto" (native when the shared library is built and the column set is
    numeric-friendly, else python).

    ``mode`` (Spark's malformed-record policy): ``PERMISSIVE`` (default —
    short rows null-fill, long rows truncate), ``DROPMALFORMED`` (rows with
    the wrong field count are dropped), ``FAILFAST`` (raise on the first
    malformed row).

    ``schema``: explicit [(name, type)] (from a DDL string) — skips
    inference, names the columns, and casts each to its declared type.
    """
    mode = mode.upper()
    if mode not in _MODES:
        raise ValueError(f"mode={mode!r}; expected one of {_MODES}")
    if schema is not None:
        engine = "python"      # explicit-schema cast path is host-side
    if engine in ("auto", "native"):
        from . import native_csv

        if mode != "PERMISSIVE":
            # native pads short rows NaN (permissive); exact drop/failfast
            # field-count semantics live in the python engine
            if engine == "native":
                raise RuntimeError("native CSV engine supports "
                                   "mode=PERMISSIVE only")
        else:
            degraded = False
            try:
                frame = native_csv.try_read_csv(
                    path, header=header, infer_schema=infer_schema,
                    delimiter=delimiter, quote=quote,
                    required=(engine == "native"))
            except FileNotFoundError:
                raise          # permanent: the python engine can't help
            except (OSError, MemoryError,
                    native_csv.NativeIngestError) as e:
                if engine == "native":
                    raise      # explicit native request: never degrade
                # The native → python rung of the ingest degradation
                # ladder (ISSUE 11): a mid-read I/O error, an allocation
                # failure, or a dead prefetch producer (real or injected
                # via utils.faults site "ingest_native") re-reads the
                # file through the python engine — correctness over
                # speed, observable via the recovery event + counters.
                from ..utils.profiling import counters
                from ..utils.recovery import RECOVERY_LOG

                RECOVERY_LOG.record(
                    "ingest_native", "fallback", rung="python",
                    cause=f"{type(e).__name__}: {e}")
                counters.increment("ingest.fault_fallback")
                counters.increment("ingest.python_fallback")
                frame, degraded = None, True
            if frame is not None:
                return frame
            if native_csv.available() and not degraded:
                # native was eligible and declined (non-numeric content,
                # ragged header, multibyte delimiter...): the ingest
                # telemetry counts the demotion so a fleet-wide scrape can
                # see what share of reads misses the fast path
                from ..utils.profiling import counters

                counters.increment("ingest.python_fallback")

    with open(path, "rb") as f:
        text = f.read().decode("utf-8")
    rows = parse_csv_text(text, delimiter, quote)
    if not rows:
        return Frame({})

    if header:
        names = rows[0]
        rows = rows[1:]
    else:
        names = [f"_c{i}" for i in range(len(rows[0]))]
    if schema is not None:
        if len(schema) != len(names):
            raise ValueError(
                f"schema has {len(schema)} fields but the file has "
                f"{len(names)} columns")
        names = [n for n, _ in schema]

    ncols = len(names)
    if mode != "PERMISSIVE":
        bad = [r for r in rows if len(r) != ncols]
        if bad and mode == "FAILFAST":
            raise ValueError(
                f"FAILFAST: malformed CSV record (expected {ncols} fields, "
                f"got {len(bad[0])}): {bad[0]!r}")
        if bad:  # DROPMALFORMED
            rows = [r for r in rows if len(r) == ncols]
    cols: list[list[str]] = [[] for _ in range(ncols)]
    for r in rows:
        for i in range(ncols):
            cols[i].append(r[i] if i < len(r) else "")

    data = {}
    if schema is not None:
        for (name, type_name), values in zip(schema, cols):
            data[name] = _cast_column(values, type_name)
        return Frame(data)
    for name, values in zip(names, cols):
        if infer_schema:
            data[name] = infer_column(values)
        else:
            data[name] = np.asarray([v if v not in _NULL_STRINGS else None
                                     for v in values], dtype=object)
    return Frame(data)


class DataFrameReader:
    """Builder-style reader mirroring ``spark.read().format("csv")
    .option(...).load(path)`` (`DataQuality4MachineLearningApp.java:53-55`)."""

    def __init__(self, session=None):
        self._session = session
        self._format = "csv"
        self._options: dict[str, str] = {}
        self._schema = None

    def schema(self, ddl: str) -> "DataFrameReader":
        """Explicit schema as a Spark DDL string (``"a INT, b DOUBLE"``) —
        skips inference and casts columns to the declared types."""
        self._schema = parse_ddl_schema(ddl)
        return self

    def format(self, fmt: str) -> "DataFrameReader":
        self._format = fmt.lower()
        return self

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key.lower()] = str(value)
        return self

    def options(self, **kwargs) -> "DataFrameReader":
        for k, v in kwargs.items():
            self.option(k, v)
        return self

    def _bool_opt(self, key: str, default: bool) -> bool:
        v = self._options.get(key.lower())
        return default if v is None else v.strip().lower() in ("true", "1", "yes")

    def load(self, path: str) -> Frame:
        if self._format not in ("csv", "json", "parquet"):
            raise ValueError(
                f"unsupported format {self._format!r} (csv, json, "
                "or parquet)")
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        if self._format == "parquet":
            from .parquet import read_parquet

            out = read_parquet(path)
        elif self._format == "json":
            from .jsonl import read_json

            out = read_json(path,
                            multi_line=self._bool_opt("multiline", False))
        else:
            out = read_csv(
                path,
                header=self._bool_opt("header", False),
                infer_schema=self._bool_opt("inferschema", False),
                delimiter=self._options.get(
                    "sep", self._options.get("delimiter", ",")),
                engine=self._options.get("engine", "auto"),
                quote=self._options.get("quote", '"'),
                mode=self._options.get("mode", "PERMISSIVE"),
                schema=self._schema,
            )
        # Sharded-frames ingest hand-off (spark.shard.enabled): loaded
        # frames above the minRows bound land row-sharded, so the whole
        # downstream pipeline — DQ filters, SQL, fit packing — runs the
        # sharded lowerings without re-placement. One flag check when
        # sharding is off.
        from ..parallel.shard import maybe_shard_frame

        return maybe_shard_frame(out)

    def csv(self, path: str, header: bool = False, inferSchema: bool = False) -> Frame:
        return self.option("header", header).option("inferSchema", inferSchema).load(path)

    def json(self, path: str, multiLine: bool = False) -> Frame:
        return self.format("json").option("multiLine", multiLine).load(path)

    def parquet(self, path: str) -> Frame:
        return self.format("parquet").load(path)
