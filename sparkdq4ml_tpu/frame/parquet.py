"""Parquet read/write — the columnar half of the data-loader capability.

Spark's default on-disk format (`df.write.parquet` / `spark.read
.parquet`); the reference app only touches CSV
(`DataQuality4MachineLearningApp.java:53-55`), but a user switching from
Spark expects the columnar path too. Parquet is already column-major, so
the mapping to the engine's column-store Frame is direct: one Arrow
column per Frame column, no row pivoting anywhere — numerics zero-copy
into numpy on read where Arrow allows.

Gated on pyarrow (present in this image); a clear error otherwise.
"""

from __future__ import annotations

import numpy as np

from .frame import Frame


def _require_pyarrow():
    try:
        import pyarrow
        import pyarrow.parquet
    except ImportError as e:                      # pragma: no cover
        raise ImportError(
            "parquet support requires pyarrow, which is not installed "
            "(use csv/json formats instead)") from e
    return pyarrow


def write_parquet(frame, path: str, compression: str = "snappy") -> None:
    """Persist valid rows (masked slots never leave the engine)."""
    pa = _require_pyarrow()
    d = frame.to_pydict()
    cols = {}
    for name in frame.columns:
        v = d[name]
        arr = np.asarray(v)
        if arr.dtype != object and arr.ndim == 2:
            # equal-length vector column (a 2D device array in the
            # engine) → Arrow fixed-shape-agnostic list column
            cols[name] = pa.array([[float(e) for e in row] for row in arr],
                                  type=pa.list_(pa.float64()))
            continue
        if arr.dtype == object:
            vals = list(v)
            # vector/array cells → Arrow lists; else strings (None = null)
            if any(isinstance(x, (list, tuple, np.ndarray))
                   for x in vals if x is not None):
                cols[name] = pa.array(
                    [None if x is None else
                     [float(e) for e in np.asarray(x).ravel()]
                     for x in vals],
                    type=pa.list_(pa.float64()))
            else:
                cols[name] = pa.array(
                    [None if x is None else str(x) for x in vals],
                    type=pa.string())
        else:
            cols[name] = pa.array(arr)
    import pyarrow.parquet as pq

    pq.write_table(pa.table(cols), path, compression=compression)


def read_parquet(path: str) -> Frame:
    pa = _require_pyarrow()
    import pyarrow.parquet as pq

    table = pq.read_table(path)
    data = {}
    for name in table.column_names:
        col = table.column(name)
        t = col.type
        if pa.types.is_list(t) or pa.types.is_large_list(t):
            data[name] = np.asarray(
                [None if x is None else np.asarray(x, np.float64)
                 for x in col.to_pylist()], dtype=object)
        elif (pa.types.is_string(t) or pa.types.is_large_string(t)
              or pa.types.is_binary(t)):
            data[name] = np.asarray(col.to_pylist(), dtype=object)
        elif pa.types.is_boolean(t):
            data[name] = np.asarray(col.to_pylist(), dtype=bool)
        else:
            # nullable numerics: Arrow nulls become NaN (the engine's
            # numeric null), intact values pass through
            arr = col.to_numpy(zero_copy_only=False)
            if col.null_count:
                arr = np.asarray(arr, np.float64)
                mask = np.asarray(col.is_null().to_pylist(), bool)
                arr = np.where(mask, np.nan, arr)
            data[name] = arr
    return Frame(data)
