"""Window functions: ``Window.partitionBy(...).orderBy(...)`` + ranking /
offset / windowed-aggregate expressions, mirroring Spark's
``pyspark.sql.Window`` and ``F.row_number().over(w)`` surface (a capability
upgrade over the reference app, which exercises no window functions —
SURVEY.md §2.2; provided so groupBy/sort/SQL users find the full relational
toolkit).

Design, consistent with the engine's host-boundary rule (frame.py: sort/join/
groupBy plan on host, numeric data stays in device arrays): the window *plan*
(partitioning + intra-partition order) is computed host-side with lexsort —
order-dependent by nature, like ``Frame.sort`` — then each function is
evaluated vectorized per partition and scattered back to the frame's original
row slots, so the result is an ordinary aligned column and masked rows stay
masked. Numeric results return as device arrays.

Frame semantics for ordered windows follow Spark's default frame
``RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW``: running aggregates
include all *peer* rows (ties in the order key). Unordered windows aggregate
the whole partition.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..config import float_dtype, int_dtype
from ..ops.expressions import Col, Expr

_RANKING_FNS = ("row_number", "rank", "dense_rank", "percent_rank",
                "cume_dist", "ntile")
_OFFSET_FNS = ("lag", "lead")
_AGG_FNS = ("count", "sum", "avg", "mean", "min", "max")
# value-picking fns: the value at the frame's first/last/n-th row
_VALUE_FNS = ("first_value", "last_value", "nth_value")


# Spark's frame-boundary sentinels (pyspark.sql.Window uses extreme ints)
_UNBOUNDED = (1 << 62)


class WindowSpec:
    """Immutable partition/order/frame specification."""

    def __init__(self, partition_cols: Sequence[str] = (),
                 order_cols: Sequence[tuple[str, bool]] = (),
                 frame: tuple = None):
        self.partition_cols = tuple(partition_cols)
        self.order_cols = tuple(order_cols)
        self.frame = frame            # None | ("rows"|"range", start, end)

    def partition_by(self, *cols: str) -> "WindowSpec":
        return WindowSpec(self.partition_cols + tuple(_colname(c) for c in cols),
                          self.order_cols, self.frame)

    partitionBy = partition_by

    def order_by(self, *cols) -> "WindowSpec":
        return WindowSpec(self.partition_cols,
                          self.order_cols + tuple(_order_item(c) for c in cols),
                          self.frame)

    orderBy = order_by

    def rows_between(self, start: int, end: int) -> "WindowSpec":
        """ROWS frame: physical row offsets relative to the current row
        (``Window.unboundedPreceding`` / ``currentRow`` /
        ``unboundedFollowing`` sentinels, or plain ints — Spark API)."""
        start, end = int(start), int(end)
        if start > end:
            raise ValueError(f"frame start {start} > end {end}")
        return WindowSpec(self.partition_cols, self.order_cols,
                          ("rows", start, end))

    rowsBetween = rows_between

    def range_between(self, start: int, end: int) -> "WindowSpec":
        """RANGE frame. Supported bounds: the unbounded/current-row
        sentinel combinations (value offsets would need per-row order-key
        arithmetic — not implemented; Spark's common uses are the
        sentinel forms)."""
        start, end = int(start), int(end)
        if start > end:
            raise ValueError(f"frame start {start} > end {end}")
        for v in (start, end):
            if v not in (-_UNBOUNDED, 0, _UNBOUNDED) and abs(v) >= _UNBOUNDED:
                raise ValueError("bad frame bound")
        if start not in (-_UNBOUNDED, 0) or end not in (0, _UNBOUNDED):
            if not (start == -_UNBOUNDED and end == _UNBOUNDED):
                raise NotImplementedError(
                    "range_between supports only unboundedPreceding/"
                    "currentRow/unboundedFollowing bounds")
        return WindowSpec(self.partition_cols, self.order_cols,
                          ("range", start, end))

    rangeBetween = range_between

    def describe(self) -> str:
        parts = []
        if self.partition_cols:
            parts.append("PARTITION BY " + ", ".join(self.partition_cols))
        if self.order_cols:
            parts.append("ORDER BY " + ", ".join(
                f"{c}{'' if asc else ' DESC'}" for c, asc in self.order_cols))
        if self.frame is not None:
            kind, s, e = self.frame

            def b(v):
                if v <= -_UNBOUNDED:
                    return "UNBOUNDED PRECEDING"
                if v >= _UNBOUNDED:
                    return "UNBOUNDED FOLLOWING"
                if v == 0:
                    return "CURRENT ROW"
                return f"{-v} PRECEDING" if v < 0 else f"{v} FOLLOWING"
            parts.append(f"{kind.upper()} BETWEEN {b(s)} AND {b(e)}")
        return " ".join(parts)

    def __repr__(self):
        return f"WindowSpec({self.describe()})"


def _key_parts(k: np.ndarray) -> list[np.ndarray]:
    """Decompose one sort/group key into lexsort component arrays, highest
    priority first. Object (string) keys become (not-null flag, value with
    None→"") so nulls form their own group — distinct from the empty string —
    and sort first (Spark's NULLS FIRST); bool keys cast to int8 (numpy
    forbids unary minus on bool, needed for DESC)."""
    if k.dtype == object:
        flag = np.asarray([x is not None for x in k], np.int8)
        vals = np.asarray([x if x is not None else "" for x in k],
                          dtype=object)
        return [flag, vals]
    if k.dtype == np.bool_:
        return [k.astype(np.int8)]
    if np.issubdtype(k.dtype, np.floating):
        # NaN = SQL NULL: the not-null flag makes NaN keys sort first
        # ascending (NULLS FIRST) and, negated for DESC, last (NULLS LAST)
        return [(~np.isnan(k)).astype(np.int8), k]
    return [k]


def _neq(ks: np.ndarray) -> np.ndarray:
    """Adjacent-row "value changed" flags for a sorted key component, with
    SQL NULL grouping: NaN equals NaN (nulls form one group, as Spark's
    windows treat them)."""
    if ks.dtype == object:
        return np.asarray([ks[i] != ks[i - 1] for i in range(1, len(ks))],
                          bool)
    neq = ks[1:] != ks[:-1]
    if np.issubdtype(ks.dtype, np.floating):
        neq &= ~(np.isnan(ks[1:]) & np.isnan(ks[:-1]))
    return neq


def _peer_upto(peer: np.ndarray, s: int, e: int) -> np.ndarray:
    """For each sorted row in partition [s, e), the count of partition rows
    up to and including its last peer (ties in the order key) — the row set
    of the default RANGE ...CURRENT ROW frame."""
    pk = peer[s:e].copy()
    pk[0] = True
    block_id = np.cumsum(pk) - 1
    block_end = np.r_[np.flatnonzero(pk)[1:], e - s]
    return block_end[block_id]


def _colname(c) -> str:
    if isinstance(c, str):
        return c
    if isinstance(c, Col):
        return c.name
    raise TypeError(f"window partition key must be a column name, got {c!r}")


def _order_item(c) -> tuple[str, bool]:
    """Accept "name", ("name", ascending), a Col, or a
    ``col.asc()``/``col.desc()`` SortOrder marker (the Spark idiom
    ``Window.orderBy(col("x").desc())``)."""
    from ..ops.expressions import SortOrder

    if isinstance(c, SortOrder):
        return (_colname(c.child), c.ascending)
    if isinstance(c, tuple) and len(c) == 2:
        return (_colname(c[0]), bool(c[1]))
    return (_colname(c), True)


class Window:
    """Entry points, Spark-style: ``Window.partitionBy("k").orderBy("v")``."""

    unboundedPreceding = unbounded_preceding = -_UNBOUNDED
    unboundedFollowing = unbounded_following = _UNBOUNDED
    currentRow = current_row = 0

    @staticmethod
    def partition_by(*cols: str) -> WindowSpec:
        return WindowSpec().partition_by(*cols)

    partitionBy = partition_by

    @staticmethod
    def order_by(*cols) -> WindowSpec:
        return WindowSpec().order_by(*cols)

    orderBy = order_by


class WindowFunction:
    """An unbound window function (``row_number()``); ``.over(spec)`` binds it.

    Spark raises at analysis time when a ranking function is used without an
    OVER clause; evaluating an unbound WindowFunction raises equivalently.
    """

    def __init__(self, fn: str, column: Optional[str] = None,
                 offset: int = 1, default=None, n: Optional[int] = None):
        self.fn = fn
        self.column = column
        self.offset = offset
        self.default = default
        self.n = n

    def over(self, spec: WindowSpec) -> "WindowExpr":
        return WindowExpr(self, spec)

    def __repr__(self):
        return f"{self.fn}({self.column or ''})"


class WindowExpr(Expr):
    """A window function bound to a WindowSpec — a regular column Expr, usable
    in ``withColumn``/``select`` and produced by SQL ``fn(...) OVER (...)``."""

    def __init__(self, func: WindowFunction, spec: WindowSpec):
        if func.fn in _RANKING_FNS + _OFFSET_FNS and not spec.order_cols:
            raise ValueError(f"{func.fn}() requires an ORDER BY in its window")
        self.func = func
        self.spec = spec

    @property
    def name(self) -> str:
        # Descriptive like Spark's generated names, so two different window
        # expressions in one select never collide in the output columns.
        return f"{self.func!r} OVER ({self.spec.describe()})"

    def __str__(self):
        return self.name

    # -- evaluation --------------------------------------------------------
    def eval(self, frame):
        from ..utils.profiling import counters

        func, spec = self.func, self.spec
        # The window plan is host-side by design (module docstring): the
        # mask + every referenced device column pull to host here. ONE
        # counted sync per window evaluation — the same batch convention
        # as the join key-pull — so host-boundary audits see it.
        counters.increment("frame.host_sync")
        m = np.asarray(frame.mask)
        idx = np.flatnonzero(m)                      # valid slots only
        nv = len(idx)

        def host(name):
            arr = frame._column_values(name)
            a = arr if (isinstance(arr, np.ndarray) and arr.dtype == object) \
                else np.asarray(arr)
            return a[idx]

        # -- plan: lexsort by (partition keys, then order keys) ------------
        pkeys = [_key_parts(host(c)) for c in spec.partition_cols]
        okeys = []
        for cname, asc in spec.order_cols:
            parts = _key_parts(host(cname))
            if not asc:
                if parts[-1].dtype == object:
                    raise ValueError("descending window order on string "
                                     "columns is not supported")
                parts = [-p for p in parts]
            okeys.append(parts)
        # np.lexsort: primary key LAST → flatten in reverse priority order
        # (order keys before partitions, secondary components before primary)
        lex = [comp for parts in reversed(pkeys + okeys)
               for comp in reversed(parts)]
        order = (np.lexsort(lex) if lex else np.arange(nv))

        # partition boundaries in sorted domain (null grouping: _key_parts
        # separates nulls via the flag component, _neq folds NaN with NaN)
        boundary = np.zeros(nv, bool)
        if nv:
            boundary[0] = True
        for parts in pkeys:
            for comp in parts:
                boundary[1:] |= _neq(comp[order])

        # peer boundaries: partition boundary OR any order-key change
        peer = boundary.copy()
        for parts in okeys:
            for comp in parts:
                peer[1:] |= _neq(comp[order])

        starts = np.flatnonzero(boundary)
        ends = np.r_[starts[1:], nv]

        # -- evaluate per partition (vectorized inside each slice) ---------
        vals_sorted, fill, is_string = self._compute(
            frame, func, host, order, starts, ends, peer, nv)

        # -- scatter back to original slots --------------------------------
        if is_string:
            out = np.full(frame.num_slots, None, dtype=object)
            tmp = np.empty(nv, dtype=object)
            tmp[order] = vals_sorted
            out[idx] = tmp
            return out
        tmp = np.empty(nv, dtype=vals_sorted.dtype)
        tmp[order] = vals_sorted
        out = np.full(frame.num_slots, fill, dtype=vals_sorted.dtype)
        out[idx] = tmp
        return jnp.asarray(out)

    def _compute(self, frame, func, host, order, starts, ends, peer, nv):
        """Returns (values in sorted domain, masked-slot fill, is_string)."""
        fn = func.fn
        fdt = np.dtype(float_dtype())
        idt = np.dtype(int_dtype())

        if fn in _RANKING_FNS:
            pos = np.arange(nv)
            gstart = np.zeros(nv, idt)
            for s, e in zip(starts, ends):
                gstart[s:e] = s
            if fn == "row_number":
                return (pos - gstart + 1).astype(idt), 0, False
            # index of first row of the current peer group
            peer_start = np.maximum.accumulate(np.where(peer, pos, 0))
            if fn == "rank":
                return (peer_start - gstart + 1).astype(idt), 0, False
            if fn == "dense_rank":
                cp = np.cumsum(peer)
                return (cp - cp[gstart] + 1).astype(idt), 0, False
            npart = np.zeros(nv, idt)
            for s, e in zip(starts, ends):
                npart[s:e] = e - s
            if fn == "percent_rank":
                r = (peer_start - gstart).astype(fdt)
                denom = np.maximum(npart - 1, 1).astype(fdt)
                return np.where(npart > 1, r / denom, 0.0).astype(fdt), \
                    np.nan, False
            if fn == "cume_dist":
                # rows ≤ current peer group = index just past the last peer
                out = np.empty(nv, fdt)
                for s, e in zip(starts, ends):
                    out[s:e] = _peer_upto(peer, s, e) / (e - s)
                return out, np.nan, False
            if fn == "ntile":
                k = int(func.n)
                if k < 1:
                    raise ValueError("ntile requires a positive bucket count")
                out = np.empty(nv, idt)
                for s, e in zip(starts, ends):
                    n = e - s
                    base, rem = divmod(n, min(k, n) if n else 1)
                    # Spark: first `rem` buckets get base+1 rows
                    sizes = np.full(min(k, n), base, np.int64)
                    sizes[:rem] += 1
                    out[s:e] = np.repeat(np.arange(1, len(sizes) + 1), sizes)
                return out, 0, False

        if fn in _OFFSET_FNS:
            v = host(func.column)[order]
            off = func.offset if fn == "lag" else -func.offset
            is_string = v.dtype == object
            if is_string:
                out = np.full(nv, None, dtype=object)
                default = func.default
            else:
                if not np.issubdtype(v.dtype, np.floating):
                    v = v.astype(fdt)  # int lag needs a null (NaN) slot
                out = np.full(nv, np.nan, dtype=v.dtype)
                default = np.nan if func.default is None else func.default
            for s, e in zip(starts, ends):
                seg = v[s:e]
                if off == 0:           # lag/lead 0 = the current row (Spark)
                    out[s:e] = seg
                    continue
                shifted = np.full(e - s, default,
                                  dtype=object if is_string else seg.dtype)
                if off > 0 and e - s > off:
                    shifted[off:] = seg[:-(off)]
                elif off < 0 and e - s > -off:
                    shifted[:off] = seg[-off:]
                out[s:e] = shifted
            return out, (None if is_string else np.nan), is_string

        if fn in _VALUE_FNS:
            v = host(func.column)[order]
            is_string = v.dtype == object
            ordered = bool(self.spec.order_cols)
            frame_spec = self.spec.frame
            _require_order_for_frame(frame_spec, ordered)
            if fn == "nth_value" and int(func.n) < 1:
                raise ValueError("nth_value requires a positive offset")
            if is_string:
                out = np.full(nv, None, dtype=object)
            else:
                v = v.astype(np.float64)
                out = np.full(nv, np.nan, np.float64)
            for s, e in zip(starts, ends):
                n = e - s
                if n == 0:
                    continue
                if frame_spec is not None:
                    lo, hi, empty = _frame_bounds(frame_spec, peer, s, e, n)
                elif ordered:
                    # default frame: RANGE UNBOUNDED PRECEDING..CURRENT ROW
                    # (incl. peers) — last_value famously tracks the
                    # current peer group, not the partition end
                    upto = _peer_upto(peer, s, e)
                    lo = np.zeros(n, np.int64)
                    hi = upto - 1
                    empty = lo > hi
                else:                    # whole partition
                    lo = np.zeros(n, np.int64)
                    hi = np.full(n, n - 1, np.int64)
                    empty = lo > hi
                if fn == "first_value":
                    pick = lo
                elif fn == "last_value":
                    pick = hi
                else:
                    pick = lo + int(func.n) - 1
                    empty = empty | (pick > hi)
                seg = v[s:e]
                vals = seg[np.clip(pick, 0, n - 1)]
                if is_string:
                    res = np.array(vals, dtype=object)
                    res[empty] = None
                else:
                    res = np.where(empty, np.nan, vals)
                out[s:e] = res
            if is_string:
                return out, None, True
            return out.astype(fdt), np.nan, False

        if fn in _AGG_FNS:
            agg = {"mean": "avg"}.get(fn, fn)
            counting_all = agg == "count" and func.column is None
            if counting_all:
                v = np.ones(nv, fdt)
                null = np.zeros(nv, bool)
            else:
                v = host(func.column)[order]
                if v.dtype == object:
                    if agg != "count":   # COUNT alone is dtype-agnostic
                        raise ValueError(
                            f"windowed {fn}() over a string column is not "
                            "supported")
                    null = np.asarray([x is None for x in v], bool)
                    v = np.ones(nv, np.float64)
                else:
                    v = v.astype(np.float64)
                    null = np.isnan(v)
            ordered = bool(self.spec.order_cols)
            frame_spec = self.spec.frame
            _require_order_for_frame(frame_spec, ordered)
            out = np.empty(nv, np.float64)
            for s, e in zip(starts, ends):
                seg = np.where(null[s:e], 0.0, v[s:e])
                cnt = (~null[s:e]).astype(np.float64)
                if frame_spec is not None:
                    out[s:e] = _framed_agg(agg, frame_spec, seg, cnt,
                                           v[s:e], null[s:e],
                                           peer, s, e)
                    continue
                if not ordered:          # whole-partition aggregate
                    out[s:e] = _segment_agg(agg, seg, cnt, v[s:e], null[s:e])
                    continue
                # running aggregate incl. peers (RANGE ... CURRENT ROW)
                upto = _peer_upto(peer, s, e)       # rows included per row
                cs, cc = np.cumsum(seg), np.cumsum(cnt)
                if agg == "count":
                    out[s:e] = cc[upto - 1]
                elif agg == "sum":
                    # zero non-null rows in the frame so far → NULL, not
                    # 0 (Spark; caught by the pandas differential sweep)
                    out[s:e] = np.where(cc[upto - 1] > 0, cs[upto - 1],
                                        np.nan)
                elif agg == "avg":
                    c = cc[upto - 1]
                    out[s:e] = np.where(c > 0, cs[upto - 1] / np.maximum(c, 1),
                                        np.nan)
                else:  # min / max: accumulate with nulls neutralized
                    neutral = np.inf if agg == "min" else -np.inf
                    acc = np.where(null[s:e], neutral, v[s:e])
                    run = (np.minimum if agg == "min" else np.maximum) \
                        .accumulate(acc)
                    # all-null-so-far → NaN; decided by the non-null count,
                    # so legitimate ±inf values pass through untouched
                    out[s:e] = np.where(cc[upto - 1] > 0, run[upto - 1],
                                        np.nan)
            if agg == "count":
                return out.astype(idt), 0, False
            return out.astype(fdt), np.nan, False

        raise ValueError(f"unknown window function {fn!r}")


def _require_order_for_frame(frame_spec, ordered: bool) -> None:
    """Spark: ROWS frames always need ordering; RANGE frames need it
    whenever a CURRENT ROW bound makes the frame row-dependent
    (unbounded-both is the only orderless form)."""
    if frame_spec is not None and not ordered:
        kind_, fs_, fe_ = frame_spec
        if kind_ == "rows" or not (fs_ <= -_UNBOUNDED
                                   and fe_ >= _UNBOUNDED):
            raise ValueError(f"a {kind_.upper()} frame requires an "
                             "ORDER BY in its window")


def _frame_bounds(frame_spec, peer, s, e, n):
    """Per-row inclusive frame bounds for one partition (sorted domain):
    returns ``(lo, hi, empty)``. ROWS offsets clip to the partition;
    RANGE bounds resolve through peer groups (CURRENT ROW includes all
    peers, Spark semantics)."""
    kind, fs, fe = frame_spec
    r = np.arange(n)
    if kind == "range":
        upto = _peer_upto(peer, s, e)              # rows ≤ last peer
        pk = peer[s:e].copy()
        pk[0] = True                               # callers ensure n > 0
        peer_start = np.maximum.accumulate(np.where(pk, r, 0))
        lo = np.zeros(n, np.int64) if fs <= -_UNBOUNDED else peer_start
        hi = np.full(n, n - 1, np.int64) if fe >= _UNBOUNDED else upto - 1
    else:                                          # rows
        lo = np.zeros(n, np.int64) if fs <= -_UNBOUNDED else \
            np.clip(r + fs, 0, n)                  # n ⇒ empty below
        hi = np.full(n, n - 1, np.int64) if fe >= _UNBOUNDED else \
            np.clip(r + fe, -1, n - 1)             # −1 ⇒ empty below
    return lo, hi, lo > hi


def _framed_agg(agg, frame_spec, seg, cnt, raw, null, peer, s, e):
    """Aggregate over an explicit ROWS/RANGE frame for one partition
    (host-side, vectorized): per sorted row r, the inclusive window
    [r+start, r+end] clipped to the partition (ROWS), or the sentinel
    RANGE forms resolved through peer groups. Spark semantics for empty /
    all-null windows: count = 0, sum/avg/min/max = null."""
    n = len(seg)
    if n == 0:
        return np.empty(0, np.float64)
    lo, hi, empty = _frame_bounds(frame_spec, peer, s, e, n)
    lo_c = np.clip(lo, 0, n - 1)
    hi_c = np.clip(hi, 0, n - 1)
    S = np.concatenate([[0.0], np.cumsum(seg)])
    C = np.concatenate([[0.0], np.cumsum(cnt)])
    wcnt = np.where(empty, 0.0, C[hi_c + 1] - C[lo_c])
    if agg == "count":
        return wcnt
    wsum = np.where(empty, 0.0, S[hi_c + 1] - S[lo_c])
    if agg == "sum":
        return np.where(wcnt > 0, wsum, np.nan)
    if agg == "avg":
        return np.where(wcnt > 0, wsum / np.maximum(wcnt, 1.0), np.nan)

    # min / max with nulls neutralized
    neutral = np.inf if agg == "min" else -np.inf
    acc = np.where(null, neutral, raw.astype(np.float64))
    reduce_ = np.minimum if agg == "min" else np.maximum
    if np.all(lo_c == 0):                  # frame starts at partition top
        val = reduce_.accumulate(acc)[hi_c]
    elif np.all(hi_c == n - 1):            # frame runs to partition end
        val = reduce_.accumulate(acc[::-1])[::-1][lo_c]
    else:
        val = _window_reduce(reduce_, acc, lo_c, hi_c, neutral)
    return np.where(wcnt > 0, val, np.nan)


def _window_reduce(reduce_, acc, lo, hi, neutral):
    """Per-row reduce of acc[lo[r]..hi[r]] for bounded fixed-span windows
    (lo/hi come from a common offset pair, so hi−lo is constant except at
    the clipped partition edges — pad with the neutral and slide)."""
    n = len(acc)
    w = int(np.max(hi - lo)) + 1 if n else 1
    w = max(w, 1)
    padded = np.concatenate([np.full(w - 1, neutral), acc,
                             np.full(w - 1, neutral)])
    sw = np.lib.stride_tricks.sliding_window_view(padded, w)
    # window covering [lo, hi] of width hi-lo+1 ≤ w sits at padded index
    # hi + (w-1) - (w-1) = ... anchor on hi: take the window ENDING at hi
    # (padded end index hi + w - 1), then mask off entries before lo via
    # the left neutral padding — entries [hi-w+1, hi]; those below lo are
    # within the neutral pad only when lo == hi-w+1, which holds except at
    # clipped edges where extra (smaller) entries are real rows BELOW lo.
    vals = sw[hi]  # window [hi-w+1, hi] in padded coords
    # rows below lo inside the span must be neutralized
    offs = np.arange(w)
    starts = hi - w + 1
    mask_bad = (starts[:, None] + offs[None, :]) < lo[:, None]
    vals = np.where(mask_bad, neutral, vals)
    return reduce_.reduce(vals, axis=1)


def _segment_agg(agg, seg, cnt, raw, null):
    n = cnt.sum()
    if agg == "count":
        return n
    if n == 0:
        return np.nan
    if agg == "sum":
        return seg.sum()
    if agg == "avg":
        return seg.sum() / n
    vals = raw[~null]
    return vals.min() if agg == "min" else vals.max()


# -- function constructors (exported via sparkdq4ml_tpu.functions) ----------

def row_number() -> WindowFunction:
    """Sequential number within the partition, by window order (1-based)."""
    return WindowFunction("row_number")


def rank() -> WindowFunction:
    """Rank with gaps after ties (SQL RANK)."""
    return WindowFunction("rank")


def dense_rank() -> WindowFunction:
    """Rank without gaps (SQL DENSE_RANK)."""
    return WindowFunction("dense_rank")


def percent_rank() -> WindowFunction:
    """(rank - 1) / (partition size - 1); 0 for single-row partitions."""
    return WindowFunction("percent_rank")


def cume_dist() -> WindowFunction:
    """Fraction of partition rows ≤ the current row's order key."""
    return WindowFunction("cume_dist")


def ntile(n: int) -> WindowFunction:
    """Partition rows into ``n`` ordered buckets (1-based), sizes differing
    by at most one (Spark/SQL NTILE)."""
    return WindowFunction("ntile", n=n)


def lag(col: Union[str, Col], offset: int = 1, default=None) -> WindowFunction:
    """Value of ``col`` ``offset`` rows before the current row in the window
    order; ``default`` (null if omitted) beyond the partition edge."""
    return WindowFunction("lag", column=_colname(col), offset=offset,
                          default=default)


def lead(col: Union[str, Col], offset: int = 1, default=None) -> WindowFunction:
    """Value of ``col`` ``offset`` rows after the current row."""
    return WindowFunction("lead", column=_colname(col), offset=offset,
                          default=default)


def first_value(col: Union[str, Col]) -> WindowFunction:
    """Value at the frame's first row (default frame: the partition
    start). Spark's ``first(col).over(w)`` maps here."""
    return WindowFunction("first_value", column=_colname(col))


def last_value(col: Union[str, Col]) -> WindowFunction:
    """Value at the frame's last row. Under the default frame (RANGE
    UNBOUNDED PRECEDING..CURRENT ROW) this tracks the current peer
    group — Spark's famously surprising semantics — not the partition
    end; add ROWS/RANGE ... UNBOUNDED FOLLOWING for that."""
    return WindowFunction("last_value", column=_colname(col))


def nth_value(col: Union[str, Col], n: int) -> WindowFunction:
    """Value at the frame's n-th row (1-based); null when the frame has
    fewer than ``n`` rows."""
    return WindowFunction("nth_value", column=_colname(col), n=n)


def window_agg(fn: str, column: Optional[str]) -> WindowFunction:
    """Windowed aggregate builder — ``sum("x").over(w)`` routes here."""
    return WindowFunction(fn, column=column)
