from .frame import Frame
from .csv import DataFrameReader, read_csv
