from .frame import Frame, list_column
from .csv import DataFrameReader, read_csv
