"""ctypes binding to the native C++ CSV tokenizer (``native/csvparse.cpp``).

Role: the data-loader fast path — the analogue of the Univocity parser inside
Spark's CSV source (SURVEY.md §2.2 "CSV reader"). The native tokenizer handles
the common all-numeric case (which is what feature matrices are); anything
else returns ``None`` here and the pure-Python reader takes over, so the
framework works identically whether or not the shared library is built
(``make -C native``).

The C side parses the file into column-major float64 with NaN for empty
fields, handling bare-CR/CRLF/LF records; Python decides integer-vs-double per
column exactly like ``csv.infer_column`` and uploads to device once.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..config import float_dtype, int_dtype

_LIB = None
_LIB_TRIED = False

_SO_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "libdqcsv.so"),
    os.path.join(os.path.dirname(__file__), "_native", "libdqcsv.so"),
]


def _load():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    for p in _SO_PATHS:
        p = os.path.abspath(p)
        if os.path.exists(p):
            try:
                lib = ctypes.CDLL(p)
            except OSError:
                continue
            lib.dq_parse_numeric_csv.restype = ctypes.c_longlong
            lib.dq_parse_numeric_csv.argtypes = [
                ctypes.c_char_p,                      # path
                ctypes.c_char,                        # delimiter
                ctypes.c_char,                        # quote
                ctypes.c_int,                         # skip_header
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),  # out data
                ctypes.POINTER(ctypes.c_longlong),    # out ncols
                ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),    # out int_flags
            ]
            lib.dq_free.restype = None
            lib.dq_free.argtypes = [ctypes.c_void_p]
            _LIB = lib
            break
    return _LIB


def available() -> bool:
    return _load() is not None


def try_read_csv(path: str, header: bool, infer_schema: bool, delimiter: str,
                 quote: str = '"', required: bool = False):
    """Native read; returns a Frame or None (fallback to python engine)."""
    lib = _load()
    if lib is None:
        if required:
            raise RuntimeError(
                "native CSV engine requested but native/libdqcsv.so is not "
                "built (run `make -C native`)")
        return None
    if len(delimiter.encode("utf-8")) != 1 or len(quote.encode("utf-8")) != 1:
        return None  # ctypes c_char needs exactly one BYTE → python engine
    if not infer_schema or header:
        # Native fast path only covers the inferred all-numeric, headerless
        # shape (the reference's shape); let python handle the rest.
        if required:
            raise RuntimeError("native CSV engine only supports "
                               "header=False, infer_schema=True")
        return None

    data_p = ctypes.POINTER(ctypes.c_double)()
    ncols = ctypes.c_longlong(0)
    intf_p = ctypes.POINTER(ctypes.c_char)()
    nrows = lib.dq_parse_numeric_csv(
        path.encode(), delimiter.encode(), quote.encode(),
        1 if header else 0,
        ctypes.byref(data_p), ctypes.byref(ncols), ctypes.byref(intf_p))
    if nrows < 0:
        if nrows == -2:
            raise FileNotFoundError(path)
        return None  # non-numeric content → python engine
    data = {}
    try:
        nc = ncols.value
        if nc == 0 or nrows == 0:
            from .frame import Frame
            return Frame({})
        # No intermediate .copy(): astype below always copies out of the
        # C buffer (dtype conversion or copy=True default), so an extra
        # staging copy would just add a full-matrix memory pass.
        flat = np.ctypeslib.as_array(data_p, shape=(nc * nrows,))
        cols = flat.reshape(nc, nrows)  # column-major from C
        int_flags = bytes(ctypes.cast(intf_p, ctypes.POINTER(ctypes.c_char * nc)).contents)
        for j in range(nc):
            col = cols[j]
            if int_flags[j]:
                data[f"_c{j}"] = col.astype(np.dtype(int_dtype()))
            else:
                data[f"_c{j}"] = col.astype(np.dtype(float_dtype()))
    finally:
        lib.dq_free(data_p)
        lib.dq_free(intf_p)

    from .frame import Frame

    return Frame(data)
