"""ctypes binding to the native C++ CSV tokenizer (``native/csvparse.cpp``).

Role: the data-loader fast path — the analogue of the Univocity parser inside
Spark's CSV source (SURVEY.md §2.2 "CSV reader"). The native tokenizer handles
the common all-numeric case (which is what feature matrices are), with or
without a header record (names are read host-side, the body is skipped
C-side); anything else returns ``None`` here and the pure-Python reader takes
over, so the framework works identically whether or not the shared library is
built (``make -C native``).

Two native paths, selected by ``spark.ingest.*`` conf (see ``config``):

* **one-shot** — the whole file parses into column-major float64 in one
  call (the legacy contract; ``spark.ingest.streaming=false`` pins exactly
  this with the v1 ABI and auto tiers);
* **streaming** — files larger than one chunk (``spark.ingest.chunkBytes``)
  parse through the ``dq_stream`` API in bounded chunks cut on STRUCTURAL
  record boundaries (quote-parity aware, so a quoted field containing
  newlines is never torn). A producer thread runs the native parse (the
  ctypes call releases the GIL) up to ``spark.ingest.prefetch`` chunks
  ahead of the consumer, which converts each chunk's columns and hands
  them to JAX — parse of chunk N+1 overlaps the dtype convert + (async)
  device transfer of chunk N, and per-process memory stays bounded by
  ``chunk_bytes * (prefetch + 2)`` instead of the whole file. Column
  dtype finalizes at EOF from the tokenizer's cumulative integral flags:
  float columns concatenate ON DEVICE from the streamed chunks; integral
  columns re-use per-chunk int32 host staging so results are bit-identical
  to the one-shot read (both are the same elementwise ``astype``).

Both native paths emit ``ingest.*`` counters and a ``frame.ingest`` span
(bytes, rows, chunks, threads, GB/s, simd verdict); the python-engine
fallback is counted by the caller (``frame/csv.py``).
"""

from __future__ import annotations

import ctypes
import os
import queue
import threading
from typing import Optional

import numpy as np

from ..config import config, float_dtype, int_dtype
from ..utils import faults as _faults
from ..utils.observability import span
from ..utils.profiling import counters


class NativeIngestError(RuntimeError):
    """The native streaming layer failed mid-read — a prefetch producer
    thread died (its exception rides as ``__cause__``), or an injected
    ``ingest_native`` chaos fault. ``frame/csv.py`` catches this (with
    ``OSError``/``MemoryError``) and degrades the read to the python
    engine, which re-reads the file from scratch — the native → python
    rung of the ingest degradation ladder."""


_LIB = None
_LIB_TRIED = False

_SO_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "libdqcsv.so"),
    os.path.join(os.path.dirname(__file__), "_native", "libdqcsv.so"),
]

_SIMD_CONF = {"auto": -1, "off": 0, "scalar": 0, "avx2": 1, "avx512": 2}
_SIMD_NAMES = {0: "scalar", 1: "avx2", 2: "avx512"}


def _load():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    for p in _SO_PATHS:
        p = os.path.abspath(p)
        if os.path.exists(p):
            try:
                lib = ctypes.CDLL(p)
            except OSError:
                continue
            pd = ctypes.POINTER(ctypes.c_double)
            lib.dq_parse_numeric_csv.restype = ctypes.c_longlong
            lib.dq_parse_numeric_csv.argtypes = [
                ctypes.c_char_p,                      # path
                ctypes.c_char,                        # delimiter
                ctypes.c_char,                        # quote
                ctypes.c_int,                         # skip_header
                ctypes.POINTER(pd),                   # out data
                ctypes.POINTER(ctypes.c_longlong),    # out ncols
                ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),  # out int_flags
            ]
            lib.dq_free.restype = None
            lib.dq_free.argtypes = [ctypes.c_void_p]
            if hasattr(lib, "dq_stream_open"):  # v2 + streaming ABI
                lib.dq_parse_numeric_csv_v2.restype = ctypes.c_longlong
                lib.dq_parse_numeric_csv_v2.argtypes = (
                    lib.dq_parse_numeric_csv.argtypes[:4]
                    + [ctypes.c_int, ctypes.c_int]        # simd, threads
                    + lib.dq_parse_numeric_csv.argtypes[4:])
                lib.dq_effective_simd.restype = ctypes.c_int
                lib.dq_effective_simd.argtypes = [ctypes.c_int]
                lib.dq_stream_open.restype = ctypes.c_void_p
                lib.dq_stream_open.argtypes = [
                    ctypes.c_char_p, ctypes.c_char, ctypes.c_char,
                    ctypes.c_int,                     # skip_header
                    ctypes.c_longlong,                # chunk_bytes
                    ctypes.c_int, ctypes.c_int,       # threads, simd
                ]
                lib.dq_stream_ncols.restype = ctypes.c_longlong
                lib.dq_stream_ncols.argtypes = [ctypes.c_void_p]
                lib.dq_stream_simd.restype = ctypes.c_int
                lib.dq_stream_simd.argtypes = [ctypes.c_void_p]
                lib.dq_stream_next.restype = ctypes.c_longlong
                lib.dq_stream_next.argtypes = [ctypes.c_void_p,
                                               ctypes.POINTER(pd)]
                lib.dq_stream_int_flags.restype = None
                lib.dq_stream_int_flags.argtypes = [ctypes.c_void_p,
                                                    ctypes.c_char_p]
                lib.dq_stream_close.restype = None
                lib.dq_stream_close.argtypes = [ctypes.c_void_p]
            if hasattr(lib, "dq_stream_bind"):  # zero-stitch bind ABI
                lib.dq_stream_total_rows.restype = ctypes.c_longlong
                lib.dq_stream_total_rows.argtypes = [ctypes.c_void_p]
                lib.dq_stream_bind.restype = ctypes.c_int
                lib.dq_stream_bind.argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_longlong, ctypes.c_int,
                ]
                lib.dq_stream_next_into.restype = ctypes.c_longlong
                lib.dq_stream_next_into.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong)]
            _LIB = lib
            break
    return _LIB


def available() -> bool:
    return _load() is not None


def streaming_available() -> bool:
    """True when the built library carries the dq_stream/v2 ABI."""
    lib = _load()
    return lib is not None and hasattr(lib, "dq_stream_open")


def simd_level(requested: Optional[str] = None) -> str:
    """Effective SIMD tier name for a conf request (default: the session
    conf) — the simd-vs-scalar verdict the ``frame.ingest`` span reports."""
    lib = _load()
    if lib is None or not hasattr(lib, "dq_effective_simd"):
        return "unavailable"
    req = _SIMD_CONF.get((requested or config.ingest_simd).lower(), -1)
    return _SIMD_NAMES.get(int(lib.dq_effective_simd(req)), "scalar")


def try_read_csv(path: str, header: bool, infer_schema: bool, delimiter: str,
                 quote: str = '"', required: bool = False):
    """Native read; returns a Frame or None (fallback to python engine)."""
    lib = _load()
    if lib is None:
        if required:
            raise RuntimeError(
                "native CSV engine requested but native/libdqcsv.so is not "
                "built (run `make -C native`)")
        return None
    if len(delimiter.encode("utf-8")) != 1 or len(quote.encode("utf-8")) != 1:
        return None  # ctypes c_char needs exactly one BYTE → python engine
    if not infer_schema:
        # Native fast path only covers the inferred all-numeric shape (the
        # reference's shape); explicit schemas stay on the python engine.
        if required:
            raise RuntimeError("native CSV engine only supports "
                               "infer_schema=True")
        return None
    names = None
    if header:
        # Column names come from the header record host-side; the C
        # tokenizer skips that record (skip_header) and parses the numeric
        # body. Anything irregular — unreadable text, a header wider or
        # narrower than the data — falls back to the python engine.
        names = _read_header_names(path, delimiter, quote)
        if names is None:
            return None

    # chaos hook (one None check without a plan): a due io_error raises
    # InjectedIOError here — the flaky-disk model — and frame/csv.py
    # degrades the read to the python engine.
    _faults.inject("ingest_native")
    if config.ingest_streaming and hasattr(lib, "dq_stream_open"):
        try:
            size = os.path.getsize(path)
        except OSError:
            raise FileNotFoundError(path)
        if size > config.ingest_chunk_bytes:
            return _stream_read(lib, path, size, names, header, delimiter,
                                quote)
        return _oneshot_read(lib, path, size, names, header, delimiter,
                             quote, v2=True)
    # spark.ingest.streaming=false: the EXACT legacy one-shot path (v1 ABI,
    # env-driven auto tiers, no span/counters) — byte-for-byte the pre-
    # streaming behavior.
    return _oneshot_read(lib, path, None, names, header, delimiter, quote,
                         v2=False)


def _oneshot_read(lib, path, size, names, header, delimiter, quote, v2):
    """Whole-file native parse (v2: conf-driven simd/threads + ingest
    telemetry; v1: the untouched legacy contract)."""
    data_p = ctypes.POINTER(ctypes.c_double)()
    ncols = ctypes.c_longlong(0)
    intf_p = ctypes.POINTER(ctypes.c_char)()
    if v2:
        simd = _SIMD_CONF.get(config.ingest_simd.lower(), -1)
        with span("frame.ingest", cat="frame", path=os.path.basename(path),
                  mode="oneshot") as sp:
            import time

            t0 = time.perf_counter()
            nrows = lib.dq_parse_numeric_csv_v2(
                path.encode(), delimiter.encode(), quote.encode(),
                1 if header else 0, simd, config.ingest_threads,
                ctypes.byref(data_p), ctypes.byref(ncols),
                ctypes.byref(intf_p))
            frame = _finish_oneshot(lib, path, nrows, data_p, ncols, intf_p,
                                    names)
            if nrows > 0 and size:
                el = time.perf_counter() - t0
                counters.increment("ingest.files")
                counters.increment("ingest.bytes", size)
                counters.increment("ingest.rows", nrows)
                sp.set(bytes=size, rows=int(nrows), chunks=1,
                       threads=config.ingest_threads or 0,
                       simd=simd_level(),
                       gb_s=round(size / el / 1e9, 4) if el > 0 else 0.0)
        return frame
    nrows = lib.dq_parse_numeric_csv(
        path.encode(), delimiter.encode(), quote.encode(),
        1 if header else 0,
        ctypes.byref(data_p), ctypes.byref(ncols), ctypes.byref(intf_p))
    return _finish_oneshot(lib, path, nrows, data_p, ncols, intf_p, names)


def _finish_oneshot(lib, path, nrows, data_p, ncols, intf_p, names):
    if nrows < 0:
        if nrows == -2:
            raise FileNotFoundError(path)
        return None  # non-numeric content → python engine
    data = {}
    try:
        nc = ncols.value
        if names is not None and len(names) != nc:
            return None  # ragged header vs body → python semantics
        if nc == 0 or nrows == 0:
            if names:
                return None  # header-only file: python's typing is exact
            from .frame import Frame
            return Frame({})
        # No intermediate .copy(): astype below always copies out of the
        # C buffer (dtype conversion or copy=True default), so an extra
        # staging copy would just add a full-matrix memory pass.
        flat = np.ctypeslib.as_array(data_p, shape=(nc * nrows,))
        cols = flat.reshape(nc, nrows)  # column-major from C
        int_flags = bytes(ctypes.cast(intf_p, ctypes.POINTER(ctypes.c_char * nc)).contents)
        for j in range(nc):
            col = cols[j]
            name = names[j] if names is not None else f"_c{j}"
            if int_flags[j]:
                data[name] = col.astype(np.dtype(int_dtype()))
            else:
                data[name] = col.astype(np.dtype(float_dtype()))
    finally:
        lib.dq_free(data_p)
        lib.dq_free(intf_p)

    from .frame import Frame

    return Frame(data)


def _aligned_empty(n: int, dtype, align: int = 64) -> np.ndarray:
    """Uninitialized 1-D array whose data pointer is ``align``-byte
    aligned — the alignment XLA requires to adopt a host buffer zero-copy
    when the runtime supports adoption (``_device_handoff_mode() ==
    "alias"``), and a cache-line-aligned store target for the native
    column writes either way."""
    dt = np.dtype(dtype)
    raw = np.empty(n * dt.itemsize + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + n * dt.itemsize].view(dt)


# ---- device handoff + bind-buffer pool -------------------------------------
# How a finished host column becomes a jax.Array is probed ONCE per
# process, because jax's import behavior differs by version/backend:
#   "alias"  dlpack import aliases host memory (true zero-copy): fastest,
#            but the buffer now belongs to the engine — never reuse it.
#   "copy"   dlpack import copies (jax 0.4.x on CPU). The copy runs ~3x
#            faster than device_put's path, and since the engine owns a
#            copy, the parse buffers can be POOLED: reused bind buffers
#            have warm (already-faulted) pages, and on fault-throttled
#            hosts (gVisor-class sandboxes, small VMs) first-touch faults
#            on a couple hundred MB of fresh columns otherwise cost more
#            than the parse itself.
#   "put"    no usable dlpack: plain device_put (also a copy → pool too).
_HANDOFF_MODE: Optional[str] = None
_POOL_LOCK = threading.Lock()
_POOL: list = []  # (fbuf, ibuf) pairs checked in after the engine copied
_POOL_MAX_ENTRIES = 2
_POOL_CAP_BYTES = 1 << 30


def _device_handoff_mode() -> str:
    global _HANDOFF_MODE
    if _HANDOFF_MODE is None:
        try:
            import warnings

            import jax.dlpack

            probe = _aligned_empty(16, np.float64)
            probe[:] = 1.0
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                d = jax.dlpack.from_dlpack(probe.__dlpack__())
            d.block_until_ready()
            probe[0] = 2.0
            _HANDOFF_MODE = "alias" if float(d[0]) == 2.0 else "copy"
        except Exception:
            _HANDOFF_MODE = "put"
    return _HANDOFF_MODE


def _to_device(arr: np.ndarray):
    """Host column -> jax.Array via the probed fastest path."""
    if _device_handoff_mode() in ("alias", "copy"):
        import warnings

        import jax.dlpack

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            return jax.dlpack.from_dlpack(arr.__dlpack__())
    import jax

    return jax.device_put(arr)


def _pool_checkout(nf: int, fdtype, ni: int):
    with _POOL_LOCK:
        for k, (f, i) in enumerate(_POOL):
            if f.dtype == np.dtype(fdtype) and f.size >= nf and i.size >= ni:
                del _POOL[k]
                return f, i
    return _aligned_empty(nf, fdtype), _aligned_empty(ni, np.int32)


def _pool_checkin(fbuf: np.ndarray, ibuf: np.ndarray) -> None:
    """Return bind buffers for reuse — only when the engine holds COPIES
    of the columns (alias mode hands the memory itself to the engine)."""
    if _device_handoff_mode() == "alias":
        return
    if fbuf.nbytes + ibuf.nbytes > _POOL_CAP_BYTES:
        return
    with _POOL_LOCK:
        if len(_POOL) < _POOL_MAX_ENTRIES:
            _POOL.append((fbuf, ibuf))


def _stream_read(lib, path, size, names, header, delimiter, quote):
    """Streaming native read: bounded-chunk parse → device columns.

    Two modes behind one ``read_csv`` surface:

    * **pinned** (unquoted files, the overwhelming case): one classify
      sweep bounds the row count, the final engine-dtype column buffers
      (float32/float64 + int32 staging) come 64-byte aligned from a
      process-level pool (warm pages — see the pool note above
      ``_device_handoff_mode``), and every chunk parses STRAIGHT into its
      final rows inside ``dq_stream_next_into`` (typed stores in the
      native walk — no per-chunk malloc, no astype, no concatenate). At
      EOF each column hands to JAX through the probed fastest path
      (``_to_device``): dlpack adoption where the runtime aliases host
      buffers, else one bulk dlpack/device_put copy per column. Bit
      parity: the native (float)/(int32) casts are the same IEEE
      elementwise conversions as the one-shot path's numpy ``astype``.
    * **chunked** (quoted files, or a pre-bind libdqcsv build): the
      original per-chunk f64 blocks + host-side ``astype`` staging.

    In both modes a producer thread blocks in the native parse (GIL
    released) up to ``spark.ingest.prefetch`` chunks ahead of the
    consumer, so parse, conversion/transfer, and downstream compute
    overlap.
    """
    simd = _SIMD_CONF.get(config.ingest_simd.lower(), -1)
    h = lib.dq_stream_open(path.encode(), delimiter.encode(), quote.encode(),
                           1 if header else 0, config.ingest_chunk_bytes,
                           config.ingest_threads, simd)
    if not h:
        raise FileNotFoundError(path)
    import time

    t0 = time.perf_counter()
    try:
        nc = int(lib.dq_stream_ncols(h))
        if nc < 0:
            return None  # non-numeric prologue → python engine
        if names is not None and len(names) != nc:
            return None  # ragged header vs body → python semantics
        if nc == 0:
            if names:
                return None  # header-only file: python's typing is exact
            from .frame import Frame
            return Frame({})
        verdict = _SIMD_NAMES.get(int(lib.dq_stream_simd(h)), "scalar")
        pinned = hasattr(lib, "dq_stream_bind")
        with span("frame.ingest", cat="frame", path=os.path.basename(path),
                  mode="stream") as sp:
            if pinned:
                # _stream_pinned falls back to the chunked body itself if
                # the bind is refused; a None from either body is
                # DEFINITIVE (non-numeric content) — never retried.
                out = _stream_pinned(lib, h, nc, names, size)
            else:
                out = _stream_chunked(lib, h, nc, names)
            if out is None:
                return None  # non-numeric mid-file → python engine
            data, total_rows, nchunks = out
            el = time.perf_counter() - t0
            counters.increment("ingest.files")
            counters.increment("ingest.streamed")
            counters.increment("ingest.bytes", size)
            counters.increment("ingest.rows", total_rows)
            counters.increment("ingest.chunks", nchunks)
            sp.set(bytes=size, rows=total_rows, chunks=nchunks,
                   threads=config.ingest_threads or 0, simd=verdict,
                   prefetch=config.ingest_prefetch, pinned=pinned,
                   gb_s=round(size / el / 1e9, 4) if el > 0 else 0.0)
    finally:
        lib.dq_stream_close(h)

    from .frame import Frame

    # Sharded ingest hand-off: streamed chunks assembled into the pooled
    # engine-dtype buffers place straight into the row-sharded layout
    # (contiguous ranges — chunk order, and with it row order, is
    # preserved exactly); the prefetch thread keeps overlapping parse
    # with this device transfer. One flag check when sharding is off.
    from ..parallel.shard import maybe_shard_frame

    return maybe_shard_frame(Frame(data))


def _stream_pinned(lib, h, nc, names, size):
    """Bind-mode body: parse chunks into preallocated aligned typed
    buffers; returns ``(data, rows, chunks)``, or None for the python
    fallback (non-numeric content; the caller must not retry chunked —
    None here is definitive because native already scanned the file)."""
    import jax

    fdt = np.dtype(float_dtype())
    idt = np.dtype(int_dtype())
    want_f64 = fdt == np.dtype(np.float64)
    # Exact row bound from the native structural count (one read-only SIMD
    # sweep) — exact sizing is what lets the buffer pool actually hit: a
    # bytes-derived bound overallocates ~the field width, which balloons
    # the pooled footprint past the cap. Quoted files have no structural
    # count (-1): bound by bytes — every EMITTED record consumes at least
    # 2 input bytes (blank lines are skipped, so ≥ 1 content byte + a
    # separator; ragged short rows make nc-based bounds unsafe), +2 for
    # an unterminated tail — where the overallocation stays VIRTUAL
    # (untouched pages are never faulted in) and such buffers simply
    # exceed the pool cap.
    # chaos hook: a due pool_exhaust fault models an allocation-starved
    # bind pool — degrade one level to the chunked body (per-chunk
    # malloc'd blocks, no pooled buffers) instead of dying.
    if _faults.fired("ingest_native", "pool_exhaust"):
        from ..utils.recovery import RECOVERY_LOG

        RECOVERY_LOG.record(
            "ingest_native", "fallback", rung="chunked",
            cause="pool exhausted",
            detail="bind-buffer pool exhausted; chunked stream body")
        counters.increment("ingest.fault_fallback")
        return _stream_chunked(lib, h, nc, names)
    total_cap = int(lib.dq_stream_total_rows(h))
    if total_cap < 0:
        total_cap = size // 2 + 2
    # Column stride padded to 16 elements: with a 64-byte-aligned base,
    # every column of both blocks starts 64-byte aligned too (16 * 4-byte
    # lanes = one cache line; 16 * 8-byte lanes = two).
    stride = ((max(total_cap, 1) + 15) // 16) * 16
    fbuf, ibuf = _pool_checkout(
        nc * stride, np.float64 if want_f64 else np.float32, nc * stride)
    # Release-ONCE discipline: the buffers return to the pool on EVERY
    # exit — success (after the engine finished reading them), the
    # definitive-None parse failure, the alloc-failure raise, a dead
    # prefetch producer — via the finally below. The flag stops a double
    # checkin (two pool entries aliasing one buffer would hand the same
    # memory to two concurrent readers).
    released = False

    def _release():
        nonlocal released
        if not released:
            released = True
            _pool_checkin(fbuf, ibuf)

    rc = int(lib.dq_stream_bind(
        h, fbuf.ctypes.data_as(ctypes.c_void_p),
        ibuf.ctypes.data_as(ctypes.c_void_p), stride, 1 if want_f64 else 0))
    if rc != 0:
        _release()
        return _stream_chunked(lib, h, nc, names)
    # On a real accelerator a column's float rows are device_put as soon
    # as they are KNOWN-float, so host->device DMA overlaps the parse of
    # the next chunk and the final concat runs on device. "Known-float"
    # follows the native single-lane store protocol (SinkTyped): while a
    # column's integral flag is alive only its i32 lane is written, so
    # the float lane must not be snapshot yet — when the flag dies, the
    # native backfill has (synchronously, before the chunk call returns)
    # completed the float lane for every row so far, and the whole
    # [0, row_end) range ships at once; thereafter per-chunk. Columns
    # integral at EOF never ship float rows — they hand over as int32.
    # No transferred region is ever rewritten: backfill only targets
    # columns transitioning alive->dead, which by construction have no
    # prior float transfers. On the CPU backend there is no DMA to
    # overlap — columns hand over whole at EOF through the probed
    # fastest path (_to_device: dlpack adoption or bulk copy).
    cpu_backend = jax.default_backend() == "cpu"
    chunks = _bind_chunk_iter(lib, h, nc)
    try:
        dev_chunks: list[list] = [[] for _ in range(nc)]
        dev_rows = [0] * nc  # float rows already transferred per column
        total_rows = 0
        nchunks = 0
        for rows, (off, chunk_flags) in chunks:
            if rows == -2:
                raise MemoryError("native CSV stream allocation failure")
            if rows < 0:
                return None  # non-numeric mid-file → python engine
            nchunks += 1
            total_rows += rows
            if not cpu_backend:
                for j in range(nc):
                    if chunk_flags[j]:
                        continue  # i32 lane live: float lane unwritten
                    base = j * stride + dev_rows[j]
                    dev_chunks[j].append(
                        jax.device_put(fbuf[base:base + total_rows -
                                            dev_rows[j]]))
                    dev_rows[j] = total_rows
        flags = _stream_flags(lib, h, nc)
        data = {}
        for j in range(nc):
            name = names[j] if names is not None else f"_c{j}"
            base = j * stride
            if flags[j]:
                col = ibuf[base:base + total_rows]
                col = col if idt == np.dtype(np.int32) else col.astype(idt)
                # dlpack commits to the HOST device — correct on the CPU
                # backend, but on an accelerator it would strand int
                # columns on the CPU next to float columns living on the
                # accelerator (mixed-device Frames fail on first use):
                # device_put instead.
                data[name] = (_to_device(col) if cpu_backend
                              else jax.device_put(col))
            elif cpu_backend:
                data[name] = _to_device(fbuf[base:base + total_rows])
            else:
                import jax.numpy as jnp

                data[name] = (dev_chunks[j][0] if len(dev_chunks[j]) == 1
                              else jnp.concatenate(dev_chunks[j]))
        # The engine must be done reading the bind buffers before they
        # can be pooled for the next read (checkin is a no-op in alias
        # mode, where the columns ARE these buffers).
        jax.block_until_ready(list(data.values()))
        return data, total_rows, nchunks
    finally:
        # Quiesce the prefetch producer BEFORE pooling the buffers: on a
        # consumer-side exception the producer may still be parsing a
        # chunk INTO fbuf/ibuf, and a checkin at that moment would hand
        # live-written memory to the next reader. Closing the iterator
        # runs its finally (stop + drain + join); only then is the
        # checkin safe.
        chunks.close()
        _release()


def _stream_chunked(lib, h, nc, names):
    """Per-chunk f64 blocks + host astype staging — quoted files and
    pre-bind libdqcsv builds. Returns ``(data, rows, chunks)`` or None."""
    import jax

    fdt = np.dtype(float_dtype())
    idt = np.dtype(int_dtype())
    # One host-side np.concatenate + a single device_put per column
    # measures ~5x cheaper on XLA:CPU than per-chunk puts + an XLA
    # concatenate, so staging stays host-side there; accelerators stream
    # each converted chunk to the device immediately. Results are
    # bit-identical either way (same astype, same concatenation).
    cpu_backend = jax.default_backend() == "cpu"

    dev_chunks: list[list] = [[] for _ in range(nc)]  # float col chunks
    int_chunks: list[Optional[list]] = [[] for _ in range(nc)]  # host i32
    total_rows = 0
    nchunks = 0
    for rows, data_p in _chunk_iter(lib, h):
        if rows == -2:
            raise MemoryError("native CSV stream allocation failure")
        if rows < 0:
            return None  # non-numeric mid-file → python engine
        nchunks += 1
        flat = np.ctypeslib.as_array(data_p, shape=(nc * rows,))
        cols = flat.reshape(nc, rows)
        flags = _stream_flags(lib, h, nc)
        for j in range(nc):
            # Float path streams to the device now (accelerators) or
            # stages host-side (CPU backend); integral candidates also
            # stage the EXACT int32 the one-shot read would produce
            # (astype is elementwise, so per-chunk == whole-file
            # bit-wise).
            fcol = cols[j].astype(fdt)
            dev_chunks[j].append(
                fcol if cpu_backend else jax.device_put(fcol))
            ij = int_chunks[j]
            if ij is not None:
                if flags[j]:
                    ij.append(cols[j].astype(idt))
                else:
                    int_chunks[j] = None  # integrality broke
        lib.dq_free(data_p)
        total_rows += rows

    flags = _stream_flags(lib, h, nc)
    data = {}
    for j in range(nc):
        name = names[j] if names is not None else f"_c{j}"
        if flags[j] and int_chunks[j] is not None:
            data[name] = (int_chunks[j][0] if len(int_chunks[j]) == 1
                          else np.concatenate(int_chunks[j]))
        elif cpu_backend:
            host = (dev_chunks[j][0] if len(dev_chunks[j]) == 1
                    else np.concatenate(dev_chunks[j]))
            data[name] = jax.device_put(host)
        else:
            import jax.numpy as jnp

            data[name] = (dev_chunks[j][0] if len(dev_chunks[j]) == 1
                          else jnp.concatenate(dev_chunks[j]))
    return data, total_rows, nchunks


def _stream_flags(lib, h, nc) -> bytes:
    buf = ctypes.create_string_buffer(nc)
    lib.dq_stream_int_flags(h, buf)
    return buf.raw[:nc]


#: Reserved queue code: the producer thread died and the payload is its
#: exception (never emitted by the native layer, whose codes stop at -2).
_PRODUCER_ERROR = -3


def _prefetch_iter(next_chunk, release=None):
    """Yield ``(rows, payload)`` chunks from a ``next_chunk()`` callable.

    With ``spark.ingest.prefetch`` > 0, a producer thread runs the native
    parse up to that many chunks ahead (bounded queue = bounded memory);
    the terminal code (0 EOF / -1 fallback / -2 alloc) is yielded too so
    the consumer owns all error handling. The producer never outlives the
    iterator: closing/failing the consumer sets ``stop`` and any chunk
    that cannot be handed over is released via ``release(payload)``
    (malloc'd blocks in chunked mode; bind mode has no ownership to
    reclaim and passes no release).

    A DYING producer must never strand the consumer on the bounded
    queue: any exception it raises (a ctypes failure, an injected
    ``ingest_native:thread_death``) is handed through the queue as a
    ``_PRODUCER_ERROR`` item and re-raised here as
    :class:`NativeIngestError` (original as ``__cause__``); as
    belt-and-braces, the consumer's waits are timed and probe
    ``t.is_alive()``, so even a producer killed without a handoff
    surfaces as an error, never a hang.
    """
    depth = config.ingest_prefetch
    if depth <= 0:  # synchronous mode: no thread, parse inline
        while True:
            rows, payload = next_chunk()
            if rows <= 0:
                if rows < 0:
                    yield rows, payload
                return
            yield rows, payload
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def produce():
        while True:
            try:
                if _faults.fired("ingest_native", "thread_death"):
                    raise RuntimeError("injected prefetch-producer death")
                item = next_chunk()
            except BaseException as e:  # surface, never silently die
                item = (_PRODUCER_ERROR, e)
            rows, payload = item
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    break
                except queue.Full:
                    continue
            else:  # consumer gone: release the orphaned chunk
                if rows > 0 and release is not None:
                    release(payload)
                return
            if rows <= 0:
                return

    t = threading.Thread(target=produce, name="dqcsv-prefetch", daemon=True)
    t.start()
    try:
        while True:
            while True:
                try:
                    rows, payload = q.get(timeout=0.5)
                    break
                except queue.Empty:
                    if not t.is_alive():
                        # the producer may have put its final item and
                        # exited between the Empty and the liveness
                        # probe: drain once more before declaring death
                        try:
                            rows, payload = q.get_nowait()
                            break
                        except queue.Empty:
                            raise NativeIngestError(
                                "prefetch producer thread died without "
                                "handing off a chunk") from None
            if rows == _PRODUCER_ERROR:
                raise NativeIngestError(
                    f"prefetch producer thread died: {payload!r}"
                ) from payload
            if rows <= 0:
                if rows < 0:
                    yield rows, payload
                return
            yield rows, payload
    finally:
        stop.set()
        while True:  # drain queued chunks / unblock a waiting producer
            try:
                rows, payload = q.get_nowait()
                if rows > 0 and release is not None:
                    release(payload)
            except queue.Empty:
                break
        t.join()


def _chunk_iter(lib, h):
    """``(rows, data_ptr)`` chunks — per-chunk malloc'd blocks the
    consumer (or the iterator, on teardown) must ``dq_free``."""
    def next_chunk():
        data_p = ctypes.POINTER(ctypes.c_double)()
        rows = int(lib.dq_stream_next(h, ctypes.byref(data_p)))
        if rows > 0 and _faults.fired("ingest_native", "torn_chunk"):
            # chaos: a short read / torn chunk — the real parse result
            # is discarded and the failure raised as the native-layer
            # error class, so engine=auto degrades to the python engine
            # while an explicit engine="native" request still raises
            # (the same contract as io_error/thread_death)
            lib.dq_free(data_p)
            raise NativeIngestError("injected short-read/torn chunk")
        return rows, (data_p if rows > 0 else None)

    return _prefetch_iter(next_chunk, release=lib.dq_free)


def _bind_chunk_iter(lib, h, nc):
    """``(rows, (row_off, flags))`` for the bind-mode stream — values land
    directly in the bound buffers, so there is no chunk ownership to
    reclaim. ``flags`` is the integral-flag state AS OF THE END OF THIS
    CHUNK, captured in the producer (the thread that ran the parse) and
    handed through the queue: with prefetch the producer may already be
    parsing — and BACKFILLING — later chunks while the consumer processes
    this one, so a live ``dq_stream_int_flags`` read from the consumer
    would race those writes. The snapshot is what makes acting on a dead
    flag safe: once a column's flag is dead in the post-chunk-k snapshot,
    its float rows [0, rows_k) are final (backfill fires only on the
    alive->dead transition, and later chunks write only later rows)."""
    def next_chunk():
        off = ctypes.c_longlong(0)
        rows = int(lib.dq_stream_next_into(h, ctypes.byref(off)))
        if rows > 0 and _faults.fired("ingest_native", "torn_chunk"):
            # chaos: torn chunk in bind mode — values already written to
            # the bound buffers are abandoned (the pool checkin in
            # _stream_pinned's finally reclaims them after the producer
            # quiesces); raised as the native-layer class so the
            # engine=auto/"native" degrade contract matches io_error
            raise NativeIngestError("injected short-read/torn chunk")
        flags = _stream_flags(lib, h, nc) if rows > 0 else b""
        return rows, (off.value if rows > 0 else 0, flags)

    return _prefetch_iter(next_chunk)


def _read_header_names(path: str, delimiter: str, quote: str):
    """First non-blank record's fields, via the same record/field scanner
    the python engine uses (one quoting state machine to maintain) — or
    None when the header can't be confidently read, sending the read to
    the python engine. Fail-closed cases:

    - undecodable bytes, or no complete first record inside the probe
      window (an unquoted record terminator proves completeness even when
      the file is larger than the probe);
    - the python engine and the C prologue would pick DIFFERENT header
      records: python's blank-record skip is ``str.strip()`` (any unicode
      whitespace), the C side's is space/tab only, so a ``\\x0b``-only
      first line would make C skip the REAL header as its header record
      and parse it as data — a silent extra row. Detected by replicating
      the C pick host-side and comparing.

    The probe reads 64 KiB; when the file continues past it, the sniff is
    cut at the LAST record separator before decoding (separators are
    ASCII, so the cut can never split a multibyte UTF-8 character — the
    old whole-probe decode raised ``UnicodeDecodeError`` whenever the
    read truncated mid-character, spuriously demoting native-eligible
    files to the python engine).
    """
    try:
        with open(path, "rb") as f:
            chunk = f.read(1 << 16)
            more = f.read(1) != b""
    except OSError:
        return None
    if more:
        cut = max(chunk.rfind(b"\n"), chunk.rfind(b"\r"))
        if cut < 0:
            return None  # no complete record inside the probe: punt
        chunk = chunk[:cut + 1]
    try:
        text = chunk.decode("utf-8")
    except UnicodeDecodeError:
        return None
    if more and not _has_unquoted_record_end(text, quote):
        return None  # first record may be truncated by the probe: punt
    from .csv import parse_csv_text, split_fields

    rows = parse_csv_text(text, delimiter, quote)
    if not rows:
        return None
    # The record the C prologue will treat as the header: first record
    # (plain \r\n|\r|\n split, no quote awareness — the C side's skip
    # happens in the same byte-level terms) whose content is not
    # space/tab-only. If its fields differ from python's first record,
    # the engines would disagree on where data starts: fall back.
    c_first = None
    for rec in _plain_records(text):
        if rec.strip(" \t") != "":
            c_first = rec
            break
    if c_first is None or split_fields(c_first, delimiter, quote) != rows[0]:
        return None
    return list(rows[0])


def _plain_records(text: str):
    """Byte-level record split (\\r\\n, \\r, \\n), quote-unaware — the C
    prologue's view of the file."""
    rec = []
    i = 0
    n = len(text)
    while i < n:
        ch = text[i]
        if ch == "\n" or ch == "\r":
            yield "".join(rec)
            rec = []
            if ch == "\r" and i + 1 < n and text[i + 1] == "\n":
                i += 1
        else:
            rec.append(ch)
        i += 1
    if rec:
        yield "".join(rec)


def _has_unquoted_record_end(text: str, quote: str) -> bool:
    """True when an unquoted record terminator exists in ``text`` — proof
    the first record is complete inside the probe window even for quoted
    files (RFC-4180: terminators inside quotes don't end a record)."""
    in_quotes = False
    for ch in text:
        if ch == quote:
            in_quotes = not in_quotes
        elif (ch == "\n" or ch == "\r") and not in_quotes:
            return True
    return False
