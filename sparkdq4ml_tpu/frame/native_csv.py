"""ctypes binding to the native C++ CSV tokenizer (``native/csvparse.cpp``).

Role: the data-loader fast path — the analogue of the Univocity parser inside
Spark's CSV source (SURVEY.md §2.2 "CSV reader"). The native tokenizer handles
the common all-numeric case (which is what feature matrices are), with or
without a header record (names are read host-side, the body is skipped
C-side); anything else returns ``None`` here and the pure-Python reader takes
over, so the framework works identically whether or not the shared library is
built (``make -C native``).

The C side parses the file into column-major float64 with NaN for empty
fields, handling bare-CR/CRLF/LF records; Python decides integer-vs-double per
column exactly like ``csv.infer_column`` and uploads to device once.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..config import float_dtype, int_dtype

_LIB = None
_LIB_TRIED = False

_SO_PATHS = [
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "libdqcsv.so"),
    os.path.join(os.path.dirname(__file__), "_native", "libdqcsv.so"),
]


def _load():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    for p in _SO_PATHS:
        p = os.path.abspath(p)
        if os.path.exists(p):
            try:
                lib = ctypes.CDLL(p)
            except OSError:
                continue
            lib.dq_parse_numeric_csv.restype = ctypes.c_longlong
            lib.dq_parse_numeric_csv.argtypes = [
                ctypes.c_char_p,                      # path
                ctypes.c_char,                        # delimiter
                ctypes.c_char,                        # quote
                ctypes.c_int,                         # skip_header
                ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),  # out data
                ctypes.POINTER(ctypes.c_longlong),    # out ncols
                ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),    # out int_flags
            ]
            lib.dq_free.restype = None
            lib.dq_free.argtypes = [ctypes.c_void_p]
            _LIB = lib
            break
    return _LIB


def available() -> bool:
    return _load() is not None


def try_read_csv(path: str, header: bool, infer_schema: bool, delimiter: str,
                 quote: str = '"', required: bool = False):
    """Native read; returns a Frame or None (fallback to python engine)."""
    lib = _load()
    if lib is None:
        if required:
            raise RuntimeError(
                "native CSV engine requested but native/libdqcsv.so is not "
                "built (run `make -C native`)")
        return None
    if len(delimiter.encode("utf-8")) != 1 or len(quote.encode("utf-8")) != 1:
        return None  # ctypes c_char needs exactly one BYTE → python engine
    if not infer_schema:
        # Native fast path only covers the inferred all-numeric shape (the
        # reference's shape); explicit schemas stay on the python engine.
        if required:
            raise RuntimeError("native CSV engine only supports "
                               "infer_schema=True")
        return None
    names = None
    if header:
        # Column names come from the header record host-side; the C
        # tokenizer skips that record (skip_header) and parses the numeric
        # body. Anything irregular — unreadable text, a header wider or
        # narrower than the data — falls back to the python engine.
        names = _read_header_names(path, delimiter, quote)
        if names is None:
            return None

    data_p = ctypes.POINTER(ctypes.c_double)()
    ncols = ctypes.c_longlong(0)
    intf_p = ctypes.POINTER(ctypes.c_char)()
    nrows = lib.dq_parse_numeric_csv(
        path.encode(), delimiter.encode(), quote.encode(),
        1 if header else 0,
        ctypes.byref(data_p), ctypes.byref(ncols), ctypes.byref(intf_p))
    if nrows < 0:
        if nrows == -2:
            raise FileNotFoundError(path)
        return None  # non-numeric content → python engine
    data = {}
    try:
        nc = ncols.value
        if names is not None and len(names) != nc:
            return None  # ragged header vs body → python semantics
        if nc == 0 or nrows == 0:
            if names:
                return None  # header-only file: python's typing is exact
            from .frame import Frame
            return Frame({})
        # No intermediate .copy(): astype below always copies out of the
        # C buffer (dtype conversion or copy=True default), so an extra
        # staging copy would just add a full-matrix memory pass.
        flat = np.ctypeslib.as_array(data_p, shape=(nc * nrows,))
        cols = flat.reshape(nc, nrows)  # column-major from C
        int_flags = bytes(ctypes.cast(intf_p, ctypes.POINTER(ctypes.c_char * nc)).contents)
        for j in range(nc):
            col = cols[j]
            name = names[j] if names is not None else f"_c{j}"
            if int_flags[j]:
                data[name] = col.astype(np.dtype(int_dtype()))
            else:
                data[name] = col.astype(np.dtype(float_dtype()))
    finally:
        lib.dq_free(data_p)
        lib.dq_free(intf_p)

    from .frame import Frame

    return Frame(data)


def _read_header_names(path: str, delimiter: str, quote: str):
    """First non-blank record's fields, via the same record/field scanner
    the python engine uses (one quoting state machine to maintain) — or
    None when the header can't be confidently read, sending the read to
    the python engine. Fail-closed cases:

    - undecodable bytes, or no complete first record inside the probe
      window (an unquoted record terminator proves completeness even when
      the file is larger than the probe);
    - the python engine and the C prologue would pick DIFFERENT header
      records: python's blank-record skip is ``str.strip()`` (any unicode
      whitespace), the C side's is space/tab only, so a ``\\x0b``-only
      first line would make C skip the REAL header as its header record
      and parse it as data — a silent extra row. Detected by replicating
      the C pick host-side and comparing.
    """
    try:
        with open(path, "rb") as f:
            chunk = f.read(1 << 16)
            more = f.read(1) != b""
    except OSError:
        return None
    try:
        text = chunk.decode("utf-8")
    except UnicodeDecodeError:
        return None
    if more and not _has_unquoted_record_end(text, quote):
        return None  # first record may be truncated by the probe: punt
    from .csv import parse_csv_text, split_fields

    rows = parse_csv_text(text, delimiter, quote)
    if not rows:
        return None
    # The record the C prologue will treat as the header: first record
    # (plain \r\n|\r|\n split, no quote awareness — the C side's skip
    # happens in the same byte-level terms) whose content is not
    # space/tab-only. If its fields differ from python's first record,
    # the engines would disagree on where data starts: fall back.
    c_first = None
    for rec in _plain_records(text):
        if rec.strip(" \t") != "":
            c_first = rec
            break
    if c_first is None or split_fields(c_first, delimiter, quote) != rows[0]:
        return None
    return list(rows[0])


def _plain_records(text: str):
    """Byte-level record split (\\r\\n, \\r, \\n), quote-unaware — the C
    prologue's view of the file."""
    rec = []
    i = 0
    n = len(text)
    while i < n:
        ch = text[i]
        if ch == "\n" or ch == "\r":
            yield "".join(rec)
            rec = []
            if ch == "\r" and i + 1 < n and text[i + 1] == "\n":
                i += 1
        else:
            rec.append(ch)
        i += 1
    if rec:
        yield "".join(rec)


def _has_unquoted_record_end(text: str, quote: str) -> bool:
    """True when an unquoted record terminator exists in ``text`` — proof
    the first record is complete inside the probe window even for quoted
    files (RFC-4180: terminators inside quotes don't end a record)."""
    in_quotes = False
    for ch in text:
        if ch == quote:
            in_quotes = not in_quotes
        elif (ch == "\n" or ch == "\r") and not in_quotes:
            return True
    return False
