"""Structured tracing + metrics — the observability subsystem.

The reference's only observability is stdout banners and a post-hoc
``objectiveHistory`` print (SURVEY.md §5); this module is the production
replacement: a span-based tracer with hierarchical, contextvar-propagated
spans (session → sql query → frame op → fit → solver iteration block) and a
metrics registry that extends :data:`utils.profiling.counters` (monotonic
counters) with gauges and fixed-bucket latency histograms.

Exporters (all host-side, on demand — never on the hot path):

* :func:`chrome_trace` / :func:`dump_chrome_trace` — Chrome trace-event JSON
  loadable in Perfetto / ``chrome://tracing``,
* logfmt event lines through :func:`utils.logging.format_kv` (one DEBUG line
  per finished span when ``log_spans`` is on),
* :func:`prometheus_text` — a Prometheus text-format snapshot of every
  counter, gauge, and histogram in one scrape,
* :func:`trace_report` — a human-readable span tree.

Cost contract: **disabled mode is a near-zero no-op** — every instrumented
site guards on one ``TRACER.enabled`` flag read and allocates nothing (the
shared :data:`_NOOP` context manager is returned, no Span object exists),
so the fused device paths keep their "no host reads" hygiene. Enabling
observability MAY add host syncs (honest span timing blocks on the traced
dispatch where noted); that is the explicit price of turning it on.

Wired through the framework:

* ``frame/frame.py`` — op spans (:func:`op_span` decorator; rows in/out),
* ``sql/parser.py`` — per-query span with the query text and an
  ``explain()``-style plan summary,
* ``models/solvers.py`` / ``regression.py`` / ``classification.py`` — fit
  spans with cold-compile vs steady split (jit trace-cache hit/miss),
  iteration counts, final objective, retry/fallback annotations pulled from
  ``utils.recovery.RECOVERY_LOG``,
* ``parallel/distributed.py`` / ``mesh.py`` — per-shard Gramian timing,
  collective/shard_map build counters, mesh-size gauge,
* ``session.py`` — ``spark.observability.*`` conf + ``SPARKDQ4ML_OBS`` env
  gating, ``session.metrics()`` / ``trace_report()`` / ``dump_trace(path)``.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import json
import logging
import os
import threading
import time
from typing import Callable, Optional

from . import profiling
from .logging import format_kv

logger = logging.getLogger("sparkdq4ml_tpu.observability")

ENV_VAR = "SPARKDQ4ML_OBS"

# ---------------------------------------------------------------------------
# Metrics: gauges + fixed-bucket histograms (counters live in
# utils.profiling.counters so the recovery mirror keeps one home)
# ---------------------------------------------------------------------------

#: Default latency buckets (milliseconds) — fixed at creation so scrapes see
#: a stable schema; spans record their duration into ``span_ms.<category>``.
DEFAULT_BUCKETS_MS = (0.1, 0.5, 1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0)

#: THE metric-name registry — every literal name passed to
#: ``counters.increment`` / ``METRICS.set_gauge`` / ``METRICS.observe``
#: must be declared here (enforced statically by dqlint's
#: ``metric-name`` rule, ``analysis/rules/metric_names.py``): a typo'd
#: counter compiles, runs, and silently creates a ghost series no
#: dashboard reads. name → (type, help); the Prometheus exporter renders
#: the declared help text. Kept a PURE LITERAL so the rule can
#: ``ast.literal_eval`` it without importing the engine (the CONF_KEYS
#: pattern).
METRIC_NAMES = {
    # frame engine
    "frame.host_sync": ("counter", "counted device->host boundary pulls"),
    "frame.cache": ("counter", "Frame.cache()/persist() materializations"),
    # fused expression pipeline (ops/compiler.py)
    "pipeline.flush": ("counter", "pending-pipeline materializations"),
    "pipeline.compile": ("counter", "fused programs traced+compiled"),
    "pipeline.hit": ("counter", "fused-program plan-cache replays"),
    "pipeline.fallback": ("counter", "flushes degraded to eager replay"),
    "pipeline.fault_fallback": ("counter",
                                "flushes eager-replayed by the fault "
                                "ladder"),
    "pipeline.evict": ("counter", "plan-cache LRU evictions"),
    "pipeline.oom_chunked": ("counter",
                             "over-budget flushes run row-chunked"),
    "pipeline.shard_gather": ("counter",
                              "sharded flushes gathered to single-device "
                              "by the shard_flush ladder"),
    # grouped execution (ops/segments.py)
    "grouped.compile": ("counter", "grouped programs traced+compiled"),
    "grouped.hit": ("counter", "grouped-program plan-cache replays"),
    "grouped.fallback": ("counter", "grouped ops on the host path"),
    "grouped.fault_fallback": ("counter",
                               "grouped ops host-degraded by the fault "
                               "ladder"),
    "grouped.dense_miss": ("counter", "dense lowering misfits rerouted"),
    "grouped.evict": ("counter", "grouped plan-cache LRU evictions"),
    "grouped.shard_gather": ("counter",
                             "sharded grouped/distinct programs gathered "
                             "to single-device by the shard_merge "
                             "ladder"),
    # row-sharded frames (parallel/shard.py)
    "shard.place": ("counter", "frames laid out row-sharded"),
    "shard.gather": ("counter", "sharded frames degraded to "
                                "single-device placement"),
    "shard.join_partitioned": ("counter",
                               "joins planned via the hash-partition "
                               "shuffle lowering"),
    "shard.fit_passthrough": ("counter",
                              "fit placements consuming shard partials "
                              "directly (no re-shard)"),
    # streaming ingest (frame/native_csv.py)
    "ingest.files": ("counter", "native CSV files read"),
    "ingest.bytes": ("counter", "native CSV bytes parsed"),
    "ingest.rows": ("counter", "native CSV rows parsed"),
    "ingest.chunks": ("counter", "streamed parse chunks"),
    "ingest.streamed": ("counter", "files read via the streaming path"),
    "ingest.python_fallback": ("counter",
                               "files degraded to the python engine"),
    "ingest.fault_fallback": ("counter",
                              "native reads degraded by the fault "
                              "ladder"),
    # solver / jit layers
    "solver.fits": ("counter", "model fits dispatched"),
    "solver.iterations": ("counter", "solver iterations run"),
    "jit.trace_miss": ("counter", "jit-factory cache misses (new trace)"),
    "jit.trace_hit": ("counter", "jit-factory cache hits"),
    # parallel / mesh
    "parallel.psum_dispatches": ("counter", "collective dispatches"),
    "parallel.shard_map_builds": ("counter", "shard_map programs built"),
    "mesh.devices": ("gauge", "devices in the session mesh"),
    # device memory (utils/meminfo.py)
    "mem.live_bytes": ("gauge", "live-array census bytes"),
    "mem.peak_bytes": ("gauge", "process-lifetime census peak bytes"),
    # tracer internals
    "trace.dropped_spans": ("counter", "spans evicted by the bounded "
                                       "buffer"),
    # tail-based request-tree retention (TailSampler)
    "trace.kept": ("counter", "request trees promoted to the retained "
                              "store by the tail keep-policy"),
    "trace.dropped": ("counter", "request trees aged out of the tail "
                                 "ring without being kept"),
    # incident flight recorder (utils/incidents.py)
    "incident.written": ("counter", "incident bundles persisted to the "
                                    "incident dir"),
    "incident.failed": ("counter", "incident bundle writes degraded to "
                                   "in-memory retention"),
    # fault injection (utils/faults.py)
    "faults.injected": ("counter", "chaos faults fired"),
    # serving layer (serve/)
    "serve.admit": ("counter", "queries admitted"),
    "serve.reject": ("counter", "queries rejected (all reasons)"),
    "serve.shed": ("counter", "queries shed by an open breaker"),
    "serve.complete": ("counter", "queries completed ok"),
    "serve.error": ("counter", "queries failed in execution"),
    "serve.deadline_exceeded": ("counter", "queries past their deadline"),
    "serve.late_result": ("counter", "executed values discarded late"),
    "serve.requeue": ("counter", "retryable failures requeued"),
    "serve.tenants_reaped": ("counter", "idle stateless tenants reaped"),
    "serve.queue_depth": ("gauge", "queued jobs across tenants"),
    "serve.in_flight": ("gauge", "jobs executing right now"),
    "serve.tenants": ("gauge", "known tenant states"),
    "serve.workers": ("gauge", "live worker threads"),
    "serve.slo_burn": ("gauge", "SLO error-budget burn rate, all "
                                "tenants (1.0 = burning the 1% budget "
                                "exactly)"),
    "serve.queue_ms": ("histogram", "queue wait per executed job"),
    "serve.exec_ms": ("histogram", "execution wall per job"),
    "serve.e2e_ms": ("histogram", "client-experienced end-to-end "
                                  "latency"),
    # cross-request plan coalescing (serve/coalesce.py)
    "serve.coalesce.batched": ("counter", "queries served by a "
                                          "cross-request batched "
                                          "dispatch"),
    "serve.coalesce.dispatches": ("counter", "cross-request batched "
                                             "device dispatches"),
    "serve.coalesce.degraded": ("counter", "batches degraded to "
                                           "per-request replay"),
    "serve.coalesce.batch_size": ("histogram", "members per batched "
                                               "dispatch"),
    "serve.coalesce.window_ms": ("histogram", "hold-window wait per "
                                              "batched dispatch"),
    # network serving front end (serve/net.py + serve/client.py)
    "net.accept": ("counter", "socket connections accepted"),
    "net.requests": ("counter", "wire requests parsed (both framings)"),
    "net.pages": ("counter", "result pages streamed"),
    "net.page_deadline": ("counter", "result streams truncated by the "
                                     "wire deadline between pages"),
    "net.bytes_in": ("counter", "request bytes read off the wire"),
    "net.bytes_out": ("counter", "response bytes written to the wire"),
    "net.conn_reset": ("counter", "connections dropped by a reset "
                                  "(injected or real)"),
    "net.conn_timeout": ("counter", "connections closed by the "
                                    "read/write timeout (slow-loris "
                                    "guard)"),
    "net.partial_write": ("counter", "responses truncated mid-write"),
    "net.frame_overflow": ("counter", "requests refused over "
                                      "maxFrameBytes"),
    "net.client_gone": ("counter", "mid-stream client disconnects "
                                   "(result discarded via "
                                   "serve.late_result)"),
    "net.idem_hit": ("counter", "idempotency-key dedup hits (no "
                                "re-execution)"),
    "net.error_frames": ("counter", "structured error frames/responses "
                                    "sent"),
    "net.active": ("gauge", "open socket connections"),
    "net.client_retry": ("counter", "resilient-client attempt retries"),
    "net.client_hedge": ("counter", "resilient-client hedged attempts"),
    # cost-based plan optimizer (sql/optimizer.py + lowering hooks)
    "optimizer.rewrite": ("counter", "plan rewrites applied"),
    "optimizer.fallback": ("counter",
                           "queries degraded to the unrewritten plan"),
    "optimizer.split": ("counter",
                        "mega-stage flushes split at a warm prefix"),
    "optimizer.mem_chunk": ("counter",
                            "flushes chunked by remembered byte bounds"),
    "optimizer.dense_skip": ("counter",
                             "grouped dense attempts skipped by miss "
                             "history"),
    # adaptive query execution (sql/adaptive.py + boundary hooks)
    "aqe.replans": ("counter", "mid-query re-plan events applied, all "
                               "triggers"),
    "aqe.fallback": ("counter", "re-plan decision points degraded to "
                                "the static plan by the aqe fault "
                                "ladder"),
    # plan-stats observatory (utils/statstore.py)
    "stats.record": ("counter", "flush observations recorded"),
    "stats.evict": ("counter", "stats entries evicted (maxEntries)"),
    "stats.drain_sync": ("counter",
                         "batched deferred-observation device pulls"),
    "stats.pending_dropped": ("counter",
                              "deferred observations dropped at the "
                              "pending bound"),
    "stats.loaded": ("counter", "stats entries adopted from a snapshot"),
    "stats.persisted": ("counter", "stats snapshots written"),
    "stats.persist_failed": ("counter",
                             "snapshot writes degraded to in-memory "
                             "only"),
    "stats.load_failed": ("counter",
                          "corrupt/stale snapshots degraded to empty"),
    # device-cost observatory (utils/costprof.py)
    "costprof.extracted": ("counter",
                           "AOT cost profiles extracted (lower+compile, "
                           "zero device execution)"),
    "costprof.failed": ("counter",
                        "cost extractions degraded to unprofiled "
                        "(surfaces render '-')"),
    "shard.skew": ("gauge", "worst/mean shard row-balance ratio of the "
                            "most recent sharded placement"),
    "shard.exchange_bytes": ("counter",
                             "statically-sized cross-shard exchange "
                             "volume, all kinds"),
    "profiling.captures": ("counter",
                           "managed jax-profiler captures armed"),
    # data-quality observatory (utils/dqprof.py)
    "dq.sketches": ("counter",
                    "column/rule sketch reductions dispatched from "
                    "flush hooks"),
    "dq.drain_sync": ("counter",
                      "batched cold-path drains of deferred dq "
                      "sketches (the only dq host syncs)"),
    "dq.pending_dropped": ("counter",
                           "deferred dq observations dropped at the "
                           "pending bound"),
    "dq.profile_failed": ("counter",
                          "flushes degraded to unprofiled by the "
                          "dq_profile fault ladder"),
    "dq.rule_evals": ("counter",
                      "eager DQ-rule evaluations accounted"),
    "dq.baseline_pinned": ("counter",
                           "drift baselines pinned (first drain or "
                           "persisted snapshot adoption)"),
    "dq.drift_breach": ("counter",
                        "column drift scores past "
                        "spark.dq.driftThreshold"),
    "dq.violation_spike": ("counter",
                           "per-drain rule violation-rate spikes"),
    "dq.program_evict": ("counter",
                         "dq sketch programs evicted at the cache "
                         "bound"),
}

#: Dynamic metric-name families (formatted per site/tenant/category at
#: runtime): any name starting with one of these prefixes is declared by
#: the family. prefix → (type, help). Same pure-literal contract as
#: :data:`METRIC_NAMES`.
METRIC_NAME_PREFIXES = {
    "recovery.": ("counter", "resilience-layer event mirror (action and "
                             "per-site action.site keys)"),
    "faults.injected.": ("counter", "per-site injected-fault mirror"),
    "jit.backend.": ("counter", "jax monitoring compile events"),
    "solver.": ("counter", "per-solver dispatch counters"),
    "serve.reject.": ("counter", "per-reason admission rejections"),
    "serve.e2e_ms.": ("histogram", "per-tenant end-to-end latency "
                                   "(series-capped)"),
    "serve.slo_burn.": ("gauge", "per-tenant SLO error-budget burn rate "
                                 "(series-capped)"),
    "span_ms.": ("histogram", "span wall-clock latency by category"),
    "costprof.": ("counter", "device-cost observatory activity"),
    "aqe.replans.": ("counter", "per-trigger mid-query re-plan events "
                                "(build-flip/broadcast/skew-split/"
                                "re-bucket/grouped-lowering)"),
    "shard.exchange_bytes.": ("counter",
                              "per-kind cross-shard exchange volume "
                              "(psum/all_to_all/gather)"),
    "dq.violations.": ("counter", "per-rule DQ violation rows"),
    "dq.violation_rate.": ("gauge", "per-rule cumulative violation "
                                    "fraction"),
    "dq.drift.": ("gauge", "per-column PSI drift vs the pinned "
                           "baseline"),
}


class Histogram:
    """Fixed-bucket histogram (Prometheus convention: cumulative bucket
    counts keyed by upper bound ``le``, plus ``sum`` and ``count``).
    Thread-safe; buckets are fixed at construction."""

    def __init__(self, name: str, buckets=DEFAULT_BUCKETS_MS):
        self.name = name
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        i = 0
        for i, b in enumerate(self.buckets):  # ≤ ~14 buckets: linear is fine
            if v <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cumulative, acc = {}, 0
        for b, c in zip(self.buckets, counts):
            acc += c
            cumulative[b] = acc
        cumulative[float("inf")] = total
        return {"buckets": cumulative, "sum": s, "count": total}


class MetricsRegistry:
    """Gauges + histograms, by name. Counters intentionally stay in
    :data:`utils.profiling.counters` (one monotonic registry, one recovery
    mirror); :func:`metrics_snapshot` merges all three views."""

    def __init__(self):
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def histogram(self, name: str, buckets=None) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = Histogram(name, buckets or DEFAULT_BUCKETS_MS)
                self._histograms[name] = h
            return h

    def observe(self, name: str, value: float, buckets=None) -> None:
        self.histogram(name, buckets).observe(value)

    def snapshot(self) -> dict:
        with self._lock:
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        out: dict = dict(gauges)
        for name, h in hists.items():
            out[name] = h.snapshot()
        return out

    def clear(self) -> None:
        with self._lock:
            self._gauges.clear()
            self._histograms.clear()


#: Process-global metrics registry (gauges + histograms).
METRICS = MetricsRegistry()


def metrics_snapshot() -> dict:
    """One merged registry view: every monotonic counter (including the
    ``recovery.*`` mirror from PR 1), every gauge, and every histogram
    summary, flat by name."""
    out: dict = dict(profiling.counters.snapshot())
    out.update(METRICS.snapshot())
    return out


# ---------------------------------------------------------------------------
# Tracer: hierarchical spans, contextvar-propagated
# ---------------------------------------------------------------------------


class _NoopSpan:
    """Shared disabled-mode stand-in: reentrant, stateless, allocation-free.
    Every method is a no-op returning self so instrumented sites never
    branch on the enabled flag twice."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopSpan()

_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "sparkdq4ml_obs_current_span", default=None)


class Span:
    """One traced operation. Use as a context manager (normal case) or via
    ``Tracer.begin``/``Tracer.end`` for long-lived spans (the session root).
    ``set(**attrs)`` attaches structured attributes at any point.

    ``trace_id`` is the span id of the trace's ROOT span (a root's
    trace_id is its own sid) — emitted by BOTH exporters (logfmt lines and
    Chrome-trace ``args``), so a logfmt line can be cross-referenced into
    the Perfetto view of the same run."""

    __slots__ = ("name", "cat", "attrs", "sid", "parent_id", "trace_id",
                 "tid", "ts_us", "dur_us", "_t0", "_token", "_tracer",
                 "_mem")

    def __init__(self, tracer: "Tracer", name: str, cat: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.sid = tracer._next_id()
        parent = _CURRENT.get()
        if parent is None:
            # Ambient fallback: a long-lived root opened with ``begin``
            # (the session span) parents spans whose context lost the
            # link — worker threads (fresh contexts) and callers whose
            # enclosing ``with span`` exited after ``begin`` ran inside
            # it (the contextvar reset would otherwise orphan everything
            # that follows). Lock-free read: end()/clear() may empty the
            # list between the check and the index, so tolerate that
            # instead of crashing the instrumented user operation.
            try:
                parent = tracer._ambient[-1]
            except IndexError:
                parent = None
        self.parent_id = parent.sid if parent is not None else None
        self.trace_id = parent.trace_id if parent is not None else self.sid
        self.tid = threading.get_ident()
        self.ts_us = 0
        self.dur_us: Optional[int] = None
        self._t0 = 0.0
        self._token: Optional[contextvars.Token] = None
        self._mem = None              # meminfo.SpanSampler when sampling

    def set(self, **attrs) -> "Span":
        # Copy-on-write, never in-place: exporters snapshot ``self.attrs``
        # by reference from other threads (open spans export live), and a
        # concurrent in-place mutation would raise "dictionary changed
        # size during iteration" mid-scrape. A reference swap is atomic.
        self.attrs = {**self.attrs, **attrs}
        return self

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        if self._tracer.mem_sample:
            from . import meminfo

            self._mem = meminfo.span_sampler()
        self.ts_us = self._tracer._now_us()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        self.dur_us = int((time.perf_counter() - self._t0) * 1e6)
        if self._mem is not None:
            self.attrs = {**self.attrs, **self._mem.finish()}
            self._mem = None
        if et is not None:
            self.attrs = {**self.attrs, "error": et.__name__}
        if self._token is not None:
            try:
                _CURRENT.reset(self._token)
            except ValueError:   # crossed contexts (begin/end style misuse)
                _CURRENT.set(None)
            self._token = None
        self._tracer._finish(self)
        return False


class Tracer:
    """Span recorder. ``enabled`` is THE hot-path gate: every instrumented
    site reads it once and returns :data:`_NOOP` when off. Finished spans
    land in a bounded buffer (oldest dropped) and their durations feed the
    ``span_ms.<category>`` histograms."""

    #: Minimum spacing of the resource-counter samples the Chrome-trace
    #: exporter renders as ``"ph": "C"`` tracks (microseconds). Sampling
    #: is activity-driven (taken at span completion, throttled to this
    #: interval) so an idle process records nothing.
    counter_sample_us = 20_000
    #: Bounded counter-sample history (oldest dropped).
    max_counter_samples = 4096

    def __init__(self, max_spans: int = 10_000):
        self.enabled = False
        self.log_spans = False
        self.mem_sample = False       # per-span device-memory sampling
        self.max_spans = max_spans
        self.dropped = 0              # spans evicted by the bounded buffer
        self._spans: list[Span] = []
        self._open: dict[int, Span] = {}
        self._ambient: list[Span] = []   # begun roots (see Span.__init__)
        self._sinks: list = []        # per-query collectors (query_stats)
        self._csamples: list = []     # (ts_us, {metric: value}) track
        self._last_csample_us = 0
        self._lock = threading.Lock()
        self._id = 0
        self._epoch_s = time.time()
        self._pc0 = time.perf_counter()

    # -- internals --------------------------------------------------------
    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _now_us(self) -> int:
        return int((self._epoch_s
                    + (time.perf_counter() - self._pc0)) * 1e6)

    def _finish(self, s: Span) -> None:
        with self._lock:
            self._open.pop(s.sid, None)
            self._spans.append(s)
            excess = len(self._spans) - self.max_spans
            if excess > 0:
                # The bounded buffer wrapping used to be SILENT — a trace
                # that looks complete but starts mid-query. Count it so
                # trace_report()/chrome_trace() can say what's missing.
                del self._spans[:excess]
                self.dropped += excess
            sinks = list(self._sinks)
        if excess > 0:
            profiling.counters.increment("trace.dropped_spans", excess)
        for sink in sinks:
            try:
                sink(s)
            except Exception:   # a broken collector must not break the op
                logger.debug("span sink failed", exc_info=True)
        self._maybe_sample_counters()
        METRICS.observe(f"span_ms.{s.cat or 'other'}",
                        (s.dur_us or 0) / 1e3)
        if self.log_spans:
            logger.debug(
                "span %s",
                format_kv(name=s.name, cat=s.cat,
                          dur_ms=round((s.dur_us or 0) / 1e3, 3),
                          trace_id=s.trace_id, span_id=s.sid,
                          parent_id=s.parent_id, **s.attrs))

    def _maybe_sample_counters(self) -> None:
        """Resource-counter sampling for the Chrome-trace ``"ph": "C"``
        tracks (Perfetto renders them as graphs under the span
        timeline): the live-bytes census, serving queue depth, and the
        pipeline hit/compile counters, taken at span completion and
        throttled to :data:`counter_sample_us`. Runs only while tracing
        is enabled (we are in ``_finish``) — the disabled path never
        reaches here."""
        now = self._now_us()
        with self._lock:
            if now - self._last_csample_us < self.counter_sample_us:
                return
            self._last_csample_us = now
        from . import meminfo
        from . import profiling

        sample = {
            "mem.live_bytes": meminfo.live_bytes(),
            "serve.queue_depth": METRICS.get_gauge("serve.queue_depth"),
            "pipeline.hit": profiling.counters.get("pipeline.hit"),
            "pipeline.compile": profiling.counters.get("pipeline.compile"),
        }
        with self._lock:
            self._csamples.append((now, sample))
            if len(self._csamples) > self.max_counter_samples:
                del self._csamples[: len(self._csamples)
                                   - self.max_counter_samples]

    def counter_samples(self) -> list:
        with self._lock:
            return list(self._csamples)

    # -- recording --------------------------------------------------------
    def span(self, name: str, cat: str = "", **attrs):
        """Context manager for one traced operation. Returns the shared
        no-op when disabled — one flag check, zero allocation."""
        if not self.enabled:
            return _NOOP
        return Span(self, name, cat, attrs)

    def begin(self, name: str, cat: str = "", **attrs):
        """Open a long-lived span (e.g. the session root) that outlives the
        calling frame. Pair with :meth:`end`. Child spans nest under it via
        the context AND the ambient-root fallback (so spans from worker
        threads or sibling contexts still parent correctly)."""
        if not self.enabled:
            return _NOOP
        s = Span(self, name, cat, attrs)
        s.ts_us = self._now_us()
        s._t0 = time.perf_counter()
        _CURRENT.set(s)
        with self._lock:
            self._open[s.sid] = s
            self._ambient.append(s)
        return s

    def end(self, s) -> None:
        if s is None or s is _NOOP:
            return
        s.dur_us = int((time.perf_counter() - s._t0) * 1e6)
        if s._mem is not None:
            s.attrs = {**s.attrs, **s._mem.finish()}
            s._mem = None
        if _CURRENT.get() is s:
            _CURRENT.set(None)
        with self._lock:
            if s in self._ambient:
                self._ambient.remove(s)
        self._finish(s)

    # -- views ------------------------------------------------------------
    def spans(self) -> list:
        """Finished + still-open spans (open ones report duration so far)."""
        with self._lock:
            done = list(self._spans)
            open_ = list(self._open.values())
        return done + open_

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._open.clear()
            self._ambient.clear()
            self._csamples.clear()
            self._last_csample_us = 0
            self.dropped = 0


#: Process-global tracer. Disabled by default; ``session`` conf/env turn it
#: on (or call :func:`enable` directly).
TRACER = Tracer()


def enabled() -> bool:
    return TRACER.enabled


def enable(max_spans: int = 10_000, log_spans: bool = False) -> None:
    """Turn recording on (idempotent). Previously recorded spans are kept;
    call ``TRACER.clear()`` / ``reset()`` for a fresh buffer."""
    TRACER.max_spans = int(max_spans)
    TRACER.log_spans = bool(log_spans)
    TRACER.enabled = True
    _install_jax_compile_listener()


def disable() -> None:
    """Stop recording. Already-recorded spans stay exportable."""
    TRACER.enabled = False


def reset() -> None:
    """Clear spans, gauges, histograms, the tail sampler's request trees,
    and the device-memory peak tracker (counters have their own
    ``profiling.counters.clear``)."""
    TRACER.clear()
    METRICS.clear()
    TAIL.clear()
    from . import meminfo

    meminfo.reset_peak()


def span(name: str, cat: str = "", **attrs):
    """Module-level convenience: ``with observability.span("x"): ...``."""
    if not TRACER.enabled:
        return _NOOP
    return TRACER.span(name, cat, **attrs)


def current_span():
    """The innermost active span in this context (the :data:`_NOOP`
    singleton when disabled or outside any span) — instrumented sites use
    it to attach attributes computed mid-operation without re-plumbing the
    span object."""
    if not TRACER.enabled:
        return _NOOP
    s = _CURRENT.get()
    return s if s is not None else _NOOP


def current_ids() -> tuple:
    """``(trace_id, span_id)`` of the innermost active span — ``(None,
    None)`` when tracing is off or no span is open. Recovery events attach
    these so a retry/fallback line in the structured log can be cross-
    referenced into the logfmt span stream and the Perfetto view."""
    if not TRACER.enabled:
        return (None, None)
    s = _CURRENT.get()
    if s is None:
        try:
            s = TRACER._ambient[-1]
        except IndexError:
            return (None, None)
    return (s.trace_id, s.sid)


# ---------------------------------------------------------------------------
# Distributed trace context (W3C traceparent) + tail-based retention
# ---------------------------------------------------------------------------

#: Exact length of a version-00 ``traceparent`` value
#: (``"00-" + 32 hex + "-" + 16 hex + "-" + 2 hex``). The length bound is
#: checked FIRST, so a hostile megabyte header costs one ``len()``.
_TP_LEN = 55
_HEX_DIGITS = frozenset("0123456789abcdef")


def _is_lower_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX_DIGITS for c in s)


class TraceContext:
    """Wire-level trace identity of ONE served request.

    The client mints one per logical query (``trace_id`` constant across
    retries AND hedges; each attempt carries a fresh child span id so the
    server can tell attempts apart) and sends it W3C-``traceparent``-style
    in both framings. The server adopts it — or, on absent/malformed/
    hostile input, degrades to a locally-minted root (NEVER an error) — and
    echoes ``trace_id`` in the end frame so every ``ClientResult`` is
    joinable to the server-side span tree.

    ``root_trace``/``root_sid`` are filled by :func:`request_span` with the
    INTERNAL integer ids of the adopted root span: the tail sampler keys
    its pending request trees by them, and late stream spans (emitted from
    the wire layer after the execute span closed) parent through them.
    """

    __slots__ = ("trace_id", "parent_id", "remote", "defer",
                 "root_trace", "root_sid")

    def __init__(self, trace_id: str, parent_id: Optional[str] = None,
                 remote: bool = False, defer: bool = False):
        self.trace_id = trace_id
        self.parent_id = parent_id
        self.remote = remote
        #: when True the wire layer finalizes the request tree (it still
        #: has stream spans to record after the server-side verdict).
        self.defer = defer
        self.root_trace: Optional[int] = None
        self.root_sid: Optional[int] = None

    @classmethod
    def mint(cls) -> "TraceContext":
        """A fresh locally-minted root context."""
        return cls(os.urandom(16).hex(), None, remote=False)

    @classmethod
    def parse(cls, value) -> Optional["TraceContext"]:
        """Strict parse of a version-00 ``traceparent``; ``None`` on ANY
        deviation (wrong type/length/version, non-hex, all-zero ids)."""
        if not isinstance(value, str) or len(value) != _TP_LEN:
            return None
        parts = value.split("-")
        if len(parts) != 4:
            return None
        version, trace, parent, flags = parts
        if version != "00":
            return None
        if len(trace) != 32 or not _is_lower_hex(trace) \
                or trace == "0" * 32:
            return None
        if len(parent) != 16 or not _is_lower_hex(parent) \
                or parent == "0" * 16:
            return None
        if len(flags) != 2 or not _is_lower_hex(flags):
            return None
        return cls(trace, parent, remote=True)

    @classmethod
    def adopt(cls, value, defer: bool = False) -> "TraceContext":
        """Parse ``value`` or degrade to a locally-minted root. Passing an
        existing context through is idempotent (``defer`` only widens)."""
        if isinstance(value, cls):
            value.defer = value.defer or defer
            return value
        ctx = cls.parse(value)
        if ctx is None:
            ctx = cls.mint()
        ctx.defer = defer
        return ctx

    def child_traceparent(self) -> str:
        """A fresh per-attempt traceparent under this trace — retries and
        hedges stay distinguishable server-side by their span id."""
        return f"00-{self.trace_id}-{os.urandom(8).hex()}-01"


def _span_doc(s) -> dict:
    """JSON-safe dict view of one span (the /trace wire schema)."""
    return {"name": s.name, "cat": s.cat or "other", "span_id": s.sid,
            "parent_id": s.parent_id, "trace_id": s.trace_id,
            "ts_us": s.ts_us,
            "dur_ms": round((s.dur_us or 0) / 1e3, 3),
            "attrs": {k: (v if isinstance(v, (str, int, float, bool,
                                              type(None))) else repr(v))
                      for k, v in s.attrs.items()}}


class TailSampler:
    """Tail-based retention of completed request span trees.

    Every served request registers its root span here; the tracer sink
    buckets the request's finished spans by the root's internal trace id.
    On completion the tree lands in a bounded ring (recent context, kept
    or not) and the keep-policy — error, deadline_exceeded, any
    ``recovery_fault`` annotation, a breaker transition, or e2e latency
    over the serving SLO — promotes it to the retained store keyed by the
    WIRE trace id (what the client holds). Healthy-path cost when
    observability is disabled stays zero: nothing registers, the sink
    sees an empty pending map."""

    #: Pending-bucket bound: a wire layer that dies before finalizing must
    #: not leak request buckets forever (oldest dropped).
    MAX_PENDING = 1024

    def __init__(self, ring_size: int = 256, retained_size: int = 64):
        self.ring_size = int(ring_size)
        self.retained_size = int(retained_size)
        self._pending: dict = {}    # internal root trace id -> bucket
        self._ring: list = []       # completed tree docs, oldest first
        self._retained: dict = {}   # wire trace id -> [tree docs]
        self._exemplars: dict = {}  # histogram name -> {le: (tid, value)}
        self._lock = threading.Lock()

    def configure(self, ring_size: Optional[int] = None,
                  retained_size: Optional[int] = None) -> None:
        with self._lock:
            if ring_size is not None:
                self.ring_size = max(1, int(ring_size))
            if retained_size is not None:
                self.retained_size = max(1, int(retained_size))

    # -- collection -------------------------------------------------------
    def open_request(self, root, ctx: TraceContext) -> None:
        bucket = {"ctx": ctx, "spans": [], "verdict": None}
        prior = getattr(ctx, "root_trace", None)
        with self._lock:
            if prior is not None:
                # a requeued attempt re-roots the same context: carry the
                # earlier attempt's spans into the new bucket so the full
                # retry history stays one tree
                old = self._pending.pop(prior, None)
                if old is not None:
                    bucket["spans"] = old["spans"]
            self._pending[root.trace_id] = bucket
            while len(self._pending) > self.MAX_PENDING:
                self._pending.pop(next(iter(self._pending)))

    def _on_span(self, s) -> None:
        # tracer sink — one dict lookup per finished span; request spans
        # only (everything else misses the pending map).
        b = self._pending.get(s.trace_id)
        if b is not None:
            b["spans"].append(s)

    def finish_request(self, ctx, *, status=None, reason=None,
                       e2e_ms=None, breaker_opened: bool = False,
                       slo_ms=None) -> None:
        """Attach the server-side completion verdict. Finalizes the tree
        immediately unless the context defers to the wire layer (stream
        spans still to come — it calls :meth:`complete` when done)."""
        key = getattr(ctx, "root_trace", None)
        if key is None:
            return
        with self._lock:
            b = self._pending.get(key)
        if b is None:
            return
        if b["verdict"] is None:
            # first verdict wins: the winning resolution is what the
            # client saw — a lost-race worker's later value must not
            # rewrite a deadline verdict as "ok"
            b["verdict"] = {"status": status, "reason": reason,
                            "e2e_ms": e2e_ms,
                            "breaker_opened": bool(breaker_opened),
                            "slo_ms": slo_ms}
        if not getattr(ctx, "defer", False):
            self.complete(ctx)

    def complete(self, ctx) -> Optional[dict]:
        """Finalize one request tree: evaluate the keep-policy, land the
        doc in the ring, promote to the retained store when kept.
        Idempotent — the second call for a context is a no-op."""
        key = getattr(ctx, "root_trace", None)
        if key is None:
            return None
        with self._lock:
            b = self._pending.pop(key, None)
        if b is None:
            return None
        v = b["verdict"] or {}
        spans = b["spans"]
        reasons = []
        if v.get("status") == "error":
            reasons.append("error")
        if v.get("status") == "deadline_exceeded" \
                or v.get("reason") == "deadline":
            reasons.append("deadline_exceeded")
        if any("recovery_fault" in s.attrs for s in spans):
            reasons.append("recovery_fault")
        if any("dq_drift" in s.attrs for s in spans):
            reasons.append("dq_drift")
        if v.get("breaker_opened"):
            reasons.append("breaker_transition")
        slo_ms, e2e_ms = v.get("slo_ms"), v.get("e2e_ms")
        if slo_ms and e2e_ms and e2e_ms > slo_ms:
            reasons.append("slow")
        doc = {"trace_id": ctx.trace_id, "remote": ctx.remote,
               "status": v.get("status"), "reason": v.get("reason"),
               "e2e_ms": e2e_ms, "kept": bool(reasons),
               "keep_reasons": reasons,
               "spans": [_span_doc(s) for s in spans]}
        aged_unkept = 0
        with self._lock:
            self._ring.append(doc)
            while len(self._ring) > self.ring_size:
                if not self._ring.pop(0)["kept"]:
                    aged_unkept += 1
            if reasons:
                self._retained.setdefault(ctx.trace_id, []).append(doc)
                while len(self._retained) > self.retained_size:
                    self._retained.pop(next(iter(self._retained)))
        if reasons:
            profiling.counters.increment("trace.kept")
            if e2e_ms is not None:
                # last kept trace per latency bucket backs the
                # OpenMetrics exemplars on serve.e2e_ms
                self.exemplar("serve.e2e_ms", e2e_ms, ctx.trace_id)
        if aged_unkept:
            profiling.counters.increment("trace.dropped", aged_unkept)
        return doc

    # -- exemplars --------------------------------------------------------
    def exemplar(self, hist_name: str, value: float, trace_id: str,
                 buckets=DEFAULT_BUCKETS_MS) -> None:
        """Remember ``trace_id`` as the last kept trace for the histogram
        bucket ``value`` falls into (OpenMetrics exemplar source)."""
        le = float("inf")
        for b in buckets:
            if value <= b:
                le = float(b)
                break
        with self._lock:
            self._exemplars.setdefault(hist_name, {})[le] = (
                trace_id, float(value))

    def exemplars(self, hist_name: str) -> dict:
        with self._lock:
            return dict(self._exemplars.get(hist_name, ()))

    def pending_tree(self, trace_id: str) -> Optional[dict]:
        """Snapshot an IN-FLIGHT request tree by its wire trace id — the
        flight recorder fires mid-request (breaker trip, requeue
        exhaustion), before the wire layer finalizes the bucket, so the
        completed-tree views come up empty exactly when an incident
        bundle wants the tree most."""
        with self._lock:
            for b in self._pending.values():
                ctx = b["ctx"]
                if getattr(ctx, "trace_id", None) == trace_id:
                    v = b["verdict"] or {}
                    return {"trace_id": trace_id,
                            "remote": getattr(ctx, "remote", False),
                            "status": v.get("status"),
                            "reason": v.get("reason"),
                            "e2e_ms": v.get("e2e_ms"),
                            "partial": True,
                            "spans": [_span_doc(s) for s in b["spans"]]}
        return None

    # -- views ------------------------------------------------------------
    def lookup(self, trace_id: str) -> list:
        """Every completed tree for one WIRE trace id (retries/hedges of
        one logical query share it) — retained store first, then the
        recent ring."""
        with self._lock:
            trees = list(self._retained.get(trace_id, ()))
            if not trees:
                trees = [d for d in self._ring
                         if d["trace_id"] == trace_id]
        return trees

    def recent(self, limit: int = 50, trace_id: Optional[str] = None) \
            -> list:
        with self._lock:
            ring = list(self._ring)
        if trace_id is not None:
            ring = [d for d in ring if d["trace_id"] == trace_id]
        return ring[-max(0, int(limit)):]

    def retained_ids(self) -> list:
        with self._lock:
            return list(self._retained)

    def report(self) -> dict:
        with self._lock:
            return {"pending": len(self._pending),
                    "ring": len(self._ring),
                    "retained": len(self._retained),
                    "ring_size": self.ring_size,
                    "retained_size": self.retained_size}

    def clear(self) -> None:
        with self._lock:
            self._pending.clear()
            self._ring.clear()
            self._retained.clear()
            self._exemplars.clear()


#: Process-global tail sampler; its sink rides the tracer (only called
#: while tracing is enabled — the disabled path never reaches sinks).
TAIL = TailSampler()
TRACER._sinks.append(TAIL._on_span)


def request_span(name: str, ctx: Optional[TraceContext],
                 cat: str = "serve", **attrs):
    """Root span for one served request: detached from any ambient/session
    parent so the request tree owns its internal trace id, stamped with
    the wire trace identity, and registered with the tail sampler.
    Returns the shared no-op when tracing is off or no context given."""
    t = TRACER
    if not t.enabled or ctx is None:
        return _NOOP
    s = Span(t, name, cat, attrs)
    s.parent_id = None
    s.trace_id = s.sid
    wire = {"wire_trace_id": ctx.trace_id}
    if ctx.remote:
        wire["wire_parent_id"] = ctx.parent_id
        wire["remote"] = True
    s.attrs = {**s.attrs, **wire}
    # open BEFORE re-rooting the context: the sampler reads the previous
    # root to merge a requeued attempt's spans into the new bucket
    TAIL.open_request(s, ctx)
    ctx.root_trace = s.sid
    ctx.root_sid = s.sid
    return s


def emit_span(name: str, cat: str = "", dur_ms: float = 0.0,
              ctx: Optional[TraceContext] = None, **attrs) -> None:
    """Record an already-elapsed interval as a finished span, back-dated
    by ``dur_ms``. The serving layer's admission/queue/stream stages run
    outside the execute context (caller thread, asyncio thread) — this is
    how they still land in the request tree: ``ctx`` parents the span
    under the adopted request root."""
    t = TRACER
    if not t.enabled:
        return
    s = Span(t, name, cat, attrs)
    if ctx is not None and getattr(ctx, "root_sid", None) is not None:
        s.parent_id = ctx.root_sid
        s.trace_id = ctx.root_trace
    s.dur_us = int(max(float(dur_ms), 0.0) * 1000)
    s.ts_us = t._now_us() - s.dur_us
    t._finish(s)


def op_span(name: str, cat: str = "frame"):
    """Decorator for frame-op style methods: when tracing is enabled, wrap
    the call in a span carrying rows in/out (``num_slots`` — static shape
    info, never a device read) and the number of ``frame.host_sync``
    events the op (and anything nested under it) performed — the per-
    operator sync attribution EXPLAIN ANALYZE reads. Disabled cost: one
    attribute read and a branch."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            t = TRACER
            if not t.enabled:
                return fn(self, *args, **kwargs)
            sync0 = profiling.counters.get("frame.host_sync")
            with Span(t, name, cat, {"rows_in": getattr(self, "_n", None)}) \
                    as s:
                out = fn(self, *args, **kwargs)
                n = getattr(out, "_n", None)
                if n is not None:
                    s.set(rows_out=n)
                s.set(host_syncs=profiling.counters.get("frame.host_sync")
                      - sync0)
                return out
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# Recovery + compile-cache annotations
# ---------------------------------------------------------------------------


def recovery_mark() -> int:
    """Cursor into the structured recovery log; pair with
    :func:`recovery_delta` to annotate a span with the retries/fallbacks
    that happened inside it."""
    from .recovery import RECOVERY_LOG

    return len(RECOVERY_LOG)


def recovery_delta(mark: int) -> dict:
    """``{action: count}`` of recovery events recorded since ``mark``
    (empty on a clean run). The log is bounded, so a mark taken more than
    ``maxlen`` events ago degrades to counting the whole window."""
    from .recovery import RECOVERY_LOG

    events = RECOVERY_LOG.events()
    out: dict[str, int] = {}
    for e in events[max(0, min(mark, len(events))):]:
        out[e.action] = out.get(e.action, 0) + 1
    return out


def annotate_recovery(s, mark: int) -> None:
    """Attach ``recovery_<action>=count`` attributes for events since
    ``mark`` (no-op when nothing happened or the span is the no-op)."""
    if s is _NOOP:
        return
    delta = recovery_delta(mark)
    if delta:
        s.set(**{f"recovery_{k}": v for k, v in delta.items()})


def jit_cache_probe(cached_factory) -> Callable[[], str]:
    """Cold-compile vs steady detection for an ``lru_cache``-ed jit-factory
    (``fused_linear_fit_packed`` et al.): snapshot ``cache_info()`` now,
    and the returned thunk reports ``"miss"`` (a new trace+compile was
    built since) or ``"hit"`` (served from cache). Also mirrors into the
    ``jit.trace_miss`` / ``jit.trace_hit`` counters."""
    try:
        before = cached_factory.cache_info().misses
    except AttributeError:        # not an lru_cache — report unknown
        return lambda: "unknown"

    def verdict() -> str:
        try:
            missed = cached_factory.cache_info().misses > before
        except AttributeError:
            return "unknown"
        profiling.counters.increment(
            "jit.trace_miss" if missed else "jit.trace_hit")
        return "miss" if missed else "hit"
    return verdict


@contextlib.contextmanager
def fit_span(name: str, jit_factory, **attrs):
    """The shared fit-instrumentation shape (LinearRegression /
    LogisticRegression both families): one span carrying the fit attrs,
    the cold-compile vs steady verdict from :func:`jit_cache_probe` on the
    lru-cached jit factory, and recovery retry/fallback annotations for
    anything the resilience layer did inside. Yields the span (the no-op
    when disabled) — the caller sets result attrs (iterations, converged)
    on it. The enabled flag is read ONCE here, so a concurrent enable
    mid-fit cannot desync the probe from the span."""
    t = TRACER
    if not t.enabled:
        yield _NOOP
        return
    verdict = jit_cache_probe(jit_factory)
    mark = recovery_mark()
    with t.span(name, cat="fit", **attrs) as s:
        yield s
        s.set(compile=verdict())
        annotate_recovery(s, mark)


_jax_listener_installed = False


def _install_jax_compile_listener() -> None:
    """Best-effort backend compile counter: subscribe to jax's monitoring
    events and mirror compilation-related ones into the counter registry
    (``jit.backend.<event>``). Private-API dependent, so any failure just
    means the deterministic lru-level ``jit.trace_*`` counters are the
    only compile signal."""
    global _jax_listener_installed
    if _jax_listener_installed:
        return
    try:
        from jax._src import monitoring as _mon

        def _on_event(event, *a, **kw):
            if "compil" in event:
                tail = event.strip("/").replace("/", "_")
                profiling.counters.increment(f"jit.backend.{tail}")

        _mon.register_event_listener(_on_event)
        _jax_listener_installed = True
    except Exception:  # pragma: no cover - depends on jax internals
        _jax_listener_installed = True  # don't retry every enable()


# ---------------------------------------------------------------------------
# Per-query stats collection (EXPLAIN ANALYZE)
# ---------------------------------------------------------------------------


class QueryStatsCollector:
    """Scopes the span and counter streams to ONE query so EXPLAIN ANALYZE
    can attribute them to plan operators: every span finished while the
    collector is installed lands in ``spans`` (in completion order), and
    ``counter_delta()`` reports how every monotonic counter moved.

    Scoped to the INSTALLING thread: a query executes synchronously on
    one thread, and filtering by thread id keeps two concurrent EXPLAIN
    ANALYZE queries (cross-thread frame sharing is supported engine-wide)
    from polluting each other's span streams. Spans an op hands to a
    worker thread would be excluded — no instrumented path does that
    today. Counter deltas remain process-global (counters carry no
    thread identity); concurrent queries share those."""

    def __init__(self):
        self.spans: list = []
        self._tid = threading.get_ident()
        self._counters0 = profiling.counters.snapshot()

    def _on_span(self, s) -> None:
        if s.tid == self._tid:
            self.spans.append(s)

    def counter_delta(self) -> dict:
        """``{name: increment}`` for every counter that moved since the
        collector was installed (recovery/fallback/compile/host-sync
        activity of exactly this query)."""
        now = profiling.counters.snapshot()
        out = {}
        for k, v in now.items():
            d = v - self._counters0.get(k, 0)
            if d:
                out[k] = d
        return out

    def spans_named(self, *names) -> list:
        return [s for s in self.spans if s.name in names]


# query_stats nesting/concurrency state: the enabled/mem_sample restore
# is REFCOUNTED (the outermost/first collector snapshots, the last one
# out restores) so a collector exiting on one thread cannot disable
# tracing while another thread's EXPLAIN ANALYZE is mid-flight.
_QS_LOCK = threading.Lock()
_QS_ACTIVE = 0
_QS_WAS_ENABLED = False
_QS_WAS_MEM = False


@contextlib.contextmanager
def query_stats(sample_memory: bool = True):
    """Install a :class:`QueryStatsCollector` for the duration of one
    query (the EXPLAIN ANALYZE execution window). Activates tracing for
    the window if it is off — per-query activation is the contract that
    keeps the DEFAULT path a no-op — and restores the previous state
    when the LAST active collector exits (refcounted: safe under
    concurrent queries from multiple threads; each collector sees only
    its own thread's spans). ``sample_memory`` additionally turns on
    per-span device-memory sampling (``peak_mem`` attrs; see
    ``utils.meminfo``)."""
    global _QS_ACTIVE, _QS_WAS_ENABLED, _QS_WAS_MEM
    t = TRACER
    with _QS_LOCK:
        if _QS_ACTIVE == 0:
            _QS_WAS_ENABLED = t.enabled
            _QS_WAS_MEM = t.mem_sample
        _QS_ACTIVE += 1
        if not t.enabled:
            enable(max_spans=t.max_spans, log_spans=t.log_spans)
        if sample_memory:
            t.mem_sample = True
    qs = QueryStatsCollector()
    with t._lock:
        t._sinks.append(qs._on_span)
    try:
        yield qs
    finally:
        with t._lock:
            try:
                t._sinks.remove(qs._on_span)
            except ValueError:
                pass
        with _QS_LOCK:
            _QS_ACTIVE -= 1
            if _QS_ACTIVE == 0:
                t.mem_sample = _QS_WAS_MEM
                t.enabled = _QS_WAS_ENABLED


# ---------------------------------------------------------------------------
# Unified jit-cache introspection
# ---------------------------------------------------------------------------


class ProgramHandle:
    """One enumerable cached program: a stable ``program_key`` plus a way
    to RE-TRACE it abstractly (``jax.make_jaxpr`` over the recorded
    abstract argument specs — zero compiles, zero device execution).

    This is the contract between every compiled-program cache and the
    jaxpr-level auditor (``analysis/program``, the dqaudit tier) and the
    future cost-based optimizer: without it, enumerating "every program
    the engine would replay in serving" needs private imports into four
    modules. Producers register a zero-arg enumerator via
    :meth:`CacheRegistry.register_programs`.

    Fields:

    * ``cache`` — the producer's registry name (``pipeline``/``grouped``/
      ``solver``/``fit.factories``);
    * ``program_key`` — stable identity, identical to the
      ``program_key`` field of the matching ``report()`` entry;
    * ``fn`` / ``args`` / ``kwargs`` — the traceable callable and its
      abstract example arguments (``jax.ShapeDtypeStruct`` leaves for
      arrays; concrete host scalars where values are part of the calling
      convention). ``fn`` is the UN-counted trace body where the
      producer tracks replay counters — auditing must not distort stats;
    * ``variants`` — name → ``(args, kwargs)`` (compared against the
      base trace) or a LIST of such pairs (compared among themselves —
      the form real producers use, e.g. bucket x2 vs x4, so both traces
      are fresh under the current config rather than one being jax's
      cached trace of the recorded shape): alternates the producer
      declares structurally equivalent; the retrace-hazard detector
      re-traces each and compares structural jaxpr hashes;
    * ``mesh`` / ``guarded`` — the installed mesh (``None`` off-mesh)
      and whether dispatch routes through the process-wide collective
      guard (``parallel.mesh.serialize_collectives``);
    * ``meta`` — free-form producer facts (``expected_traces`` /
      ``observed_traces`` for the retrace detector, ``expect_no_consts``
      for the literal-hoisting check, …).
    """

    __slots__ = ("cache", "program_key", "fn", "args", "kwargs",
                 "variants", "mesh", "guarded", "meta")

    def __init__(self, cache: str, program_key: str, fn,
                 args: tuple = (), kwargs: Optional[dict] = None,
                 variants: Optional[dict] = None, mesh=None,
                 guarded: Optional[bool] = None,
                 meta: Optional[dict] = None):
        self.cache = cache
        self.program_key = str(program_key)
        self.fn = fn
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})
        self.variants = dict(variants or {})
        self.mesh = mesh
        self.guarded = guarded
        self.meta = dict(meta or {})

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ProgramHandle({self.cache!r}, "
                f"{self.program_key[:60]!r}, variants="
                f"{sorted(self.variants)})")


class CacheRegistry:
    """One registry every compiled-program cache reports into: the
    pipeline compiler (``ops/compiler.py``), the grouped-execution engine
    (``ops/segments.py``), the solver jit entry points
    (``models/solvers.py``), and the packed-fit factories
    (``parallel/distributed.py``) each register a zero-arg stats callable
    under a stable name. ``report()`` (surfaced as
    ``session.cache_report()``) returns the merged view; EXPLAIN ANALYZE
    diffs two reports to print one line per cached program the query
    touched. Producers additionally register a program enumerator
    (:meth:`register_programs`) yielding :class:`ProgramHandle` records —
    the re-trace surface the jaxpr auditor (``analysis/program``) and the
    future cost-based optimizer consume."""

    def __init__(self):
        self._providers: dict[str, Callable[[], dict]] = {}
        self._program_providers: dict[str, Callable[[], list]] = {}
        self._lock = threading.Lock()

    def register(self, name: str, stats_fn: Callable[[], dict]) -> None:
        """Idempotent: re-registration under the same name replaces (a
        module reload must not accumulate stale providers)."""
        with self._lock:
            self._providers[name] = stats_fn

    def register_programs(self, name: str,
                          programs_fn: Callable[[], list]) -> None:
        """Register a zero-arg enumerator returning the producer's
        currently-cached programs as :class:`ProgramHandle` records.
        Idempotent like :meth:`register`."""
        with self._lock:
            self._program_providers[name] = programs_fn

    def unregister(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)
            self._program_providers.pop(name, None)

    def programs(self) -> tuple[list, dict]:
        """Every registry-enumerable cached program, merged across
        producers. Returns ``(handles, errors)`` where ``errors`` maps a
        producer name to the exception string its enumerator raised —
        surfaced (never swallowed) so an audit can report partial
        enumeration instead of silently under-covering."""
        with self._lock:
            items = list(self._program_providers.items())
        handles: list = []
        errors: dict[str, str] = {}
        for name, fn in sorted(items):
            try:
                handles.extend(fn())
            except Exception as e:
                errors[name] = f"{type(e).__name__}: {e}"
        return handles, errors

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._providers)

    def report(self) -> dict:
        with self._lock:
            items = list(self._providers.items())
        out: dict = {}
        for name, fn in sorted(items):
            try:
                out[name] = fn()
            except Exception as e:   # introspection must never take
                out[name] = {"error": str(e)}  # a query down
        return out


#: Process-global cache registry (see :class:`CacheRegistry`).
CACHES = CacheRegistry()


def cache_report() -> dict:
    """Merged per-cache introspection: size/capacity, hits/misses/
    evictions, and per-entry detail (plan-key prefix, hit count, bucket
    histogram) where the producer tracks it."""
    return CACHES.report()


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------


def _tid_map(spans) -> dict:
    """Stable small integer per OS thread id (chrome tids read better)."""
    out: dict[int, int] = {}
    for s in spans:
        if s.tid not in out:
            out[s.tid] = len(out)
    return out


def chrome_trace() -> dict:
    """Chrome trace-event JSON object (``{"traceEvents": [...]}``) —
    complete ("X") events with microsecond timestamps; span/parent ids ride
    in ``args`` so tooling can rebuild the tree exactly. Open spans export
    with their duration so far and ``"open": true``."""
    tracer = TRACER
    spans = tracer.spans()
    tids = _tid_map(spans)
    pid = os.getpid()
    events = []
    for s in spans:
        open_ = s.dur_us is None
        dur = (tracer._now_us() - s.ts_us) if open_ else s.dur_us
        args = {k: v for k, v in s.attrs.items()}
        args["trace_id"] = s.trace_id
        args["span_id"] = s.sid
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        if open_:
            args["open"] = True
        events.append({
            "ph": "X", "name": s.name, "cat": s.cat or "other",
            "ts": s.ts_us, "dur": max(int(dur), 1),
            "pid": pid, "tid": tids[s.tid], "args": args,
        })
    # Counter ("ph": "C") events — Perfetto draws each metric as a
    # resource track under the span timeline (mem.live_bytes, serving
    # queue depth, pipeline hit/compile counts; see
    # Tracer._maybe_sample_counters for the sampling contract).
    for ts, sample in tracer.counter_samples():
        for metric, value in sample.items():
            events.append({
                "ph": "C", "name": metric, "cat": "resource",
                "ts": ts, "pid": pid,
                "args": {"value": value},
            })
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"framework": "sparkdq4ml_tpu",
                          "dropped_spans": tracer.dropped}}


def dump_chrome_trace(path: str) -> str:
    """Write :func:`chrome_trace` to ``path`` (atomic rename); returns the
    path. Open in Perfetto / ``chrome://tracing``."""
    doc = chrome_trace()
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def trace_report() -> str:
    """Human-readable span tree (indentation = parentage), oldest first."""
    spans = sorted(TRACER.spans(), key=lambda s: (s.ts_us, s.sid))
    children: dict[Optional[int], list] = {}
    for s in spans:
        children.setdefault(s.parent_id, []).append(s)
    by_id = {s.sid: s for s in spans}
    lines: list[str] = []

    def emit(s, depth):
        dur = ("open" if s.dur_us is None
               else f"{s.dur_us / 1e3:.3f} ms")
        attrs = format_kv(**s.attrs)
        lines.append("  " * depth + f"{s.name} [{s.cat or 'other'}] {dur}"
                     + (f"  {attrs}" if attrs else ""))
        for c in children.get(s.sid, []):
            emit(c, depth + 1)

    # roots: no parent, or parent already evicted from the bounded buffer
    for s in spans:
        if s.parent_id is None or s.parent_id not in by_id:
            emit(s, 0)
    if TRACER.dropped:
        lines.append(f"dropped={TRACER.dropped} spans (bounded buffer "
                     "wrapped; raise spark.observability.maxSpans)")
    return "\n".join(lines)


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return "sparkdq4ml_" + s


def _prom_num(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


#: ``# HELP`` text per metric-name prefix (first match wins); the fallback
#: names the original dotted metric so a scrape reader can map the
#: sanitized Prometheus name back to the in-process counter.
_HELP_PREFIXES = (
    ("serve.", "query-serving layer: admission, queueing, per-tenant SLO "
     "(serve/)"),
    ("net.", "network serving front end: socket protocol + resilient "
     "client (serve/net.py, serve/client.py)"),
    ("recovery.", "resilience-layer event count (utils.recovery)"),
    ("pipeline.", "fused expression-pipeline compiler (ops/compiler.py)"),
    ("grouped.", "device-resident grouped execution (ops/segments.py)"),
    ("jit.", "XLA trace/compile cache activity"),
    ("solver.", "linear-solver dispatch (models/solvers.py)"),
    ("frame.", "frame-engine op/boundary activity"),
    ("parallel.", "mesh collective dispatch (parallel/)"),
    ("mesh.", "device-mesh state"),
    ("mem.", "device-memory accounting (utils.meminfo)"),
    ("trace.", "span tracer internals"),
    ("span_ms.", "span wall-clock latency histogram, milliseconds"),
    ("sql.", "SQL layer activity"),
)


def _prom_help(name: str) -> str:
    # declared help first (METRIC_NAMES / the prefix families — the
    # registry the dqlint metric-name rule enforces), then the legacy
    # subsystem prefixes, then the name-mapping fallback
    declared = METRIC_NAMES.get(name)
    if declared is None:
        for prefix in METRIC_NAME_PREFIXES:
            if name.startswith(prefix) and name != prefix:
                declared = METRIC_NAME_PREFIXES[prefix]
                break
    if declared is not None:
        return f"{name} - {declared[1]}"
    for prefix, text in _HELP_PREFIXES:
        if name.startswith(prefix):
            return f"{name} - {text}"
    return f"{name} - sparkdq4ml_tpu metric"


def _exemplars_enabled() -> bool:
    """Render-time read of the ``spark.trace.exemplars`` conf flag (late
    import keeps this module free of a config dependency cycle)."""
    try:
        from ..config import config as _cfg

        return bool(getattr(_cfg, "trace_exemplars", False))
    except Exception:   # pragma: no cover - config always importable
        return False


def prometheus_text() -> str:
    """Prometheus text-format snapshot: every counter (including
    ``recovery.*``), every gauge, and every histogram (cumulative
    ``_bucket{le=...}`` series + ``_sum``/``_count``), one scrape. Each
    series carries ``# HELP`` (mapping the sanitized name back to the
    dotted in-process name) and ``# TYPE`` headers; metric names sanitize
    through :func:`_prom_name` (dots and any other illegal characters
    become underscores, leading digits are prefixed)."""
    lines: list[str] = []
    for name, v in sorted(profiling.counters.snapshot().items()):
        pn = _prom_name(name)
        lines.append(f"# HELP {pn} {_prom_help(name)}")
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_prom_num(v)}")
    snap = METRICS.snapshot()
    exemplars_on = _exemplars_enabled()
    for name in sorted(snap):
        v = snap[name]
        pn = _prom_name(name)
        lines.append(f"# HELP {pn} {_prom_help(name)}")
        if isinstance(v, dict):      # histogram summary
            lines.append(f"# TYPE {pn} histogram")
            ex = TAIL.exemplars(name) if exemplars_on else {}
            for le, c in v["buckets"].items():
                line = f'{pn}_bucket{{le="{_prom_num(le)}"}} {c}'
                e = ex.get(float(le))
                if e is not None:
                    # OpenMetrics exemplar: the last KEPT trace id that
                    # landed in this bucket — a scrape reader can jump
                    # straight from a latency bucket to /trace/<id>.
                    line += (f' # {{trace_id="{e[0]}"}} '
                             f'{_prom_num(e[1])}')
                lines.append(line)
            lines.append(f"{pn}_sum {_prom_num(v['sum'])}")
            lines.append(f"{pn}_count {v['count']}")
        else:
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {_prom_num(v)}")
    return "\n".join(lines) + "\n"
