"""Data-quality observatory — column profiles, violation rates, drift.

The paper's premise is data quality gating ML, yet the engine could
attribute every plan, byte, and request (statstore, cost observatory,
tracing) while staying blind to the *data* flowing through the DQ
rules: no violation rates, no column profiles, no drift signal. This
module closes that gap with three sketches that all obey the standing
hot-path contracts:

* **per-column profiles** (:class:`ColumnProfile`) — count, null/NaN
  count, min/max, Welford mean+M2, and a fixed-bucket histogram over a
  log-compressed domain. The flush hook dispatches ONE tiny device
  reduction per profiled column (``ops/compiler.run_pipeline``), keyed
  on the padded power-of-two bucket so sketch programs retrace like any
  other plan — never per raw row count. The raw moment vector is
  *decomposable* (arxiv 2112.09017 style): sharded frames compute
  per-shard partials merged by one ``psum``/``pmin``/``pmax`` inside a
  ``shard_map`` program, and host-side profiles merge exactly
  (Chan's parallel mean/M2 formula), so shard-merged and single-device
  profiles agree bucket-for-bucket.
* **per-rule violation accounting** — every registered DQ UDF column a
  flush materializes records ``[rows, passed]`` against the flush's
  INPUT mask (the DQ convention: output > 0 = pass, so the counts
  survive the fused ``WHERE rule > 0`` filter that would otherwise
  erase the failures). Eager UDF evaluations record through the same
  queue (``ops/expressions.UdfCall``).
* **drift scoring** — PSI over the fixed-bucket histograms against a
  pinned baseline (``spark.dq.baselineMode``): past
  ``spark.dq.driftThreshold`` the breach sets the ``dq.drift.<col>``
  gauge, tags the current span for the tail sampler's keep-policy, and
  captures an incident bundle carrying the before/after profiles.

Deferred-drain contract (the statstore ``drain_sync`` pattern): the hot
path only *enqueues* already-dispatched device values; the single
batched, counted host pull (``dq.drain_sync``) happens on the cold
surfaces — ``report()`` / the ``/dq`` route / EXPLAIN ANALYZE — so a
flush pays zero counted host syncs. ``spark.dq.profile.enabled=false``
reduces every hook to one conf read (test-pinned raise-monkeypatch
style) and pins EXPLAIN byte-identical.

Chaos: the ``dq_profile`` fault site fires at the sketch-dispatch
boundary; ANY failure — injected or real — degrades that flush to
unprofiled (``dq.profile_failed`` + a structured recovery event),
never fails the flush or a telemetry surface. Profiles persist into
the statstore as versioned snapshots (optional field,
merge-don't-clobber, back-compatible) under ``dqprof|<column>`` keys.

CPU-sandbox caveat: sums accumulate in float32 on device (TPU-native);
the host-side merge algebra is float64. Sketches are profiles, not
ledgers — use the statstore for exact row accounting.
"""

from __future__ import annotations

import logging
import math
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config
from .profiling import counters

logger = logging.getLogger("sparkdq4ml_tpu.dqprof")

#: Profile-document schema version — persisted snapshots carry it; a
#: version-skewed doc is ignored (absent baseline), never a crash.
PROFILE_VERSION = 1

#: Columns profiled per flush (name-sorted prefix) — bounds both the
#: per-flush dispatch count and the sketch cache population.
MAX_COLS = 16

#: Bound on not-yet-drained deferred sketch vectors (each one tiny
#: device array): past it the oldest observation drops and is counted,
#: never an unbounded device-buffer leak (statstore MAX_PENDING twin).
MAX_PENDING = 4096

#: Bound on cached sketch programs — one per (bucket, dtype, bins,
#: shards); power-of-two buckets keep the real population far below it.
MAX_PROGRAMS = 64

#: Histogram domain clip in transform space: t = sign(x)·log10(1+|x|)
#: clipped to ±TMAX covers |x| up to 1e12 before saturating into the
#: edge buckets. Fixed at module level so persisted histograms from
#: different sessions always merge bucket-for-bucket.
TMAX = 12.0

#: Leading raw-moment slots of a sketch vector, ahead of the histogram:
#: [count, nulls, sum, sumsq, min, max].
MOMENTS = 6

#: Histogram scatter-add row bound: buckets up to this size histogram
#: every row; past it a deterministic stride-sample (scaled back up by
#: the stride) caps the one super-linear op in the sketch so a profiled
#: flush stays as cheap as an unprofiled one at any bucket width. The
#: exact-count fields (count/nulls/min/max/moments) always see every
#: row.
HIST_SAMPLE = 4096

#: PSI smoothing pseudo-count per bucket — keeps an empty bucket from
#: blowing the log ratio up to infinity.
EPS = 1e-4

#: Violation-rate incident bar: a drain whose per-rule failure rate
#: (over that drain's rows alone) reaches this captures a bundle.
VIOLATION_SPIKE_RATE = 0.5
#: ... but only with at least this much evidence in the drain window.
SPIKE_MIN_ROWS = 8


class ColumnProfile:
    """One column's running profile sketch. The device side ships raw
    decomposable moments; this host-side form keeps Welford mean+M2 so
    :meth:`merge` (Chan's parallel formula) is exact and associative —
    per-shard partials, per-flush increments, and persisted snapshots
    all combine through the same algebra."""

    __slots__ = ("count", "nulls", "mean", "m2", "min", "max", "hist")

    def __init__(self, count=0, nulls=0, mean=0.0, m2=0.0,
                 min=None, max=None, hist=None):
        self.count = int(count)
        self.nulls = int(nulls)
        self.mean = float(mean)
        self.m2 = float(m2)
        self.min = None if min is None else float(min)
        self.max = None if max is None else float(max)
        self.hist = [int(c) for c in (hist or [])]

    @classmethod
    def from_raw(cls, raw) -> Optional["ColumnProfile"]:
        """Host profile from one drained device sketch vector
        (``[count, nulls, sum, sumsq, min, max, hist...]``). None for a
        malformed vector — a discarded observation, never a crash."""
        arr = np.asarray(raw, dtype=np.float64).ravel()
        if arr.size < MOMENTS:
            return None
        count = int(round(float(arr[0])))
        nulls = int(round(float(arr[1])))
        if count > 0:
            mean = float(arr[2]) / count
            # naive-moment M2: clamp the float32 cancellation floor
            m2 = max(float(arr[3]) - float(arr[2]) ** 2 / count, 0.0)
            mn, mx = float(arr[4]), float(arr[5])
        else:
            mean, m2, mn, mx = 0.0, 0.0, None, None
        hist = [int(round(float(c))) for c in arr[MOMENTS:]]
        return cls(count=count, nulls=nulls, mean=mean, m2=m2,
                   min=mn, max=mx, hist=hist)

    @property
    def variance(self) -> Optional[float]:
        """Sample variance (None below two observations)."""
        return self.m2 / (self.count - 1) if self.count > 1 else None

    def merge(self, other: "ColumnProfile") -> None:
        """Chan's parallel mean/M2 merge — exact and associative, the
        property that makes per-shard partials, per-flush increments,
        and persisted baselines one algebra (test-pinned)."""
        n1, n2 = self.count, other.count
        if n2 > 0:
            if n1 == 0:
                self.mean, self.m2 = other.mean, other.m2
            else:
                n = n1 + n2
                delta = other.mean - self.mean
                self.mean += delta * n2 / n
                self.m2 += other.m2 + delta * delta * n1 * n2 / n
            self.count = n1 + n2
        self.nulls += other.nulls
        for mine, theirs, pick in (("min", other.min, min),
                                   ("max", other.max, max)):
            cur = getattr(self, mine)
            if theirs is not None:
                setattr(self, mine,
                        theirs if cur is None else pick(cur, theirs))
        if len(self.hist) == len(other.hist):
            self.hist = [a + b for a, b in zip(self.hist, other.hist)]
        elif n2 > n1:
            # a histogramBins conf flip mid-history: buckets no longer
            # align, adopt the heavier side whole (profile, not ledger)
            self.hist = list(other.hist)

    def copy(self) -> "ColumnProfile":
        return ColumnProfile(count=self.count, nulls=self.nulls,
                             mean=self.mean, m2=self.m2, min=self.min,
                             max=self.max, hist=self.hist)

    def to_doc(self) -> dict:
        return {"version": PROFILE_VERSION, "count": self.count,
                "nulls": self.nulls, "mean": self.mean, "m2": self.m2,
                "min": self.min, "max": self.max,
                "hist": list(self.hist)}

    @classmethod
    def from_doc(cls, doc) -> Optional["ColumnProfile"]:
        """None on a version-skewed or malformed doc — a stale persisted
        snapshot degrades to "no baseline", never a crash."""
        if not isinstance(doc, dict) \
                or int(doc.get("version", 0)) != PROFILE_VERSION:
            return None
        try:
            return cls(count=doc.get("count", 0),
                       nulls=doc.get("nulls", 0),
                       mean=doc.get("mean", 0.0), m2=doc.get("m2", 0.0),
                       min=doc.get("min"), max=doc.get("max"),
                       hist=doc.get("hist"))
        except (TypeError, ValueError):
            return None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"ColumnProfile(count={self.count}, nulls={self.nulls}, "
                f"mean={self.mean:g}, bins={len(self.hist)})")


def histogram_edges(bins: int) -> list:
    """The fixed bucket edges in DATA space (``bins + 1`` values):
    bucket ``i`` covers ``[edges[i], edges[i+1])`` of the inverse of
    the log-compressed transform. Deterministic per ``bins`` value —
    the property that makes histograms mergeable across flushes,
    shards, and sessions."""
    out = []
    for i in range(int(bins) + 1):
        t = -TMAX + (2.0 * TMAX) * i / int(bins)
        out.append(math.copysign(10.0 ** abs(t) - 1.0, t))
    return out


def drift_score(baseline: Optional[ColumnProfile],
                current: Optional[ColumnProfile]) -> Optional[float]:
    """Population-stability index over the fixed-bucket histograms —
    None when either side is empty or the bucketings don't align
    (a histogramBins flip mid-session)."""
    if baseline is None or current is None:
        return None
    if baseline.count <= 0 or current.count <= 0:
        return None
    if not baseline.hist or len(baseline.hist) != len(current.hist):
        return None
    te = float(sum(baseline.hist)) + EPS * len(baseline.hist)
    ta = float(sum(current.hist)) + EPS * len(current.hist)
    score = 0.0
    for e, a in zip(baseline.hist, current.hist):
        pe = (e + EPS) / te
        pa = (a + EPS) / ta
        score += (pa - pe) * math.log(pa / pe)
    return round(score, 6)


# ---------------------------------------------------------------------------
# Device sketch programs (bounded cache, ProgramHandle-enumerable)
# ---------------------------------------------------------------------------

def _sketch_body(bins: int):
    """The per-device sketch reduction: one 1-D float32 vector of raw
    decomposable moments ``[count, nulls, sum, sumsq, min, max]`` plus
    the ``bins``-bucket histogram. NaN counts as null and is excluded
    from every moment; the padded mask tail is invalid by construction
    so padding never pollutes a profile.

    The moment/min/max reductions run over EVERY row (fused elementwise
    passes — cheap at any size), but the histogram's scatter-add is the
    one super-linear-cost op in the sketch, so past ``HIST_SAMPLE``
    rows it runs over a deterministic stride-sample scaled back up by
    the stride: the bucket *shape* stays statistically faithful while
    the per-flush cost stays O(HIST_SAMPLE) — this is what keeps a
    profiled flush as fast as an unprofiled one on wide buckets."""
    def sketch(col, mask):
        x = col.astype(jnp.float32)
        nan = jnp.isnan(x)
        valid = jnp.logical_and(mask, jnp.logical_not(nan))
        vf = valid.astype(jnp.float32)
        count = jnp.sum(vf)
        nulls = jnp.sum(jnp.logical_and(mask, nan).astype(jnp.float32))
        xv = jnp.where(valid, x, jnp.float32(0.0))
        s1 = jnp.sum(xv)
        s2 = jnp.sum(xv * xv)
        big = jnp.float32(3.0e38)    # empty → +big/-big, None on drain
        mn = jnp.min(jnp.where(valid, x, big))
        mx = jnp.max(jnp.where(valid, x, -big))
        step = -(-col.shape[0] // HIST_SAMPLE)   # static at trace time
        xs, vs = (x, vf) if step <= 1 else (x[::step], vf[::step])
        t = jnp.sign(xs) * jnp.log10(jnp.float32(1.0) + jnp.abs(xs))
        t = jnp.clip(t, -TMAX, TMAX)
        idx = ((t + TMAX) * (bins / (2.0 * TMAX))).astype(jnp.int32)
        idx = jnp.clip(idx, 0, bins - 1)
        hist = jnp.zeros((bins,), jnp.float32).at[idx].add(
            vs * jnp.float32(step))
        return jnp.concatenate(
            [jnp.stack([count, nulls, s1, s2, mn, mx]), hist])
    return sketch


def _rule_body():
    """The per-rule accounting reduction: ``[rows, passed]`` over the
    flush's input mask. The DQ convention (reference app): a rule
    output > 0 is a pass — NaN compares False, so a NaN rule output
    counts as a violation."""
    def rule(col, mask):
        x = col.astype(jnp.float32)
        mf = mask.astype(jnp.float32)
        passed = jnp.sum(jnp.where(
            jnp.logical_and(mask, x > 0), jnp.float32(1.0),
            jnp.float32(0.0)))
        return jnp.stack([jnp.sum(mf), passed])
    return rule


def _sharded(body, mesh):
    """Per-shard partials + one collective merge: sums/histogram psum,
    min/max pmin/pmax — the decomposable-partial algebra, on device.
    Returns ``(guarded dispatch fn, un-counted trace body)`` — the
    dispatch side rides the process-wide collective guard (the XLA:CPU
    overlapping-collective deadlock class)."""
    from jax.sharding import PartitionSpec as _P

    from ..parallel.mesh import DATA_AXIS, serialize_collectives, shard_map

    def merged(col, mask):
        part = body(col, mask)
        head = jax.lax.psum(part[:4], DATA_AXIS)
        rest = part[4:]
        if rest.shape[0] >= 2:
            mn = jax.lax.pmin(rest[0], DATA_AXIS)
            mx = jax.lax.pmax(rest[1], DATA_AXIS)
            tail = jax.lax.psum(rest[2:], DATA_AXIS)
            return jnp.concatenate([head, mn[None], mx[None], tail])
        return head

    traced = shard_map(merged, mesh=mesh,
                       in_specs=(_P(DATA_AXIS), _P(DATA_AXIS)),
                       out_specs=_P())
    return serialize_collectives(jax.jit(traced), mesh), traced


def _sharded_rule(body, mesh):
    """Sharded ``[rows, passed]`` accounting; same ``(guarded fn,
    traced)`` contract as :func:`_sharded`."""
    from jax.sharding import PartitionSpec as _P

    from ..parallel.mesh import DATA_AXIS, serialize_collectives, shard_map

    def merged(col, mask):
        return jax.lax.psum(body(col, mask), DATA_AXIS)

    traced = shard_map(merged, mesh=mesh,
                       in_specs=(_P(DATA_AXIS), _P(DATA_AXIS)),
                       out_specs=_P())
    return serialize_collectives(jax.jit(traced), mesh), traced


#: (kind, bucket, dtype, bins, shards) → (dispatch fn, un-counted trace
#: body, abstract arg specs, mesh, guarded). Bounded FIFO (MAX_PROGRAMS).
_PROGRAMS: dict = {}
_PROG_LOCK = threading.Lock()


def _program_key(key) -> str:
    kind, b, dtype, bins, shards = key
    return f"dq{kind}|b{b}|{dtype}|bins{bins}|shards{shards}"


def _program(kind: str, b: int, dtype, shard):
    """The cached sketch/rule program at one structural key. Sharded
    frames get the psum-merged ``shard_map`` lowering, dispatched under
    the process-wide collective guard like every mesh-bearing program."""
    bins = max(int(config.dq_histogram_bins), 1) if kind == "sketch" \
        else 0
    devices = int(shard.devices) if shard is not None else 0
    key = (kind, int(b), str(jnp.dtype(dtype)), bins, devices)
    with _PROG_LOCK:
        entry = _PROGRAMS.get(key)
    if entry is not None:
        return entry
    body = _sketch_body(bins) if kind == "sketch" else _rule_body()
    if shard is not None:
        wrap = _sharded if kind == "sketch" else _sharded_rule
        fn, traced = wrap(body, shard.mesh)
        mesh, guarded = shard.mesh, True
    else:
        traced = body
        fn = jax.jit(traced)
        mesh, guarded = None, None
    specs = (jax.ShapeDtypeStruct((int(b),), jnp.dtype(dtype)),
             jax.ShapeDtypeStruct((int(b),), jnp.bool_))
    entry = (fn, traced, specs, mesh, guarded)
    with _PROG_LOCK:
        if key not in _PROGRAMS and len(_PROGRAMS) >= MAX_PROGRAMS:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))
            counters.increment("dq.program_evict")
        _PROGRAMS.setdefault(key, entry)
    return entry


def program_handles() -> list:
    """Registry callback (``observability.CACHES.register_programs``):
    one :class:`~.observability.ProgramHandle` per cached sketch/rule
    program, so dqaudit statically bounds sketch peak bytes the same
    way it bounds every other enumerable program. ``fn`` is the
    un-counted trace body."""
    from . import observability as _obs

    with _PROG_LOCK:
        items = list(_PROGRAMS.items())
    return [_obs.ProgramHandle(
        "dqprof", _program_key(key), traced, args=specs,
        mesh=mesh, guarded=guarded)
        for key, (_, traced, specs, mesh, guarded) in items]


# ---------------------------------------------------------------------------
# Deferred observation queue + host-side state
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
#: ("col"|"rule", name, host_rows, device value) awaiting ONE batched
#: host pull — drained on the cold paths only (see drain()).
_PENDING: list = []
_PROFILES: dict = {}     # column -> ColumnProfile (cumulative)
_BASELINES: dict = {}    # column -> pinned ColumnProfile (drift ref)
_NO_BASELINE = object()  # pin attempted, mode yielded none — don't retry
_RULES: dict = {}        # rule -> {"evals", "rows", "violations"}
_DRIFT: dict = {}        # column -> latest PSI score


def enabled() -> bool:
    return bool(config.dq_profile_enabled)


def clear() -> None:
    """Drop every profile, baseline, rule tally, pending observation,
    and cached program (tests; conf flips)."""
    with _LOCK:
        _PENDING.clear()
        _PROFILES.clear()
        _BASELINES.clear()
        _RULES.clear()
        _DRIFT.clear()
    with _PROG_LOCK:
        _PROGRAMS.clear()


def _is_tracer(v) -> bool:
    try:
        return isinstance(v, jax.core.Tracer)
    except AttributeError:       # jax.core reshuffles across versions
        return "Tracer" in type(v).__name__


def _profilable(v, b: Optional[int]) -> bool:
    """Numeric 1-D device/host column at the expected bucket length —
    object (string) columns and shape surprises are skipped, never an
    error."""
    dt = getattr(v, "dtype", None)
    shape = getattr(v, "shape", None)
    if dt is None or shape is None or len(shape) != 1:
        return False
    if b is not None and int(shape[0]) != int(b):
        return False
    try:
        return np.dtype(dt).kind in "fiub"
    except TypeError:
        return False


def _enqueue(entries) -> None:
    dropped = 0
    with _LOCK:
        _PENDING.extend(entries)
        while len(_PENDING) > MAX_PENDING:
            _PENDING.pop(0)
            dropped += 1
    if dropped:
        counters.increment("dq.pending_dropped", dropped)


def observe_flush(changed, new_mask, bucket: int, shard=None,
                  rules=(), mask_in=None) -> None:
    """The flush hook (``ops/compiler.run_pipeline``, gated there on ONE
    ``spark.dq.profile.enabled`` read): dispatch one sketch reduction
    per profiled output column over the PADDED bucket arrays, plus one
    ``[rows, passed]`` reduction per registered-rule column against the
    flush's input mask, and enqueue the device results for a later
    batched drain — zero host syncs here.

    Rides the ``dq_profile`` fault site: ANY failure — injected or
    real — degrades this flush to unprofiled with a counted, structured
    recovery event; the flush itself and every telemetry surface keep
    working."""
    if not enabled():
        return
    from . import faults as _faults

    try:
        _faults.inject("dq_profile")
        b = int(bucket)
        if b <= 0:
            return
        entries = []
        for name in sorted(changed):
            if len(entries) >= MAX_COLS:
                break
            v = changed[name]
            if not _profilable(v, b):
                continue
            fn = _program("sketch", b, v.dtype, shard)[0]
            entries.append(("col", str(name), 0, fn(v, new_mask)))
        if mask_in is not None:
            for rule_name, col_name in rules:
                v = changed.get(col_name)
                if v is None or not _profilable(v, b):
                    continue
                fn = _program("rule", b, v.dtype, shard)[0]
                entries.append(("rule", str(rule_name), 0,
                                fn(v, mask_in)))
        if not entries:
            return
        counters.increment("dq.sketches", len(entries))
        _enqueue(entries)
    except Exception as e:
        counters.increment("dq.profile_failed")
        from .recovery import RECOVERY_LOG

        RECOVERY_LOG.record(
            "dq_profile", "fallback", rung="unprofiled",
            cause=f"{type(e).__name__}: {e}",
            detail="dq sketch dispatch degraded; this flush reports "
                   "no profile")
        logger.debug("dq sketch dispatch failed", exc_info=True)


def record_eval(rule: str, out) -> None:
    """Per-rule accounting for one EAGER UDF evaluation
    (``ops/expressions.UdfCall`` — gated there on ONE conf read). A
    trace-time call sees a tracer and returns immediately: compiled
    evaluations account through :func:`observe_flush` instead, so no
    evaluation is ever double-counted."""
    if not enabled():
        return
    try:
        if _is_tracer(out) or not _profilable(out, None):
            return
        rows = int(out.shape[0])
        if rows <= 0:
            return
        passed = jnp.sum(
            jnp.where(jnp.asarray(out) > 0, jnp.float32(1.0),
                      jnp.float32(0.0)))
        counters.increment("dq.rule_evals")
        _enqueue([("rule", str(rule), rows, passed)])
    except Exception:
        logger.debug("dq rule-eval hand-off failed", exc_info=True)


# ---------------------------------------------------------------------------
# Cold-path drain: profiles, baselines, drift, violation telemetry
# ---------------------------------------------------------------------------

def _record_statstore(col: str, prof: ColumnProfile) -> None:
    if not config.stats_enabled:
        return
    try:
        from . import statstore as _stats

        _stats.STORE.record_profile(f"dqprof|{col}", "dqprof",
                                    prof.to_doc())
    except Exception:
        logger.debug("dq-profile statstore hand-off failed",
                     exc_info=True)


def _adopted_baseline(col: str) -> Optional[ColumnProfile]:
    """A persisted snapshot loaded at session init may already carry
    this column's profile — the cross-session drift reference."""
    if not config.stats_enabled:
        return None
    try:
        from . import statstore as _stats

        doc = _stats.STORE.profile(f"dqprof|{col}")
    except Exception:
        return None
    return ColumnProfile.from_doc(doc) if doc else None


def _pin_baseline(col: str, prof: ColumnProfile):
    """The drift reference per ``spark.dq.baselineMode``: ``first``
    (default) adopts a persisted snapshot when one exists, else pins
    the first drained profile; ``persisted`` only ever adopts from the
    statstore; ``off`` disables drift scoring."""
    mode = str(config.dq_baseline_mode)
    if mode == "off":
        return _NO_BASELINE
    adopted = _adopted_baseline(col)
    if adopted is not None:
        baseline = adopted
    elif mode == "persisted":
        return _NO_BASELINE
    else:
        baseline = prof.copy()
    counters.increment("dq.baseline_pinned")
    return baseline


def _check_drift(col: str, prof: ColumnProfile) -> None:
    baseline = _BASELINES.get(col)
    if baseline is None:
        baseline = _BASELINES[col] = _pin_baseline(col, prof)
    if baseline is _NO_BASELINE:
        return
    score = drift_score(baseline, prof)
    if score is None:
        return
    from . import observability as _obs

    with _LOCK:
        _DRIFT[col] = score
    _obs.METRICS.set_gauge(f"dq.drift.{col}", score)
    threshold = float(config.dq_drift_threshold)
    if score <= threshold:
        return
    counters.increment("dq.drift_breach")
    # tail-sampler keep-policy hand-off: a request tree whose spans saw
    # a drift breach is evidence worth retaining (observability.TailSampler)
    _obs.current_span().set(dq_drift=col)
    from . import incidents as _incidents

    _incidents.RECORDER.record(
        "dq_drift",
        detail=f"column {col!r} drift {score:g} > threshold "
               f"{threshold:g}",
        extra={"dq_drift": {"column": col, "score": score,
                            "threshold": threshold,
                            "baseline": baseline.to_doc(),
                            "current": prof.to_doc()}})


def _apply_rule(name: str, rows: int, passed: int, window: dict) -> None:
    with _LOCK:
        r = _RULES.setdefault(
            name, {"evals": 0, "rows": 0, "violations": 0})
        r["evals"] += 1
        r["rows"] += rows
        violations = max(rows - passed, 0)
        r["violations"] += violations
        total_rows, total_viol = r["rows"], r["violations"]
    w = window.setdefault(name, [0, 0])
    w[0] += rows
    w[1] += violations
    if violations:
        counters.increment(f"dq.violations.{name}", violations)
    from . import observability as _obs

    rate = (total_viol / total_rows) if total_rows else 0.0
    _obs.METRICS.set_gauge(f"dq.violation_rate.{name}", round(rate, 6))


def _check_spikes(window: dict) -> None:
    """Violation-rate spike detection over THIS drain's evidence alone
    (a long healthy history must not mask a sudden failure wave)."""
    from . import incidents as _incidents

    for name, (rows, violations) in window.items():
        if rows < SPIKE_MIN_ROWS:
            continue
        rate = violations / rows
        if rate < VIOLATION_SPIKE_RATE:
            continue
        counters.increment("dq.violation_spike")
        _incidents.RECORDER.record(
            "dq_violations",
            detail=f"rule {name!r} violation rate {rate:.3f} over "
                   f"{rows} rows",
            extra={"dq_violations": {"rule": name, "rows": rows,
                                     "violations": violations,
                                     "rate": round(rate, 6)}})


def drain() -> None:
    """Pull every queued deferred observation in ONE batched
    ``device_get`` (cold paths only — report / the ``/dq`` route /
    EXPLAIN ANALYZE; counted ``dq.drain_sync``, never a silent sync),
    then fold the results into profiles, baselines, drift gauges, and
    per-rule violation telemetry."""
    with _LOCK:
        pending, _PENDING[:] = list(_PENDING), []
    if not pending:
        return
    try:
        values = jax.device_get([p[3] for p in pending])
        counters.increment("dq.drain_sync")
    except Exception:
        # a dead backend must not take a dq report down; the
        # observations are lost, the observatory stays coherent
        logger.debug("dq drain failed", exc_info=True)
        return
    touched: dict = {}
    window: dict = {}
    for (kind, name, rows, _), v in zip(pending, values):
        try:
            arr = np.asarray(v, dtype=np.float64).ravel()
            if kind == "col":
                prof = ColumnProfile.from_raw(arr)
                if prof is None:
                    continue
                with _LOCK:
                    cur = _PROFILES.get(name)
                    if cur is None:
                        cur = _PROFILES[name] = prof
                    else:
                        cur.merge(prof)
                touched[name] = cur
            else:
                if arr.size >= 2:       # flush path: [rows, passed]
                    total = int(round(arr[0]))
                    passed = int(round(arr[1]))
                else:                   # eager path: host rows + scalar
                    total = int(rows)
                    passed = int(round(float(arr.sum())))
                _apply_rule(name, total, passed, window)
        except Exception:
            logger.debug("dq observation discarded", exc_info=True)
    for col, prof in touched.items():
        try:
            _check_drift(col, prof)
            _record_statstore(col, prof)
        except Exception:
            logger.debug("dq drift/persist failed for %r", col,
                         exc_info=True)
    _check_spikes(window)


# ---------------------------------------------------------------------------
# Cold surfaces: report / EXPLAIN section
# ---------------------------------------------------------------------------

def report(top: Optional[int] = None, drain_first: bool = True) -> dict:
    """The observatory view (``session.dq_report()`` and the HTTP
    ``/dq`` route): one row per profiled column — sketch fields, drift
    score, pinned-baseline evidence — plus per-rule violation tallies.
    Cold surface: drains the deferred queue (``drain_first=False`` for
    re-entrant callers like the incident recorder)."""
    if not enabled():
        return {"enabled": False, "columns": [], "rules": [],
                "size": 0, "pending": 0}
    if drain_first:
        drain()
    with _LOCK:
        profiles = {k: v.copy() for k, v in _PROFILES.items()}
        baselines = dict(_BASELINES)
        rules = {k: dict(v) for k, v in _RULES.items()}
        drift = dict(_DRIFT)
        pending = len(_PENDING)
    columns = []
    for col in sorted(profiles):
        p = profiles[col]
        doc = p.to_doc()
        doc["column"] = col
        doc["variance"] = p.variance
        doc["drift"] = drift.get(col)
        base = baselines.get(col)
        doc["baseline_count"] = (base.count if isinstance(
            base, ColumnProfile) else None)
        columns.append(doc)
    if top is not None:
        columns = columns[:max(int(top), 0)]
    rule_rows = []
    for name in sorted(rules):
        r = rules[name]
        rate = (r["violations"] / r["rows"]) if r["rows"] else 0.0
        rule_rows.append({"rule": name, "evals": r["evals"],
                          "rows": r["rows"],
                          "violations": r["violations"],
                          "rate": round(rate, 6)})
    return {"enabled": True, "columns": columns, "rules": rule_rows,
            "size": len(profiles), "pending": pending,
            "bins": int(config.dq_histogram_bins),
            "drift_threshold": float(config.dq_drift_threshold),
            "baseline_mode": str(config.dq_baseline_mode)}


def rule_marks() -> Optional[dict]:
    """Pre-execution mark for EXPLAIN ANALYZE's rule-bearing detection:
    per-rule eval counts after a drain (cold surface — EXPLAIN owns
    the sync budget here). None when disabled."""
    if not enabled():
        return None
    drain()
    with _LOCK:
        return {name: r["evals"] for name, r in _RULES.items()}


def explain_lines(marks) -> list:
    """The ``== Data Quality ==`` EXPLAIN ANALYZE section — rendered
    only for rule-bearing queries (a registered DQ rule evaluated since
    ``marks``), so rule-free queries stay byte-identical. Cumulative
    observatory rows: the rule tallies and the profiled columns the
    session has accumulated."""
    if marks is None or not enabled():
        return []
    drain()
    with _LOCK:
        rules = {k: dict(v) for k, v in _RULES.items()}
        profiles = {k: v.copy() for k, v in _PROFILES.items()}
        drift = dict(_DRIFT)
    evaluated = [name for name in sorted(rules)
                 if rules[name]["evals"] > marks.get(name, 0)]
    if not evaluated:
        return []
    lines = ["== Data Quality =="]
    for name in sorted(rules):
        r = rules[name]
        rate = (r["violations"] / r["rows"]) if r["rows"] else 0.0
        lines.append(f"rule {name}: evals={r['evals']} "
                     f"rows={r['rows']} violations={r['violations']} "
                     f"rate={rate:.4f}")
    for col in sorted(profiles)[:8]:
        p = profiles[col]
        span = ("-" if p.min is None
                else f"[{p.min:g}, {p.max:g}]")
        d = drift.get(col)
        lines.append(f"column {col}: count={p.count} nulls={p.nulls} "
                     f"mean={p.mean:.4f} range={span} "
                     f"drift={'-' if d is None else format(d, 'g')}")
    return lines


# Program enumeration for the jaxpr auditor / cost observatory — the
# sketch cache is registry-enumerable like every other compiled-program
# cache (peak-byte bounding rides dqaudit's existing machinery).
def _register() -> None:
    from . import observability as _obs

    _obs.CACHES.register_programs("dqprof", program_handles)


_register()
