"""Logging configuration mirroring the reference's log4j tiering.

`log4j.properties:1-11`: root INFO → console with a timestamped pattern,
``net.jgp`` at DEBUG, Spark/engine namespaces squelched to WARN/ERROR. The
analogue here: framework namespace at DEBUG, root INFO, jax noise at WARN.
"""

from __future__ import annotations

import logging
import sys

# log4j pattern was "%d{yyyy-MM-dd HH:mm:ss} %-5p %c{1}:%L - %m%n"
_FORMAT = "%(asctime)s %(levelname)-5s %(name)s:%(lineno)d - %(message)s"
_DATEFMT = "%Y-%m-%d %H:%M:%S"


def configure_logging(framework_level: int = logging.DEBUG,
                      root_level: int = logging.INFO,
                      stream=None) -> None:
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
    root = logging.getLogger()
    root.handlers = [handler]
    root.setLevel(root_level)
    logging.getLogger("sparkdq4ml_tpu").setLevel(framework_level)
    for noisy in ("jax", "jax._src", "absl"):
        logging.getLogger(noisy).setLevel(logging.WARNING)
