"""Logging configuration mirroring the reference's log4j tiering.

`log4j.properties:1-11`: root INFO → console with a timestamped pattern,
``net.jgp`` at DEBUG, Spark/engine namespaces squelched to WARN/ERROR. The
analogue here: framework namespace at DEBUG, root INFO, jax noise at WARN.
"""

from __future__ import annotations

import logging
import sys

# log4j pattern was "%d{yyyy-MM-dd HH:mm:ss} %-5p %c{1}:%L - %m%n"
_FORMAT = "%(asctime)s %(levelname)-5s %(name)s:%(lineno)d - %(message)s"
_DATEFMT = "%Y-%m-%d %H:%M:%S"


def configure_logging(framework_level: int = logging.DEBUG,
                      root_level: int = logging.INFO,
                      stream=None, force: bool = False) -> None:
    """Install the framework's log4j-style tiering.

    ``force=False`` (default) APPENDS our handler when the root logger
    already has handlers — replacing them would clobber pytest's caplog
    and any host application's logging setup (a library must not own the
    root). ``force=True`` restores the old destructive behavior: all root
    handlers are replaced, for standalone scripts that want exactly one
    console handler."""
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
    handler._sparkdq4ml = True       # idempotency tag (see below)
    root = logging.getLogger()       # logger-ns: ok (configures the root)
    if force or not root.handlers:
        root.handlers = [handler]
    else:
        # Replace only OUR previously installed handler(s) — repeated
        # configure_logging() calls must not stack duplicates — and leave
        # foreign handlers (pytest's caplog, the host app's) untouched.
        root.handlers = [h for h in root.handlers
                         if not getattr(h, "_sparkdq4ml", False)]
        root.addHandler(handler)
    root.setLevel(root_level)
    logging.getLogger("sparkdq4ml_tpu").setLevel(framework_level)
    for noisy in ("jax", "jax._src", "absl"):
        logging.getLogger(noisy).setLevel(logging.WARNING)  # logger-ns: ok


def format_kv(**fields) -> str:
    """Structured ``key=value`` event line (logfmt convention) — the
    single render used for recovery-telemetry events
    (``utils.recovery.RecoveryEvent``) and span lines
    (``utils.observability``), so log scrapers see one stable shape.

    Only ``None`` and the empty string are elided: ``retries=0`` and
    ``duration_ms=0.0`` are MEANINGFUL measurements (a clean run, an
    instant op) and dropping them would give scrapers an unstable schema
    — the old zero-ish elision did exactly that. Values with spaces are
    quoted."""
    parts = []
    for k, v in fields.items():
        if v is None or (isinstance(v, str) and v == ""):
            continue
        s = str(v)
        if " " in s or "=" in s:
            s = '"' + s.replace('"', r'\"') + '"'
        parts.append(f"{k}={s}")
    return " ".join(parts)
