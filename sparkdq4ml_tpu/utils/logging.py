"""Logging configuration mirroring the reference's log4j tiering.

`log4j.properties:1-11`: root INFO → console with a timestamped pattern,
``net.jgp`` at DEBUG, Spark/engine namespaces squelched to WARN/ERROR. The
analogue here: framework namespace at DEBUG, root INFO, jax noise at WARN.
"""

from __future__ import annotations

import logging
import sys

# log4j pattern was "%d{yyyy-MM-dd HH:mm:ss} %-5p %c{1}:%L - %m%n"
_FORMAT = "%(asctime)s %(levelname)-5s %(name)s:%(lineno)d - %(message)s"
_DATEFMT = "%Y-%m-%d %H:%M:%S"


def configure_logging(framework_level: int = logging.DEBUG,
                      root_level: int = logging.INFO,
                      stream=None) -> None:
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
    root = logging.getLogger()
    root.handlers = [handler]
    root.setLevel(root_level)
    logging.getLogger("sparkdq4ml_tpu").setLevel(framework_level)
    for noisy in ("jax", "jax._src", "absl"):
        logging.getLogger(noisy).setLevel(logging.WARNING)


def format_kv(**fields) -> str:
    """Structured ``key=value`` event line (logfmt convention) — the
    single render used for recovery-telemetry events
    (``utils.recovery.RecoveryEvent``), so log scrapers see one stable
    shape. Empty/zero-ish values are elided; values with spaces are
    quoted."""
    parts = []
    for k, v in fields.items():
        if v is None or v == "" or v == 0 or v == 0.0:
            continue
        s = str(v)
        if " " in s or "=" in s:
            s = '"' + s.replace('"', r'\"') + '"'
        parts.append(f"{k}={s}")
    return " ".join(parts)
