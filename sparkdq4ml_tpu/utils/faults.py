"""Deterministic fault injection — the chaos layer of the resilience stack.

The reference app inherits Spark's failure machinery (task retry, lineage
recomputation, checkpointing) but none of it is *testable* there: you
cannot ask `local[*]` to lose an executor on the third task. Here failures
are first-class: a :class:`FaultPlan` schedules failures at named **sites**
in the execution path, keyed by a per-site attempt counter and a seed, so
every injected failure is reproducible run-to-run — the property the
``tests/test_faults.py`` suite is built on.

Failure classes (``kind``):

* ``device_error`` — raises :class:`InjectedDeviceError`, a
  ``jax.errors.JaxRuntimeError`` subclass, i.e. exactly the exception type
  a real XLA device fault (OOM, interconnect reset, preempted tunnel)
  surfaces as. The production catch paths cannot tell the difference,
  which is the point.
* ``nan`` — poisons one leaf of a result pytree with NaN (a diverged
  solver / flaky transfer), at a seeded element position.
* ``preempt`` — raises :class:`Preemption` (NOT a device error): the
  mid-fit preemption that ``recovery.fit_or_resume`` turns into a
  checkpoint-resume instead of a crash.
* ``device_drop`` — shrinks a mesh by ``n`` devices (default 1), the
  lost-worker scenario; ``parallel.mesh.normalize_mesh`` semantics apply
  to whatever survives.

Sites instrumented in production code are registered in
:data:`FAULT_SITES` (the dqlint ``fault-site`` rule's vocabulary — a
hook call naming an unregistered site would silently never fire). The
model-fit sites (``gram_sharded``/``fit_packed``/``solver``/``fit``/
``mesh``) came with PR 1; the post-PR-1 subsystems each carry their own:
``pipeline_flush`` (the fused expression-pipeline dispatch,
``ops/compiler.py`` + the ``Frame._flush`` ladder), ``grouped_flush``
(the segment-reduce grouped program, ``ops/segments.try_device``),
``ingest_native`` (the native streaming CSV reader,
``frame/native_csv.py``: I/O error, torn chunk, prefetch-thread death,
bind-pool exhaustion), ``serve_exec``/``serve_admit`` (the QueryServer
worker and admission gates, ``serve/``), ``coalesce`` (the cross-request
batched dispatch, ``serve/coalesce.py``: device error, wedged batch
stall, stacked-bytes OOM — every rung degrades the whole batch to
per-request replay of the same cached plan), and ``oom`` (memory pressure as
a schedulable fault: a shrunken device budget makes the pre-execution
static bound trip and the flush degrade to row-chunked execution).
Injection happens at host-level dispatch boundaries only — never inside
a traced/jitted function, where a Python-level raise would fire at trace
time, not run time.

Activation: programmatic (:func:`install_plan`, or the
:func:`inject_faults` context manager tests use) or env-driven — set
``SPARKDQ4ML_FAULTS`` (or session conf ``spark.faults``) to a
semicolon-separated spec list, e.g.::

    SPARKDQ4ML_FAULTS="gram_sharded:device_error:1,2;solver:nan:1"
    SPARKDQ4ML_FAULTS="fit:preempt:p=0.25:seed=7;mesh:device_drop:n=2"

Spec grammar: ``site:kind[:a1,a2,...][:p=prob][:n=count][:seed=s]`` —
an explicit 1-based attempt list fires deterministically on those
attempts; ``p=`` fires as a seeded Bernoulli draw per attempt (still
reproducible: the draw is a pure function of (seed, site, attempt));
with neither, the fault fires on attempt 1 only.

When no plan is installed every hook is a no-op behind one ``is None``
check — the chaos layer costs nothing in production.

See README.md "Failure model & fault injection" for the recovery side:
retry policy knobs, circuit breaker, and the fallback ladder.
"""

from __future__ import annotations

import logging
import os
import threading
import zlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

logger = logging.getLogger("sparkdq4ml_tpu.faults")

ENV_VAR = "SPARKDQ4ML_FAULTS"

KINDS = ("device_error", "nan", "preempt", "device_drop",
         "io_error", "torn_chunk", "thread_death", "pool_exhaust",
         "breaker_trip", "oom", "conn_reset", "partial_write",
         "stall", "slow_client")

#: THE fault-site registry: site → the kinds its production hooks honor.
#: Every ``inject``/``corrupt``/``fired``/``shrunk_budget``/
#: ``degrade_mesh`` call site must name a key of this dict — enforced
#: statically by the dqlint ``fault-site`` rule
#: (``analysis/rules/fault_sites.py``), because the plan matches sites by
#: string equality and a typo'd site silently never fires (the chaos test
#: behind it then passes vacuously). Kept a PURE LITERAL so the rule can
#: ``ast.literal_eval`` it without importing the engine. The README
#: "Chaos & degradation ladders" table documents each site's ladder.
FAULT_SITES = {
    "gram_sharded": ("device_error", "preempt", "nan"),
    "fit_packed": ("device_error", "preempt", "nan"),
    "solver": ("device_error", "preempt", "nan"),
    "fit": ("device_error", "preempt"),
    "mesh": ("device_drop",),
    "pipeline_flush": ("device_error", "nan"),
    "grouped_flush": ("device_error",),
    "shard_flush": ("device_error",),
    "shard_merge": ("device_error",),
    "ingest_native": ("io_error", "torn_chunk", "thread_death",
                      "pool_exhaust"),
    "serve_exec": ("device_error",),
    "serve_admit": ("breaker_trip", "oom"),
    "coalesce": ("device_error", "stall", "oom"),
    "oom": ("oom",),
    "stats_persist": ("io_error", "torn_chunk"),
    "incident": ("io_error",),
    "optimizer": ("device_error",),
    "aqe": ("device_error", "stall"),
    "cost_profile": ("device_error",),
    "dq_profile": ("device_error",),
    "net_accept": ("conn_reset",),
    "net_read": ("conn_reset", "stall", "slow_client"),
    "net_write": ("conn_reset", "partial_write", "stall"),
}


def _jax_runtime_error_base():
    import jax

    return jax.errors.JaxRuntimeError


class Preemption(RuntimeError):
    """Simulated mid-fit preemption (maintenance event / spot reclaim).

    Deliberately NOT a ``JaxRuntimeError``: retry loops must not swallow
    it as a transient device fault — ``recovery.fit_or_resume`` owns it
    (checkpoint what is done, resume from the artifact)."""


class InjectedIOError(OSError):
    """Simulated I/O failure in the native ingest layer (a flaky disk, a
    truncated network mount read). An ``OSError`` subclass — the exact
    class a real mid-read failure surfaces as — but deliberately NOT a
    ``FileNotFoundError``: a missing file is a permanent, user-visible
    condition the ingest ladder must re-raise, not degrade around."""


# The injected device error must be catchable exactly where real XLA
# faults are caught; subclassing at import time would force a jax import
# here, so the class is built lazily on first use.
_INJECTED_DEVICE_ERROR = None


def injected_device_error_class():
    global _INJECTED_DEVICE_ERROR
    if _INJECTED_DEVICE_ERROR is None:
        class InjectedDeviceError(_jax_runtime_error_base()):
            """Simulated ``XlaRuntimeError`` (device OOM / interconnect
            reset / preempted tunnel) raised by the fault plan."""

        _INJECTED_DEVICE_ERROR = InjectedDeviceError
    return _INJECTED_DEVICE_ERROR


@dataclass
class FaultSpec:
    """One scheduled failure: ``kind`` at ``site``, firing on the listed
    1-based attempts, or per-attempt with probability ``p`` (seeded)."""

    site: str
    kind: str
    attempts: Optional[frozenset] = None   # None + p=None → {1}
    p: Optional[float] = None
    n: int = 1                             # device_drop count / nan leaves
    seed: Optional[int] = None             # overrides the plan seed

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(supported: {KINDS})")
        if self.attempts is None and self.p is None:
            self.attempts = frozenset({1})

    def fires(self, attempt: int, plan_seed: int) -> bool:
        if self.attempts is not None:
            return attempt in self.attempts
        # seeded Bernoulli: pure function of (seed, site, attempt) — no
        # global RNG state, so concurrent sites never perturb each other
        return _det_uniform(self._seed(plan_seed), self.site,
                            attempt) < float(self.p)

    def _seed(self, plan_seed: int) -> int:
        return plan_seed if self.seed is None else self.seed


def _det_uniform(seed: int, site: str, attempt: int) -> float:
    """Deterministic uniform in [0, 1): crc32-keyed — ``hash(str)`` is
    salted per process and would break run-to-run reproducibility."""
    key = zlib.crc32(f"{seed}:{site}:{attempt}".encode()) & 0xFFFFFFFF
    return key / 2.0 ** 32


def parse_spec(text: str) -> FaultSpec:
    parts = [p.strip() for p in text.strip().split(":") if p.strip()]
    if len(parts) < 2:
        raise ValueError(
            f"fault spec {text!r} must be site:kind[:attempts][:p=..]"
            "[:n=..][:seed=..]")
    site, kind = parts[0], parts[1].lower()
    attempts, p, n, seed = None, None, 1, None
    for part in parts[2:]:
        if part.startswith("p="):
            p = float(part[2:])
        elif part.startswith("n="):
            n = int(part[2:])
        elif part.startswith("seed="):
            seed = int(part[5:])
        else:
            attempts = frozenset(int(a) for a in part.split(",") if a)
    return FaultSpec(site, kind, attempts, p, n, seed)


def parse_plan(text: str, seed: int = 0) -> "FaultPlan":
    """Parse a plan string: specs separated by ``;`` (or newlines —
    commas stay free for attempt lists inside a spec)."""
    sep = ";" if ";" in text else "\n"
    specs = [parse_spec(s) for s in text.split(sep) if s.strip()]
    return FaultPlan(specs, seed=seed)


@dataclass
class FaultPlan:
    """Active failure schedule + per-(site, class) attempt counters + fire
    log. Attempt counters are keyed by failure *class* (``raise`` for
    device_error/preempt, ``nan``, ``drop``) so that co-located hooks —
    an ``inject`` and a ``corrupt`` guarding the same dispatch — never
    double-count one logical attempt."""

    specs: List[FaultSpec]
    seed: int = 0
    _counts: dict = field(default_factory=dict)
    _fired: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def _has(self, site: str, kinds: Sequence[str]) -> bool:
        return any(s.site == site and s.kind in kinds for s in self.specs)

    def _tick(self, site: str, cls: str) -> int:
        key = f"{site}#{cls}"
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            return self._counts[key]

    def _due(self, site: str, attempt: int, kinds: Sequence[str]):
        for spec in self.specs:
            if spec.site == site and spec.kind in kinds \
                    and spec.fires(attempt, self.seed):
                return spec
        return None

    def _record(self, spec: FaultSpec, attempt: int):
        with self._lock:
            self._fired.append((spec.site, spec.kind, attempt))
        from . import profiling

        profiling.counters.increment("faults.injected")
        profiling.counters.increment(f"faults.injected.{spec.site}")
        # Annotate the enclosing span: EXPLAIN ANALYZE copies every
        # ``recovery_*`` span attribute onto its operator node, so the
        # plan shows WHICH operator absorbed the fault (e.g. the
        # FusedStage whose flush span was live when this fired).
        try:
            from . import observability as _obs

            if _obs.TRACER.enabled:
                _obs.current_span().set(
                    recovery_fault=f"{spec.site}:{spec.kind}")
        except Exception:       # annotation must never mask the fault
            pass
        logger.warning("fault injected: site=%s kind=%s attempt=%d",
                       spec.site, spec.kind, attempt)

    # -- introspection (test assertions) -----------------------------------
    @property
    def fired(self) -> list:
        with self._lock:
            return list(self._fired)

    def attempts_at(self, site: str, cls: str = "raise") -> int:
        with self._lock:
            return self._counts.get(f"{site}#{cls}", 0)


# -- active-plan management (module global; None == chaos off) --------------
_PLAN: Optional[FaultPlan] = None
_ENV_CHECKED = False


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    global _PLAN, _ENV_CHECKED
    _PLAN = plan
    _ENV_CHECKED = True  # an explicit install wins over the env
    return plan


def install_from_env(env: Optional[str] = None,
                     seed: int = 0) -> Optional[FaultPlan]:
    """(Re-)read the env spec; installs None when unset."""
    text = os.environ.get(ENV_VAR) if env is None else env
    return install_plan(parse_plan(text, seed=seed) if text else None)


def clear() -> None:
    install_plan(None)


def active() -> Optional[FaultPlan]:
    """The active plan — lazily picks up ``SPARKDQ4ML_FAULTS`` once so
    env-driven chaos works without a session."""
    global _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        if os.environ.get(ENV_VAR):
            install_from_env()
            _ENV_CHECKED = True
    return _PLAN


class inject_faults:
    """Context manager installing a plan for a scope (tests)::

        with inject_faults("gram_sharded:device_error:1", seed=42):
            model = lr.fit(frame)
    """

    def __init__(self, *specs, seed: int = 0):
        parsed = []
        for s in specs:
            parsed.append(s if isinstance(s, FaultSpec) else parse_spec(s))
        self.plan = FaultPlan(parsed, seed=seed)
        self._prev = None

    def __enter__(self) -> FaultPlan:
        self._prev = _PLAN
        install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc):
        install_plan(self._prev)
        return False


# -- site hooks (the production instrumentation points) ---------------------
_RAISE_KINDS = ("device_error", "preempt", "io_error")


def inject(site: str) -> None:
    """Raise the scheduled failure for ``site``, if any. The per-site
    attempt counter ticks on every call that has a matching raise-class
    spec, so a retry loop naturally walks past an attempt-1-only fault on
    its second try. Raise classes: ``device_error`` →
    :class:`InjectedDeviceError` (a ``JaxRuntimeError``), ``preempt`` →
    :class:`Preemption`, ``io_error`` → :class:`InjectedIOError` (an
    ``OSError``, the native-ingest failure class)."""
    plan = active()
    if plan is None or not plan._has(site, _RAISE_KINDS):
        return
    attempt = plan._tick(site, "raise")
    spec = plan._due(site, attempt, _RAISE_KINDS)
    if spec is None:
        return
    plan._record(spec, attempt)
    if spec.kind == "preempt":
        raise Preemption(
            f"injected preemption at {site!r} (attempt {attempt})")
    if spec.kind == "io_error":
        raise InjectedIOError(
            f"injected I/O error at {site!r} (attempt {attempt})")
    raise injected_device_error_class()(
        f"injected device error at {site!r} (attempt {attempt})")


def fired(site: str, kind: str) -> bool:
    """Generic due-test hook for the non-raising fault kinds — the chaos
    switchpoints that alter a decision instead of throwing (a torn ingest
    chunk, a dying prefetch thread, an exhausted buffer pool, a tripped
    serving breaker, an admission-gate OOM). Each ``kind`` keeps its own
    per-site attempt counter, so co-located hooks of different kinds
    never steal each other's attempts. One ``is None`` check when no plan
    is installed — the same zero-cost contract as :func:`inject`."""
    plan = active()
    if plan is None or not plan._has(site, (kind,)):
        return False
    attempt = plan._tick(site, kind)
    spec = plan._due(site, attempt, (kind,))
    if spec is None:
        return False
    plan._record(spec, attempt)
    return True


def shrunk_budget(site: str) -> Optional[int]:
    """Device-byte budget override when an ``oom`` fault is due at
    ``site`` — "memory pressure as a schedulable fault" (arxiv
    2206.14148): the flush path treats the returned budget exactly like a
    conf-shrunken ``spark.audit.deviceBudget``, so the est-peak-over-
    budget → row-chunked degrade runs under test without touching real
    allocator state. The spec's ``n`` parameter carries the budget in
    bytes (``oom:oom:1:n=65536``); the default ``n=1`` is an always-over
    1-byte budget (maximum chunking). ``None`` = no fault due."""
    plan = active()
    if plan is None or not plan._has(site, ("oom",)):
        return None
    attempt = plan._tick(site, "oom")
    spec = plan._due(site, attempt, ("oom",))
    if spec is None:
        return None
    plan._record(spec, attempt)
    return max(1, int(spec.n))


def corrupt(site: str, tree):
    """Poison one float leaf element of ``tree`` with NaN when a ``nan``
    fault is due at ``site`` (seeded element choice); otherwise return
    ``tree`` unchanged."""
    plan = active()
    if plan is None or not plan._has(site, ("nan",)):
        return tree
    attempt = plan._tick(site, "nan")
    spec = plan._due(site, attempt, ("nan",))
    if spec is None:
        return tree
    plan._record(spec, attempt)
    return _poison(tree, spec._seed(plan.seed), site, attempt)


def _poison(tree, seed: int, site: str, attempt: int):
    """NaN one element of one inexact array leaf, chosen deterministically."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    targets = [i for i, leaf in enumerate(leaves)
               if hasattr(leaf, "dtype") and hasattr(leaf, "size")
               and np.issubdtype(np.asarray(leaf).dtype, np.inexact)
               and np.asarray(leaf).size > 0]
    if not targets:
        return tree
    u = _det_uniform(seed, site + "#leaf", attempt)
    li = targets[int(u * len(targets)) % len(targets)]
    leaf = leaves[li]
    size = int(np.asarray(leaf).size)
    ei = int(_det_uniform(seed, site + "#elem", attempt) * size) % size
    if isinstance(leaf, jax.Array):
        flat = jnp.ravel(leaf).at[ei].set(jnp.nan).reshape(leaf.shape)
    else:
        flat = np.array(leaf, copy=True)
        flat.reshape(-1)[ei] = np.nan
    leaves[li] = flat
    return jax.tree_util.tree_unflatten(treedef, leaves)


def degrade_mesh(site: str, mesh):
    """Drop ``n`` devices from ``mesh`` when a ``device_drop`` fault is due
    at ``site`` — the lost-worker scenario. Never drops below 1 device."""
    plan = active()
    if plan is None or mesh is None \
            or not plan._has(site, ("device_drop",)):
        return mesh
    attempt = plan._tick(site, "drop")
    spec = plan._due(site, attempt, ("device_drop",))
    if spec is None:
        return mesh
    plan._record(spec, attempt)
    devices = list(mesh.devices.flat)
    keep = max(1, len(devices) - spec.n)
    if keep == len(devices):
        return mesh
    from ..parallel.mesh import make_mesh

    logger.warning("fault plan dropped %d device(s): mesh %d -> %d",
                   len(devices) - keep, len(devices), keep)
    return make_mesh(devices=devices[:keep])
