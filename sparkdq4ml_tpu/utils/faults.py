"""Deterministic fault injection — the chaos layer of the resilience stack.

The reference app inherits Spark's failure machinery (task retry, lineage
recomputation, checkpointing) but none of it is *testable* there: you
cannot ask `local[*]` to lose an executor on the third task. Here failures
are first-class: a :class:`FaultPlan` schedules failures at named **sites**
in the execution path, keyed by a per-site attempt counter and a seed, so
every injected failure is reproducible run-to-run — the property the
``tests/test_faults.py`` suite is built on.

Failure classes (``kind``):

* ``device_error`` — raises :class:`InjectedDeviceError`, a
  ``jax.errors.JaxRuntimeError`` subclass, i.e. exactly the exception type
  a real XLA device fault (OOM, interconnect reset, preempted tunnel)
  surfaces as. The production catch paths cannot tell the difference,
  which is the point.
* ``nan`` — poisons one leaf of a result pytree with NaN (a diverged
  solver / flaky transfer), at a seeded element position.
* ``preempt`` — raises :class:`Preemption` (NOT a device error): the
  mid-fit preemption that ``recovery.fit_or_resume`` turns into a
  checkpoint-resume instead of a crash.
* ``device_drop`` — shrinks a mesh by ``n`` devices (default 1), the
  lost-worker scenario; ``parallel.mesh.normalize_mesh`` semantics apply
  to whatever survives.

Sites instrumented in production code: ``gram_sharded``
(``parallel.distributed.compute_gram``'s sharded path), ``fit_packed``
(the packed linear-fit dispatch in ``models.regression``), ``solver``
(``models.solvers.solve`` and the packed fit's result pytree), ``fit``
(``recovery.fit_or_resume``'s fit call), and ``mesh`` (session mesh
construction). Injection happens at host-level dispatch boundaries only —
never inside a traced/jitted function, where a Python-level raise would
fire at trace time, not run time.

Activation: programmatic (:func:`install_plan`, or the
:func:`inject_faults` context manager tests use) or env-driven — set
``SPARKDQ4ML_FAULTS`` (or session conf ``spark.faults``) to a
semicolon-separated spec list, e.g.::

    SPARKDQ4ML_FAULTS="gram_sharded:device_error:1,2;solver:nan:1"
    SPARKDQ4ML_FAULTS="fit:preempt:p=0.25:seed=7;mesh:device_drop:n=2"

Spec grammar: ``site:kind[:a1,a2,...][:p=prob][:n=count][:seed=s]`` —
an explicit 1-based attempt list fires deterministically on those
attempts; ``p=`` fires as a seeded Bernoulli draw per attempt (still
reproducible: the draw is a pure function of (seed, site, attempt));
with neither, the fault fires on attempt 1 only.

When no plan is installed every hook is a no-op behind one ``is None``
check — the chaos layer costs nothing in production.

See README.md "Failure model & fault injection" for the recovery side:
retry policy knobs, circuit breaker, and the fallback ladder.
"""

from __future__ import annotations

import logging
import os
import threading
import zlib
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

logger = logging.getLogger("sparkdq4ml_tpu.faults")

ENV_VAR = "SPARKDQ4ML_FAULTS"

KINDS = ("device_error", "nan", "preempt", "device_drop")


def _jax_runtime_error_base():
    import jax

    return jax.errors.JaxRuntimeError


class Preemption(RuntimeError):
    """Simulated mid-fit preemption (maintenance event / spot reclaim).

    Deliberately NOT a ``JaxRuntimeError``: retry loops must not swallow
    it as a transient device fault — ``recovery.fit_or_resume`` owns it
    (checkpoint what is done, resume from the artifact)."""


# The injected device error must be catchable exactly where real XLA
# faults are caught; subclassing at import time would force a jax import
# here, so the class is built lazily on first use.
_INJECTED_DEVICE_ERROR = None


def injected_device_error_class():
    global _INJECTED_DEVICE_ERROR
    if _INJECTED_DEVICE_ERROR is None:
        class InjectedDeviceError(_jax_runtime_error_base()):
            """Simulated ``XlaRuntimeError`` (device OOM / interconnect
            reset / preempted tunnel) raised by the fault plan."""

        _INJECTED_DEVICE_ERROR = InjectedDeviceError
    return _INJECTED_DEVICE_ERROR


@dataclass
class FaultSpec:
    """One scheduled failure: ``kind`` at ``site``, firing on the listed
    1-based attempts, or per-attempt with probability ``p`` (seeded)."""

    site: str
    kind: str
    attempts: Optional[frozenset] = None   # None + p=None → {1}
    p: Optional[float] = None
    n: int = 1                             # device_drop count / nan leaves
    seed: Optional[int] = None             # overrides the plan seed

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(supported: {KINDS})")
        if self.attempts is None and self.p is None:
            self.attempts = frozenset({1})

    def fires(self, attempt: int, plan_seed: int) -> bool:
        if self.attempts is not None:
            return attempt in self.attempts
        # seeded Bernoulli: pure function of (seed, site, attempt) — no
        # global RNG state, so concurrent sites never perturb each other
        return _det_uniform(self._seed(plan_seed), self.site,
                            attempt) < float(self.p)

    def _seed(self, plan_seed: int) -> int:
        return plan_seed if self.seed is None else self.seed


def _det_uniform(seed: int, site: str, attempt: int) -> float:
    """Deterministic uniform in [0, 1): crc32-keyed — ``hash(str)`` is
    salted per process and would break run-to-run reproducibility."""
    key = zlib.crc32(f"{seed}:{site}:{attempt}".encode()) & 0xFFFFFFFF
    return key / 2.0 ** 32


def parse_spec(text: str) -> FaultSpec:
    parts = [p.strip() for p in text.strip().split(":") if p.strip()]
    if len(parts) < 2:
        raise ValueError(
            f"fault spec {text!r} must be site:kind[:attempts][:p=..]"
            "[:n=..][:seed=..]")
    site, kind = parts[0], parts[1].lower()
    attempts, p, n, seed = None, None, 1, None
    for part in parts[2:]:
        if part.startswith("p="):
            p = float(part[2:])
        elif part.startswith("n="):
            n = int(part[2:])
        elif part.startswith("seed="):
            seed = int(part[5:])
        else:
            attempts = frozenset(int(a) for a in part.split(",") if a)
    return FaultSpec(site, kind, attempts, p, n, seed)


def parse_plan(text: str, seed: int = 0) -> "FaultPlan":
    """Parse a plan string: specs separated by ``;`` (or newlines —
    commas stay free for attempt lists inside a spec)."""
    sep = ";" if ";" in text else "\n"
    specs = [parse_spec(s) for s in text.split(sep) if s.strip()]
    return FaultPlan(specs, seed=seed)


@dataclass
class FaultPlan:
    """Active failure schedule + per-(site, class) attempt counters + fire
    log. Attempt counters are keyed by failure *class* (``raise`` for
    device_error/preempt, ``nan``, ``drop``) so that co-located hooks —
    an ``inject`` and a ``corrupt`` guarding the same dispatch — never
    double-count one logical attempt."""

    specs: List[FaultSpec]
    seed: int = 0
    _counts: dict = field(default_factory=dict)
    _fired: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def _has(self, site: str, kinds: Sequence[str]) -> bool:
        return any(s.site == site and s.kind in kinds for s in self.specs)

    def _tick(self, site: str, cls: str) -> int:
        key = f"{site}#{cls}"
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            return self._counts[key]

    def _due(self, site: str, attempt: int, kinds: Sequence[str]):
        for spec in self.specs:
            if spec.site == site and spec.kind in kinds \
                    and spec.fires(attempt, self.seed):
                return spec
        return None

    def _record(self, spec: FaultSpec, attempt: int):
        with self._lock:
            self._fired.append((spec.site, spec.kind, attempt))
        logger.warning("fault injected: site=%s kind=%s attempt=%d",
                       spec.site, spec.kind, attempt)

    # -- introspection (test assertions) -----------------------------------
    @property
    def fired(self) -> list:
        with self._lock:
            return list(self._fired)

    def attempts_at(self, site: str, cls: str = "raise") -> int:
        with self._lock:
            return self._counts.get(f"{site}#{cls}", 0)


# -- active-plan management (module global; None == chaos off) --------------
_PLAN: Optional[FaultPlan] = None
_ENV_CHECKED = False


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    global _PLAN, _ENV_CHECKED
    _PLAN = plan
    _ENV_CHECKED = True  # an explicit install wins over the env
    return plan


def install_from_env(env: Optional[str] = None,
                     seed: int = 0) -> Optional[FaultPlan]:
    """(Re-)read the env spec; installs None when unset."""
    text = os.environ.get(ENV_VAR) if env is None else env
    return install_plan(parse_plan(text, seed=seed) if text else None)


def clear() -> None:
    install_plan(None)


def active() -> Optional[FaultPlan]:
    """The active plan — lazily picks up ``SPARKDQ4ML_FAULTS`` once so
    env-driven chaos works without a session."""
    global _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        if os.environ.get(ENV_VAR):
            install_from_env()
            _ENV_CHECKED = True
    return _PLAN


class inject_faults:
    """Context manager installing a plan for a scope (tests)::

        with inject_faults("gram_sharded:device_error:1", seed=42):
            model = lr.fit(frame)
    """

    def __init__(self, *specs, seed: int = 0):
        parsed = []
        for s in specs:
            parsed.append(s if isinstance(s, FaultSpec) else parse_spec(s))
        self.plan = FaultPlan(parsed, seed=seed)
        self._prev = None

    def __enter__(self) -> FaultPlan:
        self._prev = _PLAN
        install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc):
        install_plan(self._prev)
        return False


# -- site hooks (the production instrumentation points) ---------------------
def inject(site: str) -> None:
    """Raise the scheduled failure for ``site``, if any. The per-site
    attempt counter ticks on every call that has a matching raise-class
    spec, so a retry loop naturally walks past an attempt-1-only fault on
    its second try."""
    plan = active()
    if plan is None or not plan._has(site, ("device_error", "preempt")):
        return
    attempt = plan._tick(site, "raise")
    spec = plan._due(site, attempt, ("device_error", "preempt"))
    if spec is None:
        return
    plan._record(spec, attempt)
    if spec.kind == "preempt":
        raise Preemption(
            f"injected preemption at {site!r} (attempt {attempt})")
    raise injected_device_error_class()(
        f"injected device error at {site!r} (attempt {attempt})")


def corrupt(site: str, tree):
    """Poison one float leaf element of ``tree`` with NaN when a ``nan``
    fault is due at ``site`` (seeded element choice); otherwise return
    ``tree`` unchanged."""
    plan = active()
    if plan is None or not plan._has(site, ("nan",)):
        return tree
    attempt = plan._tick(site, "nan")
    spec = plan._due(site, attempt, ("nan",))
    if spec is None:
        return tree
    plan._record(spec, attempt)
    return _poison(tree, spec._seed(plan.seed), site, attempt)


def _poison(tree, seed: int, site: str, attempt: int):
    """NaN one element of one inexact array leaf, chosen deterministically."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    targets = [i for i, leaf in enumerate(leaves)
               if hasattr(leaf, "dtype") and hasattr(leaf, "size")
               and np.issubdtype(np.asarray(leaf).dtype, np.inexact)
               and np.asarray(leaf).size > 0]
    if not targets:
        return tree
    u = _det_uniform(seed, site + "#leaf", attempt)
    li = targets[int(u * len(targets)) % len(targets)]
    leaf = leaves[li]
    size = int(np.asarray(leaf).size)
    ei = int(_det_uniform(seed, site + "#elem", attempt) * size) % size
    if isinstance(leaf, jax.Array):
        flat = jnp.ravel(leaf).at[ei].set(jnp.nan).reshape(leaf.shape)
    else:
        flat = np.array(leaf, copy=True)
        flat.reshape(-1)[ei] = np.nan
    leaves[li] = flat
    return jax.tree_util.tree_unflatten(treedef, leaves)


def degrade_mesh(site: str, mesh):
    """Drop ``n`` devices from ``mesh`` when a ``device_drop`` fault is due
    at ``site`` — the lost-worker scenario. Never drops below 1 device."""
    plan = active()
    if plan is None or mesh is None \
            or not plan._has(site, ("device_drop",)):
        return mesh
    attempt = plan._tick(site, "drop")
    spec = plan._due(site, attempt, ("device_drop",))
    if spec is None:
        return mesh
    plan._record(spec, attempt)
    devices = list(mesh.devices.flat)
    keep = max(1, len(devices) - spec.n)
    if keep == len(devices):
        return mesh
    from ..parallel.mesh import make_mesh

    logger.warning("fault plan dropped %d device(s): mesh %d -> %d",
                   len(devices) - keep, len(devices), keep)
    return make_mesh(devices=devices[:keep])
