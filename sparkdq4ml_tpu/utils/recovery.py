"""Failure detection & recovery (SURVEY.md §5 "Failure detection / elastic
recovery").

The reference inherits Spark's recovery model — task retry, lineage
recomputation, checkpoint dirs — but configures none of it (``local[*]``,
no checkpoint dir, `DataQuality4MachineLearningApp.java:38-41`). The
TPU-native equivalents of those three primitives:

* **Detection** — :func:`check_finite` inspects a result pytree for
  NaN/Inf (a diverged solver, a flaky interconnect transfer); the global
  NaN traps in ``utils.debug`` localize the producing op when needed.
  Device-side faults (OOM, interconnect resets, preempted tunnels)
  surface as ``XlaRuntimeError`` and are caught by :func:`retry`.
* **Deterministic re-execution (lineage)** — every fit in this framework
  is a pure function of (frame, params, seed), so a failed task re-runs
  identically; :func:`retry` is the task-retry loop
  (``spark.task.maxFailures`` analogue).
* **Checkpointing** — :func:`fit_or_resume` persists the fitted stage via
  the models/base persistence layer and resumes from the artifact after a
  driver crash/preemption instead of refitting (the checkpoint-dir
  analogue).
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Optional

import jax
import numpy as np

logger = logging.getLogger("sparkdq4ml_tpu.recovery")


class FitFailure(RuntimeError):
    """A computation failed (non-finite result or device error) and did not
    recover within the configured retries."""


def check_finite(tree, _seen=None) -> bool:
    """True when every inexact array leaf in ``tree`` is fully finite.

    Works on device arrays, numpy arrays, fitted models (via their
    ``_persist_attrs`` when declared, else their instance ``__dict__`` —
    models with custom persistence must not silently pass), and arbitrary
    pytrees; non-numeric leaves pass. Cycles are guarded.
    """
    if _seen is None:
        _seen = set()
    if id(tree) in _seen:
        return True
    _seen.add(id(tree))

    attrs = getattr(tree, "_persist_attrs", None)
    if attrs is not None:
        return all(check_finite(getattr(tree, a, None), _seen)
                   for a in attrs)
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) == 1 and leaves[0] is tree \
            and not isinstance(tree, (jax.Array, np.ndarray, float,
                                      np.floating)) \
            and hasattr(tree, "__dict__"):
        # tree itself is one opaque leaf (a model object): scan its public
        # attributes directly
        return check_finite({k: v for k, v in vars(tree).items()
                             if not k.startswith("_")}, _seen)
    for leaf in leaves:
        if isinstance(leaf, (jax.Array, np.ndarray, float, np.floating)):
            arr = np.asarray(leaf)
            if np.issubdtype(arr.dtype, np.inexact) \
                    and not np.all(np.isfinite(arr)):
                return False
        elif hasattr(leaf, "__dict__") and id(leaf) not in _seen:
            # opaque object leaf (e.g. a model with custom save()): scan
            # its PUBLIC instance attributes instead of passing it blindly.
            # Private attrs are skipped — e.g. a model's _summary_source
            # frame legitimately carries NaN in masked slots.
            _seen.add(id(leaf))
            public = {k: v for k, v in vars(leaf).items()
                      if not k.startswith("_")}
            if not check_finite(public, _seen):
                return False
    return True


def retry(fn: Callable, retries: int = 3,
          validate: Callable = check_finite,
          on_failure: Optional[Callable] = None):
    """Run ``fn()`` with detection + deterministic re-execution.

    A device-side fault (``XlaRuntimeError``) or a result failing
    ``validate`` triggers a re-run, up to ``retries`` attempts total;
    ``on_failure(attempt, error_or_none)`` runs between attempts (e.g. to
    clear caches or re-seed). Raises :class:`FitFailure` when exhausted.
    """
    if retries < 1:
        raise ValueError("retries must be >= 1")
    last_err = None
    for attempt in range(1, retries + 1):
        try:
            out = fn()
        except jax.errors.JaxRuntimeError as e:   # XlaRuntimeError subclass
            last_err = e
            logger.warning("attempt %d/%d failed with device error: %s",
                           attempt, retries, e)
        else:
            if validate is None or validate(out):
                return out
            last_err = None
            logger.warning("attempt %d/%d produced non-finite results",
                           attempt, retries)
        if on_failure is not None:
            on_failure(attempt, last_err)
    raise FitFailure(
        f"computation failed after {retries} attempts"
        + (f": {last_err}" if last_err is not None else " (non-finite)"))


def fit_or_resume(estimator, frame, checkpoint_dir: str, mesh=None,
                  retries: int = 1):
    """Fit with a persistent checkpoint: if ``checkpoint_dir`` already holds
    a saved stage, load and return it WITHOUT refitting (crash/preemption
    resume); otherwise fit (with :func:`retry` semantics when
    ``retries > 1``), save, and return the model.
    """
    import inspect
    import shutil

    from ..models.base import load_stage, save_stage

    if os.path.exists(os.path.join(checkpoint_dir, "stage.json")) or \
            os.path.exists(os.path.join(checkpoint_dir, "metadata.json")):
        logger.info("resuming fitted stage from %s", checkpoint_dir)
        return load_stage(checkpoint_dir)

    takes_mesh = "mesh" in inspect.signature(estimator.fit).parameters

    def do_fit():
        if takes_mesh:
            return estimator.fit(frame, mesh=mesh)
        return estimator.fit(frame)

    model = retry(do_fit, retries=retries)
    # Atomic checkpoint: write to a sibling tmp dir, then one rename —
    # a crash mid-save (the scenario this module exists for) must never
    # leave a half-written dir that the resume branch would pick up.
    tmp = checkpoint_dir.rstrip("/\\") + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    save_stage(model, tmp)
    shutil.rmtree(checkpoint_dir, ignore_errors=True)
    os.rename(tmp, checkpoint_dir)
    return model
